"""Scenario runner facade (reference: ``python/fedml/runner.py:14-123``).

Chooses the scenario runtime from ``args.training_type`` / ``args.backend`` /
``args.role`` and accepts custom ``ClientTrainer`` / ``ServerAggregator``
override points, exactly like the reference's FedMLRunner.
"""

from __future__ import annotations

import logging

from . import constants


class FedMLRunner:
    def __init__(
        self,
        args,
        device,
        dataset,
        model,
        client_trainer=None,
        server_aggregator=None,
    ):
        self.args = args
        if args.training_type == constants.FEDML_TRAINING_PLATFORM_SIMULATION:
            self.runner = self._init_simulation_runner(
                args, device, dataset, model, client_trainer, server_aggregator
            )
        elif args.training_type == constants.FEDML_TRAINING_PLATFORM_CROSS_SILO:
            self.runner = self._init_cross_silo_runner(
                args, device, dataset, model, client_trainer, server_aggregator
            )
        elif args.training_type == constants.FEDML_TRAINING_PLATFORM_DISTRIBUTED:
            self.runner = self._init_distributed_runner(args, device, dataset, model)
        elif args.training_type == constants.FEDML_TRAINING_PLATFORM_CROSS_DEVICE:
            self.runner = self._init_cross_device_runner(
                args, device, dataset, model, server_aggregator
            )
        else:
            raise ValueError(f"unsupported training_type {args.training_type!r}")

    @staticmethod
    def _init_simulation_runner(
        args, device, dataset, model, client_trainer, server_aggregator
    ):
        from .simulation.simulator import SimulatorMesh, SimulatorSingleProcess

        if args.backend == constants.FEDML_SIMULATION_TYPE_SP:
            return SimulatorSingleProcess(
                args, device, dataset, model, client_trainer, server_aggregator
            )
        if args.backend == constants.FEDML_SIMULATION_TYPE_MESH:
            return SimulatorMesh(
                args, device, dataset, model, client_trainer, server_aggregator
            )
        raise ValueError(f"unsupported simulation backend {args.backend!r}")

    @staticmethod
    def _init_cross_silo_runner(
        args, device, dataset, model, client_trainer, server_aggregator
    ):
        from .cross_silo import FedMLCrossSiloClient, FedMLCrossSiloServer

        if args.role == "server":
            return FedMLCrossSiloServer(args, device, dataset, model, server_aggregator)
        return FedMLCrossSiloClient(args, device, dataset, model, client_trainer)

    @staticmethod
    def _init_distributed_runner(args, device, dataset, model):
        from .cheetah import CheetahRunner

        return CheetahRunner(args, device, dataset, model)

    @staticmethod
    def _init_cross_device_runner(args, device, dataset, model, server_aggregator):
        from .cross_device import ServerMNN

        return ServerMNN(args, device, dataset, model, server_aggregator)

    def run(self):
        from .core.mlops import telemetry
        from .core.runstate import EXIT_PREEMPTED, PreemptionError

        # periodic host CPU/RSS + HBM sampling on a daemon thread (off by
        # default; --sys_perf_interval_s N with tracking enabled turns it on)
        sampler = telemetry.start_sys_perf_sampler(self.args)
        try:
            return self.runner.run()
        except PreemptionError as e:
            # drained + committed: exit with the distinct "preempted,
            # resumable" status (75, EX_TEMPFAIL) so supervisors restart
            # with --resume auto instead of treating this as a crash
            logging.getLogger(__name__).warning("%s", e)
            raise SystemExit(EXIT_PREEMPTED)
        finally:
            if sampler is not None:
                sampler.stop()
