"""FedGKT: group knowledge transfer — small client nets, big server net.

reference: ``simulation/mpi/fedgkt/`` (GKTServerTrainer.py 416 LoC,
GKTClientTrainer.py) — clients train a small feature extractor + classifier;
the server trains a large network on the clients' extracted features with a
CE + KL(client soft labels) loss, and returns its own soft labels for the
client's KD term. Only features/logits cross the boundary, never raw data.
"""

from __future__ import annotations

import logging
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

logger = logging.getLogger(__name__)


class ClientFeatureNet(nn.Module):
    """Small client net (reference: resnet-8 client; here a compact CNN/MLP
    extractor + local classifier head)."""

    feat_dim: int = 64

    @nn.compact
    def __call__(self, x):
        h = x.reshape((x.shape[0], -1))
        h = nn.relu(nn.Dense(128)(h))
        return nn.relu(nn.Dense(self.feat_dim)(h))


class ServerNet(nn.Module):
    """Large server net over client features (reference: resnet-49 tail)."""

    num_classes: int

    @nn.compact
    def __call__(self, feats):
        h = nn.relu(nn.Dense(256)(feats))
        h = nn.relu(nn.Dense(256)(h))
        return nn.Dense(self.num_classes)(h)


def kl_soft(p_logits, q_logits, T: float = 1.0):
    """KL(softmax(p/T) || softmax(q/T)) per sample."""
    p = jax.nn.log_softmax(p_logits / T)
    q = jax.nn.log_softmax(q_logits / T)
    return (jnp.exp(p) * (p - q)).sum(-1)


class FedGKTAPI:
    def __init__(self, args, device, dataset, model=None):
        self.args = args
        self.ds = dataset
        self.n = dataset.client_num
        C = dataset.class_num
        feat_dim = int(getattr(args, "gkt_feat_dim", 64))
        self.temp = float(getattr(args, "gkt_temperature", 3.0))
        self.alpha = float(getattr(args, "gkt_alpha", 1.0))  # KD weight
        self.extractor = ClientFeatureNet(feat_dim)
        self.client_head = nn.Dense(C)
        self.server_net = ServerNet(C)
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        ke, kh, ks = jax.random.split(rng, 3)
        dummy = jnp.zeros((1,) + dataset.train_x.shape[2:])
        e0 = self.extractor.init(ke, dummy)
        h0 = self.client_head.init(kh, jnp.zeros((1, feat_dim)))
        self.client_ex = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (self.n,) + x.shape), e0
        )
        self.client_hd = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (self.n,) + x.shape), h0
        )
        self.server_params = self.server_net.init(ks, jnp.zeros((1, feat_dim)))
        lr = float(getattr(args, "learning_rate", 0.05))
        self.c_opt = optax.sgd(lr)
        self.s_opt = optax.adam(1e-3)
        self.s_opt_state = self.s_opt.init(self.server_params)

        def client_loss(ex, hd, x, y, mask, server_logits):
            feats = self.extractor.apply(ex, x)
            logits = self.client_head.apply(hd, feats)
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            kd = kl_soft(server_logits, logits, self.temp)
            per = ce + self.alpha * kd
            return (per * mask).sum() / jnp.maximum(mask.sum(), 1.0)

        def closs(params, x, y, mask, server_logits):
            ex, hd = params
            return client_loss(ex, hd, x, y, mask, server_logits)

        @jax.jit
        def client_update(ex, hd, c_state, x, y, mask, server_logits):
            loss, grads = jax.value_and_grad(closs)(
                (ex, hd), x, y, mask, server_logits
            )
            updates, c_state = self.c_opt.update(grads, c_state, (ex, hd))
            ex, hd = optax.apply_updates((ex, hd), updates)
            feats = self.extractor.apply(ex, x)
            logits = self.client_head.apply(hd, feats)
            return ex, hd, c_state, feats, logits, loss

        self._client_update = client_update
        self.c_opt_states = jax.vmap(
            lambda e, h: self.c_opt.init((e, h))
        )(self.client_ex, self.client_hd)

        def server_loss(sp, feats, y, mask, client_logits):
            logits = self.server_net.apply(sp, feats)
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            kd = kl_soft(client_logits, logits, self.temp)
            per = ce + self.alpha * kd
            return (per * mask).sum() / jnp.maximum(mask.sum(), 1.0)

        @jax.jit
        def server_update(sp, s_state, feats, y, mask, client_logits):
            loss, grads = jax.value_and_grad(server_loss)(
                sp, feats, y, mask, client_logits
            )
            updates, s_state = self.s_opt.update(grads, s_state, sp)
            sp = optax.apply_updates(sp, updates)
            logits = self.server_net.apply(sp, feats)
            return sp, s_state, logits, loss

        self._server_update = server_update
        self.history = []

    def train(self) -> Dict[str, float]:
        rounds = int(self.args.comm_round)
        last: Dict[str, float] = {}
        C = self.ds.class_num
        # per-client cached server logits (start at zeros = uniform teacher)
        server_logits = jnp.zeros((self.n, self.ds.cap, C))
        for r in range(rounds):
            c_losses, s_losses = [], []
            for c in range(self.n):
                ex = jax.tree.map(lambda t: t[c], self.client_ex)
                hd = jax.tree.map(lambda t: t[c], self.client_hd)
                cs = jax.tree.map(lambda t: t[c], self.c_opt_states)
                x, y, cnt = self.ds.client_shard(c)
                xj = jnp.asarray(x)
                yj = jnp.asarray(y).astype(jnp.int32)
                mask = (jnp.arange(self.ds.cap) < cnt).astype(jnp.float32)
                # several local full-batch steps per round (reference: client
                # trains `epochs` local epochs before the exchange)
                for _ in range(max(int(getattr(self.args, "epochs", 1)), 1)):
                    ex, hd, cs, feats, logits, closs_v = self._client_update(
                        ex, hd, cs, xj, yj, mask, server_logits[c]
                    )
                # client → server: features + soft labels (never raw x)
                self.server_params, self.s_opt_state, slogits, sloss_v = (
                    self._server_update(self.server_params, self.s_opt_state,
                                        feats, yj, mask, logits)
                )
                server_logits = server_logits.at[c].set(slogits)
                self.client_ex = jax.tree.map(
                    lambda a, t: a.at[c].set(t), self.client_ex, ex)
                self.client_hd = jax.tree.map(
                    lambda a, t: a.at[c].set(t), self.client_hd, hd)
                self.c_opt_states = jax.tree.map(
                    lambda a, t: a.at[c].set(t), self.c_opt_states, cs)
                c_losses.append(float(closs_v))
                s_losses.append(float(sloss_v))
            # eval: client-0 extractor + server net (reference: server-side
            # eval on the big model)
            ex0 = jax.tree.map(lambda t: t[0], self.client_ex)
            feats = self.extractor.apply(ex0, jnp.asarray(self.ds.test_x))
            logits = self.server_net.apply(self.server_params, feats)
            acc = float(
                (jnp.argmax(logits, -1) == jnp.asarray(self.ds.test_y)).mean()
            )
            last = {"test_acc": acc,
                    "train_loss": float(np.mean(c_losses)),
                    "server_loss": float(np.mean(s_losses))}
            self.history.append({"round": r, **last})
            logger.info("fedgkt round %d: closs=%.4f sloss=%.4f acc=%.4f",
                        r, last["train_loss"], last["server_loss"], acc)
        return last
