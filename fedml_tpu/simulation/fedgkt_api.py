"""FedGKT: group knowledge transfer — small client nets, big server net.

reference: ``simulation/mpi/fedgkt/`` (GKTServerTrainer.py 416 LoC,
GKTClientTrainer.py) — clients train a small feature extractor + classifier;
the server trains a large network on the clients' extracted features with a
CE + KL(client soft labels) loss, and returns its own soft labels for the
client's KD term. Only features/logits cross the boundary, never raw data.

TPU-first: one jitted program per round —
- the CLIENT phase is ``vmap``-ped over the stacked ``[clients, cap, ...]``
  dataset (local epochs are a ``lax.scan`` inside), so the whole cohort's
  extractor/classifier updates are a single fused device program;
- the SERVER phase is inherently sequential (its params update after each
  client's features, reference GKTServerTrainer.train_large_model_on_the_server),
  so it runs as ONE ``lax.scan`` over the client axis instead of n Python
  dispatches;
- eval follows the reference's protocol: the server net is scored through
  EVERY client's extractor (mean accuracy), not just client 0's.
"""

from __future__ import annotations

import logging
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

logger = logging.getLogger(__name__)


class ClientFeatureNet(nn.Module):
    """Small client net (reference: resnet-8 client; here a compact CNN/MLP
    extractor + local classifier head)."""

    feat_dim: int = 64

    @nn.compact
    def __call__(self, x):
        h = x.reshape((x.shape[0], -1))
        h = nn.relu(nn.Dense(128)(h))
        return nn.relu(nn.Dense(self.feat_dim)(h))


class ServerNet(nn.Module):
    """Large server net over client features (reference: resnet-49 tail)."""

    num_classes: int

    @nn.compact
    def __call__(self, feats):
        h = nn.relu(nn.Dense(256)(feats))
        h = nn.relu(nn.Dense(256)(h))
        return nn.Dense(self.num_classes)(h)


def kl_soft(p_logits, q_logits, T: float = 1.0):
    """KL(softmax(p/T) || softmax(q/T)) per sample."""
    p = jax.nn.log_softmax(p_logits / T)
    q = jax.nn.log_softmax(q_logits / T)
    return (jnp.exp(p) * (p - q)).sum(-1)


class FedGKTAPI:
    def __init__(self, args, device, dataset, model=None):
        self.args = args
        self.ds = dataset
        self.n = dataset.client_num
        C = dataset.class_num
        feat_dim = int(getattr(args, "gkt_feat_dim", 64))
        self.temp = float(getattr(args, "gkt_temperature", 3.0))
        self.alpha = float(getattr(args, "gkt_alpha", 1.0))  # KD weight
        self.epochs = max(int(getattr(args, "epochs", 1)), 1)
        self.extractor = ClientFeatureNet(feat_dim)
        self.client_head = nn.Dense(C)
        self.server_net = ServerNet(C)
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        ke, kh, ks = jax.random.split(rng, 3)
        dummy = jnp.zeros((1,) + dataset.train_x.shape[2:])
        e0 = self.extractor.init(ke, dummy)
        h0 = self.client_head.init(kh, jnp.zeros((1, feat_dim)))
        self.client_ex = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (self.n,) + x.shape), e0
        )
        self.client_hd = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (self.n,) + x.shape), h0
        )
        self.server_params = self.server_net.init(ks, jnp.zeros((1, feat_dim)))
        lr = float(getattr(args, "learning_rate", 0.05))
        self.c_opt = optax.sgd(lr)
        self.s_opt = optax.adam(1e-3)
        self.s_opt_state = self.s_opt.init(self.server_params)
        self.c_opt_states = jax.vmap(
            lambda e, h: self.c_opt.init((e, h))
        )(self.client_ex, self.client_hd)

        def client_loss(params, x, y, mask, server_logits):
            ex, hd = params
            feats = self.extractor.apply(ex, x)
            logits = self.client_head.apply(hd, feats)
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            kd = kl_soft(server_logits, logits, self.temp)
            per = ce + self.alpha * kd
            return (per * mask).sum() / jnp.maximum(mask.sum(), 1.0)

        def client_update(ex, hd, c_state, x, y, mask, server_logits):
            """``epochs`` full-batch steps under lax.scan, then the features
            and soft labels that cross to the server."""

            def epoch(carry, _):
                ex, hd, c_state = carry
                loss, grads = jax.value_and_grad(client_loss)(
                    (ex, hd), x, y, mask, server_logits
                )
                updates, c_state = self.c_opt.update(
                    grads, c_state, (ex, hd)
                )
                ex, hd = optax.apply_updates((ex, hd), updates)
                return (ex, hd, c_state), loss

            (ex, hd, c_state), losses = jax.lax.scan(
                epoch, (ex, hd, c_state), None, length=self.epochs
            )
            feats = self.extractor.apply(ex, x)
            logits = self.client_head.apply(hd, feats)
            return ex, hd, c_state, feats, logits, losses.mean()

        def server_loss(sp, feats, y, mask, client_logits):
            logits = self.server_net.apply(sp, feats)
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            kd = kl_soft(client_logits, logits, self.temp)
            per = ce + self.alpha * kd
            return (per * mask).sum() / jnp.maximum(mask.sum(), 1.0)

        def server_update(sp, s_state, feats, y, mask, client_logits):
            loss, grads = jax.value_and_grad(server_loss)(
                sp, feats, y, mask, client_logits
            )
            updates, s_state = self.s_opt.update(grads, s_state, sp)
            sp = optax.apply_updates(sp, updates)
            logits = self.server_net.apply(sp, feats)
            return sp, s_state, logits, loss

        @jax.jit
        def round_fn(client_ex, client_hd, c_opt_states, server_params,
                     s_opt_state, server_logits, x, y, masks):
            # client phase: the whole cohort in one vmapped program
            ex, hd, cs, feats, logits, closses = jax.vmap(client_update)(
                client_ex, client_hd, c_opt_states, x, y, masks, server_logits
            )

            # server phase: sequential by construction → one scan, not n
            # Python dispatches
            def body(carry, inp):
                sp, ss = carry
                f, yy, m, cl = inp
                sp, ss, slog, sl = server_update(sp, ss, f, yy, m, cl)
                return (sp, ss), (slog, sl)

            (server_params, s_opt_state), (slogits, slosses) = jax.lax.scan(
                body, (server_params, s_opt_state), (feats, y, masks, logits)
            )
            return (ex, hd, cs, server_params, s_opt_state, slogits,
                    closses.mean(), slosses.mean())

        self._round_fn = round_fn

        @jax.jit
        def eval_fn(client_ex, server_params, test_x, test_y):
            """Server net through EVERY client's extractor → mean accuracy
            (reference: server-side eval across edge feature extractors)."""

            def one(ex):
                feats = self.extractor.apply(ex, test_x)
                logits = self.server_net.apply(server_params, feats)
                return (jnp.argmax(logits, -1) == test_y).mean()

            return jax.vmap(one)(client_ex).mean()

        self._eval_fn = eval_fn
        self.history = []

    def train(self) -> Dict[str, float]:
        rounds = int(self.args.comm_round)
        last: Dict[str, float] = {}
        C = self.ds.class_num
        # per-client cached server logits (start at zeros = uniform teacher)
        server_logits = jnp.zeros((self.n, self.ds.cap, C))
        x = jnp.asarray(self.ds.train_x)
        y = jnp.asarray(self.ds.train_y).astype(jnp.int32)
        masks = (
            jnp.arange(self.ds.cap)[None, :]
            < jnp.asarray(self.ds.train_counts)[:, None]
        ).astype(jnp.float32)
        test_x = jnp.asarray(self.ds.test_x)
        test_y = jnp.asarray(self.ds.test_y)
        for r in range(rounds):
            (self.client_ex, self.client_hd, self.c_opt_states,
             self.server_params, self.s_opt_state, server_logits,
             closs, sloss) = self._round_fn(
                self.client_ex, self.client_hd, self.c_opt_states,
                self.server_params, self.s_opt_state, server_logits,
                x, y, masks,
            )
            acc = float(self._eval_fn(
                self.client_ex, self.server_params, test_x, test_y
            ))
            last = {"test_acc": acc,
                    "train_loss": float(closs),
                    "server_loss": float(sloss)}
            self.history.append({"round": r, **last})
            logger.info("fedgkt round %d: closs=%.4f sloss=%.4f acc=%.4f",
                        r, last["train_loss"], last["server_loss"], acc)
        return last
