"""FedGAN: federated generative adversarial training.

reference: ``simulation/mpi/fedgan/`` (FedGanAPI.py, FedGANTrainer.py —
vanilla BCE GAN trained locally per client, FedGANAggregator averages BOTH
the generator and the discriminator each round).

TPU-first: the whole cohort's local adversarial training runs as ONE
vmapped program — per client, ``epochs`` alternating D/G full-batch steps
under ``lax.scan``; the round then weighted-averages both nets (the same
stacked-tree kernel FedAvg uses). No per-client Python dispatches.
"""

from __future__ import annotations

import logging
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core.aggregate import weighted_average
from ..models.gan import Discriminator, Generator

logger = logging.getLogger(__name__)


class FedGanAPI:
    def __init__(self, args, device, dataset, model=None):
        self.args = args
        self.ds = dataset
        self.n = dataset.client_num
        self.z_dim = int(getattr(args, "gan_z_dim", 32))
        self.epochs = max(int(getattr(args, "epochs", 1)), 1)
        sample_shape = tuple(dataset.train_x.shape[2:])
        self.gen = Generator(sample_shape)
        self.disc = Discriminator()
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        kg, kd = jax.random.split(rng)
        self.g_params = self.gen.init(kg, jnp.zeros((1, self.z_dim)))
        self.d_params = self.disc.init(
            kd, jnp.zeros((1,) + sample_shape)
        )
        lr = float(getattr(args, "learning_rate", 2e-4))
        self.g_opt = optax.adam(lr, b1=0.5)
        self.d_opt = optax.adam(lr, b1=0.5)
        self.root_rng = rng

        def d_loss(dp, gp, x, mask, z):
            fake = self.gen.apply(gp, z)
            real_logit = self.disc.apply(dp, x)
            fake_logit = self.disc.apply(dp, fake)
            per = optax.sigmoid_binary_cross_entropy(
                real_logit, jnp.ones_like(real_logit)
            ) + optax.sigmoid_binary_cross_entropy(
                fake_logit, jnp.zeros_like(fake_logit)
            )
            return (per * mask).sum() / jnp.maximum(mask.sum(), 1.0)

        def g_loss(gp, dp, mask, z):
            fake = self.gen.apply(gp, z)
            fake_logit = self.disc.apply(dp, fake)
            per = optax.sigmoid_binary_cross_entropy(
                fake_logit, jnp.ones_like(fake_logit)
            )
            return (per * mask).sum() / jnp.maximum(mask.sum(), 1.0)

        def client_update(gp, dp, go, do, x, mask, rng):
            """epochs alternating D/G steps on this client's shard."""

            def step(carry, erng):
                gp, dp, go, do = carry
                z = jax.random.normal(
                    erng, (x.shape[0], self.z_dim)
                )
                dl, dg = jax.value_and_grad(d_loss)(dp, gp, x, mask, z)
                du, do2 = self.d_opt.update(dg, do, dp)
                dp2 = optax.apply_updates(dp, du)
                gl, gg = jax.value_and_grad(g_loss)(gp, dp2, mask, z)
                gu, go2 = self.g_opt.update(gg, go, gp)
                gp2 = optax.apply_updates(gp, gu)
                return (gp2, dp2, go2, do2), (dl, gl)

            erngs = jax.random.split(rng, self.epochs)
            (gp, dp, go, do), (dls, gls) = jax.lax.scan(
                step, (gp, dp, go, do), erngs
            )
            return gp, dp, go, do, dls.mean(), gls.mean()

        @jax.jit
        def round_fn(g_params, d_params, g_opts, d_opts, x, masks, rngs,
                     weights):
            gs, ds_, gos, dos, dl, gl = jax.vmap(client_update)(
                g_params, d_params, g_opts, d_opts, x, masks, rngs
            )
            g_avg = weighted_average(gs, weights)
            d_avg = weighted_average(ds_, weights)
            return g_avg, d_avg, gos, dos, dl.mean(), gl.mean()

        self._round_fn = round_fn
        self.history = []

    def train(self) -> Dict[str, float]:
        x = jnp.asarray(self.ds.train_x)
        masks = (
            jnp.arange(self.ds.cap)[None, :]
            < jnp.asarray(self.ds.train_counts)[:, None]
        ).astype(jnp.float32)
        weights = jnp.asarray(self.ds.train_counts, jnp.float32)
        # stacked per-client copies of both nets + their optimizer states
        g_params = jax.tree.map(
            lambda t: jnp.broadcast_to(t[None], (self.n,) + t.shape),
            self.g_params,
        )
        d_params = jax.tree.map(
            lambda t: jnp.broadcast_to(t[None], (self.n,) + t.shape),
            self.d_params,
        )
        g_opts = jax.vmap(self.g_opt.init)(g_params)
        d_opts = jax.vmap(self.d_opt.init)(d_params)
        last: Dict[str, float] = {}
        for r in range(int(self.args.comm_round)):
            rngs = jax.random.split(
                jax.random.fold_in(self.root_rng, r), self.n
            )
            g_avg, d_avg, g_opts, d_opts, dl, gl = self._round_fn(
                g_params, d_params, g_opts, d_opts, x, masks, rngs, weights
            )
            # re-broadcast the averaged nets (reference: sync_model round FSM)
            g_params = jax.tree.map(
                lambda t: jnp.broadcast_to(t[None], (self.n,) + t.shape), g_avg
            )
            d_params = jax.tree.map(
                lambda t: jnp.broadcast_to(t[None], (self.n,) + t.shape), d_avg
            )
            self.g_params, self.d_params = g_avg, d_avg
            last = {"d_loss": float(dl), "g_loss": float(gl)}
            self.history.append({"round": r, **last})
            logger.info("fedgan round %d: d=%.4f g=%.4f", r, last["d_loss"],
                        last["g_loss"])
        # generator quality proxy: the averaged D's score on fresh samples
        # should sit near chance (0.5) if G fools it
        z = jax.random.normal(jax.random.fold_in(self.root_rng, 777),
                              (256, self.z_dim))
        fake = self.gen.apply(self.g_params, z)
        p_fake = float(jax.nn.sigmoid(
            self.disc.apply(self.d_params, fake)
        ).mean())
        last["d_score_on_fake"] = p_fake
        return last

    def sample(self, n: int, seed: int = 0) -> np.ndarray:
        z = jax.random.normal(jax.random.PRNGKey(seed), (n, self.z_dim))
        return np.asarray(self.gen.apply(self.g_params, z))
