"""Hierarchical FL: two-level aggregation (groups → global).

reference: ``simulation/sp/hierarchical_fl/`` (trainer.py/group.py/client.py)
— groups run ``group_comm_round`` local aggregation rounds, then the global
server averages group models. TPU re-design: clients live in a packed
``[groups, group_size, cap, ...]`` layout so one inner round is a NESTED vmap
(outer over groups, inner over each group's cohort) ending in a per-group
weighted average — the whole group epoch is one fused device program.
"""

from __future__ import annotations

import logging
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..core.aggregate import weighted_average
from ..ml.local_train import make_local_train_fn
from .sp_api import FedAvgAPI

logger = logging.getLogger(__name__)


class HierarchicalFLAPI(FedAvgAPI):
    def __init__(self, args, device, dataset, model, client_trainer=None,
                 server_aggregator=None):
        super().__init__(args, device, dataset, model, client_trainer,
                         server_aggregator)
        self.group_num = int(getattr(args, "group_num", 2))
        self.group_comm_round = int(getattr(args, "group_comm_round", 2))
        # static client → group assignment (reference: random partition)
        rs = np.random.RandomState(int(getattr(args, "random_seed", 0)))
        perm = rs.permutation(self.ds.client_num)
        self.groups = np.array_split(perm, self.group_num)

        local_train = make_local_train_fn(model, args, self.ds.cap)
        # inner vmap: clients of one group; outer vmap: groups
        per_group = jax.vmap(local_train, in_axes=(None, 0, 0, 0, 0))

        def group_round(group_params, gx, gy, gn, grngs):
            """One intra-group round. group_params has leading [G] axis."""
            stacked, metrics = jax.vmap(per_group, in_axes=(0, 0, 0, 0, 0))(
                group_params, gx, gy, gn, grngs
            )
            # weighted average within each group → [G, ...]
            agg = jax.vmap(weighted_average)(stacked, metrics["num_samples"])
            return agg, metrics

        self._group_round = jax.jit(group_round)

    def _train_round(self, round_idx: int) -> Dict[str, float]:
        G = self.group_num
        size = min(len(g) for g in self.groups)
        # sample `size` clients per group (equal sizes → static shapes)
        rs = np.random.RandomState(round_idx)
        cohorts = np.stack(
            [rs.choice(g, size, replace=False) for g in self.groups]
        )  # [G, size]
        gx = jnp.asarray(self.ds.train_x[cohorts])
        gy = jnp.asarray(self.ds.train_y[cohorts])
        gn = jnp.asarray(self.ds.train_counts[cohorts])
        round_rng = jax.random.fold_in(self.root_rng, round_idx)

        # broadcast global params to every group
        group_params = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (G,) + x.shape), self.global_params
        )
        losses = []
        for inner in range(self.group_comm_round):
            rngs = jax.random.split(
                jax.random.fold_in(round_rng, inner), G * size
            ).reshape(G, size, -1)
            group_params, metrics = self._group_round(
                group_params, gx, gy, gn, rngs
            )
            losses.append(float(jnp.mean(metrics["train_loss"])))

        # global level: weight groups by their sample counts
        group_weights = jnp.asarray(
            [float(self.ds.train_counts[c].sum()) for c in cohorts]
        )
        self.global_params = weighted_average(group_params, group_weights)
        return {"train_loss": float(np.mean(losses))}
