"""Mesh-parallel Parrot simulation — FL clients sharded over TPU chips.

The TPU-native replacement for BOTH of the reference's multi-process backends
(``simulation/mpi/fedavg/*`` — one OS process per worker exchanging pickled
MPI messages — and ``simulation/nccl/base_framework/*`` — per-GPU local
aggregators doing torch.distributed broadcast/reduce; see SURVEY.md §3.2/§3.3):

- the cohort's packed arrays are sharded over a 1-D ``clients`` mesh axis
  (`jax.sharding.NamedSharding`); global params are replicated
- ONE jit'd round program does: vmap(local_train) over the sharded cohort →
  weighted average. XLA lowers the average across shards to a reduce over ICI
  — the explicit `dist.reduce(SUM)` + 2-rank gather groups of the reference
  (``params.py:98-127``) become compiler-inserted collectives
- cohort padding (to a multiple of the axis size, zero weight) replaces the
  reference's padded schedule tensors (``Server.py:124-128``)

There are no messages, no pickling, no per-worker processes: a round is one
device program launch.
"""

from __future__ import annotations

import logging
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import constants
from ..core.aggregate import weighted_average
from ..device import build_mesh
from ..ml.local_train import make_local_train_fn
from .sp_api import FedAvgAPI

logger = logging.getLogger(__name__)

PyTree = Any


class MeshFedAvgAPI(FedAvgAPI):
    """FedAvg-family rounds with the cohort sharded over a ``clients`` axis."""

    def __init__(self, args, device, dataset, model, client_trainer=None,
                 server_aggregator=None):
        super().__init__(args, device, dataset, model, client_trainer,
                         server_aggregator)
        axis_sizes = args.parse_mesh_shape() or None
        self.mesh = build_mesh(axis_sizes)
        if constants.MESH_AXIS_CLIENTS not in self.mesh.axis_names:
            raise ValueError(
                f"mesh {self.mesh.axis_names} lacks a "
                f"'{constants.MESH_AXIS_CLIENTS}' axis"
            )
        self.axis_size = self.mesh.shape[constants.MESH_AXIS_CLIENTS]
        self._shard = NamedSharding(self.mesh, P(constants.MESH_AXIS_CLIENTS))
        self._repl = NamedSharding(self.mesh, P())

        local_train = make_local_train_fn(model, args, self.ds.cap)

        def round_fn(global_params, cx, cy, cn, rngs, wmask):
            stacked, metrics = jax.vmap(
                local_train, in_axes=(None, 0, 0, 0, 0)
            )(global_params, cx, cy, cn, rngs)
            weights = metrics["num_samples"] * wmask
            w_agg = weighted_average(stacked, weights)
            loss = (metrics["train_loss"] * wmask).sum() / jnp.maximum(
                wmask.sum(), 1.0
            )
            return w_agg, loss

        self._round_fn = jax.jit(
            round_fn,
            in_shardings=(
                self._repl, self._shard, self._shard, self._shard,
                self._shard, self._shard,
            ),
            out_shardings=(self._repl, self._repl),
        )
        logger.info(
            "mesh simulator: %d-way client sharding over %s",
            self.axis_size, self.mesh,
        )

    def _train_round(self, round_idx: int):
        cohort = self._client_sampling(round_idx)
        pad = (-len(cohort)) % self.axis_size
        wmask = np.ones(len(cohort) + pad, np.float32)
        if pad:
            wmask[len(cohort):] = 0.0
            cohort = np.concatenate([cohort, np.zeros(pad, cohort.dtype)])

        cx = jax.device_put(self.ds.train_x[cohort], self._shard)
        cy = jax.device_put(self.ds.train_y[cohort], self._shard)
        cn = jax.device_put(self.ds.train_counts[cohort], self._shard)
        round_rng = jax.random.fold_in(self.root_rng, round_idx)
        rngs = jax.device_put(
            jax.device_get(jax.random.split(round_rng, len(cohort))), self._shard
        )
        wmask_d = jax.device_put(wmask, self._shard)

        w_agg, loss = self._round_fn(
            self.global_params, cx, cy, cn, rngs, wmask_d
        )
        if self.opt_name == constants.FEDML_FEDERATED_OPTIMIZER_FEDOPT:
            import optax

            from ..core.aggregate import pseudo_gradient

            pg = pseudo_gradient(self.global_params, w_agg)
            updates, self.server_opt_state = self.server_opt.update(
                pg, self.server_opt_state, self.global_params
            )
            self.global_params = optax.apply_updates(self.global_params, updates)
        else:
            self.global_params = w_agg
        return {"train_loss": float(loss)}
