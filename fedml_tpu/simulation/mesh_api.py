"""Mesh-parallel Parrot simulation — FL clients sharded over TPU chips.

The TPU-native replacement for BOTH of the reference's multi-process backends
(``simulation/mpi/fedavg/*`` — one OS process per worker exchanging pickled
MPI messages — and ``simulation/nccl/base_framework/*`` — per-GPU local
aggregators doing torch.distributed broadcast/reduce; see SURVEY.md §3.2/§3.3):

- the cohort's packed arrays are sharded over the mesh by RULE-DRIVEN
  ``NamedSharding`` specs: an ordered list of ``(regex, PartitionSpec)``
  rules over named pytree leaves (``scale/partition_rules.py``, the
  ``match_partition_rules`` pattern from the large-model JAX ecosystem —
  SNIPPETS.md [2]/[3]). The defaults reproduce the original hard-coded
  behavior exactly — cohort arrays split on the leading ``clients`` axis,
  round state replicated — and ``--mesh_partition_rules`` /
  ``--mesh_state_rules`` override per-leaf placement without code changes
  (pinned bitwise-equal in ``tests/test_scale.py``)
- the round runs the SAME engine as the sp backend (`FedAvgAPI._train_round`):
  vmap(local_train) over the sharded cohort → attack → defend → weighted
  average → DP. XLA propagates the input shardings through the jit'd cohort
  program and lowers the cross-shard reduction to collectives over ICI — the
  explicit `dist.reduce(SUM)` + 2-rank gather groups of the reference
  (``params.py:98-127``) become compiler-inserted collectives
- cohort padding (to a multiple of the axis size, zero weight) replaces the
  reference's padded schedule tensors (``Server.py:124-128``)

There are no messages, no pickling, no per-worker processes: a round is one
device program launch. Because the whole FedAvg-family engine is inherited,
every federated optimizer (FedProx/FedOpt/FedNova/FedSGD/SCAFFOLD), the
full trust pipeline (attack → defend → aggregate → DP, ``sp_api.py``) and
the million-client registry/prefetch substrate (``scale/``) work
identically on the multi-chip path.
"""

from __future__ import annotations

import logging
import threading

import jax
import numpy as np

from .. import constants
from ..core.mlops import telemetry
from ..device import build_mesh
from ..scale.partition_rules import (
    DEFAULT_COHORT_RULES,
    DEFAULT_STATE_RULES,
    is_scalar_leaf,
    make_shardings,
    match_partition_rules,
    parse_partition_rules,
)
from .sp_api import FedAvgAPI

logger = logging.getLogger(__name__)


class MeshFedAvgAPI(FedAvgAPI):
    """FedAvg-family rounds with the cohort sharded over a ``clients`` axis."""

    # cohorts are host-gathered and placed sharded over the mesh — the
    # single-device HBM-resident fast path must not allocate in __init__
    hbm_resident_default = False
    # the cohort axis is SHARDED over devices: lax.map would serialize the
    # whole mesh onto one program — vmap is structural here
    cohort_impl_default = "vmap"

    def __init__(self, args, device, dataset, model, client_trainer=None,
                 server_aggregator=None):
        super().__init__(args, device, dataset, model, client_trainer,
                         server_aggregator)
        axis_sizes = args.parse_mesh_shape() or None
        self.mesh = build_mesh(axis_sizes)
        if constants.MESH_AXIS_CLIENTS not in self.mesh.axis_names:
            raise ValueError(
                f"mesh {self.mesh.axis_names} lacks a "
                f"'{constants.MESH_AXIS_CLIENTS}' axis"
            )
        self.axis_size = self.mesh.shape[constants.MESH_AXIS_CLIENTS]
        # rule-driven placement (scale/partition_rules.py): cohort-plane
        # leaves are named "cohort/{x,y,counts,aux}" (aux = the per-round
        # rngs and padding weight mask), round-state leaves keep their
        # pytree paths ("global_params/...", "server_opt_state/...") —
        # the defaults reproduce the legacy first-axis sharding byte for
        # byte
        self.cohort_rules = (
            parse_partition_rules(getattr(args, "mesh_partition_rules", ""))
            or list(DEFAULT_COHORT_RULES)
        )
        self.state_rules = (
            parse_partition_rules(getattr(args, "mesh_state_rules", ""))
            or list(DEFAULT_STATE_RULES)
        )
        # rule resolution is derivable from (rule set, tree structure,
        # scalar pattern) — cache the resulting NamedSharding pytrees so
        # the per-round hot path never re-runs regex matching (the
        # prefetch worker thread also resolves through here, hence the
        # lock around the memo)
        self._sharding_cache = {}
        self._sharding_lock = threading.Lock()
        logger.info(
            "mesh simulator: %d-way client sharding over %s "
            "(%d cohort rules, %d state rules)",
            self.axis_size, self.mesh,
            len(self.cohort_rules), len(self.state_rules),
        )

    def _ledger_world(self):
        """Pin the mesh topology into the run ledger's run_meta: a resumed
        run on a different chip count would silently change cohort padding
        (and so the padded-row math) — ``RunLedger.ensure_meta`` turns that
        into a loud mismatch error instead."""
        world = super()._ledger_world()
        world["mesh_axes"] = {
            str(name): int(self.mesh.shape[name])
            for name in self.mesh.axis_names
        }
        world["device_count"] = int(len(self.mesh.devices.flat))
        return world

    # -- rule resolution ----------------------------------------------------
    def _resolve_shardings(self, which: str, rules, tree):
        """Rules + named pytree → ``NamedSharding`` pytree, memoized on
        (rule set, tree structure, scalar pattern) — all static per run."""
        from jax.tree_util import tree_leaves, tree_structure

        key = (
            which,
            tree_structure(tree),
            # the SAME scalar predicate match_partition_rules applies —
            # the memo is only sound if the key classifies leaves
            # identically to the resolver
            tuple(is_scalar_leaf(leaf) for leaf in tree_leaves(tree)),
        )
        with self._sharding_lock:
            hit = self._sharding_cache.get(key)
        if hit is None:
            hit = make_shardings(
                self.mesh, match_partition_rules(rules, tree)
            )
            with self._sharding_lock:
                self._sharding_cache[key] = hit
        return hit

    def _cohort_shardings(self, named):
        """Resolve the cohort rules over named host arrays → shardings."""
        return self._resolve_shardings("cohort", self.cohort_rules, named)

    # -- FedAvgAPI placement hooks ------------------------------------------
    def _pad_cohort(self, cohort: np.ndarray):
        pad = (-len(cohort)) % self.axis_size
        wmask = np.ones(len(cohort) + pad, np.float32)
        if pad:
            wmask[len(cohort):] = 0.0
            cohort = np.concatenate([cohort, np.zeros(pad, cohort.dtype)])
        return cohort, wmask

    def _place_cohort(self, arrays):
        # one rule resolution + sharded device_put per gather; this is the
        # mesh path's own "gather" phase AND the streamed-cohort placement
        # hook (the prefetcher's worker thread calls it for round r+1)
        cx, cy, cn = arrays
        named = {
            "cohort/x": np.asarray(cx),
            "cohort/y": np.asarray(cy),
            "cohort/counts": np.asarray(cn, np.int32),
        }
        sh = self._cohort_shardings(named)
        return (
            jax.device_put(named["cohort/x"], sh["cohort/x"]),
            jax.device_put(named["cohort/y"], sh["cohort/y"]),
            jax.device_put(named["cohort/counts"], sh["cohort/counts"]),
        )

    def _gather_resident(self, cohort: np.ndarray):
        # host-side gather + sharded device_put: the sp base times this
        # callsite — this shard placement is what its span measures here
        return self._place_cohort((
            self.ds.train_x[cohort],
            self.ds.train_y[cohort],
            self.ds.train_counts[cohort],
        ))

    def _place(self, arr):
        # per-client auxiliaries (per-round rngs, the padding weight mask)
        # ride the cohort rules under "cohort/aux" — leading axis = clients.
        # device_put reshards device-to-device: staging through the host
        # (device_get) here was a per-round gather of the whole aux array
        # over ICI (graftshard S004)
        named = {"cohort/aux": arr}
        sh = self._cohort_shardings(named)
        return jax.device_put(named["cohort/aux"], sh["cohort/aux"])

    def _prepare_round(self):
        # keep global params placed per the state rules (default replicated)
        # so the cohort program reads them without broadcast in the hot loop
        with telemetry.phase("place_params", record=False):
            self.global_params = self._place_state(
                {"global_params": self.global_params}
            )["global_params"]

    def _place_state(self, state):
        # the fused program's donated state must live on the SAME device set
        # as the sharded cohort inputs: commit every leaf per the state
        # rules (default: replicated over the mesh — a no-op copy once
        # steady state re-feeds program outputs). XLA then propagates the
        # input shardings through the fused round and lowers the
        # cross-shard reduction to collectives over ICI.
        with telemetry.phase("place_state", record=False):
            sh = self._resolve_shardings("state", self.state_rules, state)
            return jax.tree.map(jax.device_put, state, sh)
