"""Mesh-parallel Parrot simulation — FL clients sharded over TPU chips.

The TPU-native replacement for BOTH of the reference's multi-process backends
(``simulation/mpi/fedavg/*`` — one OS process per worker exchanging pickled
MPI messages — and ``simulation/nccl/base_framework/*`` — per-GPU local
aggregators doing torch.distributed broadcast/reduce; see SURVEY.md §3.2/§3.3):

- the cohort's packed arrays are sharded over a 1-D ``clients`` mesh axis
  (`jax.sharding.NamedSharding`); global params are replicated
- the round runs the SAME engine as the sp backend (`FedAvgAPI._train_round`):
  vmap(local_train) over the sharded cohort → attack → defend → weighted
  average → DP. XLA propagates the input shardings through the jit'd cohort
  program and lowers the cross-shard reduction to collectives over ICI — the
  explicit `dist.reduce(SUM)` + 2-rank gather groups of the reference
  (``params.py:98-127``) become compiler-inserted collectives
- cohort padding (to a multiple of the axis size, zero weight) replaces the
  reference's padded schedule tensors (``Server.py:124-128``)

There are no messages, no pickling, no per-worker processes: a round is one
device program launch. Because the whole FedAvg-family engine is inherited,
every federated optimizer (FedProx/FedOpt/FedNova/FedSGD/SCAFFOLD) and the
full trust pipeline (attack → defend → aggregate → DP, ``sp_api.py``) work
identically on the multi-chip path.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import constants
from ..core.mlops import telemetry
from ..device import build_mesh
from .sp_api import FedAvgAPI

logger = logging.getLogger(__name__)


class MeshFedAvgAPI(FedAvgAPI):
    """FedAvg-family rounds with the cohort sharded over a ``clients`` axis."""

    # cohorts are host-gathered and placed sharded over the mesh — the
    # single-device HBM-resident fast path must not allocate in __init__
    hbm_resident_default = False
    # the cohort axis is SHARDED over devices: lax.map would serialize the
    # whole mesh onto one program — vmap is structural here
    cohort_impl_default = "vmap"

    def __init__(self, args, device, dataset, model, client_trainer=None,
                 server_aggregator=None):
        super().__init__(args, device, dataset, model, client_trainer,
                         server_aggregator)
        axis_sizes = args.parse_mesh_shape() or None
        self.mesh = build_mesh(axis_sizes)
        if constants.MESH_AXIS_CLIENTS not in self.mesh.axis_names:
            raise ValueError(
                f"mesh {self.mesh.axis_names} lacks a "
                f"'{constants.MESH_AXIS_CLIENTS}' axis"
            )
        self.axis_size = self.mesh.shape[constants.MESH_AXIS_CLIENTS]
        self._shard = NamedSharding(self.mesh, P(constants.MESH_AXIS_CLIENTS))
        self._repl = NamedSharding(self.mesh, P())
        logger.info(
            "mesh simulator: %d-way client sharding over %s",
            self.axis_size, self.mesh,
        )

    def _ledger_world(self):
        """Pin the mesh topology into the run ledger's run_meta: a resumed
        run on a different chip count would silently change cohort padding
        (and so the padded-row math) — ``RunLedger.ensure_meta`` turns that
        into a loud mismatch error instead."""
        world = super()._ledger_world()
        world["mesh_axes"] = {
            str(name): int(self.mesh.shape[name])
            for name in self.mesh.axis_names
        }
        world["device_count"] = int(len(self.mesh.devices.flat))
        return world

    # -- FedAvgAPI placement hooks ------------------------------------------
    def _pad_cohort(self, cohort: np.ndarray):
        pad = (-len(cohort)) % self.axis_size
        wmask = np.ones(len(cohort) + pad, np.float32)
        if pad:
            wmask[len(cohort):] = 0.0
            cohort = np.concatenate([cohort, np.zeros(pad, cohort.dtype)])
        return cohort, wmask

    def _gather_cohort(self, cohort: np.ndarray):
        # host-side gather + sharded device_put: the mesh path's own
        # "gather" phase (the sp base times this callsite — this shard
        # placement is what its span measures here)
        cx = jax.device_put(self.ds.train_x[cohort], self._shard)
        cy = jax.device_put(self.ds.train_y[cohort], self._shard)
        cn = jax.device_put(
            self.ds.train_counts[cohort].astype(np.int32), self._shard
        )
        return cx, cy, cn

    def _place(self, arr):
        return jax.device_put(jax.device_get(arr), self._shard)

    def _prepare_round(self):
        # keep global params replicated across the mesh so the cohort program
        # reads them without broadcast inside the hot loop
        with telemetry.phase("place_params", record=False):
            self.global_params = jax.device_put(self.global_params, self._repl)

    def _place_state(self, state):
        # the fused program's donated state must live on the SAME device set
        # as the sharded cohort inputs: commit every leaf replicated over the
        # mesh (a no-op copy once steady state re-feeds program outputs).
        # XLA then propagates the input shardings through the fused round and
        # lowers the cross-shard reduction to collectives over ICI.
        with telemetry.phase("place_state", record=False):
            return jax.tree.map(
                lambda x: jax.device_put(x, self._repl), state
            )
