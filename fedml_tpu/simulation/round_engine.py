"""Fused, donated round engine: one XLA program per FedAvg-family round.

The unfused path (``FedAvgAPI._train_round``) drives every round from Python:
separate dispatches for the cohort step, aggregation, the server optimizer and
DP, with fresh HBM allocations for the model / optimizer / control-variate
state each round. This module collapses all of it into a single ``jax.jit``
with ``donate_argnums`` on the round state, so

- a steady-state round is ONE device-program launch (the recompilation guard
  in ``tests/test_round_fusion.py`` pins exactly one compile per config);
- the model, server-optimizer and SCAFFOLD control-variate buffers are
  donated — XLA updates them in place instead of holding the 2x HBM copy of
  the stacked ``[cohort, ...]`` leaves plus old-and-new state;
- central/local DP noising and the jit-safe attack/defense kernels run inside
  the same program (FL-WBC keeps host-side per-client history and a custom
  ``ServerAggregator`` is arbitrary Python — both fall back to the unfused
  path, see ``FedAvgAPI._fusion_blockers``).

Superround mode (``make_superround_step``) additionally moves client sampling
on-device (fold-in PRNG choice over client ids) and runs K rounds under
``jax.lax.scan`` — steady-state throughput is then bounded by device compute,
not Python dispatch. It requires the HBM-resident dataset (the cohort gather
happens inside the program) and uses device-side sampling, so its cohort
trajectory differs from the host-side ``np.random.RandomState(round_idx)``
reference semantics EXCEPT under full participation, where both degenerate to
``arange`` and the trajectories coincide exactly (the parity tests rely on
this).

Round state is a flat dict — ``{"global_params", "server_opt_state"?,
"c_global"?, "c_locals"?}`` — matching ``FedAvgAPI._round_state``. Callers
must treat the state they passed in as CONSUMED (donation invalidates the
buffers) and adopt the returned state; ``checkpoint.CheckpointManager.save``
copies leaves to host before the next round can be dispatched.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax

from .. import constants
from ..core.aggregate import (
    fednova_normalized_direction,
    pseudo_gradient,
    weighted_average,
)
from ..utils.tree import tree_flatten_to_vector, tree_unflatten_from_vector

PyTree = Any
RoundState = Dict[str, PyTree]


def _masked_mean(values, wmask):
    """Device-side twin of ``sp_api._masked_mean`` (same math, no host pull)."""
    if values is None:
        return jnp.float32(jnp.nan)
    if wmask is None:
        return jnp.mean(values)
    return (values * wmask).sum() / jnp.maximum(wmask.sum(), 1.0)


def build_round_core(api, n_cohort: int, n_valid: int):
    """Build the pure round function for ``api``'s config.

    ``n_cohort`` is the (padded) cohort length, ``n_valid`` the number of real
    clients — both static per config, so the zero-weight-padding slices
    compile to static slicing exactly like the unfused path.

    Returns ``core(state, cohort_idx, cx, cy, cn, rngs, wmask, round_rng) ->
    (state, metrics)``. The attack/defense hook order and every PRNG fold-in
    mirror ``FedAvgAPI._train_round`` / ``_aggregate`` bit for bit — the
    parity tests compare the two paths to atol 1e-5 over multiple rounds.

    jit-safety note: the attacker's host-side ``np.random`` mask draws are
    seeded by config only (``random_seed``; ``attack_model``'s round offset
    defaults to 0 on both paths), so under trace they bake into compile-time
    constants IDENTICAL to what the unfused path recomputes every round.
    """
    attacker, defender, dp = api.attacker, api.defender, api.dp
    fedsgd, fednova, scaffold = api.fedsgd, api.fednova, api.scaffold
    fedopt = api.opt_name == constants.FEDML_FEDERATED_OPTIMIZER_FEDOPT
    server_opt = api.server_opt
    cohort_fn = api.cohort_fn
    client_num = api.ds.client_num

    def aggregate(gp, stacked, weights, rng):
        # mirror of FedAvgAPI._aggregate minus the unfusable paths (custom
        # aggregator, FL-WBC) which are excluded by _fusion_blockers
        if dp is not None and dp.dp_type == "ldp":
            keys = jax.random.split(jax.random.fold_in(rng, 3), n_cohort)
            stacked = jax.vmap(dp.randomize)(stacked, keys)
        elif dp is not None and dp.dp_type == "cdp":
            stacked = dp.clip_client_updates(stacked, gp)

        needs_flat = attacker.is_model_attack() or defender.is_defense_enabled()
        if not needs_flat:
            return weighted_average(stacked, weights)

        if n_valid < n_cohort:  # drop zero-weight padding for rank defenses
            stacked = jax.tree.map(lambda x: x[:n_valid], stacked)
            weights = weights[:n_valid]
        _, treedef, shapes = tree_flatten_to_vector(gp)
        flat = jax.vmap(lambda t: tree_flatten_to_vector(t)[0])(stacked)
        gvec, _, _ = tree_flatten_to_vector(gp)
        if attacker.is_model_attack():
            flat = attacker.attack_model(
                flat, weights, jax.random.fold_in(rng, 1)
            )
        if defender.is_defense_enabled():
            agg_vec = defender.defend(
                flat, weights, gvec, jax.random.fold_in(rng, 2),
                client_ids=None,
            )
        else:
            w = weights / jnp.maximum(weights.sum(), 1e-12)
            agg_vec = (w[:, None] * flat).sum(0)
        return tree_unflatten_from_vector(agg_vec, treedef, shapes)

    def core(state: RoundState, cohort_idx, cx, cy, cn, rngs, wmask,
             round_rng) -> Tuple[RoundState, Dict[str, jax.Array]]:
        gp = state["global_params"]
        if attacker.is_data_attack():
            cx, cy = attacker.attack_data(cx, cy, n_valid)

        if fedsgd:
            grads, metrics = cohort_fn(gp, cx, cy, cn, rngs)
            weights = (metrics["num_samples"] if wmask is None
                       else metrics["num_samples"] * wmask)
            agg_grad = aggregate(gp, grads, weights, round_rng)
            updates, opt_state = server_opt.update(
                agg_grad, state["server_opt_state"], gp
            )
            gp = optax.apply_updates(gp, updates)
            new_state = dict(state, global_params=gp,
                             server_opt_state=opt_state)
            # (the unfused path applies no central-DP noise on FedSGD either)
            return new_state, {
                "train_loss": _masked_mean(metrics["train_loss"], wmask),
                # on-device round counter: telemetry RoundRecords realize it
                # host-side AFTER the round (no sync on the dispatch path)
                "examples": weights.sum(),
            }

        if scaffold:
            c_cohort = jax.tree.map(lambda x: x[cohort_idx], state["c_locals"])
            stacked, metrics, new_c = cohort_fn(
                gp, cx, cy, cn, rngs, state["c_global"], c_cohort
            )
            real = cohort_idx[:n_valid]
            new_c_r = jax.tree.map(lambda x: x[:n_valid], new_c)
            c_cohort_r = jax.tree.map(lambda x: x[:n_valid], c_cohort)
            delta_c = jax.tree.map(
                lambda n, o: (n - o).mean(0), new_c_r, c_cohort_r
            )
            scale = n_valid / client_num
            c_global = jax.tree.map(
                lambda cg, d: cg + scale * d, state["c_global"], delta_c
            )
            c_locals = jax.tree.map(
                lambda all_c, nc: all_c.at[real].set(nc),
                state["c_locals"], new_c_r,
            )
            state = dict(state, c_global=c_global, c_locals=c_locals)
        else:
            stacked, metrics = cohort_fn(gp, cx, cy, cn, rngs)

        weights = (metrics["num_samples"] if wmask is None
                   else metrics["num_samples"] * wmask)

        if fednova:
            tau = metrics["tau"]
            p = weights / jnp.maximum(weights.sum(), 1e-12)
            tau_eff = (p * tau).sum()
            norm_dir = fednova_normalized_direction(gp, stacked, tau)
            d = weighted_average(norm_dir, weights)
            gp = jax.tree.map(lambda g, dd: g - tau_eff * dd, gp, d)
        elif fedopt:
            w_agg = aggregate(gp, stacked, weights, round_rng)
            pg = pseudo_gradient(gp, w_agg)
            updates, opt_state = server_opt.update(
                pg, state["server_opt_state"], gp
            )
            gp = optax.apply_updates(gp, updates)
            state = dict(state, server_opt_state=opt_state)
        else:
            gp = aggregate(gp, stacked, weights, round_rng)

        if dp is not None and dp.dp_type == "cdp":
            gp = dp.randomize_global(gp, jax.random.fold_in(round_rng, 7))
        new_state = dict(state, global_params=gp)
        return new_state, {
            "train_loss": _masked_mean(metrics["train_loss"], wmask),
            "examples": weights.sum(),
        }

    return core


def make_fused_round_step(api, n_cohort: int, n_valid: int):
    """One jit'd, donated program per round.

    ``donate_argnums=(0,)`` donates every leaf of the round state — the old
    global params / optimizer state / control variates are updated in place.
    The caller must adopt the returned state and never touch the donated one.
    """
    core = build_round_core(api, n_cohort, n_valid)
    return jax.jit(core, donate_argnums=(0,))


def make_superround_step(api, k: int, n_cohort: int):
    """K rounds per launch: on-device sampling + ``lax.scan`` pipelining.

    Requires the HBM-resident dataset (``api._dev_x`` et al.) — the per-round
    cohort gather is a device-side ``jnp.take`` inside the scan body, so the
    host does nothing between rounds. Client sampling is a fold-in PRNG
    ``jax.random.choice`` over client ids (without replacement), keyed by the
    same per-round key the single-round path uses for everything else.

    Returns ``superround(state, start_round) -> (state, metrics)`` where
    ``metrics`` holds stacked per-round outputs (``train_loss[k]``,
    ``examples[k]``) — the host-side unpack point for per-round telemetry —
    jit'd with the state donated.
    """
    core = build_round_core(api, n_cohort, n_valid=n_cohort)
    dev_x, dev_y, dev_counts = api._dev_x, api._dev_y, api._dev_counts
    total = int(api.ds.client_num)
    per = int(n_cohort)
    root_rng = api.root_rng
    # registry mode (fedml_tpu/scale/): cohorts come from the SAME jit'd
    # Gumbel-top-K sampler the host-driven path uses — keyed only by
    # (registry seed, round), so the scan's cohort trajectory is identical
    # to per-round launches and the engine can replay it for accounting
    eng = getattr(api, "cohort_engine", None)
    if eng is not None:
        reg_sample = eng.registry.device_sampler(per)
        reg_ptrs = eng.registry.device_shard_ptrs()

    def superround(state: RoundState, start_round):
        def body(st, r):
            rkey = jax.random.fold_in(root_rng, r)
            if eng is not None:  # registry K-of-N → backing shard rows
                cohort = jnp.take(reg_ptrs, reg_sample(r), axis=0)
            elif total == per:  # full participation: matches the host path
                cohort = jnp.arange(per, dtype=jnp.int32)
            else:
                cohort = jax.random.choice(
                    jax.random.fold_in(rkey, 13), total, (per,), replace=False
                ).astype(jnp.int32)
            cx = jnp.take(dev_x, cohort, axis=0)
            cy = jnp.take(dev_y, cohort, axis=0)
            cn = jnp.take(dev_counts, cohort, axis=0)
            rngs = jax.random.split(rkey, per)
            st, metrics = core(st, cohort, cx, cy, cn, rngs, None, rkey)
            return st, {"train_loss": metrics["train_loss"],
                        "examples": metrics["examples"]}

        rr = start_round + jnp.arange(k, dtype=jnp.int32)
        state, scan_metrics = jax.lax.scan(body, state, rr)
        return state, scan_metrics

    return jax.jit(superround, donate_argnums=(0,))
