"""Decentralized (gossip) FL: DSGD and PushSum over a topology.

reference: ``simulation/sp/decentralized/`` (client_dsgd.py, client_pushsum.py,
topology_manager.py) and ``simulation/mpi/decentralized_framework/``. The
reference loops per-node neighbor messages in Python; here every node's params
live stacked ``[n, ...]`` and one gossip round is a single mixing matmul
``W @ params`` per leaf (MXU), after vmapped local SGD:

- DSGD (symmetric W, undirected):  x ← W (x − η∇f)
- PushSum (asymmetric column-stochastic P, directed): push-weights w track
  mass; the de-biased estimate is z = x / w
"""

from __future__ import annotations

import logging
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..core.topology import AsymmetricTopologyManager, SymmetricTopologyManager
from ..ml.evaluate import make_eval_fn
from ..ml.local_train import make_local_train_fn

logger = logging.getLogger(__name__)


class DecentralizedFLAPI:
    def __init__(self, args, device, dataset, model):
        self.args = args
        self.ds = dataset
        self.bundle = model
        self.n = self.ds.client_num
        self.algorithm = str(getattr(args, "decentralized_algorithm", "dsgd")).lower()
        seed = int(getattr(args, "random_seed", 0))
        self.root_rng = jax.random.PRNGKey(seed)

        if self.algorithm == "pushsum":
            topo = AsymmetricTopologyManager(
                self.n, int(getattr(args, "out_neighbor_num", 2)), seed=seed
            )
            topo.generate_topology()
            # column-stochastic for pushsum (mass conservation)
            W = topo.mixing_matrix().T
            self.W = jnp.asarray(W / W.sum(axis=0, keepdims=True))
        else:
            topo = SymmetricTopologyManager(
                self.n, int(getattr(args, "topology_neighbor_num", 2))
            )
            topo.generate_topology()
            self.W = jnp.asarray(topo.mixing_matrix())
        self.topology = topo

        params0 = model.init(self.root_rng)
        # every node starts from the same init (reference does too)
        self.node_params = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (self.n,) + x.shape), params0
        )
        self.push_weights = jnp.ones((self.n,))

        local_train = make_local_train_fn(model, args, self.ds.cap)
        cohort = jax.vmap(local_train, in_axes=(0, 0, 0, 0, 0))

        def round_fn(node_params, W, x, y, counts, rngs, push_w):
            trained, metrics = cohort(node_params, x, y, counts, rngs)
            mixed = jax.tree.map(
                lambda p: jnp.tensordot(W, p, axes=1), trained
            )
            new_push = W @ push_w
            return mixed, new_push, metrics

        self._round = jax.jit(round_fn)
        self.evaluate = make_eval_fn(model)
        self.history = []

    def _debias(self):
        """PushSum estimate z = x / w; DSGD is already unbiased."""
        if self.algorithm != "pushsum":
            return self.node_params
        w = self.push_weights
        return jax.tree.map(
            lambda p: p / w.reshape((-1,) + (1,) * (p.ndim - 1)), self.node_params
        )

    def train(self) -> Dict[str, float]:
        rounds = int(self.args.comm_round)
        freq = max(int(getattr(self.args, "frequency_of_the_test", 5)), 1)
        x = jnp.asarray(self.ds.train_x)
        y = jnp.asarray(self.ds.train_y)
        counts = jnp.asarray(self.ds.train_counts)
        last = {}
        for r in range(rounds):
            rngs = jax.random.split(jax.random.fold_in(self.root_rng, r), self.n)
            self.node_params, self.push_weights, metrics = self._round(
                self.node_params, self.W, x, y, counts, rngs, self.push_weights
            )
            if r % freq == 0 or r == rounds - 1:
                # consensus model = average of de-biased node models
                avg = jax.tree.map(
                    lambda p: p.mean(0), self._debias()
                )
                last = self.evaluate(avg, self.ds.test_x, self.ds.test_y)
                # consensus distance: how far nodes are from agreement
                flat = jnp.concatenate([
                    jnp.reshape(l, (self.n, -1))
                    for l in jax.tree.leaves(self._debias())
                ], axis=1)
                last["consensus_dist"] = float(
                    jnp.linalg.norm(flat - flat.mean(0, keepdims=True), axis=1).mean()
                )
                logger.info(
                    "decentralized %s round %d: acc=%.4f consensus=%.4f",
                    self.algorithm, r, last["test_acc"], last["consensus_dist"],
                )
                self.history.append({"round": r, **last})
        return last
