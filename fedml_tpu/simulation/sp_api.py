"""Single-process Parrot simulation — the canonical FL loop, TPU-first.

Replaces the reference's ``simulation/sp/fedavg/fedavg_api.py:65-232`` (Python
loop: per-client deepcopy → torch train → dict-average) and its per-optimizer
clones (``sp/fedopt``, ``sp/fedprox``, ``sp/fednova``, ``sp/fedsgd``) with ONE
engine:

- the round's cohort trains as ``vmap(local_train)`` over a stacked
  ``[cohort, cap, ...]`` gather of the packed dataset — one fused XLA program
- aggregation is the stacked weighted-average kernel (core/aggregate.py)
- the federated optimizer enters as (a) a flag inside the local loss
  (FedProx), (b) a server-side optax transform on the pseudo-gradient
  (FedOpt/FedAdam/FedYogi/FedAdagrad), (c) normalized averaging (FedNova),
  (d) gradient-level averaging (FedSGD), or (e) control variates (SCAFFOLD)
- hook order preserved from the reference: attack → on_before_aggregation →
  defend → aggregate → DP → on_after_aggregation

Client sampling stays host-side and round-seeded exactly like the reference
(``fedavg_api.py:125-140``: ``np.random.seed(round_idx)`` + choice).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import constants
from ..core.aggregate import (
    fednova_normalized_direction,
    pseudo_gradient,
    weighted_average,
)
from ..core.dp import FedPrivacyMechanism
from ..core.mlops import telemetry
from ..core.security.attacker import FedMLAttacker
from ..core.security.defender import FedMLDefender
from ..ml.evaluate import make_eval_fn
from ..ml.local_train import make_grad_fn, make_local_train_fn
from ..ml.optimizer import create_server_optimizer
from ..utils.tree import (
    tree_flatten_to_vector,
    tree_scale,
    tree_sub,
    tree_unflatten_from_vector,
    tree_zeros_like,
)

logger = logging.getLogger(__name__)

PyTree = Any

SERVER_OPT_FAMILY = (
    constants.FEDML_FEDERATED_OPTIMIZER_FEDOPT,
    constants.FEDML_FEDERATED_OPTIMIZER_FEDSGD,
)


class FedAvgAPI:
    """One engine for the sp FedAvg-family optimizers.

    ``federated_optimizer`` ∈ {FedAvg, FedAvg_seq, FedProx, FedOpt, FedNova,
    FedSGD, SCAFFOLD}. (FedAvg_seq is identical to FedAvg here: "sequential
    multi-client per device" is an artifact of the reference's MPI process
    model — under vmap the whole cohort is already one device program.)
    """

    # subclasses whose placement hooks gather host-side (mesh) flip this OFF
    # so __init__ never parks a dead dataset copy in device-0 HBM
    hbm_resident_default = True

    # cohort execution: "vmap" fuses the round into one batched program (the
    # TPU design); "map" runs clients sequentially under lax.map — identical
    # math, same stacked outputs. "auto" picks map ONLY for conv models on
    # XLA:CPU, where vmapped convs lower to a grouped-conv path ~100x slower
    # than the plain conv (measured: resnet56 compiles >60 min and the
    # same-substrate cnn leg ran 0.01x; lax.map keeps each conv un-grouped).
    # The mesh engine pins vmap — its cohort axis is SHARDED over devices.
    cohort_impl_default = "auto"

    @staticmethod
    def _hbm_budget() -> int:
        try:
            stats = jax.devices()[0].memory_stats() or {}
            limit = int(stats.get("bytes_limit", 0))
            if limit > 0:
                return int(limit * 0.6)
        except Exception:
            pass
        return 4 * 1024**3

    def __init__(self, args, device, dataset, model, client_trainer=None,
                 server_aggregator=None):
        self.args = args
        self.device = device
        self.ds = dataset
        self.bundle = model
        self.opt_name = str(args.federated_optimizer)
        self.custom_trainer = client_trainer
        self.custom_aggregator = server_aggregator

        # million-client cohort substrate (fedml_tpu/scale/ — docs/scale.md):
        # when --client_registry is set, WHO participates each round comes
        # from a registry of N virtual clients (on-device seeded K-of-N
        # sampling) and the cohort's shards stream in through a
        # double-buffered prefetcher instead of a resident gather. The round
        # math below is untouched — cohorts are still dataset rows.
        from ..scale import build_cohort_engine

        self.cohort_engine = build_cohort_engine(args, dataset)
        if (self.cohort_engine is not None
                and self.opt_name
                == constants.FEDML_FEDERATED_OPTIMIZER_SCAFFOLD
                and not self.cohort_engine.registry.injective_shards()):
            # aliased shard pointers put duplicate rows in every cohort;
            # SCAFFOLD's per-client variate scatter (.at[rows].set) is
            # order-unspecified under duplicates — refuse loudly rather
            # than silently break the bitwise-determinism guarantee
            raise ValueError(
                "SCAFFOLD needs per-client control variates, but this "
                "registry aliases multiple clients onto the same data "
                "shard (non-injective shard pointers). Use an injective "
                "registry (ClientRegistry.from_dataset) or a different "
                "federated_optimizer."
            )
        if self.cohort_engine is not None:
            self.cohort_engine.set_host_gather(self._host_gather_rows)
            self.cohort_engine.set_cohort_transform(
                lambda rows: self._pad_cohort(rows)[0]
            )
            logger.info(
                "cohort engine: %d registered clients, cohort %d, "
                "prefetch depth %d",
                self.cohort_engine.registry.num_clients,
                self.cohort_engine.cohort_size,
                self.cohort_engine.prefetcher.depth,
            )

        seed = int(getattr(args, "random_seed", 0))
        self.root_rng = jax.random.PRNGKey(seed)
        self.global_params = model.init(self.root_rng)

        self.scaffold = self.opt_name == constants.FEDML_FEDERATED_OPTIMIZER_SCAFFOLD
        self.fedsgd = self.opt_name == constants.FEDML_FEDERATED_OPTIMIZER_FEDSGD
        self.fednova = self.opt_name == constants.FEDML_FEDERATED_OPTIMIZER_FEDNOVA

        cap = self.ds.cap
        impl = str(
            getattr(args, "sp_cohort_impl", "") or self.cohort_impl_default
        ).lower()
        if self.cohort_impl_default == "vmap" and impl != "vmap":
            # mesh engine: the cohort axis is SHARDED over devices — lax.map
            # would silently serialize the whole pod onto one program.
            # ("auto" resolves to vmap here anyway; only "map" conflicts.)
            if impl == "map":
                logger.warning(
                    "sp_cohort_impl='map' ignored: this engine requires "
                    "vmap (cohort axis sharded over devices)"
                )
            impl = "vmap"
        if impl == "auto":
            conv_model = bool(getattr(model, "conv_model", False))
            on_cpu = jax.devices()[0].platform == "cpu"
            impl = "map" if (conv_model and on_cpu) else "vmap"
        if impl not in ("vmap", "map"):
            raise ValueError(f"sp_cohort_impl must be vmap|map|auto, got {impl!r}")
        if impl == "map":
            logger.info("sp engine: lax.map cohort (conv-on-CPU fallback)")
        if self.fedsgd:
            fn = make_grad_fn(model, args, cap)
            if impl == "map":
                self.cohort_fn = jax.jit(
                    lambda gp, cx, cy, cn, rngs:
                    jax.lax.map(lambda o: fn(gp, *o), (cx, cy, cn, rngs))
                )
            else:
                self.cohort_fn = jax.jit(
                    jax.vmap(fn, in_axes=(None, 0, 0, 0, 0))
                )
        else:
            fn = make_local_train_fn(model, args, cap, scaffold=self.scaffold)
            if impl == "map":
                if self.scaffold:
                    self.cohort_fn = jax.jit(
                        lambda gp, cx, cy, cn, rngs, cg, cls:
                        jax.lax.map(
                            lambda o: fn(gp, o[0], o[1], o[2], o[3], cg, o[4]),
                            (cx, cy, cn, rngs, cls),
                        )
                    )
                else:
                    self.cohort_fn = jax.jit(
                        lambda gp, cx, cy, cn, rngs:
                        jax.lax.map(lambda o: fn(gp, *o), (cx, cy, cn, rngs))
                    )
            else:
                axes = (None, 0, 0, 0, 0) + ((None, 0) if self.scaffold else ())
                self.cohort_fn = jax.jit(jax.vmap(fn, in_axes=axes))

        # server optimizer over pseudo-gradients (FedOpt family + FedSGD)
        self.server_opt = None
        self.server_opt_state = None
        if self.opt_name in SERVER_OPT_FAMILY:
            self.server_opt = create_server_optimizer(args)
            self.server_opt_state = self.server_opt.init(self.global_params)

        if self.scaffold:
            self.c_global = tree_zeros_like(self.global_params)
            # per-client control variates, stacked [clients, ...]
            self.c_locals = jax.tree.map(
                lambda x: jnp.zeros((self.ds.client_num,) + x.shape, x.dtype),
                self.global_params,
            )

        # HBM-resident federation (SURVEY.md §7 "Heterogeneous per-client data
        # residency"): park the whole packed dataset on device once and gather
        # cohorts there — no per-round host→device transfer. Falls back to
        # host-side gather for datasets too large for HBM. The budget is
        # queried from the device (60% of its memory limit, leaving room for
        # params/grads/cohort working set); 4 GB if the backend reports none.
        total_bytes = self.ds.train_x.nbytes + self.ds.train_y.nbytes
        self.hbm_resident = self.hbm_resident_default and bool(
            getattr(args, "hbm_resident", total_bytes < self._hbm_budget())
        )
        if (self.cohort_engine is not None
                and max(int(getattr(args, "superround_k", 0) or 0), 0) <= 1):
            # registry rounds stream through the prefetcher — a resident
            # dataset copy would be dead HBM for the whole run. Superround
            # is the exception: its scan gathers on device and needs
            # _dev_x et al.
            self.hbm_resident = False
        if self.hbm_resident:
            self._dev_x = jax.device_put(self.ds.train_x)
            self._dev_y = jax.device_put(self.ds.train_y)
            self._dev_counts = jax.device_put(
                self.ds.train_counts.astype(np.int32)
            )

        self.evaluate = make_eval_fn(model)
        self.attacker = FedMLAttacker.get_instance()
        self.attacker.init(args)
        self.defender = FedMLDefender.get_instance()
        self.defender.init(args)
        if self.custom_aggregator is not None and self.defender.is_defense_enabled():
            # a robust defense (krum/median/...) IS the aggregation rule — it
            # cannot compose with a user ServerAggregator override. Silently
            # dropping either one would betray whoever configured it, so fail
            # fast. (Model attacks DO compose: they transform client rows
            # before whatever aggregation runs — see _aggregate.)
            raise ValueError(
                "enable_defense and a custom ServerAggregator are mutually "
                f"exclusive: defense_type={self.defender.defense_type!r} "
                "replaces the aggregation rule. Disable one of them."
            )
        self.dp = (
            FedPrivacyMechanism.from_args(args)
            if bool(getattr(args, "enable_dp", False))
            else None
        )
        self.history: List[Dict[str, float]] = []

        # -- fused round engine (round_engine.py): one donated XLA program per
        # round. "auto" fuses whenever the config has no host-side hook that
        # must run between cohort step and aggregation; "on" demands it (and
        # errors on a blocked config); "off" keeps the legacy multi-dispatch
        # path. Built lazily on first run_round so subclass __init__ (mesh's
        # sharding setup) has completed.
        self._round_step = None
        self._superround_step = None
        self._superround_k = max(int(getattr(args, "superround_k", 0) or 0), 0)
        self._fusion_ready = False
        mode = str(getattr(args, "round_fusion", "auto") or "auto").lower()
        if mode not in ("auto", "on", "off"):
            raise ValueError(f"round_fusion must be auto|on|off, got {mode!r}")
        blockers = self._fusion_blockers()
        if mode == "on" and blockers:
            raise ValueError(
                "round_fusion='on' but this config cannot fuse: "
                + "; ".join(blockers)
            )
        self._fusion_enabled = mode != "off" and not blockers
        if blockers and mode != "off":
            logger.info("round fusion off: %s", "; ".join(blockers))

    # -- sampling (reference: fedavg_api.py:125-140) ------------------------
    def _cohort_size(self) -> int:
        """Real (unpadded) clients per round — registry cohort size when the
        scale substrate is on, the reference min() rule otherwise."""
        if self.cohort_engine is not None:
            return self.cohort_engine.cohort_size
        return min(int(self.args.client_num_per_round), self.ds.client_num)

    def _client_sampling(self, round_idx: int) -> np.ndarray:
        if self.cohort_engine is not None:
            # registry path: seeded on-device K-of-N over the population,
            # mapped through shard pointers to dataset rows (scale/)
            return self.cohort_engine.data_cohort(round_idx)
        total = self.ds.client_num
        per_round = min(int(self.args.client_num_per_round), total)
        if total == per_round:
            return np.arange(total)
        rs = np.random.RandomState(round_idx)
        return rs.choice(total, per_round, replace=False)

    # -- cohort placement hooks (overridden by the mesh backend) ------------
    def _pad_cohort(self, cohort: np.ndarray):
        """Return (cohort, wmask): pad the cohort for even device sharding.

        wmask is None (no padding) on the single-device path; the mesh backend
        pads to a multiple of the ``clients`` axis size and returns a 0/1 mask
        (1 for real clients, 0 for padding) — the reference's padded schedule
        tensors (``Server.py:124-128``) reborn as a weight mask.
        """
        return cohort, None

    def _gather_cohort(self, cohort: np.ndarray):
        """Gather the cohort's packed shards → (cx, cy, cn) on device.

        Registry mode streams through the cohort engine's prefetcher
        (round r's gather was scheduled while round r-1 trained);
        otherwise the resident gather below runs."""
        if self.cohort_engine is not None:
            return self.cohort_engine.gather(cohort, self._place_cohort)
        return self._gather_resident(cohort)

    def _host_gather_rows(self, rows: np.ndarray):
        """Host-side shard read for the streaming path (runs on the
        prefetcher's worker thread)."""
        return (
            self.ds.train_x[rows],
            self.ds.train_y[rows],
            self.ds.train_counts[rows].astype(np.int32),
        )

    def _place_cohort(self, arrays):
        """Commit gathered host shards to device (mesh: rule-sharded)."""
        cx, cy, cn = arrays
        return jnp.asarray(cx), jnp.asarray(cy), jnp.asarray(cn)

    def _gather_resident(self, cohort: np.ndarray):
        if self.hbm_resident:
            idx = jnp.asarray(cohort)
            cx = jnp.take(self._dev_x, idx, axis=0)
            cy = jnp.take(self._dev_y, idx, axis=0)
            cn = jnp.take(self._dev_counts, idx, axis=0)
        else:
            from .. import native

            # host gather through the C++ threaded path when available
            cx = jnp.asarray(native.gather_rows(self.ds.train_x, cohort))
            cy = jnp.asarray(
                native.gather_rows(self.ds.train_y, cohort)
                if self.ds.train_y.dtype in (np.float32, np.int32)
                else self.ds.train_y[cohort]
            )
            cn = jnp.asarray(self.ds.train_counts[cohort])
        return cx, cy, cn

    def _place(self, arr):
        """Place a per-client array (leading cohort dim); mesh shards it."""
        return arr

    def _prepare_round(self) -> None:
        """Pre-round placement hook (mesh re-commits params replicated)."""

    def _place_state(self, state):
        """Commit the round state's placement (mesh: replicated)."""
        return state

    # -- fused round engine (round_engine.py) -------------------------------
    def _fusion_blockers(self) -> List[str]:
        """Host-side hooks that cannot live inside one jit'd program."""
        blockers = []
        if self.custom_aggregator is not None:
            blockers.append("custom ServerAggregator (arbitrary Python)")
        if (self.defender.is_defense_enabled()
                and self.defender.defense_type == "wbc"):
            blockers.append("FL-WBC defense (host-side per-client history)")
        if type(self)._train_round is not FedAvgAPI._train_round:
            blockers.append(
                f"{type(self).__name__} overrides _train_round"
            )
        # round_engine inlines THIS class's aggregation; a subclass override
        # (e.g. TurboAggregate's additive-share aggregation) would be
        # silently bypassed by the fused mirror
        if type(self)._aggregate is not FedAvgAPI._aggregate:
            blockers.append(
                f"{type(self).__name__} overrides _aggregate"
            )
        return blockers

    def _setup_round_fusion(self) -> None:
        """Build the jit'd round programs once (lazily, post-subclass-init)."""
        self._fusion_ready = True
        if not self._fusion_enabled:
            return
        from .round_engine import make_fused_round_step, make_superround_step

        per = self._cohort_size()
        cohort0, wmask0 = self._pad_cohort(
            np.arange(per) % self.ds.client_num
        )
        self._round_step = make_fused_round_step(
            self, n_cohort=len(cohort0), n_valid=per
        )
        if self._superround_k > 1:
            if self.hbm_resident and wmask0 is None:
                self._superround_step = make_superround_step(
                    self, self._superround_k, n_cohort=per
                )
            else:
                logger.info(
                    "superround off: needs the HBM-resident single-device "
                    "path (hbm_resident=%s, padded=%s)",
                    self.hbm_resident, wmask0 is not None,
                )
                self._superround_k = 0

    def _round_state(self) -> Dict:
        """The donated round state (also the checkpoint payload)."""
        state = {"global_params": self.global_params}
        if self.server_opt_state is not None:
            state["server_opt_state"] = self.server_opt_state
        if self.scaffold:
            state["c_global"] = self.c_global
            state["c_locals"] = self.c_locals
        return state

    def _set_round_state(self, state: Dict) -> None:
        """Adopt the round state returned by a donated program. The previous
        buffers are CONSUMED by donation — never read them again."""
        self.global_params = state["global_params"]
        if "server_opt_state" in state:
            self.server_opt_state = state["server_opt_state"]
        if self.scaffold:
            self.c_global = state["c_global"]
            self.c_locals = state["c_locals"]

    def run_round(self, round_idx: int) -> Dict[str, float]:
        """One federated round: the fused single-program path when the config
        allows it, the legacy multi-dispatch ``_train_round`` otherwise.

        With ``--enable_tracking`` each round opens a telemetry RoundRecord
        (phase spans, dispatch latency, HBM, compile events) and may open or
        close a ``--profile_rounds`` jax.profiler window. Disabled, both are
        one boolean check."""
        if not self._fusion_ready:
            self._setup_round_fusion()
        telemetry.on_round_start(round_idx)
        rec = telemetry.begin_round(
            round_idx, fused=self._round_step is not None
        )
        if self._round_step is None:
            out = self._train_round(round_idx)
        else:
            out = self._train_round_fused(round_idx)
        telemetry.end_round(rec, train_loss=out.get("train_loss"))
        telemetry.on_round_end(round_idx)
        return out

    def run_rounds(self, start_round: int, k: int) -> Dict[str, Any]:
        """Run rounds [start_round, start_round + k) — ONE superround launch
        when the config compiled one for exactly ``k`` rounds, else a Python
        loop of single rounds. Returns ``{"train_loss": losses}`` with one
        (device-resident) loss per round."""
        if not self._fusion_ready:
            self._setup_round_fusion()
        if self._superround_step is not None and k == self._superround_k:
            telemetry.on_round_start(start_round)
            tracked = telemetry.enabled()
            t0 = time.perf_counter() if tracked else 0.0
            self._prepare_round()
            state, scan_metrics = self._superround_step(
                self._place_state(self._round_state()), jnp.int32(start_round)
            )
            self._set_round_state(state)
            if self.cohort_engine is not None:
                # the scan sampled rounds [start, start+k) on device with
                # the registry's own sampler; replay them host-side so the
                # participation/staleness counters stay truthful
                self.cohort_engine.note_rounds(start_round, k)
            if tracked:
                # one record per scanned round, unpacked from the scan's
                # stacked on-device counters (the only host sync tracking
                # adds — the untracked path stays fully asynchronous)
                jax.block_until_ready(state)
                telemetry.emit_superround(
                    start_round, k, time.perf_counter() - t0, scan_metrics
                )
            telemetry.on_round_end(start_round + k - 1)
            return {"train_loss": scan_metrics["train_loss"]}
        return {"train_loss": [
            self.run_round(start_round + j)["train_loss"] for j in range(k)
        ]}

    def _train_round_fused(self, round_idx: int) -> Dict[str, float]:
        """One round as ONE donated device program (round_engine.py).

        Returns train_loss as a DEVICE scalar — no host sync. train() keeps
        dispatch asynchronous: while the device executes round r, the host
        already samples and gathers round r+1's cohort. Only under an active
        telemetry record does the round block for dispatch→ready latency.
        """
        rec = telemetry.current_record()
        with telemetry.phase("sample"):
            self._prepare_round()
            cohort, wmask = self._pad_cohort(self._client_sampling(round_idx))
        with telemetry.phase("gather"):
            cx, cy, cn = self._gather_cohort(cohort)
        with telemetry.phase("prep"):
            round_rng = jax.random.fold_in(self.root_rng, round_idx)
            rngs = self._place(jax.random.split(round_rng, len(cohort)))
            wm = None if wmask is None else self._place(jnp.asarray(wmask))
            cohort_idx = jnp.asarray(cohort, jnp.int32)
            st = self._place_state(self._round_state())
        t_dispatch = time.perf_counter()
        with telemetry.phase("dispatch"):
            state, metrics = self._round_step(
                st, cohort_idx, cx, cy, cn, rngs, wm, round_rng,
            )
        self._set_round_state(state)
        if rec is not None:
            rec.lazy["examples"] = metrics.get("examples")
            with telemetry.phase("device_wait"):
                jax.block_until_ready(state)
            rec.dispatch_latency_s = time.perf_counter() - t_dispatch
        return {"train_loss": metrics["train_loss"]}

    # -- one round (legacy multi-dispatch path; kept as the numerical
    # -- reference the fusion parity tests compare against) -----------------
    def _train_round(self, round_idx: int) -> Dict[str, float]:
        rec = telemetry.current_record()
        with telemetry.phase("sample"):
            self._prepare_round()
            cohort, wmask = self._pad_cohort(self._client_sampling(round_idx))
            n_valid = len(cohort) if wmask is None else int(wmask.sum())
        with telemetry.phase("gather"):
            cx, cy, cn = self._gather_cohort(cohort)
        if self.attacker.is_data_attack():
            cx, cy = self.attacker.attack_data(cx, cy, n_valid)

        round_rng = jax.random.fold_in(self.root_rng, round_idx)
        rngs = self._place(jax.random.split(round_rng, len(cohort)))
        wm = None if wmask is None else self._place(jnp.asarray(wmask))

        if self.fedsgd:
            with telemetry.phase("train"):
                grads, metrics = self.cohort_fn(self.global_params, cx, cy, cn, rngs)
            weights = metrics["num_samples"] if wm is None else metrics["num_samples"] * wm
            if rec is not None:
                rec.lazy["examples"] = weights.sum()
            agg_grad = self._aggregate(grads, weights, round_rng, n_valid, cohort)
            updates, self.server_opt_state = self.server_opt.update(
                agg_grad, self.server_opt_state, self.global_params
            )
            import optax

            self.global_params = optax.apply_updates(self.global_params, updates)
            with telemetry.phase("loss_sync"):
                return {"train_loss": _masked_mean(metrics["train_loss"], wm)}

        if self.scaffold:
            c_cohort = jax.tree.map(lambda x: x[cohort], self.c_locals)
            with telemetry.phase("train"):
                stacked, metrics, new_c = self.cohort_fn(
                    self.global_params, cx, cy, cn, rngs, self.c_global, c_cohort
                )
            # scatter back new control variates; update c_global by the mean
            # delta scaled by cohort/total (SCAFFOLD option II). Only the
            # n_valid real clients participate — padded rows are dropped.
            real = cohort[:n_valid]
            new_c_r = jax.tree.map(lambda x: x[:n_valid], new_c)
            c_cohort_r = jax.tree.map(lambda x: x[:n_valid], c_cohort)
            delta_c = jax.tree.map(
                lambda n, o: (n - o).mean(0), new_c_r, c_cohort_r
            )
            scale = n_valid / self.ds.client_num
            self.c_global = jax.tree.map(
                lambda cg, d: cg + scale * d, self.c_global, delta_c
            )
            self.c_locals = jax.tree.map(
                lambda all_c, nc: all_c.at[real].set(nc), self.c_locals, new_c_r
            )
        else:
            with telemetry.phase("train"):
                stacked, metrics = self.cohort_fn(self.global_params, cx, cy, cn, rngs)

        weights = metrics["num_samples"] if wm is None else metrics["num_samples"] * wm
        if rec is not None:
            rec.lazy["examples"] = weights.sum()

        if self.fednova:
            # w_new = w_g - tau_eff * Σ p_i (w_g - w_i)/tau_i
            tau = metrics["tau"]
            p = weights / jnp.maximum(weights.sum(), 1e-12)
            tau_eff = (p * tau).sum()
            norm_dir = fednova_normalized_direction(self.global_params, stacked, tau)
            d = weighted_average(norm_dir, weights)
            self.global_params = jax.tree.map(
                lambda g, dd: g - tau_eff * dd, self.global_params, d
            )
        else:
            w_agg = self._aggregate(stacked, weights, round_rng, n_valid, cohort)
            if self.opt_name == constants.FEDML_FEDERATED_OPTIMIZER_FEDOPT:
                import optax

                pg = pseudo_gradient(self.global_params, w_agg)
                updates, self.server_opt_state = self.server_opt.update(
                    pg, self.server_opt_state, self.global_params
                )
                self.global_params = optax.apply_updates(self.global_params, updates)
            else:
                self.global_params = w_agg

        if self.dp is not None and self.dp.dp_type == "cdp":
            self.global_params = self.dp.randomize_global(
                self.global_params, jax.random.fold_in(round_rng, 7)
            )
        with telemetry.phase("loss_sync"):
            # _masked_mean pulls a host float, so this span absorbs the
            # device wait for everything dispatched above
            return {"train_loss": _masked_mean(metrics.get("train_loss"), wm)}

    # -- aggregation with trust hooks ---------------------------------------
    def _aggregate(
        self, stacked: PyTree, weights: jax.Array, rng, n_valid: int = None,
        client_ids=None,
    ) -> PyTree:
        """attack → defend → weighted-average → (local/central DP applied by
        caller), all on the stacked [cohort, ...] arrays.

        ``n_valid``: number of real (non-padding) leading rows. Zero-weight
        padding is harmless to the weighted average, but rank-based defenses
        (Krum, median, ...) and the attack kernels see every row — so the
        trust paths slice to the real cohort first.
        """
        with telemetry.phase("aggregate"):
            return self._aggregate_impl(stacked, weights, rng, n_valid,
                                        client_ids)

    def _aggregate_impl(
        self, stacked: PyTree, weights: jax.Array, rng, n_valid: int = None,
        client_ids=None,
    ) -> PyTree:
        if self.dp is not None and self.dp.dp_type == "ldp":
            keys = jax.random.split(jax.random.fold_in(rng, 3), weights.shape[0])
            stacked = jax.vmap(self.dp.randomize)(stacked, keys)
        elif self.dp is not None and self.dp.dp_type == "cdp":
            # bound per-client sensitivity before averaging; the noise is
            # added to the aggregate by the caller (randomize_global)
            stacked = self.dp.clip_client_updates(stacked, self.global_params)

        n = int(weights.shape[0]) if n_valid is None else int(n_valid)

        needs_flat = self.attacker.is_model_attack() or self.defender.is_defense_enabled()
        if not needs_flat:
            if self.custom_aggregator is not None:
                return self._custom_aggregate(stacked, weights, n)
            return weighted_average(stacked, weights)

        # flatten to [n, dim] once for the attack/defense kernels; drop
        # zero-weight padding rows so rank-based defenses see real clients
        if n < weights.shape[0]:
            stacked = jax.tree.map(lambda x: x[:n], stacked)
            weights = weights[:n]
        _, treedef, shapes = tree_flatten_to_vector(self.global_params)
        flat = jax.vmap(lambda t: tree_flatten_to_vector(t)[0])(stacked)
        gvec, _, _ = tree_flatten_to_vector(self.global_params)
        if self.attacker.is_model_attack():
            flat = self.attacker.attack_model(
                flat, weights, jax.random.fold_in(rng, 1)
            )
        if self.defender.is_defense_enabled():
            ids = None if client_ids is None else list(client_ids)[:n]
            agg_vec = self.defender.defend(
                flat, weights, gvec, jax.random.fold_in(rng, 2), client_ids=ids
            )
        elif self.custom_aggregator is not None:
            # model attack + custom aggregator compose: the attack transformed
            # the client rows, the user's rule aggregates whatever arrived
            attacked = jax.vmap(
                lambda v: tree_unflatten_from_vector(v, treedef, shapes)
            )(flat)
            return self._custom_aggregate(attacked, weights, int(weights.shape[0]))
        else:
            w = weights / jnp.maximum(weights.sum(), 1e-12)
            agg_vec = (w[:, None] * flat).sum(0)
        return tree_unflatten_from_vector(agg_vec, treedef, shapes)

    def _custom_aggregate(self, stacked: PyTree, weights: jax.Array, n: int) -> PyTree:
        """Run the user ServerAggregator's hook chain on the first n rows."""
        raw = [
            (float(weights[i]), jax.tree.map(lambda x: x[i], stacked))
            for i in range(n)
        ]
        raw = self.custom_aggregator.on_before_aggregation(raw)
        agg = self.custom_aggregator.aggregate(raw)
        return self.custom_aggregator.on_after_aggregation(agg)

    # -- the training loop (reference: fedavg_api.py:65-123) ----------------
    # -- round checkpoint / resume ------------------------------------------
    # The reference has NO round-resume anywhere (SURVEY §5); killed runs
    # restart from round 0. With args.checkpoint_dir set, the global model
    # (+ round index, and the server optimizer / SCAFFOLD variates when
    # present) persists via Orbax every checkpoint_every_rounds rounds and
    # train() resumes mid-federation after a crash.
    def _ckpt_state(self) -> Dict:
        # same structure as the donated round state; CheckpointManager.save
        # copies every leaf to host BEFORE the next round's donation can
        # invalidate these buffers (tested in test_round_fusion.py)
        return self._round_state()

    def _maybe_resume(self, ckpt) -> int:
        """Restore the newest round checkpoint; returns the round to START."""
        step = ckpt.latest_step()
        if step is None:
            return 0
        restored = ckpt.restore_latest(self._ckpt_state())
        self.global_params = restored["global_params"]
        if "server_opt_state" in restored:
            self.server_opt_state = restored["server_opt_state"]
        if self.scaffold:
            self.c_global = restored["c_global"]
            self.c_locals = restored["c_locals"]
        telemetry.counter_inc("run.resumes")
        logger.info("sp engine: resumed federation at round %d", step + 1)
        return step + 1

    def _ledger_world(self) -> Dict[str, Any]:
        """Run-identity fields pinned into the ledger's run_meta line; the
        mesh engine extends this with its device topology so a resumed run
        on a mismatched mesh fails loudly instead of silently resharding."""
        world = {
            "engine": type(self).__name__,
            "optimizer": self.opt_name,
            "client_num_in_total": int(self.ds.client_num),
            "client_num_per_round": int(self.args.client_num_per_round),
        }
        if self.cohort_engine is not None:
            # registry identity (population size, seed, column digest):
            # resuming against a DIFFERENT registry would silently resample
            # every remaining cohort — ensure_meta turns that into an error
            world["registry"] = self.cohort_engine.ledger_identity()
        return world

    def train(self) -> Dict[str, float]:
        from ..core import mlops, runstate

        rounds = int(self.args.comm_round)
        freq = max(int(getattr(self.args, "frequency_of_the_test", 5)), 1)
        ckpt = None
        ledger = None
        guard = None
        start_round = 0
        ckpt_dir = str(getattr(self.args, "checkpoint_dir", "") or "")
        every = runstate.checkpoint_cadence(self.args)
        mode = runstate.resume_mode(self.args)
        if ckpt_dir:
            from ..checkpoint import CheckpointManager

            ckpt = CheckpointManager(ckpt_dir)
            try:
                if mode == "never" and ckpt.latest_step() is not None:
                    raise RuntimeError(
                        f"--resume never, but {ckpt_dir} already holds a "
                        f"checkpoint (step {ckpt.latest_step()}) — point at "
                        "a fresh checkpoint_dir or use --resume auto"
                    )
                if mode == "require" and ckpt.latest_step() is None:
                    raise RuntimeError(
                        f"--resume require, but {ckpt_dir} holds no "
                        "checkpoint to resume from"
                    )
                start_round = self._maybe_resume(ckpt)
                ledger = runstate.RunLedger.for_checkpoint_dir(ckpt_dir)
                ledger.ensure_meta(
                    seed=int(getattr(self.args, "random_seed", 0)),
                    world=self._ledger_world(),
                )
            except Exception:
                # a refused resume (mode conflict, world-identity mismatch)
                # must not leak the orbax manager's worker threads — a
                # lingering executor racing a later jax trace is a
                # process-killing segfault on CPU hosts
                ckpt.close()
                raise
            last_committed = ledger.last_round()
            if last_committed is not None \
                    and last_committed != start_round - 1:
                logger.warning(
                    "run ledger %s ends at round %d but the checkpoint "
                    "resumes at round %d — ledger history may be from an "
                    "uncommitted crash window", ledger.path, last_committed,
                    start_round,
                )
            # preemption-safe drain: SIGTERM/SIGINT latches, the in-flight
            # chunk finishes, checkpoint + ledger commit, and train raises
            # PreemptionError (exit EXIT_PREEMPTED at the CLI)
            guard = runstate.preemption_guard()
            if bool(getattr(self.args, "preempt_signals", True)):
                guard.install()
            guard.reset()
        last_eval: Dict[str, float] = {}
        try:
            if start_round >= rounds:
                # re-invoking a COMPLETED federation: evaluate the restored
                # model instead of returning an empty dict to consumers
                last_eval = self.evaluate(
                    self.global_params, self.ds.test_x, self.ds.test_y
                )
                return last_eval
            round_idx = start_round
            pending: List[tuple] = []  # (round, cohort) awaiting a commit
            while round_idx < rounds:
                k = self._chunk_len(round_idx, rounds, freq,
                                    every if ckpt is not None else 0)
                self.args.round_idx = round_idx + k - 1
                t0 = time.perf_counter()
                if k > 1:
                    # superround: K rounds in one donated scan program;
                    # per-round losses come back stacked [K]
                    with mlops.MLOpsProfilerEvent("train"):
                        losses = self.run_rounds(round_idx, k)["train_loss"]
                    dt = time.perf_counter() - t0
                    for j in range(k):
                        mlops.log_round_info(round_idx + j, rounds)
                        self.history.append({
                            "round": round_idx + j, "round_time_s": dt / k,
                            "train_loss": losses[j],
                        })
                else:
                    mlops.log_round_info(round_idx, rounds)
                    with mlops.MLOpsProfilerEvent("train"):
                        train_metrics = self.run_round(round_idx)
                    dt = time.perf_counter() - t0
                    self.history.append({
                        "round": round_idx, "round_time_s": dt,
                        **train_metrics,
                    })
                last_round = round_idx + k - 1
                entry = self.history[-1]
                if last_round % freq == 0 or last_round == rounds - 1:
                    # runs BETWEEN rounds (the round's record is already
                    # closed): registry histogram only, never a record phase
                    with telemetry.phase("eval", record=False):
                        last_eval = self.evaluate(
                            self.global_params, self.ds.test_x, self.ds.test_y
                        )
                    entry.update(last_eval)
                    mlops.log({"round": last_round, **last_eval},
                              step=last_round)
                    logger.info(
                        "round %d: loss=%.4f acc=%.4f (%.3fs)",
                        last_round, last_eval["test_loss"],
                        last_eval["test_acc"], dt / k,
                    )
                if ledger is not None:
                    # cohorts are host-sampled per round except under a
                    # superround scan (on-device sampling) — deterministic
                    # either way, but only the host path is recordable
                    for j in range(round_idx, last_round + 1):
                        pending.append((
                            j,
                            None if k > 1
                            else [int(c) for c in self._client_sampling(j)],
                        ))
                if ckpt is not None and (
                    (last_round + 1) % every == 0 or last_round == rounds - 1
                ):
                    step = ckpt.save(self._ckpt_state(), step=last_round)
                    for r, cohort in pending:
                        ledger.commit_round(r, ckpt_step=step, cohort=cohort)
                    pending.clear()
                round_idx += k
                if guard is not None and guard.requested() \
                        and round_idx < rounds:
                    from ..core.runstate import PreemptionError

                    # drain commit: the chunk above completed; persist its
                    # state NOW (even off the checkpoint cadence) so the
                    # restart resumes exactly here instead of re-training
                    if ckpt.latest_step() != last_round:
                        step = ckpt.save(self._ckpt_state(), step=last_round)
                        for r, cohort in pending:
                            ledger.commit_round(r, ckpt_step=step,
                                                cohort=cohort)
                        pending.clear()
                    telemetry.counter_inc("run.preemptions")
                    raise PreemptionError(last_round)
        finally:
            if ckpt is not None:  # release Orbax threads even on a crash
                ckpt.close()
            if self.cohort_engine is not None:
                self.cohort_engine.close()
            self._finalize_history()
        return last_eval

    def _chunk_len(self, r: int, rounds: int, freq: int, every: int) -> int:
        """Superround chunk length starting at round ``r``.

        Returns the configured K only when no round STRICTLY INSIDE the chunk
        needs a host-side action (eval or checkpoint) — those may only land on
        the chunk's last round, where the scan has already returned. Anything
        else runs as a single round, so the observable eval/checkpoint
        schedule is identical to the unchunked loop. At most two programs ever
        compile: the K-scan and the single round.
        """
        k = self._superround_k
        if k <= 1 or r + k > rounds:
            return 1
        if every:
            from ..core import runstate

            if runstate.preemption_guard().requested():
                # step-granular drain: SIGTERM already latched — never
                # launch another K-round scan program (it cannot be
                # interrupted mid-scan); single rounds bound the drain
                # latency to ONE round, and the train loop's guard check
                # commits + exits right after it
                return 1
        if not self._fusion_ready:
            self._setup_round_fusion()
        if self._superround_step is None:
            return 1
        if telemetry.profiler_blocks_chunk(r, r + k):
            # a --profile_rounds boundary inside the chunk: single rounds so
            # the trace window opens/closes exactly on the requested rounds
            return 1
        for ri in range(r, r + k - 1):
            if ri % freq == 0:
                return 1
            if every and (ri + 1) % every == 0:
                return 1
        return k

    def _finalize_history(self) -> None:
        """Realize any still-on-device train_loss scalars (the fused path
        keeps dispatch async — metrics are only pulled here or at evals)."""
        for e in self.history:
            tl = e.get("train_loss")
            if tl is not None and not isinstance(tl, float):
                e["train_loss"] = float(np.asarray(tl))


def _masked_mean(values, wmask) -> float:
    """Mean of per-client scalars, ignoring zero-mask (padding) entries."""
    if values is None:
        return float("nan")
    if wmask is None:
        return float(jnp.mean(values))
    return float((values * wmask).sum() / jnp.maximum(wmask.sum(), 1.0))

