"""FedNAS: federated neural architecture search (DARTS-based).

reference: ``simulation/mpi/fednas/`` (FedNASTrainer.search — alternate an
architecture step on held-out data with a weight step on train data, first-
order DARTS; FedNASAggregator — average weights AND alphas across clients;
after the search phase the argmax genotype is trained).

TPU-first: the whole cohort searches as ONE vmapped program. Each client's
local search is a ``lax.scan`` of (alpha-step on the validation half,
w-step on the train half); the round averages both param groups with the
same stacked-tree kernel as FedAvg. Arch params live in the regular param
tree (``models/darts.py``) and are split by path mask, so "average weights
and alphas" is a single weighted average of the whole tree — exactly the
reference's aggregate, with none of its tensor bookkeeping.
"""

from __future__ import annotations

import logging
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core.aggregate import weighted_average
from ..models.darts import genotype
from ..ml.evaluate import make_eval_fn

logger = logging.getLogger(__name__)


class FedNASAPI:
    def __init__(self, args, device, dataset, model):
        if model.name not in ("darts", "darts_search"):
            raise ValueError(
                f"FedNAS needs the darts search model, got {model.name!r}"
            )
        self.args = args
        self.ds = dataset
        self.bundle = model
        self.n = dataset.client_num
        self.epochs = max(int(getattr(args, "epochs", 1)), 1)
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        self.root_rng = rng
        self.global_params = model.init(rng)
        w_lr = float(getattr(args, "learning_rate", 0.025))
        a_lr = float(getattr(args, "arch_learning_rate", 3e-3))

        from ..models.darts import is_arch_param

        def label_fn(params):
            return jax.tree_util.tree_map_with_path(
                lambda p, _: "arch" if is_arch_param(p) else "weights", params
            )

        # one optimizer tree, two schedules — reference keeps two torch
        # optimizers (SGD for w, Adam for alpha); multi_transform is the
        # functional equivalent
        self.opt = optax.multi_transform(
            {"weights": optax.sgd(w_lr, momentum=0.9),
             "arch": optax.adam(a_lr, b1=0.5, b2=0.999)},
            label_fn,
        )

        def ce(params, x, y, mask):
            logits = model.apply(params, x, train=True)
            per = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            return (per * mask).sum() / jnp.maximum(mask.sum(), 1.0)

        def mask_tree(grads, params, want_arch: bool):
            return jax.tree_util.tree_map_with_path(
                lambda p, g: g if is_arch_param(p) == want_arch
                else jnp.zeros_like(g),
                grads,
            )

        # steps per round ≈ the minibatch count a torch epoch would take —
        # full-batch GD needs comparable step counts to learn, not 1/epoch
        half = max(self.ds.cap // 2, 1)
        self.steps_per_round = self.epochs * max(
            half // max(int(getattr(args, "batch_size", 16)), 1), 4
        )

        def client_search(params, opt_state, xt, yt, mt, xv, yv, mv):
            """steps x (alpha-step on valid, w-step on train) — first-order
            DARTS (reference architect.step with unrolled=False)."""

            def epoch(carry, _):
                params, opt_state = carry
                # arch step on the held-out half
                al, ag = jax.value_and_grad(ce)(params, xv, yv, mv)
                ag = mask_tree(ag, params, want_arch=True)
                au, opt_state = self.opt.update(ag, opt_state, params)
                params = optax.apply_updates(params, au)
                # weight step on the train half
                wl, wg = jax.value_and_grad(ce)(params, xt, yt, mt)
                wg = mask_tree(wg, params, want_arch=False)
                wu, opt_state = self.opt.update(wg, opt_state, params)
                params = optax.apply_updates(params, wu)
                return (params, opt_state), (wl, al)

            (params, opt_state), (wls, als) = jax.lax.scan(
                epoch, (params, opt_state), None, length=self.steps_per_round
            )
            return params, opt_state, wls.mean(), als.mean()

        @jax.jit
        def round_fn(stacked_params, opt_states, xt, yt, mt, xv, yv, mv,
                     weights):
            ps, os_, wl, al = jax.vmap(client_search)(
                stacked_params, opt_states, xt, yt, mt, xv, yv, mv
            )
            avg = weighted_average(ps, weights)
            return avg, os_, wl.mean(), al.mean()

        self._round_fn = round_fn
        self._eval = make_eval_fn(model)
        self.history = []

    def _split_halves(self):
        """Each client's shard splits into train/valid halves (reference
        FedNASTrainer uses train_queue/valid_queue)."""
        x = np.asarray(self.ds.train_x)
        y = np.asarray(self.ds.train_y)
        counts = np.asarray(self.ds.train_counts)
        half = self.ds.cap // 2
        xt, xv = x[:, :half], x[:, half:2 * half]
        yt, yv = y[:, :half], y[:, half:2 * half]
        nt = np.minimum(counts, half)
        nv = np.clip(counts - half, 0, half)
        mt = (np.arange(half)[None] < nt[:, None]).astype(np.float32)
        mv = (np.arange(half)[None] < nv[:, None]).astype(np.float32)
        # clients whose data fits in one half still need a valid signal:
        # fall back to the train half for alpha
        empty_v = mv.sum(1) < 1
        if empty_v.any():
            xv[empty_v], yv[empty_v], mv[empty_v] = (
                xt[empty_v], yt[empty_v], mt[empty_v],
            )
        return map(jnp.asarray, (xt, yt, mt, xv, yv, mv))

    def train(self) -> Dict[str, float]:
        xt, yt, mt, xv, yv, mv = self._split_halves()
        weights = jnp.asarray(self.ds.train_counts, jnp.float32)
        stacked = jax.tree.map(
            lambda t: jnp.broadcast_to(t[None], (self.n,) + t.shape),
            self.global_params,
        )
        opt_states = jax.vmap(self.opt.init)(stacked)
        last: Dict[str, float] = {}
        for r in range(int(self.args.comm_round)):
            avg, opt_states, wl, al = self._round_fn(
                stacked, opt_states, xt, yt, mt, xv, yv, mv, weights
            )
            self.global_params = avg
            stacked = jax.tree.map(
                lambda t: jnp.broadcast_to(t[None], (self.n,) + t.shape), avg
            )
            metrics = self._eval(avg, self.ds.test_x, self.ds.test_y)
            last = {
                "test_acc": metrics["test_acc"],
                "train_loss": float(wl),
                "arch_loss": float(al),
            }
            self.history.append({"round": r, **last})
            logger.info(
                "fednas round %d: wl=%.4f al=%.4f acc=%.4f",
                r, float(wl), float(al), metrics["test_acc"],
            )
        last["genotype"] = genotype(self.global_params)
        logger.info("fednas genotype: %s", last["genotype"])
        return last
