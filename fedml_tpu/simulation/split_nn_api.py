"""SplitNN: model split at a cut layer between clients and server.

reference: ``simulation/mpi/split_nn/`` (SplitNNAPI.py, client.py, server.py)
— each client owns the bottom of the network, the server owns the top; clients
take turns: activations at the cut cross client→server, gradients w.r.t. the
activations cross back. This is the reference's only layer-cut (proto
pipeline-parallel) precedent (SURVEY.md §2.5).

JAX realization: the exchanged tensors are exactly the intermediates of the
joint gradient; the client/server update split is preserved (separate param
trees + optimizers), and each client's pass is one jitted step.
"""

from __future__ import annotations

import logging
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

logger = logging.getLogger(__name__)


class ClientBottom(nn.Module):
    hidden: int = 64

    @nn.compact
    def __call__(self, x):
        h = x.reshape((x.shape[0], -1))
        return nn.relu(nn.Dense(self.hidden)(h))


class ServerTop(nn.Module):
    num_classes: int

    @nn.compact
    def __call__(self, h):
        h = nn.relu(nn.Dense(64)(h))
        return nn.Dense(self.num_classes)(h)


class SplitNNAPI:
    def __init__(self, args, device, dataset, model=None):
        self.args = args
        self.ds = dataset
        self.n = dataset.client_num
        self.bottom = ClientBottom(int(getattr(args, "split_hidden_dim", 64)))
        self.top = ServerTop(dataset.class_num)
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        kb, kt = jax.random.split(rng)
        dummy = jnp.zeros((1,) + dataset.train_x.shape[2:])
        b0 = self.bottom.init(kb, dummy)
        # per-client bottoms (reference: each client has its own lower model)
        self.client_params = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (self.n,) + x.shape), b0
        )
        self.server_params = self.top.init(
            kt, jnp.zeros((1, int(getattr(args, "split_hidden_dim", 64))))
        )
        lr = float(getattr(args, "learning_rate", 0.05))
        self.c_opt = optax.sgd(lr)
        self.s_opt = optax.sgd(lr)
        self.s_opt_state = self.s_opt.init(self.server_params)
        self.c_opt_states = jax.vmap(self.c_opt.init)(self.client_params)
        self.batch_size = int(getattr(args, "batch_size", 16))

        def loss_fn(cp, sp, xb, yb, mask):
            acts = self.bottom.apply(cp, xb)  # ← client→server activations
            logits = self.top.apply(sp, acts)
            per = optax.softmax_cross_entropy_with_integer_labels(logits, yb)
            return (per * mask).sum() / jnp.maximum(mask.sum(), 1.0)

        @jax.jit
        def step(cp, c_state, sp, s_state, xb, yb, mask):
            loss, (gc, gs) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
                cp, sp, xb, yb, mask
            )  # gc flows through the activation-gradient the server sends back
            cu, c_state = self.c_opt.update(gc, c_state, cp)
            su, s_state = self.s_opt.update(gs, s_state, sp)
            return (
                optax.apply_updates(cp, cu), c_state,
                optax.apply_updates(sp, su), s_state, loss,
            )

        self._step = step

        @jax.jit
        def predict(cp, sp, xb):
            return self.top.apply(sp, self.bottom.apply(cp, xb))

        self._predict = predict
        self.history = []

    def train(self) -> Dict[str, float]:
        rounds = int(self.args.comm_round)
        bs = self.batch_size
        last: Dict[str, float] = {}
        for r in range(rounds):
            losses = []
            # clients take turns against the shared server top (reference:
            # round-robin client order, SplitNNAPI.py)
            for c in range(self.n):
                cp = jax.tree.map(lambda t: t[c], self.client_params)
                cs = jax.tree.map(lambda t: t[c], self.c_opt_states)
                x, y, cnt = self.ds.client_shard(c)
                for i in range(0, self.ds.cap - bs + 1, bs):
                    xb = jnp.asarray(x[i : i + bs])
                    yb = jnp.asarray(y[i : i + bs]).astype(jnp.int32)
                    mask = (jnp.arange(i, i + bs) < cnt).astype(jnp.float32)
                    cp, cs, self.server_params, self.s_opt_state, loss = (
                        self._step(cp, cs, self.server_params,
                                   self.s_opt_state, xb, yb, mask)
                    )
                    losses.append(float(loss))
                self.client_params = jax.tree.map(
                    lambda all_t, t: all_t.at[c].set(t), self.client_params, cp
                )
                self.c_opt_states = jax.tree.map(
                    lambda all_t, t: all_t.at[c].set(t), self.c_opt_states, cs
                )
            # eval with client 0's bottom (reference evaluates acts owner-side)
            cp0 = jax.tree.map(lambda t: t[0], self.client_params)
            logits = self._predict(cp0, self.server_params,
                                   jnp.asarray(self.ds.test_x))
            acc = float(
                (jnp.argmax(logits, -1) == jnp.asarray(self.ds.test_y)).mean()
            )
            last = {"test_acc": acc, "train_loss": float(np.mean(losses))}
            self.history.append({"round": r, **last})
            logger.info("split_nn round %d: loss=%.4f acc=%.4f",
                        r, last["train_loss"], acc)
        return last
