"""TurboAggregate: secure aggregation via additive shares in a ring of groups.

reference: ``simulation/sp/turboaggregate/`` (TA_trainer.py, mpc_function.py
281 LoC — additive shares + Lagrange coding demo). Demo semantics preserved:
each client splits its update into additive shares so no single party (or
sub-threshold coalition) sees an individual update, yet the group sums —
passed along the ring — reconstruct the exact aggregate. The share split is
over the LightSecAgg finite field (core/mpc/lightsecagg.py) so the demo is
information-theoretically hiding, not just float-noise masking.
"""

from __future__ import annotations

import logging
from typing import Dict

import jax
import numpy as np

from ..core.mpc import lightsecagg as lsa
from ..utils.tree import tree_flatten_to_vector, tree_unflatten_from_vector
from .sp_api import FedAvgAPI

logger = logging.getLogger(__name__)


class TurboAggregateAPI(FedAvgAPI):
    """FedAvg where the server only ever sees share-sums, not raw updates."""

    def __init__(self, args, device, dataset, model, client_trainer=None,
                 server_aggregator=None):
        super().__init__(args, device, dataset, model, client_trainer,
                         server_aggregator)
        self.q_bits = int(getattr(args, "ta_quantize_bits", 8))
        self.group_size = int(getattr(args, "ta_group_size", 2))

    def _aggregate(self, stacked, weights, rng, n_valid=None, client_ids=None):
        """Replace the trusted-server average with additive-share aggregation.

        Each client i quantizes its weighted update and splits it into
        ``group_size`` additive shares mod p; share s goes to ring position
        (i+s). Every position sums what it received; the server adds the
        position sums — algebraically Σ_i update_i, with no position ever
        holding a complete individual update.
        """
        import jax.numpy as jnp

        n = int(weights.shape[0]) if n_valid is None else int(n_valid)
        if n < weights.shape[0]:
            stacked = jax.tree.map(lambda x: x[:n], stacked)
            weights = weights[:n]
        w = np.asarray(weights, np.float64)
        w = w / max(w.sum(), 1e-12)
        _, treedef, shapes = tree_flatten_to_vector(self.global_params)
        flat = np.asarray(
            jax.vmap(lambda t: tree_flatten_to_vector(t)[0])(stacked)
        )
        d = flat.shape[1]
        rs = np.random.RandomState(
            int(getattr(self.args, "random_seed", 0)) + 17
        )
        S = min(self.group_size, n)
        position_sums = np.zeros((n, d), np.int64)
        for i in range(n):
            q = lsa.quantize_to_field(flat[i] * w[i], self.q_bits)
            shares = rs.randint(0, lsa.FIELD_P, size=(S - 1, d)).astype(np.int64)
            last = (q - shares.sum(axis=0)) % lsa.FIELD_P
            all_shares = np.concatenate([shares, last[None]], axis=0)
            for s in range(S):
                position_sums[(i + s) % n] = (
                    position_sums[(i + s) % n] + all_shares[s]
                ) % lsa.FIELD_P
        total = np.zeros(d, np.int64)
        for i in range(n):
            total = (total + position_sums[i]) % lsa.FIELD_P
        agg = lsa.dequantize_from_field(total, self.q_bits)
        return tree_unflatten_from_vector(
            jnp.asarray(agg, jnp.float32), treedef, shapes
        )
