"""Classical vertical FL: two parties with a feature partition.

reference: ``simulation/sp/classical_vertical_fl/vfl_api.py`` (253 LoC) +
``party_models.py``, MPI variant ``simulation/mpi/classical_vertical_fl/``
(guest_trainer.py/host_trainer.py). Protocol semantics preserved: the host
never sees labels, the guest never sees host features; what crosses the party
boundary is the host's intermediate representation (forward) and the gradient
w.r.t. that representation (backward) — here realized by splitting the joint
gradient by party param tree, which computes exactly those exchanged tensors.
"""

from __future__ import annotations

import logging
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..models.vfl import InteractiveHead, PartyEncoder

logger = logging.getLogger(__name__)


class VerticalFLAPI:
    def __init__(self, args, device, dataset, model=None):
        self.args = args
        self.ds = dataset
        feat_dim = int(np.prod(dataset.train_x.shape[2:]))
        self.split = feat_dim // 2  # guest gets [:split], host the rest
        k = int(getattr(args, "vfl_hidden_dim", 32))
        self.guest_enc = PartyEncoder((64, k))
        self.host_enc = PartyEncoder((64, k))
        self.head = InteractiveHead(dataset.class_num)
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        kg, kh, kt = jax.random.split(rng, 3)
        dummy_g = jnp.zeros((1, self.split))
        dummy_h = jnp.zeros((1, feat_dim - self.split))
        self.params = {
            "guest": self.guest_enc.init(kg, dummy_g),
            "host": self.host_enc.init(kh, dummy_h),
            "head": self.head.init(kt, jnp.zeros((1, k))),
        }
        self.opt = optax.sgd(float(getattr(args, "learning_rate", 0.05)))
        self.opt_state = self.opt.init(self.params)
        self.batch_size = int(getattr(args, "batch_size", 32))

        def loss_fn(params, xg, xh, yb):
            g = self.guest_enc.apply(params["guest"], xg)
            h = self.host_enc.apply(params["host"], xh)
            logits = self.head.apply(params["head"], g + h)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb
            ).mean()

        @jax.jit
        def step(params, opt_state, xg, xh, yb):
            loss, grads = jax.value_and_grad(loss_fn)(params, xg, xh, yb)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._step = step

        @jax.jit
        def predict(params, xg, xh):
            g = self.guest_enc.apply(params["guest"], xg)
            h = self.host_enc.apply(params["host"], xh)
            return self.head.apply(params["head"], g + h)

        self._predict = predict
        self.history = []

    def _flat(self, x):
        return np.asarray(x).reshape(x.shape[0], -1)

    def train(self) -> Dict[str, float]:
        # VFL uses the centralized sample set (all clients' rows share ids)
        X = self._flat(
            self.ds.train_x.reshape((-1,) + self.ds.train_x.shape[2:])
        )
        Y = self.ds.train_y.reshape(-1)
        keep = np.concatenate([
            np.arange(c) + i * self.ds.cap
            for i, c in enumerate(self.ds.train_counts)
        ])
        X, Y = X[keep], Y[keep]
        rs = np.random.RandomState(int(getattr(self.args, "random_seed", 0)))
        rounds = int(self.args.comm_round)
        bs = self.batch_size
        last: Dict[str, float] = {}
        for r in range(rounds):
            perm = rs.permutation(len(X))
            losses = []
            for i in range(0, len(X) - bs + 1, bs):
                idx = perm[i : i + bs]
                xb, yb = X[idx], Y[idx].astype(np.int32)
                self.params, self.opt_state, loss = self._step(
                    self.params, self.opt_state,
                    jnp.asarray(xb[:, : self.split]),
                    jnp.asarray(xb[:, self.split :]),
                    jnp.asarray(yb),
                )
                losses.append(float(loss))
            Xt = self._flat(self.ds.test_x)
            logits = self._predict(
                self.params, jnp.asarray(Xt[:, : self.split]),
                jnp.asarray(Xt[:, self.split :]),
            )
            acc = float(
                (jnp.argmax(logits, -1) == jnp.asarray(self.ds.test_y)).mean()
            )
            last = {"test_acc": acc, "train_loss": float(np.mean(losses))}
            self.history.append({"round": r, **last})
            logger.info("vfl round %d: loss=%.4f acc=%.4f", r,
                        last["train_loss"], acc)
        return last
