"""Simulator facades (reference: ``python/fedml/simulation/simulator.py``).

``SimulatorSingleProcess`` (reference :25-56) and ``SimulatorMesh`` — the
TPU-native replacement for both SimulatorMPI (:59-174) and SimulatorNCCL
(:177-189); process-per-worker becomes shard-per-worker (see mesh_api.py).
Per-optimizer dispatch mirrors the reference's ``args.federated_optimizer``
branching.
"""

from __future__ import annotations

from .. import constants
from .mesh_api import MeshFedAvgAPI
from .sp_api import FedAvgAPI

_FEDAVG_FAMILY = (
    constants.FEDML_FEDERATED_OPTIMIZER_FEDAVG,
    constants.FEDML_FEDERATED_OPTIMIZER_FEDAVG_SEQ,
    constants.FEDML_FEDERATED_OPTIMIZER_FEDPROX,
    constants.FEDML_FEDERATED_OPTIMIZER_FEDOPT,
    constants.FEDML_FEDERATED_OPTIMIZER_FEDNOVA,
    constants.FEDML_FEDERATED_OPTIMIZER_FEDSGD,
    constants.FEDML_FEDERATED_OPTIMIZER_SCAFFOLD,
)


class SimulatorSingleProcess:
    def __init__(self, args, device, dataset, model, client_trainer=None,
                 server_aggregator=None):
        opt = args.federated_optimizer
        if opt in _FEDAVG_FAMILY:
            self.fl_trainer = FedAvgAPI(
                args, device, dataset, model, client_trainer, server_aggregator
            )
        elif opt == constants.FEDML_FEDERATED_OPTIMIZER_HIERARCHICAL_FL:
            from .hierarchical_api import HierarchicalFLAPI

            self.fl_trainer = HierarchicalFLAPI(
                args, device, dataset, model, client_trainer, server_aggregator
            )
        elif opt == constants.FEDML_FEDERATED_OPTIMIZER_DECENTRALIZED_FL:
            from .decentralized_api import DecentralizedFLAPI

            self.fl_trainer = DecentralizedFLAPI(args, device, dataset, model)
        elif opt == constants.FEDML_FEDERATED_OPTIMIZER_VFL:
            from .vfl_api import VerticalFLAPI

            self.fl_trainer = VerticalFLAPI(args, device, dataset, model)
        elif opt == constants.FEDML_FEDERATED_OPTIMIZER_SPLIT_NN:
            from .split_nn_api import SplitNNAPI

            self.fl_trainer = SplitNNAPI(args, device, dataset, model)
        elif opt == constants.FEDML_FEDERATED_OPTIMIZER_TURBOAGGREGATE:
            from .turboaggregate_api import TurboAggregateAPI

            self.fl_trainer = TurboAggregateAPI(args, device, dataset, model)
        elif opt == constants.FEDML_FEDERATED_OPTIMIZER_FEDGKT:
            from .fedgkt_api import FedGKTAPI

            self.fl_trainer = FedGKTAPI(args, device, dataset, model)
        elif opt == constants.FEDML_FEDERATED_OPTIMIZER_FEDSEG:
            from .fedseg_api import FedSegAPI

            self.fl_trainer = FedSegAPI(
                args, device, dataset, model, client_trainer, server_aggregator
            )
        elif opt == constants.FEDML_FEDERATED_OPTIMIZER_FEDGAN:
            from .fedgan_api import FedGanAPI

            self.fl_trainer = FedGanAPI(args, device, dataset, model)
        elif opt == constants.FEDML_FEDERATED_OPTIMIZER_FEDNAS:
            from .fednas_api import FedNASAPI

            self.fl_trainer = FedNASAPI(args, device, dataset, model)
        else:
            raise ValueError(f"unsupported federated_optimizer {opt!r}")

    def run(self):
        return self.fl_trainer.train()


class SimulatorMesh:
    """Cohort sharded over the ``clients`` mesh axis (replaces MPI + NCCL)."""

    def __init__(self, args, device, dataset, model, client_trainer=None,
                 server_aggregator=None):
        self.fl_trainer = MeshFedAvgAPI(
            args, device, dataset, model, client_trainer, server_aggregator
        )

    def run(self):
        return self.fl_trainer.train()
