"""FedSeg: federated semantic segmentation.

reference: ``simulation/mpi/fedseg/`` (FedSegAPI.py, FedSegTrainer.py,
utils.py Evaluator — pixel accuracy + mIoU over pascal_voc/cityscapes).

TPU-first: the per-algorithm runtime collapses into the fused sp engine —
the segmentation task enters through the loss registry
(``ml/losses.segmentation_loss``: per-pixel CE) and the model zoo (``fcn``/
``deeplab``), so client training IS the vmapped FedAvg kernel. This class
only adds what is segmentation-specific: the mIoU evaluation pass
(reference utils.py Evaluator.Mean_Intersection_over_Union).
"""

from __future__ import annotations

import logging
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from .sp_api import FedAvgAPI

logger = logging.getLogger(__name__)


def make_miou_fn(bundle, num_classes: int, batch_size: int = 64):
    """jit'd confusion-matrix accumulation → per-class IoU."""

    @jax.jit
    def confusion_batch(params, bx, by):
        logits = bundle.apply(params, bx, train=False)
        pred = jnp.argmax(logits, -1).reshape(-1)
        true = by.reshape(-1)
        idx = true * num_classes + pred
        return jnp.bincount(idx, length=num_classes * num_classes)

    def miou(params, test_x, test_y) -> Dict[str, float]:
        cm = np.zeros(num_classes * num_classes, np.int64)
        for i in range(0, test_x.shape[0], batch_size):
            cm += np.asarray(confusion_batch(
                params,
                jnp.asarray(test_x[i:i + batch_size]),
                jnp.asarray(test_y[i:i + batch_size]).astype(jnp.int32),
            ))
        cm = cm.reshape(num_classes, num_classes)
        inter = np.diag(cm).astype(np.float64)
        union = cm.sum(0) + cm.sum(1) - np.diag(cm)
        present = union > 0
        iou = inter[present] / np.maximum(union[present], 1)
        return {
            "test_miou": float(iou.mean()) if present.any() else 0.0,
            "pixel_acc": float(inter.sum() / max(cm.sum(), 1)),
        }

    return miou


class FedSegAPI(FedAvgAPI):
    """FedAvg over a segmentation model + mIoU evaluation."""

    def __init__(self, args, device, dataset, model, client_trainer=None,
                 server_aggregator=None):
        if dataset.task != "segmentation":
            raise ValueError(
                f"FedSeg needs a segmentation dataset, got task {dataset.task!r}"
            )
        super().__init__(args, device, dataset, model, client_trainer,
                         server_aggregator)
        self._miou = make_miou_fn(model, dataset.class_num)

    def train(self):
        result = super().train()
        extra = self._miou(self.global_params, self.ds.test_x, self.ds.test_y)
        logger.info("fedseg final: %s", extra)
        result = dict(result or {})
        result.update(extra)
        return result
