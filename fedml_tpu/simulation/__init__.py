"""``fedml_tpu.simulation`` — the Parrot pillar (FL simulation)."""

from .simulator import SimulatorMesh, SimulatorSingleProcess

__all__ = ["SimulatorMesh", "SimulatorSingleProcess"]
