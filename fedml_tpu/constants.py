"""Framework-wide constants.

Mirrors the role of the reference's ``python/fedml/constants.py:1-44`` (platform
names, backend names, federated-optimizer registry), re-grounded for a TPU-native
stack: the simulation backends are single-process ("sp") and a TPU device-mesh
backend ("mesh") that replaces the reference's MPI/NCCL process-per-worker model.
"""

# ---------------------------------------------------------------------------
# Training platforms (reference: constants.py:2-5)
# ---------------------------------------------------------------------------
FEDML_TRAINING_PLATFORM_SIMULATION = "simulation"
FEDML_TRAINING_PLATFORM_CROSS_SILO = "cross_silo"
FEDML_TRAINING_PLATFORM_CROSS_DEVICE = "cross_device"
FEDML_TRAINING_PLATFORM_DISTRIBUTED = "distributed"  # "Cheetah" — real here, stub in ref

# ---------------------------------------------------------------------------
# Simulation backends (reference: constants.py:7-9 — sp / MPI / NCCL).
# TPU-native: "sp" keeps the single-process semantics; "mesh" maps simulated FL
# clients onto a jax.sharding.Mesh axis (replaces both MPI and NCCL backends).
# ---------------------------------------------------------------------------
FEDML_SIMULATION_TYPE_SP = "sp"
FEDML_SIMULATION_TYPE_MESH = "mesh"
SIMULATION_BACKENDS = (FEDML_SIMULATION_TYPE_SP, FEDML_SIMULATION_TYPE_MESH)

# Cross-silo / cross-device transports (reference: fedml_comm_manager.py:72-133).
COMM_BACKEND_LOOPBACK = "LOOPBACK"  # in-process test fixture (absent in reference)
COMM_BACKEND_GRPC = "GRPC"
COMM_BACKEND_TCP = "TCP"
COMM_BACKEND_MQTT = "MQTT"  # broker plane (control only; payload store = S3 split)
COMM_BACKENDS = (
    COMM_BACKEND_LOOPBACK, COMM_BACKEND_GRPC, COMM_BACKEND_TCP,
    COMM_BACKEND_MQTT,
)

# Cross-silo scenarios (reference: constants.py:26-28)
FEDML_CROSS_SILO_SCENARIO_HORIZONTAL = "horizontal"
FEDML_CROSS_SILO_SCENARIO_HIERARCHICAL = "hierarchical"

# ---------------------------------------------------------------------------
# Federated optimizers (reference: constants.py:29-44 declares 16 names)
# ---------------------------------------------------------------------------
FEDML_FEDERATED_OPTIMIZER_FEDAVG = "FedAvg"
FEDML_FEDERATED_OPTIMIZER_FEDAVG_SEQ = "FedAvg_seq"
FEDML_FEDERATED_OPTIMIZER_FEDOPT = "FedOpt"
FEDML_FEDERATED_OPTIMIZER_FEDPROX = "FedProx"
FEDML_FEDERATED_OPTIMIZER_FEDNOVA = "FedNova"
FEDML_FEDERATED_OPTIMIZER_FEDSGD = "FedSGD"
FEDML_FEDERATED_OPTIMIZER_FEDDYN = "FedDyn"
FEDML_FEDERATED_OPTIMIZER_SCAFFOLD = "SCAFFOLD"
FEDML_FEDERATED_OPTIMIZER_MIME = "Mime"
FEDML_FEDERATED_OPTIMIZER_FEDGAN = "FedGAN"
FEDML_FEDERATED_OPTIMIZER_FEDGKT = "FedGKT"
FEDML_FEDERATED_OPTIMIZER_FEDNAS = "FedNAS"
FEDML_FEDERATED_OPTIMIZER_FEDSEG = "FedSeg"
FEDML_FEDERATED_OPTIMIZER_SPLIT_NN = "SplitNN"
FEDML_FEDERATED_OPTIMIZER_VFL = "vertical_fl"
FEDML_FEDERATED_OPTIMIZER_DECENTRALIZED_FL = "decentralized_fl"
FEDML_FEDERATED_OPTIMIZER_HIERARCHICAL_FL = "hierarchical_fl"
FEDML_FEDERATED_OPTIMIZER_TURBOAGGREGATE = "turboaggregate"
FEDML_FEDERATED_OPTIMIZER_LSA = "LSA"  # LightSecAgg

# ---------------------------------------------------------------------------
# Mesh axis names used throughout the framework
# ---------------------------------------------------------------------------
MESH_AXIS_CLIENTS = "clients"   # FL simulation: one shard = a slice of clients
MESH_AXIS_DATA = "data"         # Cheetah: data parallel
MESH_AXIS_FSDP = "fsdp"         # Cheetah: fully-sharded data parallel
MESH_AXIS_TENSOR = "tensor"     # Cheetah: tensor parallel (MXU-aligned sharding)
MESH_AXIS_SEQUENCE = "sequence" # Cheetah: sequence/context parallel (ring attention)
MESH_AXIS_EXPERT = "expert"     # Cheetah: expert parallel (MoE)
MESH_AXIS_PIPELINE = "pipeline" # Cheetah: pipeline parallel

# ---------------------------------------------------------------------------
# Persistent XLA compilation cache
# ---------------------------------------------------------------------------
# Default cache dir the bench harness writes (bench.py) and the `fedml cache`
# CLI inspects/clears — one constant so they can never point at different
# directories.
BENCH_COMPILE_CACHE_DIR_DEFAULT = "/tmp/fedml_tpu_bench_jax_cache"
