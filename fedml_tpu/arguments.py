"""Config system: one YAML file with sectioned families flattened into a single
typed attribute namespace.

Mirrors the reference's ``python/fedml/arguments.py:33-190`` (argparse ``--cf`` /
``--run_id`` / ``--rank`` / ``--role`` + YAML section families flattened into flat
attributes, last key wins) and upgrades it with what the survey flags as missing
(SURVEY.md §5 "Config / flag system"): a typed, validated schema with defaults and
helpful errors, while keeping the one-file UX.
"""

from __future__ import annotations

import argparse
from typing import Any, Dict, Optional

import yaml

from . import constants

# The reference flattens these YAML families into one namespace
# (arguments.py:163-166). We accept arbitrary families but recognise these.
KNOWN_FAMILIES = (
    "common_args",
    "data_args",
    "model_args",
    "train_args",
    "validation_args",
    "device_args",
    "comm_args",
    "tracking_args",
    "security_args",
    "attack_args",
    "defense_args",
    "dp_args",
    "parallel_args",
    "checkpoint_args",
)

# Typed schema: name -> (type, default). Anything not listed is passed through
# untyped (the reference has no schema at all; we validate what we know).
_SCHEMA: Dict[str, tuple] = {
    # common
    "training_type": (str, constants.FEDML_TRAINING_PLATFORM_SIMULATION),
    "random_seed": (int, 0),
    "scenario": (str, constants.FEDML_CROSS_SILO_SCENARIO_HORIZONTAL),
    "config_version": (str, "release"),
    # data
    "dataset": (str, "synthetic"),
    "data_cache_dir": (str, "./data_cache"),
    "partition_method": (str, "hetero"),
    "partition_alpha": (float, 0.5),
    "batch_size": (int, 32),
    # model
    "model": (str, "lr"),
    # train
    "federated_optimizer": (str, constants.FEDML_FEDERATED_OPTIMIZER_FEDAVG),
    "client_id_list": (str, "[]"),
    "client_num_in_total": (int, 10),
    "client_num_per_round": (int, 10),
    "comm_round": (int, 10),
    "epochs": (int, 1),
    "client_optimizer": (str, "sgd"),
    "learning_rate": (float, 0.03),
    "momentum": (float, 0.0),
    "weight_decay": (float, 0.0),
    "server_optimizer": (str, "sgd"),
    "server_lr": (float, 1.0),
    "server_momentum": (float, 0.0),
    "fedprox_mu": (float, 0.1),
    "clip_grad": (float, 0.0),
    # validation
    "frequency_of_the_test": (int, 5),
    # device
    "using_gpu": (bool, False),  # kept for config compat; TPU/CPU decided by JAX
    "device_type": (str, "auto"),  # auto | tpu | cpu
    "mesh_shape": (str, ""),  # e.g. "clients:8" or "data:2,tensor:4"
    # comm
    "backend": (str, constants.FEDML_SIMULATION_TYPE_SP),
    "grpc_ipconfig_path": (str, ""),
    "comm_host": (str, "127.0.0.1"),
    "comm_port": (int, 8890),
    # tracking / telemetry (core/mlops/telemetry.py)
    "enable_tracking": (bool, False),
    "tracking_dir": (str, ""),  # JSONL event sink dir (default .fedml_tpu_runs)
    # write-behind JSONL sink drain interval (core/mlops/__init__.py):
    # events buffer in memory and hit the disk every this-many seconds
    # (or at 256 buffered events, or at shutdown). 0 = flush per event.
    "tracking_flush_s": (float, 0.5),
    # distributed tracing (core/mlops/tracing.py, docs/tracing.md):
    # cross-process causal spans + flight recorder. trace_sample is the
    # deterministic per-round sampling probability for soak-scale runs;
    # trace_dir overrides where flight-recorder post-mortems land
    # (default: tracking_dir).
    "enable_tracing": (bool, False),
    "trace_sample": (float, 1.0),
    "trace_dir": (str, ""),
    "enable_wandb": (bool, False),
    # Prometheus-style text exposition of the metrics registry, refreshed
    # during the run and at exit. Empty = no file.
    "metrics_file": (str, ""),
    # jax.profiler trace window over rounds/steps [N, M): "N:M" (bare "N"
    # traces one round). Works with or without enable_tracking.
    "profile_rounds": (str, ""),
    "profile_dir": (str, ""),  # trace output dir (default: tracking dir)
    # periodic host CPU/RSS + HBM sampler (daemon thread); 0 = off
    "sys_perf_interval_s": (float, 0.0),
    "run_id": (str, "0"),
    "rank": (int, 0),
    "local_rank": (int, 0),
    "node_rank": (int, 0),
    "role": (str, "client"),
    # security
    "enable_attack": (bool, False),
    "attack_type": (str, ""),
    "enable_defense": (bool, False),
    "defense_type": (str, ""),
    # dp
    "enable_dp": (bool, False),
    "mechanism_type": (str, "laplace"),
    "epsilon": (float, 1.0),
    "delta": (float, 1e-5),
    "sensitivity": (float, 1.0),
    "dp_type": (str, "cdp"),  # cdp (central) | ldp (local)
    # checkpointing (absent in reference — SURVEY.md §5 "Checkpoint / resume")
    "checkpoint_dir": (str, ""),
    "checkpoint_every_rounds": (int, 0),
    # crash-safe rounds (core/runstate.py): checkpoint_rounds is the
    # preferred cadence knob (checkpoint_every_rounds kept as an alias);
    # resume ∈ auto|never|require decides what an existing checkpoint dir
    # means at startup; preempt_signals installs the SIGTERM/SIGINT
    # drain-and-commit handler whenever checkpointing is on
    "checkpoint_rounds": (int, 0),
    "resume": (str, "auto"),
    "preempt_signals": (bool, True),
    # idempotent at-least-once delivery (core/distributed/delivery.py):
    # sender-side retry budget (exponential backoff + jitter) and the
    # receiver-side dedup window (per-sender seqs remembered)
    "comm_retry_max_attempts": (int, 4),
    "comm_retry_backoff_s": (float, 0.05),
    "comm_retry_backoff_max_s": (float, 2.0),
    "comm_dedup_window": (int, 4096),
    # MQTT subscribe-confirmation retry budget (mqtt_backend.py)
    "mqtt_subscribe_retries": (int, 5),
    "mqtt_subscribe_timeout_s": (float, 6.0),
    # round engine (simulation/round_engine.py)
    # round_fusion: auto fuses the FedAvg-family round into ONE donated XLA
    # program whenever no host-side hook blocks it; on demands it; off keeps
    # the legacy multi-dispatch path (the parity reference).
    "round_fusion": (str, "auto"),  # auto | on | off
    # superround_k > 1 runs K rounds per device-program launch under
    # lax.scan with ON-DEVICE client sampling (needs the HBM-resident
    # single-device path; cohort trajectory differs from host sampling
    # except under full participation). 0/1 = off.
    "superround_k": (int, 0),
    # sp cohort execution: vmap | map | auto (see FedAvgAPI.cohort_impl)
    "sp_cohort_impl": (str, ""),
    # million-client cohort substrate (fedml_tpu/scale/ — docs/scale.md).
    # client_registry: a client count ("1000000" registers N virtual
    # clients over the dataset's shards) or a path to a registry saved
    # with ClientRegistry.save; empty = off (legacy sampling).
    "client_registry": (str, ""),
    # sampled clients per round at registry scale (0 = client_num_per_round
    # capped to the registry). Static per run — never a recompile source.
    "cohort_size": (int, 0),
    # cohorts prefetched ahead of the round (host→HBM double buffering);
    # 0 disables streaming (synchronous gather, same semantics)
    "cohort_prefetch": (int, 1),
    # synthetic-registry sampling-weight skew: Gamma(k) heterogeneous
    # participation propensities; 0 = uniform weights
    "registry_weight_concentration": (float, 0.0),
    # mesh placement rules (scale/partition_rules.py syntax, e.g.
    # "cohort/.*=clients;.*="): cohort-plane and round-state leaf
    # placement; empty = the built-in first-axis/replicated defaults
    "mesh_partition_rules": (str, ""),
    "mesh_state_rules": (str, ""),
    # persistent XLA compilation cache — repeat runs (and bench legs) skip
    # the compile wall entirely. Empty = disabled. Wired in fedml.init().
    "compilation_cache_dir": (str, ""),
    # async traffic plane (fedml_tpu/traffic/ — docs/traffic.md).
    # aggregation_mode: sync keeps the per-round cohort barrier (the
    # reference semantics, bitwise-unchanged); async is FedBuff-style
    # buffered aggregation — staleness-weighted updates fold as they
    # arrive, a server step fires per async_buffer_size accepted updates.
    "aggregation_mode": (str, "sync"),
    # updates per server step (K); 0 = min(10, client count), the FedBuff
    # paper default capped to the world size
    "async_buffer_size": (int, 0),
    # staleness decay exponent: weight = num_samples * (1+s)^-alpha;
    # 0 = flat weights (the sync-parity setting)
    "async_staleness_alpha": (float, 0.0),
    # drop updates staler than this many versions (the sender gets a fresh
    # model so it rejoins at version head); 0 = accept any staleness
    "async_max_staleness": (int, 0),
    # flush a partial buffer after this many seconds without progress so a
    # dropped-out tail cohort can't wedge the federation; 0 = never
    "async_flush_s": (float, 10.0),
    # admission control on C2S_SEND_MODEL: token-bucket rate (updates/s;
    # 0 = unlimited) + burst (0 = 2x buffer) and the bounded fold-queue
    # depth (0 = 4x buffer). Overload degrades to shed/NACK-retry-after.
    "async_admit_rate": (float, 0.0),
    "async_admit_burst": (int, 0),
    "async_queue_limit": (int, 0),
    # delta delivery plane (fedml_tpu/delivery/ — docs/delivery.md).
    # C2S update compression (core/compression.UpdateCodec): "" = off;
    # topk | eftopk | qsgd | quantize, with the scheme knobs below. Deltas
    # decode against the version-indexed model store, so compression now
    # composes with aggregation_mode=async.
    "compression": (str, ""),
    "compression_ratio": (float, 0.1),
    "quantize_bits": (int, 8),
    "qsgd_levels": (int, 256),
    # S2C delta shipping: auto (default — codec-encoded LOSSLESS delta
    # against the client's last-ACKed version whenever that base is still
    # in the store, loud full-frame fallback otherwise) | off
    "s2c_delta": (str, "auto"),
    # which implementation serves delta encode/decode: host (numpy
    # reference), device (jit'd kernels + dlpack emission), or auto
    # (device when JAX is importable). PERFORMANCE knob only — frames are
    # byte-identical across paths, so this is deliberately NOT part of
    # delivery_identity
    "wire_path": (str, "auto"),
    # bounded ring of committed global versions both wire ends keep
    # (VersionedModelStore capacity); also bounds how stale a compressed
    # C2S delta can be and still decode
    "delta_store_versions": (int, 8),
    # adapter-only payloads: regex over named pytree leaves (the
    # scale/partition_rules naming); matching leaves ride the C2S wire,
    # the rest stay frozen at the server's global. "" = full payloads.
    "payload_filter": (str, ""),
    # FedBuff dispatch policy (aggregation_mode=async): sync_on_consume
    # (dispatch to a step's contributors — the FedBuff default) |
    # server_push (push every version bump to all live clients) |
    # client_pull (clients request via c2s_pull_request; the server
    # answers when the version advances)
    "async_dispatch": (str, "sync_on_consume"),
    # gRPC wire format: raw (zero-copy tensor frames, the default) | npz
    # (the self-describing fallback; mixed worlds interoperate — decode
    # sniffs the body magic)
    "grpc_wire_format": (str, "raw"),
    # gRPC rank→port multiplexing: N ranks share one port/server process
    # (port = comm_port + ceil(rank / N)); 1 = legacy port-per-rank
    "grpc_ranks_per_port": (int, 1),
    # survivable serving plane (docs/robustness.md). round_deadline_s
    # closes a sync round after this many seconds with the K' <= K
    # updates that arrived (reweighted exactly — bitwise-equal to
    # full-cohort FedAvg when nobody straggles) and folds LATE arrivals
    # into the current round through the async staleness path
    # ((1+s)^-async_staleness_alpha) instead of discarding them; 0 = off
    # (the legacy round_timeout knob keeps its drop-the-stragglers
    # semantics). min_clients_per_round bounds how small a deadline
    # cohort may get.
    "round_deadline_s": (float, 0.0),
    "min_clients_per_round": (int, 1),
    # client liveness/resync FSM: heartbeat_s > 0 sends a heartbeat
    # lease every interval; heartbeat_miss_limit missed intervals
    # without ANY server traffic declare the connection lost and start
    # the bounded-exponential resync loop (c2s_resync every
    # resync_backoff_s * 2^k, capped, at most resync_max_attempts).
    "heartbeat_s": (float, 0.0),
    "heartbeat_miss_limit": (int, 3),
    "resync_backoff_s": (float, 0.5),
    "resync_backoff_max_s": (float, 10.0),
    "resync_max_attempts": (int, 30),
}

COMPRESSION_SCHEMES = ("", "topk", "eftopk", "qsgd", "quantize")
ASYNC_DISPATCH_POLICIES = ("sync_on_consume", "server_push", "client_pull")


class Arguments:
    """Flat attribute namespace loaded from a sectioned YAML file.

    Reference behavior preserved (arguments.py:62-166): families flattened,
    last key wins, command-line rank/run_id/role merged in. Added: typed
    coercion + defaults from ``_SCHEMA``.
    """

    def __init__(
        self,
        cmd_args: Optional[argparse.Namespace] = None,
        training_type: Optional[str] = None,
        comm_backend: Optional[str] = None,
        overrides: Optional[Dict[str, Any]] = None,
    ):
        # defaults first
        for key, (_, default) in _SCHEMA.items():
            setattr(self, key, default)
        # YAML config, then explicitly passed CLI flags back on top: an
        # absent flag (None) defers to the YAML key, a passed flag wins
        if cmd_args is not None:
            passed = {k: v for k, v in vars(cmd_args).items()
                      if v is not None}
            for k, v in passed.items():
                setattr(self, k, v)
            cf = getattr(cmd_args, "yaml_config_file", None) or getattr(
                cmd_args, "cf", None
            )
            if cf:
                self.load_yaml_config(cf)
                for k, v in passed.items():
                    if k not in ("yaml_config_file", "cf"):
                        self._set_typed(k, v)
        if training_type:
            self.training_type = training_type
        if comm_backend:
            self.backend = comm_backend
        if overrides:
            for k, v in overrides.items():
                self._set_typed(k, v)
        self.validate()

    # -- YAML loading (reference: arguments.py:62-166) ----------------------
    def load_yaml_config(self, yaml_path: str) -> None:
        with open(yaml_path, "r") as f:
            cfg = yaml.safe_load(f) or {}
        self.set_attr_from_config(cfg)
        self.yaml_config_file = yaml_path

    def set_attr_from_config(self, configuration: Dict[str, Any]) -> None:
        for family, family_cfg in configuration.items():
            if isinstance(family_cfg, dict):
                for k, v in family_cfg.items():
                    self._set_typed(k, v)
            else:
                self._set_typed(family, family_cfg)

    def _set_typed(self, key: str, value: Any) -> None:
        if key in _SCHEMA:
            typ, _ = _SCHEMA[key]
            if value is not None and not isinstance(value, typ):
                try:
                    if typ is bool and isinstance(value, str):
                        lowered = value.strip().lower()
                        if lowered in ("1", "true", "yes", "on"):
                            value = True
                        elif lowered in ("0", "false", "no", "off", ""):
                            value = False
                        else:
                            raise ValueError(f"not a boolean: {value!r}")
                    else:
                        value = typ(value)
                except (TypeError, ValueError) as e:
                    raise ValueError(
                        f"config key '{key}' expects {typ.__name__}, got "
                        f"{value!r}: {e}"
                    ) from None
        setattr(self, key, value)

    # -- validation (absent in reference; SURVEY.md §5 flags this gap) ------
    def validate(self) -> None:
        if self.training_type not in (
            constants.FEDML_TRAINING_PLATFORM_SIMULATION,
            constants.FEDML_TRAINING_PLATFORM_CROSS_SILO,
            constants.FEDML_TRAINING_PLATFORM_CROSS_DEVICE,
            constants.FEDML_TRAINING_PLATFORM_DISTRIBUTED,
        ):
            raise ValueError(f"unknown training_type: {self.training_type!r}")
        if (
            self.training_type == constants.FEDML_TRAINING_PLATFORM_SIMULATION
            and self.backend not in constants.SIMULATION_BACKENDS
        ):
            raise ValueError(
                f"simulation backend must be one of {constants.SIMULATION_BACKENDS},"
                f" got {self.backend!r}"
            )
        if self.client_num_per_round > self.client_num_in_total:
            raise ValueError(
                f"client_num_per_round ({self.client_num_per_round}) > "
                f"client_num_in_total ({self.client_num_in_total})"
            )
        if int(getattr(self, "cohort_size", 0) or 0) > 0 and not str(
            getattr(self, "client_registry", "") or ""
        ).strip():
            raise ValueError(
                "cohort_size requires client_registry (the registry defines "
                "the population the cohort is sampled from)"
            )
        if int(getattr(self, "cohort_size", 0) or 0) < 0:
            raise ValueError("cohort_size must be >= 0")
        mode = str(getattr(self, "aggregation_mode", "sync") or "sync")
        if mode.lower() not in ("sync", "async"):
            raise ValueError(
                f"aggregation_mode must be sync|async, got {mode!r}"
            )
        for non_negative in ("async_buffer_size", "async_max_staleness",
                             "async_admit_rate", "async_queue_limit",
                             "async_staleness_alpha", "async_flush_s",
                             "async_admit_burst", "round_deadline_s",
                             "heartbeat_s", "resync_backoff_s",
                             "resync_backoff_max_s", "resync_max_attempts"):
            if float(getattr(self, non_negative, 0) or 0) < 0:
                raise ValueError(f"{non_negative} must be >= 0")
        if float(getattr(self, "tracking_flush_s", 0.5) or 0) < 0:
            raise ValueError("tracking_flush_s must be >= 0")
        sample = float(getattr(self, "trace_sample", 1.0) or 0.0)
        if not 0.0 <= sample <= 1.0:
            raise ValueError(
                f"trace_sample must be in [0, 1], got {sample}")
        # delta delivery plane (docs/delivery.md)
        scheme = str(getattr(self, "compression", "") or "").lower()
        if scheme not in COMPRESSION_SCHEMES:
            raise ValueError(
                f"compression must be one of {COMPRESSION_SCHEMES}, "
                f"got {scheme!r}"
            )
        s2c = str(getattr(self, "s2c_delta", "auto") or "auto").lower()
        if s2c not in ("auto", "off"):
            raise ValueError(f"s2c_delta must be auto|off, got {s2c!r}")
        wire = str(getattr(self, "wire_path", "auto") or "auto").lower()
        if wire not in ("host", "device", "auto"):
            raise ValueError(
                f"wire_path must be host|device|auto, got {wire!r}")
        if int(getattr(self, "delta_store_versions", 8) or 0) < 1:
            raise ValueError("delta_store_versions must be >= 1")
        dispatch = str(
            getattr(self, "async_dispatch", "sync_on_consume")
            or "sync_on_consume").lower()
        if dispatch not in ASYNC_DISPATCH_POLICIES:
            raise ValueError(
                f"async_dispatch must be one of {ASYNC_DISPATCH_POLICIES}, "
                f"got {dispatch!r}"
            )
        if dispatch != "sync_on_consume" and mode.lower() != "async":
            raise ValueError(
                f"async_dispatch={dispatch} is a FedBuff dispatch policy — "
                "it requires aggregation_mode=async"
            )
        pattern = str(getattr(self, "payload_filter", "") or "")
        if pattern:
            import re as _re

            try:
                _re.compile(pattern)
            except _re.error as e:
                raise ValueError(
                    f"bad payload_filter regex {pattern!r}: {e}") from None
        if str(getattr(self, "grpc_wire_format", "raw")).lower() not in (
                "raw", "npz"):
            raise ValueError(
                f"grpc_wire_format must be raw|npz, got "
                f"{getattr(self, 'grpc_wire_format')!r}"
            )
        if int(getattr(self, "grpc_ranks_per_port", 1) or 1) < 1:
            raise ValueError("grpc_ranks_per_port must be >= 1")
        for positive in ("batch_size", "comm_round", "epochs"):
            if getattr(self, positive) <= 0:
                raise ValueError(f"{positive} must be positive")

    # -- misc ---------------------------------------------------------------
    def get(self, key: str, default: Any = None) -> Any:
        return getattr(self, key, default)

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in self.__dict__.items() if not k.startswith("_")}

    def __repr__(self) -> str:  # pragma: no cover
        return f"Arguments({self.to_dict()!r})"

    def parse_mesh_shape(self) -> Dict[str, int]:
        """Parse ``mesh_shape`` like ``"data:2,tensor:4"`` into an ordered dict."""
        return parse_mesh_shape(self.mesh_shape)


def parse_mesh_shape(value) -> Dict[str, int]:
    """The one parser for ``"axis:size,..."`` mesh strings (Arguments method
    and bare-namespace callers like ``cross_silo/fedllm.py`` share it)."""
    out: Dict[str, int] = {}
    if not value:
        return out
    for part in str(value).split(","):
        name, _, size = part.strip().partition(":")
        if not name or not size or not (size.lstrip("-").isdigit()):
            raise ValueError(
                f"bad mesh_shape entry {part!r}; expected 'axis:size'"
            )
        out[name] = int(size)
    return out


def add_args() -> argparse.Namespace:
    """CLI surface matching the reference (arguments.py:33-59)."""
    parser = argparse.ArgumentParser(description="fedml_tpu")
    parser.add_argument(
        "--yaml_config_file", "--cf", type=str, default="", help="yaml config file"
    )
    # defaults None throughout: _SCHEMA supplies the real defaults, and a
    # None means "not passed" so YAML keys win only for absent flags (an
    # explicitly passed flag beats YAML — see Arguments.__init__)
    parser.add_argument("--run_id", type=str, default=None)
    parser.add_argument("--rank", type=int, default=None)
    parser.add_argument("--local_rank", type=int, default=None)
    parser.add_argument("--node_rank", type=int, default=None)
    parser.add_argument("--role", type=str, default=None)
    parser.add_argument(
        "--silo_device_indices", type=int, nargs="*", default=None,
        help="chips this silo trains over (intra-silo data parallelism)",
    )
    parser.add_argument(
        "--compilation_cache_dir", type=str, default=None,
        help="persistent XLA compilation cache dir (repeat runs skip the "
        "compile wall); also settable via YAML common_args",
    )
    # crash-safe rounds (core/runstate.py)
    parser.add_argument(
        "--checkpoint_dir", type=str, default=None,
        help="Orbax checkpoint + run-ledger dir; enables round resume and "
        "the SIGTERM/SIGINT drain-and-commit handler",
    )
    parser.add_argument(
        "--checkpoint_rounds", type=int, default=None, metavar="N",
        help="commit a checkpoint + ledger entry every N rounds",
    )
    parser.add_argument(
        "--resume", type=str, default=None,
        choices=("auto", "never", "require"),
        help="what an existing checkpoint means at startup: auto resumes "
        "when present, never demands a fresh dir, require errors without one",
    )
    # million-client cohort substrate (fedml_tpu/scale/ — docs/scale.md)
    parser.add_argument(
        "--client_registry", type=str, default=None, metavar="N|PATH",
        help="register N virtual clients over the dataset's shards (or "
        "load a saved ClientRegistry npz); cohorts sample K-of-N on device",
    )
    parser.add_argument(
        "--cohort_size", type=int, default=None, metavar="K",
        help="clients sampled per round from the registry "
        "(0 = client_num_per_round)",
    )
    parser.add_argument(
        "--cohort_prefetch", type=int, default=None, metavar="D",
        help="cohorts prefetched ahead of the round (0 disables streaming)",
    )
    parser.add_argument(
        "--mesh_partition_rules", type=str, default=None,
        help="regex=axes;... placement rules for the mesh cohort plane "
        "(docs/scale.md)",
    )
    parser.add_argument(
        "--mesh_state_rules", type=str, default=None,
        help="regex=axes;... placement rules for the mesh round state "
        "(docs/scale.md)",
    )
    # survivable serving plane (docs/robustness.md)
    parser.add_argument(
        "--round_deadline_s", type=float, default=None, metavar="S",
        help="close a sync round after S seconds with the K' <= K updates "
        "that arrived (reweighted exactly); late stragglers fold into the "
        "open round via the staleness path instead of being dropped",
    )
    parser.add_argument(
        "--heartbeat_s", type=float, default=None, metavar="S",
        help="client heartbeat/lease interval; silence past "
        "heartbeat_miss_limit intervals enters the bounded-exponential "
        "resync loop (0 = liveness plane off)",
    )
    parser.add_argument(
        "--min_clients_per_round", type=int, default=None, metavar="K",
        help="smallest cohort a round deadline may close with",
    )
    # async traffic plane (fedml_tpu/traffic/ — docs/traffic.md)
    parser.add_argument(
        "--aggregation_mode", type=str, default=None,
        choices=("sync", "async"),
        help="sync = per-round cohort barrier (reference semantics); "
        "async = FedBuff-style buffered aggregation with staleness "
        "weighting and admission control",
    )
    parser.add_argument(
        "--async_buffer_size", type=int, default=None, metavar="K",
        help="server step fires per K accepted updates "
        "(0 = min(10, client count))",
    )
    parser.add_argument(
        "--async_staleness_alpha", type=float, default=None,
        help="staleness decay exponent: weight = n * (1+s)^-alpha "
        "(0 = flat weights)",
    )
    parser.add_argument(
        "--async_max_staleness", type=int, default=None,
        help="drop updates staler than this many versions (0 = unlimited)",
    )
    parser.add_argument(
        "--async_flush_s", type=float, default=None,
        help="flush a partial async buffer after this stall (0 = never)",
    )
    parser.add_argument(
        "--async_admit_rate", type=float, default=None,
        help="token-bucket admission rate on C2S_SEND_MODEL, updates/s "
        "(0 = unlimited)",
    )
    parser.add_argument(
        "--async_admit_burst", type=int, default=None,
        help="token-bucket burst (0 = 2x buffer size)",
    )
    parser.add_argument(
        "--async_queue_limit", type=int, default=None,
        help="bounded fold-queue depth; overflow is shed with retry-after "
        "(0 = 4x buffer size)",
    )
    # delta delivery plane (fedml_tpu/delivery/ — docs/delivery.md)
    parser.add_argument(
        "--compression", type=str, default=None,
        choices=("", "topk", "eftopk", "qsgd", "quantize"),
        help="C2S update compression scheme; deltas decode against the "
        "version-indexed model store (composes with async aggregation)",
    )
    parser.add_argument(
        "--compression_ratio", type=float, default=None,
        help="top-k fraction kept by topk/eftopk",
    )
    parser.add_argument(
        "--quantize_bits", type=int, default=None,
        help="bit width for --compression quantize",
    )
    parser.add_argument(
        "--qsgd_levels", type=int, default=None,
        help="quantization levels for --compression qsgd",
    )
    parser.add_argument(
        "--s2c_delta", type=str, default=None, choices=("auto", "off"),
        help="S2C sync frames: auto ships a lossless delta against the "
        "client's last-ACKed version (full-frame fallback on store "
        "eviction); off always broadcasts full models",
    )
    parser.add_argument(
        "--wire_path", type=str, default=None,
        choices=("host", "device", "auto"),
        help="delta codec implementation: host (numpy reference), device "
        "(jit'd kernels, zero-copy emission), auto (device when JAX is "
        "available); frames are byte-identical either way",
    )
    parser.add_argument(
        "--delta_store_versions", type=int, default=None, metavar="V",
        help="committed global versions each wire end keeps for delta "
        "encode/decode (the VersionedModelStore ring size)",
    )
    parser.add_argument(
        "--payload_filter", type=str, default=None, metavar="REGEX",
        help="adapter-only payloads: leaves whose a/b/c path matches ride "
        "the C2S wire, the rest stay frozen at the server's global",
    )
    parser.add_argument(
        "--async_dispatch", type=str, default=None,
        choices=("sync_on_consume", "server_push", "client_pull"),
        help="FedBuff dispatch policy for aggregation_mode=async",
    )
    parser.add_argument(
        "--grpc_wire_format", type=str, default=None, choices=("raw", "npz"),
        help="gRPC frame format: raw zero-copy tensor frames (default) or "
        "the npz fallback",
    )
    parser.add_argument(
        "--grpc_ranks_per_port", type=int, default=None, metavar="N",
        help="gRPC rank multiplexing: N ranks share one port/server "
        "(1 = legacy port-per-rank)",
    )
    # telemetry plane (defaults None so YAML keys win when the flag is absent)
    parser.add_argument(
        "--enable_tracking", action="store_true", default=None,
        help="emit JSONL events + per-round telemetry RoundRecords",
    )
    parser.add_argument(
        "--tracking_dir", type=str, default=None,
        help="JSONL event sink directory (default .fedml_tpu_runs)",
    )
    parser.add_argument(
        "--metrics_file", type=str, default=None,
        help="write the metrics registry as Prometheus text exposition here",
    )
    parser.add_argument(
        "--profile_rounds", type=str, default=None, metavar="N:M",
        help="open a jax.profiler trace window over rounds [N, M)",
    )
    parser.add_argument(
        "--profile_dir", type=str, default=None,
        help="profiler trace output dir (default: tracking dir)",
    )
    parser.add_argument(
        "--sys_perf_interval_s", type=float, default=None,
        help="sample host CPU/RSS + HBM every N seconds (0 = off)",
    )
    parser.add_argument(
        "--tracking_flush_s", type=float, default=None, metavar="S",
        help="write-behind JSONL sink drain interval (0 = per-event)",
    )
    parser.add_argument(
        "--enable_tracing", action="store_true", default=None,
        help="cross-process causal spans + crash flight recorder "
        "(docs/tracing.md); implies a JSONL sink for span records",
    )
    parser.add_argument(
        "--trace_sample", type=float, default=None, metavar="P",
        help="deterministic per-round trace sampling probability in [0,1]",
    )
    parser.add_argument(
        "--trace_dir", type=str, default=None,
        help="flight-recorder post-mortem dir (default: tracking dir)",
    )
    args, _ = parser.parse_known_args()
    return args


def load_arguments(
    training_type: Optional[str] = None, comm_backend: Optional[str] = None
) -> Arguments:
    cmd_args = add_args()
    return Arguments(cmd_args, training_type, comm_backend)
