"""Client-swarm traffic generator: soak the cross-silo server at scale.

reference: none — the reference framework was never load-tested (one server,
a handful of loopback clients; SURVEY §5). ``fedml_tpu swarm`` drives the
REAL server FSM (``FedMLServerManager`` in ``aggregation_mode=async``)
with thousands of concurrent simulated devices:

- each device runs the genuine client-side wire protocol (ONLINE status →
  version-tagged INIT/SYNC → C2S model upload → shed/NACK backoff →
  FINISH) through the real transport (loopback broker or multiprocess
  gRPC), with **seeded processes** for think time (exponential — the
  Poisson-arrival analog per device) and dropout, so a soak is
  reproducible;
- devices are *event-driven*, not thread-per-device: over loopback a
  single pump thread drains every device mailbox and one timer wheel
  schedules the delayed sends, so 2000 devices cost 2 threads, not 2000;
- the report's headline is the **p99 dispatch→ready latency** from the PR 2
  telemetry plane (``traffic.dispatch_ready_s``: server-side admission →
  update folded), next to the backpressure counters (accepted / shed /
  stale-dropped), staleness distribution, achieved server steps, and peak
  RSS — the "bounded memory under overload" evidence.

The :class:`ProcSpawner` here is the one process-launch surface shared with
the chaos harness's multiprocess-gRPC legs (ISSUE 7 satellite).
"""

from __future__ import annotations

import heapq
import json
import logging
import os
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import constants
from ..core import world as world_mod
from ..core.distributed import FedMLCommManager, Message
from ..core.mlops import telemetry
from ..core.mlops.tracing import NULL_SPAN
from ..cross_silo.message_define import MyMessage

logger = logging.getLogger(__name__)


def rss_peak_mb() -> float:
    """Peak resident set of THIS process (ru_maxrss is KiB on Linux)."""
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
    except Exception:  # pragma: no cover - non-posix
        return 0.0


def rss_now_mb() -> float:
    """CURRENT resident set from /proc/self/status VmRSS (kB). ru_maxrss
    is a high-water mark — useless for a leak slope, which needs the live
    value falling as well as rising."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) / 1024.0
    except Exception:  # pragma: no cover - non-linux
        pass
    return 0.0


class RssSampler:
    """The graftmem runtime witness's sampler: VmRSS on a fixed cadence
    from a daemon thread, joined by :meth:`stop`.

    :meth:`slope_mb_per_s` fits a least-squares line over the STEADY-STATE
    half of the samples (the second half by time) — the first half is
    warmup (imports, first compiles, buffer fills) and would make every
    healthy soak look like a leak. A retention bug shows as a positive
    slope that persists after warmup: one entry per message/sender/round
    never released is linear growth under constant load by definition.
    """

    def __init__(self, interval_s: float = 0.2):
        self.interval_s = max(float(interval_s), 0.01)
        self._lock = threading.Lock()
        self._samples: List[Tuple[float, float]] = []  # (t_monotonic, MB)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rss-sampler")

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)

    def samples(self) -> List[Tuple[float, float]]:
        with self._lock:
            return list(self._samples)

    def _loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                self._samples.append((time.monotonic(), rss_now_mb()))
            self._stop.wait(self.interval_s)
        with self._lock:
            self._samples.append((time.monotonic(), rss_now_mb()))

    def slope_mb_per_s(self) -> Optional[float]:
        """Least-squares dRSS/dt over the steady-state (second) half; None
        with fewer than 4 steady-state samples (no signal, not a pass)."""
        samples = self.samples()
        if not samples:
            return None
        t_mid = (samples[0][0] + samples[-1][0]) / 2.0
        steady = [(t, m) for (t, m) in samples if t >= t_mid]
        if len(steady) < 4:
            return None
        n = float(len(steady))
        mean_t = sum(t for t, _ in steady) / n
        mean_m = sum(m for _, m in steady) / n
        var_t = sum((t - mean_t) ** 2 for t, _ in steady)
        if var_t <= 0.0:
            return None
        cov = sum((t - mean_t) * (m - mean_m) for t, m in steady)
        return cov / var_t


# ---------------------------------------------------------------------------
# seeded device processes
# ---------------------------------------------------------------------------


class SwarmSchedule:
    """Per-device seeded think-time + dropout process.

    Think times are exponential with mean ``think_s`` — superposed over N
    devices that is a Poisson arrival process at the server. The stream
    depends only on (seed, rank), never on wall-clock or delivery order, so
    a swarm's *schedule* is deterministic (pinned by tests/test_traffic.py).
    """

    def __init__(self, seed: int, rank: int, think_s: float,
                 dropout_p: float):
        self.rank = int(rank)
        self.think_s = float(think_s)
        self.dropout_p = float(dropout_p)
        self._rng = np.random.RandomState(
            (int(seed) * 1_000_003 + int(rank)) % (2**31 - 1))

    def next_think_s(self) -> float:
        if self.think_s <= 0:
            return 0.0
        return float(self._rng.exponential(self.think_s))

    def drops_out(self) -> bool:
        return bool(self._rng.rand() < self.dropout_p)


class TimerWheel:
    """One thread, many delayed callbacks (heapq): the thread-per-Timer
    alternative melts at swarm scale (every backoff would be an OS
    thread)."""

    def __init__(self):
        self._heap: List = []
        self._seq = 0
        self._cv = threading.Condition()
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="swarm-timers")
        self._thread.start()

    def call_later(self, delay_s: float, fn: Callable[[], None]) -> None:
        with self._cv:
            self._seq += 1
            heapq.heappush(
                self._heap, (time.monotonic() + max(delay_s, 0.0),
                             self._seq, fn))
            self._cv.notify()

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify()
        self._thread.join(timeout=5)

    def _loop(self) -> None:
        while True:
            with self._cv:
                if self._stop:
                    return
                if not self._heap:
                    self._cv.wait(timeout=0.5)
                    continue
                when, _seq, fn = self._heap[0]
                now = time.monotonic()
                if when > now:
                    self._cv.wait(timeout=min(when - now, 0.5))
                    continue
                heapq.heappop(self._heap)
            try:
                fn()
            except Exception:  # a dead server mid-shutdown: keep ticking
                logger.debug("swarm timer callback failed", exc_info=True)


# ---------------------------------------------------------------------------
# the simulated device
# ---------------------------------------------------------------------------


class SwarmClientManager(FedMLCommManager):
    """A lightweight simulated device speaking the full cross-silo client
    protocol. It does not train: after a seeded think time it echoes the
    dispatched model back as its update (num_samples=1), which exercises
    every server-side path (admission, staleness, folding, aggregation)
    with realistic payload bytes at a per-device cost that scales to
    thousands.

    With ``delta_capable=True`` the device also speaks the S2C delta plane
    (docs/delivery.md): it advertises ``delta_capable`` on its C2S
    updates, keeps a small version-indexed base store, and decodes delta
    frames against the global it last held — so a swarm soak exercises the
    server's per-base encode cache and ACK tracking at scale, not just
    full-frame dispatch."""

    def __init__(self, args, schedule: SwarmSchedule, timers: TimerWheel,
                 comm=None, rank: int = 0, size: int = 0,
                 backend: str = constants.COMM_BACKEND_LOOPBACK,
                 delta_capable: bool = False):
        super().__init__(args, comm, rank, size, backend)
        self.schedule = schedule
        self.timers = timers
        self.done = threading.Event()
        # tiered worlds: a device speaks to its home edge aggregator, not
        # the root — the same wire protocol, one hop down
        from ..hierarchy import Topology

        topo = Topology.from_args(args)
        self._server_rank = (topo.home_edge(rank)
                             if topo is not None and topo.is_client(rank)
                             else 0)
        # (_version, _arrays) is a PAIR: the receive thread updates it on
        # dispatch while the timer wheel snapshots it at send time — the
        # lock keeps a delayed send from tagging version v on version
        # v+1's payload, which would corrupt the server's staleness
        # accounting (the orchestrator itself only reads the done Event
        # and the process-wide telemetry counters)
        self._state_lock = threading.Lock()
        self._version = -1
        self._arrays: List[np.ndarray] = []
        # the dispatch's wire trace context, snapshotted WITH the version
        # it arrived under: the ambient context is thread-local to the
        # receive path, and the delayed send runs on the timer-wheel
        # thread — without this hand-off the device's upload would start a
        # fresh trace instead of continuing the server's dispatch span
        self._trace_ctx = None
        self._dropped = False
        self._delta_on = bool(delta_capable)
        self._store = None
        self._leaf_meta: Optional[List] = None
        if self._delta_on:
            from ..delivery import VersionedModelStore, WireCodec

            self._store = VersionedModelStore(
                4, metric_prefix="swarm.delta_store")
            self._wire = WireCodec(getattr(args, "wire_path", "auto"),
                                   scoped=self.world.telemetry)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_CONNECTION_IS_READY, self._on_ready
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self._on_dispatch
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self._on_dispatch
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_SHED_NOTICE, self._on_shed
        )
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_FINISH, self._on_finish
        )

    def _on_ready(self, msg: Message) -> None:
        self._announce_online()

    def _announce_online(self) -> None:
        """ONLINE announcement — also the delta-base-missing recovery (the
        server clears this device's ACK on receipt, so the next dispatch
        falls back to a full frame)."""
        status = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.rank,
                         self._server_rank)
        status.add(MyMessage.MSG_ARG_KEY_CLIENT_STATUS,
                   MyMessage.CLIENT_STATUS_ONLINE)
        self._send_quiet(status)

    def _decode_frame(self, version: int, arrays,
                      dmeta) -> Optional[List[np.ndarray]]:
        """Delta-plane decode of one dispatch: full frames refresh the base
        store; delta frames decode against the stored base (or trigger the
        ONLINE resync when that base is gone)."""
        from ..delivery import flatten_leaves
        from ..delivery.device_codec import host_view

        if dmeta is None:
            self._leaf_meta = [(np.asarray(a).shape, np.asarray(a).dtype)
                               for a in arrays]
            self._store.put(version, flatten_leaves(arrays))
            return list(arrays)
        on_device = self._wire.path == "device"
        base = (self._store.get_device(int(dmeta["base_version"]))
                if on_device else self._store.get(int(dmeta["base_version"])))
        if base is None or self._leaf_meta is None:
            self.world.telemetry.counter_inc("swarm.delta_base_missing")
            self._announce_online()
            return None
        vec = self._wire.decode(base, arrays, dmeta)
        if isinstance(vec, np.ndarray):
            self._store.put(version, vec)
        else:
            # device decode: keep the device buffer as the next base and
            # slice the per-leaf views off the (dlpack) host view
            dev = vec
            vec = host_view(dev, scoped=self.world.telemetry)
            self._store.put(version, vec, device=dev)
        self.world.telemetry.counter_inc("swarm.delta_decodes")
        out, off = [], 0
        for shape, dtype in self._leaf_meta:
            n = int(np.prod(shape, dtype=np.int64))
            out.append(np.asarray(vec[off:off + n],
                                  dtype=dtype).reshape(shape))
            off += n
        return out

    def _on_dispatch(self, msg: Message) -> None:
        version = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, 0))
        with self._state_lock:
            if version <= self._version:
                # replayed/stale dispatch: checked BEFORE the delta decode
                # so a duplicated frame can never pollute the base store,
                # inflate decode counters, or fire the ONLINE resync (which
                # would clear the server's ACK and silently degrade this
                # device to full frames)
                return
        arrays = msg.get_arrays()
        if self._delta_on:
            from ..delivery.delta_codec import DELTA_KEY

            arrays = self._decode_frame(version, arrays, msg.get(DELTA_KEY))
            if arrays is None:
                return  # undecodable delta: resynced via ONLINE instead
        with self._state_lock:
            if version <= self._version:
                return  # a fresher dispatch landed during the decode
            self._version = version
            self._arrays = arrays
            self._trace_ctx = self.world.trace.current_context()
        if self._dropped:
            return  # silent device: receives, never answers
        if self.schedule.drops_out():
            self._dropped = True
            self.world.telemetry.counter_inc("swarm.dropouts")
            return
        self.timers.call_later(
            self.schedule.next_think_s(),
            lambda v=version: self._send_update(v),
        )

    def _send_update(self, version: int) -> None:
        if self.done.is_set():
            return
        with self._state_lock:
            if version != self._version:
                return  # a fresher dispatch superseded this one
            arrays = self._arrays
            ctx = self._trace_ctx
        out = Message(
            MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, self.rank,
            self._server_rank)
        out.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, version)
        out.add(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, 1.0)
        if self._delta_on:
            # ACK: this version becomes the server's S2C delta base for us
            out.add(MyMessage.MSG_ARG_KEY_DELTA_CAPABLE, 1)
        out.set_arrays(arrays)
        # continue the dispatch's trace across the think-time hop: the
        # upload span parents to the server's dispatch span, and
        # send_message stamps ITS context onto the C2S wire — a shed
        # retry is a genuinely new upload attempt, so it gets a new span
        # (transport-level retries inside send stay events, never spans)
        sp = (self.world.trace.span("upload", ctx=ctx, client=self.rank)
              if ctx is not None else NULL_SPAN)
        with sp:
            self.world.telemetry.counter_inc("swarm.updates_sent")
            self._send_quiet(out)

    def _on_shed(self, msg: Message) -> None:
        shed_version = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, -1))
        with self._state_lock:
            current = self._version
        if shed_version != current or self._dropped:
            return
        retry_s = max(
            float(msg.get(MyMessage.MSG_ARG_KEY_RETRY_AFTER_S, 0.1)), 0.01)
        self.world.telemetry.counter_inc("swarm.retries")
        self.timers.call_later(
            retry_s, lambda v=shed_version: self._send_update(v))

    def _on_finish(self, msg: Message) -> None:
        self.done.set()
        self.finish()

    def _send_quiet(self, msg: Message) -> None:
        try:
            self.send_message(msg)
        except Exception:
            # the server is gone (soak teardown, chaos kill): a traffic
            # generator must absorb that, not crash the swarm
            self.world.telemetry.counter_inc("swarm.send_failures")


# ---------------------------------------------------------------------------
# loopback pump: 2000 devices on one thread
# ---------------------------------------------------------------------------


class LoopbackPump:
    """Drains every device's loopback mailbox on ONE thread and dispatches
    through the managers' normal ``receive_message`` path (dedup window,
    payload fetch, handlers) — the event-driven replacement for a
    receive-loop thread per device."""

    def __init__(self, world: str):
        from ..core.distributed.loopback import _Broker

        self.broker = _Broker.get(world)
        self.devices: Dict[int, SwarmClientManager] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="swarm-pump")

    def add(self, device: SwarmClientManager) -> None:
        # setup-phase only: every add() happens before start(), whose
        # Thread.start() publishes the finished dict to the pump thread
        # (the same discipline as FedMLCommManager.register_comm_manager)
        device.register_message_receive_handlers()
        self.devices[device.rank] = device  # graftlint: disable=G005

    def start(self) -> None:
        # synthetic connection-ready per device, exactly like the backend's
        # own receive loop would emit
        for rank, dev in self.devices.items():
            dev.receive_message(
                MyMessage.MSG_TYPE_CONNECTION_IS_READY,
                Message(MyMessage.MSG_TYPE_CONNECTION_IS_READY, rank, rank),
            )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def _loop(self) -> None:
        from ..core.distributed.delivery import safe_deserialize

        while not self._stop.is_set():
            drained = 0
            for rank, dev in self.devices.items():
                q = self.broker.queue_for(rank)
                for _ in range(32):  # bounded burst per device per sweep
                    try:
                        data = q.get_nowait()
                    except Exception:
                        break
                    msg = safe_deserialize(data, "swarm-pump")
                    if msg is not None:
                        dev.receive_message(msg.get_type(), msg)
                    drained += 1
            if drained == 0:
                time.sleep(0.002)


# ---------------------------------------------------------------------------
# process spawner (shared with the chaos harness's gRPC legs)
# ---------------------------------------------------------------------------


class ProcSpawner:
    """Launch + supervise worker OS processes. One definition serves the
    swarm's multiprocess-gRPC device hosts AND the chaos harness's real
    multiprocess client legs."""

    def __init__(self, cwd: Optional[str] = None):
        self.cwd = cwd or os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        self.procs: List[subprocess.Popen] = []

    def spawn(self, cmd: List[str]) -> subprocess.Popen:
        env = dict(os.environ,
                   JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
        proc = subprocess.Popen(cmd, cwd=self.cwd, env=env)
        self.procs.append(proc)
        return proc

    def wait_all(self, timeout_s: float) -> List[Optional[int]]:
        deadline = time.monotonic() + timeout_s
        codes: List[Optional[int]] = []
        for p in self.procs:
            left = max(deadline - time.monotonic(), 0.1)
            try:
                codes.append(p.wait(timeout=left))
            except subprocess.TimeoutExpired:
                codes.append(None)
        return codes

    def kill_all(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=5)
        self.procs.clear()


def python_module_cmd(module: str, *args: str) -> List[str]:
    return [sys.executable, "-m", module, *args]


# ---------------------------------------------------------------------------
# orchestrator
# ---------------------------------------------------------------------------


def _s2c_delta(a) -> str:
    return str(getattr(a, "s2c_delta", "off") or "off").lower()


def _wire_path(a) -> str:
    return str(getattr(a, "wire_path", "auto") or "auto").lower()


def _trace_on(a) -> bool:
    return bool(getattr(a, "trace", False))


def _trace_sample(a) -> float:
    raw = getattr(a, "trace_sample", None)
    return 1.0 if raw is None else max(0.0, min(1.0, float(raw)))


def _trace_dir(a) -> str:
    """Shared span-sink directory for the soak: every process (server,
    loopback devices, gRPC device hosts) writes here so the merge sees one
    federation. Per-run by default so stale files from earlier soaks can
    never pollute the reconciliation."""
    explicit = str(getattr(a, "trace_dir", "") or "")
    if explicit:
        return explicit
    return os.path.join(".fedml_tpu_runs", f"trace_{a.run_id}")


def _trace_overrides(a) -> Dict:
    """Tracing knobs for a soak participant's Arguments: spans persist
    through the PR 2 JSONL sink, so a traced soak also turns tracking on,
    pointed at the shared trace dir."""
    if not _trace_on(a):
        return {}
    return dict(
        enable_tracing=True,
        trace_sample=_trace_sample(a),
        trace_dir=_trace_dir(a),
        enable_tracking=True,
        tracking_dir=_trace_dir(a),
    )


def _server_overrides(a) -> Dict:
    return dict(
        training_type="cross_silo", dataset="synthetic", model="lr",
        client_num_in_total=int(a.clients),
        client_num_per_round=int(a.clients),
        comm_round=int(a.steps), epochs=1, batch_size=8, learning_rate=0.2,
        random_seed=int(a.seed), role="server", rank=0,
        run_id=str(a.run_id),
        s2c_delta=_s2c_delta(a),
        wire_path=_wire_path(a),
        aggregation_mode="async",
        async_buffer_size=int(a.buffer),
        async_staleness_alpha=float(a.staleness_alpha),
        async_max_staleness=int(a.max_staleness),
        async_flush_s=float(a.flush_s),
        async_admit_rate=float(a.admit_rate),
        async_admit_burst=int(a.admit_burst),
        async_queue_limit=int(a.queue_limit),
        # eval only the final step: the soak measures the traffic plane,
        # not the model
        frequency_of_the_test=10**9,
        **_trace_overrides(a),
    )


def _device_args(a, rank: int, backend: str):
    import fedml_tpu as fedml
    from ..arguments import Arguments

    overrides = dict(
        training_type="cross_silo", dataset="synthetic", model="lr",
        client_num_in_total=int(a.clients),
        client_num_per_round=int(a.clients),
        comm_round=int(a.steps), role="client", rank=int(rank),
        run_id=str(a.run_id), backend=backend,
        random_seed=int(a.seed),
        wire_path=_wire_path(a),
        **_hierarchy_overrides(a, backend),
        **_trace_overrides(a),
    )
    if backend == constants.COMM_BACKEND_GRPC:
        overrides.update(
            comm_port=int(a.port), comm_host="127.0.0.1",
            grpc_ranks_per_port=_ranks_per_port(a),
        )
    return fedml.init(Arguments(overrides=overrides), should_init_logs=False)


def _ranks_per_port(a) -> int:
    """Resolved gRPC rank→port multiplexing for a swarm config: an explicit
    ``--ranks_per_port``, else one port per device-host process (the
    per-process rank-block size) — 2000 devices over 8 processes cost 9
    listening ports instead of 2001. 1 = legacy port-per-rank."""
    explicit = int(getattr(a, "ranks_per_port", 0) or 0)
    if explicit > 0:
        return explicit
    procs = max(int(getattr(a, "procs", 1) or 1), 1)
    return max((int(a.clients) + procs - 1) // procs, 1)


def _edge_count(a) -> int:
    """Edge aggregators for this soak: 0 = flat FedBuff. An explicit
    ``--edges`` wins; a bare ``--tiers 2`` derives roughly one edge per
    100 devices (min 2 so failover always has a sibling, max 64)."""
    explicit = int(getattr(a, "edges", 0) or 0)
    if explicit > 0:
        return explicit
    if int(getattr(a, "tiers", 1) or 1) < 2:
        return 0
    return max(2, min(int(a.clients) // 100, 64))


def _edge_rank_base(a, backend: str) -> int:
    """First edge rank: clients+1, pushed up to the next rank→port block
    boundary under gRPC so the edge ranks (which live in the orchestrator
    process) never share a port group with a device-host process."""
    n = int(a.clients)
    if backend != constants.COMM_BACKEND_GRPC:
        return n + 1
    per = _ranks_per_port(a)
    return ((n + per - 1) // per) * per + 1


def _hierarchy_overrides(a, backend: str) -> Dict:
    """Topology knobs every tiered-soak participant (root, edges, devices)
    must agree on — Topology.from_args keys off these."""
    edges = _edge_count(a)
    if edges <= 0:
        return {}
    return dict(hierarchy_edges=edges,
                hierarchy_edge_rank_base=_edge_rank_base(a, backend))


def _edge_args(a, rank: int, backend: str):
    """Arguments for one in-orchestrator edge aggregator: async mode to
    mirror the root's fold plane, plus the shared topology knobs."""
    import fedml_tpu as fedml
    from ..arguments import Arguments

    overrides = dict(
        training_type="cross_silo", dataset="synthetic", model="lr",
        client_num_in_total=int(a.clients),
        client_num_per_round=int(a.clients),
        comm_round=int(a.steps), role="client", rank=int(rank),
        run_id=str(a.run_id), backend=backend,
        random_seed=int(a.seed),
        wire_path=_wire_path(a),
        aggregation_mode="async",
        async_buffer_size=int(a.buffer),
        **_hierarchy_overrides(a, backend),
        **_trace_overrides(a),
    )
    if backend == constants.COMM_BACKEND_GRPC:
        overrides.update(
            comm_port=int(a.port), comm_host="127.0.0.1",
            grpc_ranks_per_port=_ranks_per_port(a),
        )
    return fedml.init(Arguments(overrides=overrides), should_init_logs=False)


def _percentiles(hist_summary: Optional[dict]) -> Dict:
    if not hist_summary:
        return {"count": 0, "sum": None,
                "p50": None, "p95": None, "p99": None}
    return {k: hist_summary.get(k)
            for k in ("count", "sum", "p50", "p95", "p99")}


def run_swarm(a) -> int:
    """The ``fedml_tpu swarm`` CLI entry: run the soak, print the JSON
    report, return a process exit code."""
    backend = str(a.backend).upper()
    if backend not in (constants.COMM_BACKEND_LOOPBACK,
                       constants.COMM_BACKEND_GRPC):
        print(json.dumps({"ok": False,
                          "error": f"unsupported swarm backend {backend}"}))
        return 2
    report = swarm_soak(a)
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0 if report["ok"] else 1


def swarm_soak(a) -> Dict:
    """The orchestrator: async server + N-device swarm; returns the soak
    report (tests call this directly; the CLI prints it)."""
    import fedml_tpu as fedml
    from .. import data as data_mod
    from .. import models as model_mod
    from ..arguments import Arguments
    from ..cross_silo import FedMLCrossSiloServer

    backend = str(a.backend).upper()
    telemetry.registry().reset()
    # thread-leak witness (graftiso I005's runtime half): every thread the
    # soak starts must be gone — or at least daemonic and world-joined —
    # after world shutdown; a leaked non-daemon thread fails the soak
    threads_before = world_mod.thread_snapshot()
    # memory-leak witness (graftmem's runtime half): VmRSS sampled across
    # the soak; a positive steady-state slope fails it
    sampler: Optional[RssSampler] = None
    if getattr(a, "leak_check", False):
        sampler = RssSampler(float(getattr(a, "leak_interval", 0.2)))
        sampler.start()
    t0 = time.monotonic()

    edges_n = _edge_count(a)
    edge_base = _edge_rank_base(a, backend)
    world_size = (edge_base + edges_n) if edges_n else int(a.clients) + 1

    server_over = dict(_server_overrides(a), backend=backend,
                       **_hierarchy_overrides(a, backend))
    if backend == constants.COMM_BACKEND_GRPC:
        server_over.update(comm_port=int(a.port), comm_host="127.0.0.1",
                           grpc_ranks_per_port=_ranks_per_port(a))
    args_s = fedml.init(Arguments(overrides=server_over),
                        should_init_logs=False)
    ds, od = data_mod.load(args_s)
    bundle = model_mod.create(args_s, od)
    server = FedMLCrossSiloServer(args_s, None, ds, bundle)

    timers = TimerWheel()
    pump: Optional[LoopbackPump] = None
    spawner: Optional[ProcSpawner] = None
    devices: List[SwarmClientManager] = []
    edge_managers: List = []
    server_thread: Optional[threading.Thread] = None
    try:
        if edges_n:
            # the edge tier lives in the orchestrator process: E is small
            # (devices are the thing that scales), and keeping the edges
            # here lets the report read their counters directly. Each edge
            # is a first-class manager with its own receive loop.
            from ..hierarchy import EdgeAggregatorManager

            for er in range(edge_base, edge_base + edges_n):
                eargs = _edge_args(a, er, backend)
                if backend == constants.COMM_BACKEND_LOOPBACK:
                    from ..core.distributed.loopback import (
                        LoopbackCommManager,
                    )

                    edge = EdgeAggregatorManager(
                        eargs,
                        comm=LoopbackCommManager(er, world_size,
                                                 str(a.run_id)),
                        rank=er, size=world_size,
                    )
                else:
                    edge = EdgeAggregatorManager(
                        eargs, rank=er, size=world_size,
                        backend=constants.COMM_BACKEND_GRPC,
                    )
                edge.run_async()
                edge_managers.append(edge)

        if backend == constants.COMM_BACKEND_LOOPBACK:
            from ..core.distributed.loopback import LoopbackCommManager

            pump = LoopbackPump(str(a.run_id))
            n = int(a.clients)
            for rank in range(1, n + 1):
                dev = SwarmClientManager(
                    _device_args(a, rank, backend),
                    SwarmSchedule(int(a.seed), rank, float(a.think_s),
                                  float(a.dropout)),
                    timers,
                    comm=LoopbackCommManager(rank, world_size,
                                             str(a.run_id)),
                    rank=rank, size=world_size,
                    delta_capable=_s2c_delta(a) != "off",
                )
                devices.append(dev)
                pump.add(dev)
        else:
            spawner = ProcSpawner()
            procs = max(int(a.procs), 1)
            base = 1
            per = (int(a.clients) + procs - 1) // procs
            for _ in range(procs):
                count = min(per, int(a.clients) - base + 1)
                if count <= 0:
                    break
                cmd = python_module_cmd(
                    "fedml_tpu.cli", "swarm", "--worker",
                    "--rank_base", str(base), "--count", str(count),
                    "--clients", str(a.clients), "--steps", str(a.steps),
                    "--port", str(a.port), "--seed", str(a.seed),
                    "--think_s", str(a.think_s), "--dropout",
                    str(a.dropout), "--run_id", str(a.run_id),
                    "--timeout", str(a.timeout),
                    "--procs", str(a.procs),
                    "--ranks_per_port", str(_ranks_per_port(a)),
                    "--s2c_delta", _s2c_delta(a),
                    "--wire_path", _wire_path(a),
                )
                if edges_n:
                    # explicit count so worker processes resolve the same
                    # topology (edge count + rank base) as the orchestrator
                    cmd += ["--edges", str(edges_n)]
                if _trace_on(a):
                    # device hosts join the same trace: the resolved dir is
                    # passed explicitly so orchestrator and workers agree
                    cmd += ["--trace",
                            "--trace_sample", str(_trace_sample(a)),
                            "--trace_dir", _trace_dir(a)]
                spawner.spawn(cmd)
                base += count

        server_thread = threading.Thread(target=server.run, daemon=True)
        if pump is not None:
            pump.start()
        server_thread.start()
        completed = server.manager.done.wait(timeout=float(a.timeout))
        # let FINISH drain to the edges, and through them to the devices
        deadline = time.monotonic() + 10.0
        for edge in edge_managers:
            edge.done.wait(timeout=max(deadline - time.monotonic(), 0.05))
        for dev in devices:
            dev.done.wait(timeout=max(deadline - time.monotonic(), 0.05))
        worker_rcs: List[Optional[int]] = []
        if spawner is not None:
            worker_rcs = spawner.wait_all(timeout_s=15.0)
    finally:
        if pump is not None:
            pump.stop()
        timers.stop()
        if spawner is not None:
            spawner.kill_all()
        server.manager.done.set()  # unblock the worker on a timed-out soak
        for edge in edge_managers:
            edge.done.set()
            edge.finish()
        server.manager.finish()
        if server_thread is not None:
            server_thread.join(timeout=10.0)
        if sampler is not None:
            sampler.stop()

    leaked = world_mod.leaked_threads(threads_before)

    wall = time.monotonic() - t0
    snap = telemetry.registry().snapshot()
    counters = snap["counters"]
    hists = snap["histograms"]
    grpc_mode = backend == constants.COMM_BACKEND_GRPC
    report = {
        # grpc mode: every device-host process must ALSO have exited 0
        # (all its devices reached FINISH); a leaked non-daemon thread
        # after world shutdown fails the soak outright
        "ok": (bool(completed) and all(rc == 0 for rc in worker_rcs)
               and not leaked),
        "leaked_threads": leaked,
        "backend": backend,
        "clients": int(a.clients),
        "steps_requested": int(a.steps),
        "steps_completed": int(server.manager.round_idx),
        "buffer_size": server.manager.async_cfg.buffer_size,
        "wall_s": round(wall, 3),
        "accepted_updates": counters.get("traffic.accepted_updates", 0.0),
        "shed_updates": counters.get("traffic.shed_updates", 0.0),
        "shed_rate_limited": counters.get("traffic.shed_rate_limited", 0.0),
        "shed_queue_full": counters.get("traffic.shed_queue_full", 0.0),
        "stale_dropped_updates": counters.get(
            "traffic.stale_dropped_updates", 0.0),
        "server_steps": counters.get("traffic.server_steps", 0.0),
        # recovery plane (docs/robustness.md): a soak that silently
        # survived a server restart / client resyncs / deadline rounds
        # must be visible in the report, not indistinguishable from a
        # clean run
        "server_recoveries": counters.get("run.server_recoveries", 0.0),
        "resyncs": counters.get("comm.resyncs", 0.0),
        "partial_rounds": counters.get("traffic.partial_rounds", 0.0),
        # device-side stats live in the device processes under grpc, not
        # this registry — report None there instead of a misleading 0
        "swarm_dropouts": (None if grpc_mode
                           else counters.get("swarm.dropouts", 0.0)),
        "swarm_updates_sent": (None if grpc_mode else
                               counters.get("swarm.updates_sent", 0.0)),
        "swarm_retries": (None if grpc_mode
                          else counters.get("swarm.retries", 0.0)),
        # delta plane (server side: valid for both backends — the server
        # always runs in the orchestrator process)
        "s2c_delta": _s2c_delta(a),
        "s2c_delta_frames": counters.get("comm.delta.s2c_delta_frames",
                                         0.0),
        "s2c_full_frames": counters.get("comm.delta.s2c_full_frames", 0.0),
        # wire path (docs/delivery.md device-direct): which codec served
        # the server's encodes, and whether the device kernels engaged
        "wire_path": _wire_path(a),
        "wire_device_encodes": counters.get("comm.wire.device_encodes", 0.0),
        "wire_device_decodes": (None if grpc_mode else counters.get(
            "comm.wire.device_decodes", 0.0)),
        "wire_host_fallbacks": counters.get("comm.wire.host_fallbacks", 0.0),
        "swarm_delta_decodes": (None if grpc_mode else
                                counters.get("swarm.delta_decodes", 0.0)),
        "devices_finished": (
            None if grpc_mode
            else sum(1 for d in devices if d.done.is_set())),
        "worker_exit_codes": worker_rcs,
        # the headline: server-side dispatch→ready (admission → folded)
        "dispatch_ready_s": _percentiles(
            hists.get("traffic.dispatch_ready_s")),
        "staleness": _percentiles(hists.get("traffic.staleness")),
        "step_s": _percentiles(hists.get("traffic.step_s")),
        "rss_peak_mb": round(rss_peak_mb(), 1),
    }
    if sampler is not None:
        slope = sampler.slope_mb_per_s()
        rss_samples = sampler.samples()
        limit = float(getattr(a, "leak_slope_mb_s", 1.0))
        # no-signal (too-short soak) fails: a leak gate that silently
        # passes when it measured nothing is not a gate
        mem_ok = slope is not None and slope <= limit
        report["mem"] = {
            "ok": mem_ok,
            "rss_slope_mb_per_s": (None if slope is None
                                   else round(slope, 4)),
            "rss_slope_limit_mb_per_s": limit,
            "rss_start_mb": round(rss_samples[0][1], 1),
            "rss_end_mb": round(rss_samples[-1][1], 1),
            "rss_samples": len(rss_samples),
            # per-container occupancy: every BoundedDict in the serving
            # plane publishes mem.<name>.occupancy/.evictions
            "containers": {
                name[len("mem."):-len(".occupancy")]: {
                    "occupancy": value,
                    "evictions": counters.get(
                        name[:-len(".occupancy")] + ".evictions", 0.0),
                }
                for name, value in sorted(snap["gauges"].items())
                if name.startswith("mem.")
                and name.endswith(".occupancy")
            },
        }
        report["ok"] = bool(report["ok"] and mem_ok)
    else:
        report["mem"] = None
    if edges_n:
        # edge tier block (docs/traffic.md): the root must fold ONLY edge
        # summaries — direct_client_updates > 0 means a device bypassed
        # its home edge, and the swarm smoke gates on it staying 0
        report["edge_tier"] = {
            "edges": edges_n,
            "edge_rank_base": edge_base,
            "edges_finished": sum(
                1 for e in edge_managers if e.done.is_set()),
            "summaries_folded": counters.get("edge.summaries_folded", 0.0),
            "summary_entries": counters.get("edge.summary_entries", 0.0),
            "direct_client_updates": counters.get(
                "edge.direct_client_updates", 0.0),
            "edge_folds": counters.get("edge.folds", 0.0),
            "summaries_sent": counters.get("edge.summaries_sent", 0.0),
            "rehomed_clients": counters.get("edge.rehomed_clients", 0.0),
            "resolicited_updates": counters.get(
                "edge.resolicited_updates", 0.0),
            "summary_decode_errors": counters.get(
                "edge.summary_decode_errors", 0.0),
            "per_edge": server.manager.edge_report(),
        }
    else:
        report["edge_tier"] = None
    report.update(_trace_report(a))
    return report


def _trace_report(a) -> Dict:
    """Merge the soak's per-process span files and attach the trace block:
    span count, per-segment critical-path shares, straggler top-k, and the
    traced dispatch→ready sum the smoke reconciles (within 5%) against the
    ``traffic.dispatch_ready_s`` histogram's measured sum."""
    if not _trace_on(a):
        return {"trace_spans": None, "critical_path_segments": None}
    from ..core import mlops
    from ..core.mlops import tracing

    mlops.flush()  # the orchestrator's own buffered span tail
    files = tracing.collect_trace_files(_trace_dir(a),
                                        run_id=str(a.run_id))
    spans, clocks = tracing.read_trace(files)
    merged = tracing.merge_trace(spans, clocks)
    shares = tracing.critical_path_shares(merged)
    traced_total, traced_folds = tracing.dispatch_ready_from_trace(merged)
    rounds_with_path = sum(
        1 for r in merged["rounds"] if tracing.critical_path(merged, r))
    return {
        "trace_spans": len(merged["spans"]),
        "trace_rounds": len(merged["rounds"]),
        "trace_rounds_with_path": rounds_with_path,
        "trace_orphans": len(merged["orphans"]),
        "critical_path_segments": {
            k: round(v, 6) for k, v in sorted(shares.items())},
        "stragglers": tracing.straggler_attribution(merged, k=5),
        "trace_dispatch_ready_s": round(traced_total, 6),
        "trace_dispatch_ready_folds": traced_folds,
        "trace_dir": _trace_dir(a),
    }


def run_device_worker(a) -> int:
    """One swarm device-host process (gRPC mode): ranks
    [rank_base, rank_base+count) as real gRPC endpoints against the
    orchestrator's server. Spawned via :class:`ProcSpawner`."""
    n = int(a.clients)
    edges_n = _edge_count(a)
    world_size = (_edge_rank_base(a, constants.COMM_BACKEND_GRPC) + edges_n
                  if edges_n else n + 1)
    devices = []
    threads_before = world_mod.thread_snapshot()
    timers = TimerWheel()
    try:
        for rank in range(int(a.rank_base),
                          int(a.rank_base) + int(a.count)):
            dev = SwarmClientManager(
                _device_args(a, rank, constants.COMM_BACKEND_GRPC),
                SwarmSchedule(int(a.seed), rank, float(a.think_s),
                              float(a.dropout)),
                timers,
                rank=rank, size=world_size,
                backend=constants.COMM_BACKEND_GRPC,
                delta_capable=_s2c_delta(a) != "off",
            )
            dev.run_async()
            devices.append(dev)
        deadline = time.monotonic() + float(a.timeout)
        for dev in devices:
            dev.done.wait(timeout=max(deadline - time.monotonic(), 0.1))
    finally:
        timers.stop()
        for dev in devices:
            dev.finish()
    if world_mod.leaked_threads(threads_before):
        return 1
    return 0 if all(d.done.is_set() for d in devices) else 1
