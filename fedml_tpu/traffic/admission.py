"""Admission control for the async traffic plane: token bucket + bounded
receive queue on ``C2S_SEND_MODEL``.

reference: none — the reference server (SURVEY §"Octopus") accepts every
model message unconditionally; under a production arrival process the
receive path is the OOM. Papaya (Huba et al., MLSys 2022) runs its async
aggregator behind admission control for exactly this reason: overload must
degrade to *load-shedding with an explicit retry-after*, never to memory
growth.

Two gates, both cheap enough for the comm receive thread:

- :class:`TokenBucket` — seeded-rate admission (``async_admit_rate``
  updates/s, ``async_admit_burst`` capacity). A denied take returns the
  time until a token is available, which rides the shed NACK as
  ``retry_after_s`` so clients back off instead of hammering.
- the **bounded fold queue** — the server manager's worker thread drains a
  ``queue.Queue(maxsize=async_queue_limit)``; when the aggregator falls
  behind, ``put_nowait`` fails and the update is shed. Memory held by
  pending updates is bounded by ``queue_limit × model size`` no matter the
  arrival rate.

Every decision is counted into the ``traffic.*`` telemetry family
(docs/telemetry.md): accepted / shed / queue-full, plus a queue-depth
gauge — the backpressure counters the swarm harness asserts on.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from ..core.mlops import telemetry


@dataclass(frozen=True)
class AdmissionVerdict:
    admitted: bool
    reason: str = ""           # "" | "rate" | "queue_full"
    retry_after_s: float = 0.0


_ADMIT = AdmissionVerdict(True)


class TokenBucket:
    """Classic token bucket; thread-safe; monotonic-clock based.

    ``rate`` tokens/s refill up to ``burst``. ``rate <= 0`` disables the
    bucket (every take succeeds) — admission off is the default so the
    sync path and small worlds never pay for it. ``clock`` is injectable
    for deterministic tests.
    """

    def __init__(self, rate: float, burst: int,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = max(int(burst), 1)
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = float(self.burst)
        self._last = clock()

    def take(self) -> float:
        """Take one token. Returns 0.0 on success, else the seconds until
        one will be available (the shed NACK's retry_after_s)."""
        if self.rate <= 0:
            return 0.0
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rate
            )
            self._last = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return 0.0
            return (1.0 - self._tokens) / self.rate

    def refund(self) -> None:
        """Return a token taken for an update that was NOT admitted after
        all (e.g. the bounded queue was full) — otherwise a queue-full
        shed would double-penalize the client by also draining the rate
        budget its retry needs."""
        if self.rate <= 0:
            return
        with self._lock:
            self._tokens = min(self.burst, self._tokens + 1.0)


class AdmissionController:
    """The C2S_SEND_MODEL admission gate the async server handler calls.

    ``offer(queue_put)`` runs the token bucket, then the caller-supplied
    bounded enqueue (a ``queue.Queue.put_nowait`` wrapper returning bool).
    Returns an :class:`AdmissionVerdict`; counters are bumped here so every
    call site reports identically.
    """

    def __init__(self, rate: float = 0.0, burst: int = 0,
                 retry_after_floor_s: float = 0.05,
                 clock: Callable[[], float] = time.monotonic):
        self.bucket = TokenBucket(rate, burst or 1, clock=clock)
        self.retry_after_floor_s = float(retry_after_floor_s)

    def offer(self, queue_put: Optional[Callable[[], bool]] = None
              ) -> AdmissionVerdict:
        wait = self.bucket.take()
        if wait > 0:
            telemetry.counter_inc("traffic.shed_updates")
            telemetry.counter_inc("traffic.shed_rate_limited")
            return AdmissionVerdict(
                False, "rate", max(wait, self.retry_after_floor_s))
        if queue_put is not None and not queue_put():
            self.bucket.refund()  # the token was never really spent
            telemetry.counter_inc("traffic.shed_updates")
            telemetry.counter_inc("traffic.shed_queue_full")
            return AdmissionVerdict(
                False, "queue_full", self.retry_after_floor_s)
        telemetry.counter_inc("traffic.accepted_updates")
        return _ADMIT

    @classmethod
    def from_args(cls, args, buffer_size: int) -> "AdmissionController":
        rate = float(getattr(args, "async_admit_rate", 0.0) or 0.0)
        burst = int(getattr(args, "async_admit_burst", 0) or 0)
        if burst <= 0:
            burst = max(2 * int(buffer_size), 8)
        return cls(rate=rate, burst=burst)


def queue_limit_from_args(args, buffer_size: int) -> int:
    """Bounded fold-queue depth: ``--async_queue_limit`` or 4x the buffer
    (never below the buffer itself — a queue smaller than one server step
    could starve the step forever)."""
    limit = int(getattr(args, "async_queue_limit", 0) or 0)
    if limit <= 0:
        limit = 4 * int(buffer_size)
    return max(limit, int(buffer_size))
