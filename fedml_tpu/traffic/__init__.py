"""``fedml_tpu.traffic`` — the production traffic plane (ISSUE 7).

Three pieces, composed by the cross-silo server manager and the swarm CLI:

- :mod:`async_aggregator` — FedBuff-style buffered asynchronous aggregation
  with exact, version-tagged staleness weighting;
- :mod:`admission` — token-bucket admission control + bounded fold queue on
  ``C2S_SEND_MODEL`` (overload → explicit shed/NACK, never OOM);
- :mod:`swarm` — the client-swarm traffic generator (``fedml_tpu swarm``):
  thousands of concurrent simulated devices with seeded Poisson think-time
  and dropout processes, over loopback or real multiprocess gRPC.

See docs/traffic.md for the knobs and the ``traffic.*`` telemetry family.
"""

from .admission import AdmissionController, AdmissionVerdict, TokenBucket
from .async_aggregator import (
    AsyncConfig,
    AsyncUpdateBuffer,
    BufferedUpdate,
    staleness_weight,
)

__all__ = [
    "AdmissionController",
    "AdmissionVerdict",
    "TokenBucket",
    "AsyncConfig",
    "AsyncUpdateBuffer",
    "BufferedUpdate",
    "staleness_weight",
]
