"""FedBuff-style buffered asynchronous aggregation (the traffic-plane
tentpole, ISSUE 7).

reference: Nguyen et al., *Federated Learning with Buffered Asynchronous
Aggregation* (AISTATS 2022) and Papaya (Huba et al., MLSys 2022). Instead of
barriering a round on the full cohort, the server folds client updates into
a buffer **as they arrive** and takes a server step after ``K`` accepted
updates. Each dispatched model is version-tagged (the round index IS the
server version), so an update's staleness ``s = server_version -
client_version`` is exact, and its aggregation weight is scaled by a
polynomial decay ``(1 + s) ** -alpha`` (alpha = 0 keeps weight 1.0 — the
setting under which buffer_size == cohort size reproduces synchronous
FedAvg bitwise, pinned by tests/test_traffic.py).

This module is deliberately passive — no threads, no transport: the server
manager owns the worker thread and the attack → defend → DP aggregation
hook chain (shared with the sync path via ``_aggregate_models``), while the
buffer owns fold bookkeeping, staleness weighting, and the ``traffic.*``
telemetry (occupancy gauge, staleness histogram, stale-drop counter).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

from ..core.mlops import telemetry


def staleness_weight(staleness: int, alpha: float) -> float:
    """Polynomial staleness decay ``(1 + s) ** -alpha``.

    ``alpha = 0`` → exactly 1.0 for every staleness (the sync-parity
    setting); larger alpha discounts stale updates harder. Negative
    staleness (a client answering a version the server has not dispatched —
    only possible through a corrupt header) clamps to 0.
    """
    s = max(int(staleness), 0)
    if alpha == 0.0:
        return 1.0
    return float((1.0 + s) ** (-float(alpha)))


@dataclass
class BufferedUpdate:
    """One accepted client update awaiting the next server step."""

    sender: int
    num_samples: float
    params: Any                 # model pytree (decoded, device-ready)
    client_version: int
    staleness: int
    weight: float               # num_samples * staleness_weight(staleness)

    def meta(self) -> dict:
        return {
            "sender": int(self.sender),
            "client_version": int(self.client_version),
            "staleness": int(self.staleness),
        }


@dataclass
class AsyncConfig:
    """The traffic-plane knobs, resolved once from args."""

    buffer_size: int
    staleness_alpha: float = 0.0
    max_staleness: int = 0      # 0 = unlimited
    flush_s: float = 0.0        # 0 = never flush a partial buffer

    @classmethod
    def from_args(cls, args, client_num: int) -> "AsyncConfig":
        k = int(getattr(args, "async_buffer_size", 0) or 0)
        if k <= 0:
            # FedBuff's paper default is K=10; never ask for more updates
            # than the world has clients or the first step never triggers
            k = min(10, max(int(client_num), 1))
        return cls(
            buffer_size=k,
            staleness_alpha=float(
                getattr(args, "async_staleness_alpha", 0.0) or 0.0),
            max_staleness=int(getattr(args, "async_max_staleness", 0) or 0),
            flush_s=float(getattr(args, "async_flush_s", 0.0) or 0.0),
        )


class AsyncUpdateBuffer:
    """The K-update fold buffer. Thread-safe; drained by the server step.

    ``fold`` returns the verdict: ``"buffered"`` (counts toward the next
    step), or ``"stale"`` (staleness beyond ``max_staleness`` — dropped,
    but the sender deserves a fresh model so it rejoins at version head).
    """

    def __init__(self, cfg: AsyncConfig):
        self.cfg = cfg
        self._lock = threading.Lock()
        self._entries: List[BufferedUpdate] = []

    def fold(self, sender: int, num_samples: float, params: Any,
             client_version: int, server_version: int) -> str:
        staleness = max(int(server_version) - int(client_version), 0)
        telemetry.observe("traffic.staleness", float(staleness))
        if 0 < self.cfg.max_staleness < staleness:
            telemetry.counter_inc("traffic.stale_dropped_updates")
            return "stale"
        entry = BufferedUpdate(
            sender=int(sender), num_samples=float(num_samples),
            params=params, client_version=int(client_version),
            staleness=staleness,
            weight=float(num_samples) * staleness_weight(
                staleness, self.cfg.staleness_alpha),
        )
        with self._lock:
            self._entries.append(entry)
            depth = len(self._entries)
        telemetry.gauge_set("traffic.buffer_occupancy", float(depth))
        return "buffered"

    def occupancy(self) -> int:
        with self._lock:
            return len(self._entries)

    def ready(self) -> bool:
        return self.occupancy() >= self.cfg.buffer_size

    def drain(self) -> List[BufferedUpdate]:
        """Take every buffered update, sorted by (sender, client_version)
        so aggregation order — and therefore the float reduction — is
        arrival-order independent."""
        with self._lock:
            entries, self._entries = self._entries, []
        telemetry.gauge_set("traffic.buffer_occupancy", 0.0)
        return sorted(entries, key=lambda e: (e.sender, e.client_version))

    def snapshot_meta(self) -> List[dict]:
        """Buffer state for the run ledger's ``run_meta``/round extras."""
        with self._lock:
            return [e.meta() for e in self._entries]
