"""Adversarial-ML attack kernels.

Re-founds the reference's attack suite (``python/fedml/core/security/attack/``:
``byzantine_attack.py`` random/zero modes, label-flipping, model-replacement
backdoor scaling, and the DLG/InvertGradient gradient-inversion
reconstruction, ``invert_gradient_attack.py``) as pure JAX. Attacks operate on
the stacked client matrix ``updates [n_clients, dim]`` so a simulated
adversary corrupts a masked subset in one fused op.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp


def byzantine_attack(
    updates: jax.Array,
    byzantine_mask: jax.Array,
    key: jax.Array,
    attack_mode: str = "random",
    scale: float = 1.0,
) -> jax.Array:
    """Corrupt masked clients' updates (reference: byzantine_attack.py).

    - ``random``: replace with gaussian noise at ``scale``× the honest norm
    - ``zero``: replace with zeros
    - ``flip``: negate (gradient sign flip)
    """
    m = byzantine_mask[:, None]
    if attack_mode == "random":
        norm = jnp.linalg.norm(updates, axis=1).mean() * scale
        noise = jax.random.normal(key, updates.shape, updates.dtype) * (
            norm / jnp.sqrt(updates.shape[1])
        )
        return updates * (1 - m) + noise * m
    if attack_mode == "zero":
        return updates * (1 - m)
    if attack_mode == "flip":
        return updates * (1 - m) - updates * m
    raise ValueError(f"unknown byzantine mode {attack_mode!r}")


def label_flipping(
    labels: jax.Array, original_class: int, target_class: int
) -> jax.Array:
    """Flip labels of one class to another (reference:
    label_flipping_attack.py)."""
    return jnp.where(labels == original_class, target_class, labels)


def model_replacement_scale(
    update: jax.Array, global_vec: jax.Array, boost: float
) -> jax.Array:
    """Backdoor model-replacement: boost the malicious delta so it survives
    averaging (reference: backdoor_attack.py scaling)."""
    return global_vec + boost * (update - global_vec)


def alie_attack(
    updates: jax.Array,
    byzantine_mask: jax.Array,
    num_std: float = 1.5,
) -> jax.Array:
    """"A Little Is Enough" backdoor/poisoning attack (reference:
    ``backdoor_attack.py``, Baruch et al. NeurIPS'19).

    Malicious clients move every coordinate to ``mean + num_std * std`` of the
    honest population — inside the plausible range, so norm-based defenses
    pass it through, yet the aggregate is steadily dragged. One fused op on
    the stacked matrix: the reference's per-client numpy loop
    (``backdoor_attack.py:63-85``) becomes two masked moment reductions.
    """
    m = byzantine_mask[:, None]
    honest = 1.0 - m
    cnt = jnp.maximum(honest.sum(), 1.0)
    mean = (updates * honest).sum(0, keepdims=True) / cnt
    var = (((updates - mean) ** 2) * honest).sum(0, keepdims=True) / cnt
    mal = mean + num_std * jnp.sqrt(var)
    return updates * (1 - m) + mal * m


def pattern_backdoor_poison(
    x: jax.Array,
    y: jax.Array,
    poison_mask: jax.Array,
    target_class: int,
    pattern_value: float = 2.8,
    pattern_size: int = 5,
) -> Tuple[jax.Array, jax.Array]:
    """Stamp a trigger patch onto selected samples and relabel them
    (reference: ``backdoor_attack.py:89-93`` ``add_pattern``:
    ``img[:, :5, :5] = 2.8``).

    ``x``: [..., H, W, C] images (NHWC — TPU-native layout) or [..., d] flat
    features; ``poison_mask``: broadcastable 0/1 over the sample axes. The
    image-vs-flat decision uses the FEATURE rank (x.ndim minus the mask's
    sample axes) — cohort-packed flat features arrive as [clients, cap, d],
    whose absolute ndim would otherwise masquerade as an image batch. The
    trigger is written with a static slice so the op stays jit-compatible.
    """
    p = pattern_size
    feature_rank = x.ndim - poison_mask.ndim
    if feature_rank >= 3:  # images [..., H, W, C]
        patch = jnp.zeros_like(x).at[..., :p, :p, :].set(1.0)
    elif feature_rank == 2:  # channel-less images [..., H, W]
        patch = jnp.zeros_like(x).at[..., :p, :p].set(1.0)
    else:  # flat features [..., d]
        patch = jnp.zeros_like(x).at[..., :p].set(1.0)
    pm = poison_mask.reshape(poison_mask.shape + (1,) * (x.ndim - poison_mask.ndim))
    x_poisoned = jnp.where(patch * pm > 0, pattern_value, x)
    y_poisoned = jnp.where(poison_mask > 0, target_class, y).astype(y.dtype)
    return x_poisoned, y_poisoned


def reveal_labels_from_gradients(last_layer_weight_grad: jax.Array) -> jax.Array:
    """iDLG label revelation (reference:
    ``revealing_labels_from_gradients_attack.py``, Zhao et al.).

    With cross-entropy loss, the last-layer weight-gradient row of a present
    class has negative projection (softmax(p) - 1 < 0 for the true class).
    Returns per-class scores; ``argmin`` gives the single-sample label
    exactly, and for batches classes with the most-negative scores are the
    labels present.

    ``last_layer_weight_grad``: [d_in, num_classes] — the flax Dense kernel
    layout (class axis LAST). A torch ``nn.Linear.weight`` grad
    ([num_classes, d_in]) must be transposed by the caller.
    """
    g = last_layer_weight_grad
    if g.ndim != 2:
        raise ValueError(f"expected 2-D last-layer grad, got {g.shape}")
    return jnp.sum(g, axis=0)


def invert_gradient_attack(
    grad_fn: Callable[[jax.Array, jax.Array], Tuple[jax.Array, ...]],
    true_grads: Tuple[jax.Array, ...],
    dummy_x: jax.Array,
    labels: jax.Array,
    lr: float = 0.1,
    iters: int = 200,
    tv_weight: float = 1e-2,
) -> jax.Array:
    """Geiping-style gradient inversion ("Inverting Gradients", reference:
    ``invert_gradient_attack.py``, 723 LoC of torch): reconstruct inputs by
    maximising cosine similarity between dummy and observed gradients with a
    total-variation prior, signed-gradient Adam steps.

    Unlike :func:`dlg_attack` (L2 matching, joint label optimisation) this
    takes labels as known — recover them first with
    :func:`reveal_labels_from_gradients` — and optimises images only. The
    whole loop is one jitted ``lax.scan`` on device.
    """
    import optax

    def cos_loss(dx):
        g = grad_fn(dx, labels)
        dot = sum(jnp.sum(a * b) for a, b in zip(g, true_grads))
        # eps inside the sqrts keeps the gradient finite at g == 0
        na = jnp.sqrt(sum(jnp.sum(a * a) for a in g) + 1e-12)
        nb = jnp.sqrt(sum(jnp.sum(b * b) for b in true_grads) + 1e-12)
        rec = 1.0 - dot / (na * nb)
        if dummy_x.ndim >= 3:  # total variation over the two spatial axes
            h_ax, w_ax = dummy_x.ndim - 3, dummy_x.ndim - 2
            tv = jnp.mean(jnp.abs(jnp.diff(dx, axis=h_ax))) + jnp.mean(
                jnp.abs(jnp.diff(dx, axis=w_ax))
            )
        else:
            tv = jnp.mean(jnp.abs(jnp.diff(dx, axis=-1)))
        return rec + tv_weight * tv

    opt = optax.adam(lr)
    opt_state = opt.init(dummy_x)

    def step(carry, _):
        dx, opt_state = carry
        g = jax.grad(cos_loss)(dx)
        g = jnp.sign(g)  # signed gradients (Geiping et al. §4)
        updates, opt_state = opt.update(g, opt_state)
        return (optax.apply_updates(dx, updates), opt_state), None

    (dx, _), _ = jax.lax.scan(step, (dummy_x, opt_state), None, length=iters)
    return dx


def dlg_attack(
    grad_fn: Callable[[jax.Array, jax.Array], Tuple[jax.Array, ...]],
    true_grads: Tuple[jax.Array, ...],
    dummy_x: jax.Array,
    dummy_y: jax.Array,
    lr: float = 0.1,
    iters: int = 100,
) -> Tuple[jax.Array, jax.Array]:
    """Deep-Leakage-from-Gradients reconstruction (reference:
    dlg_attack.py / invert_gradient_attack.py).

    Optimises dummy (x, y-logits) so that grad_fn(dummy) matches the observed
    client gradients. Adam on the gradient-matching loss (the reference's
    invert-gradient attack likewise uses Adam, invert_gradient_attack.py);
    the whole attack is one jitted lax.scan on device.
    """
    import optax

    def match_loss(params):
        dx, dy = params
        g = grad_fn(dx, jax.nn.softmax(dy))
        return sum(jnp.sum((a - b) ** 2) for a, b in zip(g, true_grads))

    opt = optax.adam(lr)
    params = (dummy_x, dummy_y)
    opt_state = opt.init(params)

    def step(carry, _):
        params, opt_state = carry
        grads = jax.grad(match_loss)(params)
        updates, opt_state = opt.update(grads, opt_state)
        return (optax.apply_updates(params, updates), opt_state), None

    (params, _), _ = jax.lax.scan(step, (params, opt_state), None, length=iters)
    return params
