"""Adversarial-ML attack kernels.

Re-founds the reference's attack suite (``python/fedml/core/security/attack/``:
``byzantine_attack.py`` random/zero modes, label-flipping, model-replacement
backdoor scaling, and the DLG/InvertGradient gradient-inversion
reconstruction, ``invert_gradient_attack.py``) as pure JAX. Attacks operate on
the stacked client matrix ``updates [n_clients, dim]`` so a simulated
adversary corrupts a masked subset in one fused op.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp


def byzantine_attack(
    updates: jax.Array,
    byzantine_mask: jax.Array,
    key: jax.Array,
    attack_mode: str = "random",
    scale: float = 1.0,
) -> jax.Array:
    """Corrupt masked clients' updates (reference: byzantine_attack.py).

    - ``random``: replace with gaussian noise at ``scale``× the honest norm
    - ``zero``: replace with zeros
    - ``flip``: negate (gradient sign flip)
    """
    m = byzantine_mask[:, None]
    if attack_mode == "random":
        norm = jnp.linalg.norm(updates, axis=1).mean() * scale
        noise = jax.random.normal(key, updates.shape, updates.dtype) * (
            norm / jnp.sqrt(updates.shape[1])
        )
        return updates * (1 - m) + noise * m
    if attack_mode == "zero":
        return updates * (1 - m)
    if attack_mode == "flip":
        return updates * (1 - m) - updates * m
    raise ValueError(f"unknown byzantine mode {attack_mode!r}")


def label_flipping(
    labels: jax.Array, original_class: int, target_class: int
) -> jax.Array:
    """Flip labels of one class to another (reference:
    label_flipping_attack.py)."""
    return jnp.where(labels == original_class, target_class, labels)


def model_replacement_scale(
    update: jax.Array, global_vec: jax.Array, boost: float
) -> jax.Array:
    """Backdoor model-replacement: boost the malicious delta so it survives
    averaging (reference: backdoor_attack.py scaling)."""
    return global_vec + boost * (update - global_vec)


def dlg_attack(
    grad_fn: Callable[[jax.Array, jax.Array], Tuple[jax.Array, ...]],
    true_grads: Tuple[jax.Array, ...],
    dummy_x: jax.Array,
    dummy_y: jax.Array,
    lr: float = 0.1,
    iters: int = 100,
) -> Tuple[jax.Array, jax.Array]:
    """Deep-Leakage-from-Gradients reconstruction (reference:
    dlg_attack.py / invert_gradient_attack.py).

    Optimises dummy (x, y-logits) so that grad_fn(dummy) matches the observed
    client gradients. Adam on the gradient-matching loss (the reference's
    invert-gradient attack likewise uses Adam, invert_gradient_attack.py);
    the whole attack is one jitted lax.scan on device.
    """
    import optax

    def match_loss(params):
        dx, dy = params
        g = grad_fn(dx, jax.nn.softmax(dy))
        return sum(jnp.sum((a - b) ** 2) for a, b in zip(g, true_grads))

    opt = optax.adam(lr)
    params = (dummy_x, dummy_y)
    opt_state = opt.init(params)

    def step(carry, _):
        params, opt_state = carry
        grads = jax.grad(match_loss)(params)
        updates, opt_state = opt.update(grads, opt_state)
        return (optax.apply_updates(params, updates), opt_state), None

    (params, _), _ = jax.lax.scan(step, (params, opt_state), None, length=iters)
    return params
