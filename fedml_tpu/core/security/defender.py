"""Defender manager singleton (reference:
``python/fedml/core/security/fedml_defender.py:20-78``): enabled by
``args.enable_defense``, dispatches on ``args.defense_type``, and is called
from the aggregation hook order on_before_agg → defend → agg → on_after_agg
(SURVEY.md §7 protocol semantics).

Kernels take the flattened stacked updates ``[n, dim]``; the caller handles
tree↔vector conversion once per round (utils.tree).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import defenses

DEFENSE_TYPES = (
    "krum",
    "multikrum",
    "geometric_median",
    "median",
    "trimmed_mean",
    "bulyan",
    "norm_diff_clipping",
    "cclip",
    "robust_learning_rate",
    "weak_dp",
    "wbc",
)


class FedMLDefender:
    _instance = None

    def __init__(self):
        self.is_enabled = False
        self.defense_type = ""
        self.args = None
        # FL-WBC: previous pseudo-gradient PER CLIENT ID (cohorts resample
        # every round, so row position is not a client identity)
        self._wbc_old = {}

    @classmethod
    def get_instance(cls) -> "FedMLDefender":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def init(self, args) -> None:
        self.is_enabled = bool(getattr(args, "enable_defense", False))
        self.defense_type = (getattr(args, "defense_type", "") or "").strip().lower()
        self.args = args
        self._wbc_old = {}
        if self.is_enabled and self.defense_type not in DEFENSE_TYPES:
            raise ValueError(
                f"unknown defense_type {self.defense_type!r}; known: {DEFENSE_TYPES}"
            )

    def is_defense_enabled(self) -> bool:
        return self.is_enabled

    def defend(
        self,
        updates: jax.Array,
        weights: jax.Array,
        global_vec: jax.Array,
        key: jax.Array,
        client_ids=None,
    ) -> jax.Array:
        """Robust-aggregate the stacked updates → one aggregated vector.

        ``client_ids``: the cohort's client identities, row-aligned with
        ``updates`` — required by stateful defenses (FL-WBC) that compare a
        client against ITS OWN previous round, not whoever sat in the same
        row last time.
        """
        a = self.args
        f = int(getattr(a, "byzantine_client_num", 1))
        t = self.defense_type
        if t == "krum":
            agg, _ = defenses.krum(updates, f, 1)
            return agg
        if t == "multikrum":
            m = int(getattr(a, "krum_param_m", max(updates.shape[0] - f, 1)))
            return defenses.multikrum_weighted(updates, weights, f, m)
        if t == "geometric_median":
            return defenses.geometric_median(updates, weights)
        if t == "median":
            return defenses.coordinate_median(updates)
        if t == "trimmed_mean":
            return defenses.trimmed_mean(
                updates, float(getattr(a, "trim_ratio", 0.1))
            )
        if t == "bulyan":
            return defenses.bulyan(updates, f)
        if t == "norm_diff_clipping":
            clipped = defenses.norm_diff_clipping(
                updates, global_vec, float(getattr(a, "norm_bound", 5.0))
            )
            w = weights / jnp.sum(weights)
            return (w[:, None] * clipped).sum(0)
        if t == "cclip":
            return defenses.cclip(
                updates, weights, tau=float(getattr(a, "tau", 10.0))
            )
        if t == "robust_learning_rate":
            return defenses.robust_learning_rate(
                updates,
                global_vec,
                int(getattr(a, "robust_threshold", updates.shape[0] // 2)),
                float(getattr(a, "server_lr", 1.0)),
            )
        if t == "weak_dp":
            w = weights / jnp.sum(weights)
            agg = (w[:, None] * updates).sum(0)
            return defenses.weak_dp(
                agg, key, float(getattr(a, "stddev", 0.002))
            )
        if t == "wbc":
            # FL-WBC applied round-wise: per-client pseudo-gradient vs the
            # SAME client's previous pseudo-gradient identifies the stagnant
            # subspace where poisoning persists; Laplace noise perturbs it.
            # First sighting of a client contributes a zero old-grad (the
            # gate then treats every coordinate as fresh).
            grads = updates - global_vec[None, :]
            n = int(updates.shape[0])
            ids = (
                [int(i) for i in client_ids]
                if client_ids is not None
                else list(range(n))
            )
            import numpy as np

            zero = np.zeros(grads.shape[1:], np.float32)
            old = jnp.asarray(
                np.stack([self._wbc_old.get(cid, zero) for cid in ids])
            )
            keys = jax.random.split(key, updates.shape[0])
            perturbed = jax.vmap(
                lambda u, g, o, k: defenses.wbc_perturb(
                    u, g, o, k,
                    float(getattr(a, "pert_strength", 1.0)),
                    float(getattr(a, "wbc_lr", 0.1)),
                )
            )(updates, grads, old, keys)
            # host-side store (one model vector per client is HBM-expensive),
            # FIFO-bounded: beyond the cap, the oldest client's history is
            # dropped and its next sighting starts fresh
            grads_np = np.asarray(grads, np.float32)
            cap = int(getattr(a, "wbc_history_cap", 4096))
            for row, cid in enumerate(ids):
                self._wbc_old.pop(cid, None)
                self._wbc_old[cid] = grads_np[row]
            while len(self._wbc_old) > cap:
                self._wbc_old.pop(next(iter(self._wbc_old)))
            w = weights / jnp.sum(weights)
            return (w[:, None] * perturbed).sum(0)
        raise ValueError(f"unknown defense_type {t!r}")
