from .attacker import FedMLAttacker  # noqa: F401
from .defender import FedMLDefender  # noqa: F401
