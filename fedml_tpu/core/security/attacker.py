"""Attacker manager singleton (reference:
``python/fedml/core/security/fedml_attacker.py:6-64``): enabled by
``args.enable_attack``, dispatches on ``args.attack_type``, and exposes hook
points the simulators call on the stacked client update matrix.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import attacks


class FedMLAttacker:
    _instance = None

    def __init__(self):
        self.is_enabled = False
        self.attack_type = ""
        self.args = None

    @classmethod
    def get_instance(cls) -> "FedMLAttacker":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def init(self, args) -> None:
        self.is_enabled = bool(getattr(args, "enable_attack", False))
        self.attack_type = (getattr(args, "attack_type", "") or "").strip().lower()
        self.args = args

    def is_model_attack(self) -> bool:
        return self.is_enabled and self.attack_type in (
            "byzantine_random",
            "byzantine_zero",
            "byzantine_flip",
            "model_replacement",
            "alie",
        )

    def is_data_attack(self) -> bool:
        return self.is_enabled and self.attack_type in (
            "label_flipping",
            "backdoor_pattern",
        )

    def attack_model(
        self, updates: jax.Array, weights: jax.Array, key: jax.Array, round_idx: int = 0
    ) -> jax.Array:
        """Corrupt a fraction of clients' updates (hook: before aggregation)."""
        if not self.is_model_attack():
            return updates
        n = updates.shape[0]
        frac = float(getattr(self.args, "byzantine_client_frac", 0.2))
        num_bad = int(round(n * frac))
        if num_bad == 0:
            return updates
        rng = np.random.RandomState(int(getattr(self.args, "random_seed", 0)) + round_idx)
        mask = np.zeros((n,), np.float32)
        mask[rng.choice(n, num_bad, replace=False)] = 1.0
        mask = jnp.asarray(mask)
        if self.attack_type.startswith("byzantine_"):
            return attacks.byzantine_attack(
                updates, mask, key, self.attack_type.split("_", 1)[1],
                scale=float(getattr(self.args, "byzantine_scale", 1.0)),
            )
        if self.attack_type == "alie":
            return attacks.alie_attack(
                updates, mask, float(getattr(self.args, "num_std", 1.5))
            )
        boost = float(getattr(self.args, "attack_boost", float(n)))
        global_vec = jnp.average(updates, axis=0, weights=weights)
        boosted = attacks.model_replacement_scale(updates, global_vec, boost)
        return updates * (1 - mask[:, None]) + boosted * mask[:, None]

    def attack_data(self, x: jax.Array, labels: jax.Array, n_valid: int = None):
        """Poison the cohort's training data → (x, labels).

        label_flipping leaves x alone; backdoor_pattern stamps the trigger
        patch on a fraction of the malicious clients' samples AND relabels
        them to the target class.

        ``n_valid``: real (non-padding) leading rows — the mesh engine pads
        the cohort to a device multiple, and malicious clients must be drawn
        from the real rows only or the attack dilutes onto zero-weight
        padding.
        """
        if not self.is_data_attack():
            return x, labels
        if self.attack_type == "label_flipping":
            return x, attacks.label_flipping(
                labels,
                int(getattr(self.args, "original_class", 0)),
                int(getattr(self.args, "target_class", 1)),
            )
        # backdoor_pattern: malicious clients poison poison_frac of samples
        n = labels.shape[0]
        # n_valid is a static Python int at trace time (the fused path bakes
        # it per config), never a tracer — safe under jit
        n_real = n if n_valid is None else min(int(n_valid), n)  # graftlint: disable=G001
        frac = float(getattr(self.args, "byzantine_client_frac", 0.2))
        num_bad = int(round(n_real * frac))  # graftlint: disable=G001 — static
        rng = np.random.RandomState(int(getattr(self.args, "random_seed", 0)))
        client_mask = np.zeros((n,), np.float32)
        if num_bad:
            client_mask[rng.choice(n_real, num_bad, replace=False)] = 1.0
        poison_frac = float(getattr(self.args, "poison_frac", 0.5))
        sample_mask = (
            rng.random_sample(labels.shape) < poison_frac
        ).astype(np.float32)
        mask = jnp.asarray(
            sample_mask * client_mask.reshape((-1,) + (1,) * (labels.ndim - 1))
        )
        return attacks.pattern_backdoor_poison(
            x, labels, mask,
            int(getattr(self.args, "target_class", 0)),
            float(getattr(self.args, "pattern_value", 2.8)),
            int(getattr(self.args, "pattern_size", 5)),
        )
