"""Robust-aggregation defense kernels.

Re-founds the reference's defense suite (``python/fedml/core/security/defense/``:
Krum/Multi-Krum ``krum_defense.py:13-40``, geometric median, Bulyan, CClip,
SLSGD trimmed mean, robust learning rate, norm-diff clipping, weak DP) as pure
JAX kernels over a **stacked client matrix** ``updates [n_clients, dim]`` plus
``weights [n_clients]``.

TPU-first design: Krum's pairwise distance matrix is one Gram matmul (MXU)
instead of the reference's O(n²) Python double loop; medians/sorts ride the
VPU; everything is jit-compatible with static shapes (k, byzantine counts are
static Python ints).

Uniform contract mirroring the reference's ``run(raw_client_grad_list,
base_aggregation_func, extra_auxiliary_info)``: each kernel either reweights
clients (returns new weights) or directly returns the aggregate vector.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def pairwise_sq_dists(updates: jax.Array) -> jax.Array:
    """[n, n] squared euclidean distances via one Gram matmul."""
    sq = jnp.sum(updates * updates, axis=1)
    gram = updates @ updates.T
    d = sq[:, None] + sq[None, :] - 2.0 * gram
    return jnp.maximum(d, 0.0)


def krum_scores(updates: jax.Array, byzantine_count: int) -> jax.Array:
    """Krum score per client: sum of its n-f-2 smallest distances to others
    (reference: krum_defense.py:25-40, `_compute_krum_score`)."""
    n = updates.shape[0]
    d = pairwise_sq_dists(updates)
    d = d + jnp.diag(jnp.full((n,), jnp.inf, d.dtype))  # exclude self
    k = max(n - byzantine_count - 2, 1)
    neg_topk, _ = jax.lax.top_k(-d, k)  # k smallest distances
    return jnp.sum(-neg_topk, axis=1)


def krum(
    updates: jax.Array, byzantine_count: int, krum_param_m: int = 1
) -> Tuple[jax.Array, jax.Array]:
    """(Multi-)Krum: select the m lowest-score clients; return (aggregate,
    selection mask). m=1 → Krum, m>1 → Multi-Krum averaging the selected."""
    scores = krum_scores(updates, byzantine_count)
    _, sel = jax.lax.top_k(-scores, krum_param_m)
    mask = jnp.zeros((updates.shape[0],)).at[sel].set(1.0)
    agg = jnp.mean(updates[sel], axis=0)
    return agg, mask


def geometric_median(
    updates: jax.Array, weights: jax.Array, iters: int = 10, eps: float = 1e-8
) -> jax.Array:
    """Weighted geometric median by Weiszfeld iteration
    (reference: geometric_median_defense.py). Fixed iteration count → static
    control flow under jit (lax.fori_loop)."""
    w = weights / jnp.sum(weights)

    def body(_, z):
        dist = jnp.linalg.norm(updates - z[None, :], axis=1)
        inv = w / jnp.maximum(dist, eps)
        return (inv[:, None] * updates).sum(0) / jnp.sum(inv)

    z0 = (w[:, None] * updates).sum(0)
    return jax.lax.fori_loop(0, iters, body, z0)


def coordinate_median(updates: jax.Array) -> jax.Array:
    """Coordinate-wise median (building block for Bulyan)."""
    return jnp.median(updates, axis=0)


def trimmed_mean(updates: jax.Array, trim_ratio: float) -> jax.Array:
    """Coordinate-wise trimmed mean (reference: slsgd_defense.py 'option 2',
    drop b largest and b smallest per coordinate)."""
    n = updates.shape[0]
    # trim_ratio is static config (a Python float), so b is a compile-time
    # constant — the sort/slice below stays statically shaped under jit
    b = int(n * trim_ratio)  # graftlint: disable=G001
    if 2 * b >= n:
        raise ValueError(f"trim_ratio {trim_ratio} removes all {n} clients")
    s = jnp.sort(updates, axis=0)
    return jnp.mean(s[b : n - b], axis=0)


def bulyan(updates: jax.Array, byzantine_count: int) -> jax.Array:
    """Bulyan (reference: bulyan_defense.py): iteratively Multi-Krum-select
    theta = n - 2f clients, then coordinate-wise trimmed mean around the
    median of the selected set."""
    n = updates.shape[0]
    f = byzantine_count
    theta = max(n - 2 * f, 1)
    scores = krum_scores(updates, f)
    _, sel = jax.lax.top_k(-scores, theta)
    selected = updates[sel]
    beta = max(theta - 2 * f, 1)
    med = jnp.median(selected, axis=0)
    dist = jnp.abs(selected - med[None, :])
    # beta closest-to-median values per coordinate
    idx = jnp.argsort(dist, axis=0)[:beta]
    closest = jnp.take_along_axis(selected, idx, axis=0)
    return jnp.mean(closest, axis=0)


def norm_diff_clipping(
    updates: jax.Array, global_vec: jax.Array, norm_bound: float
) -> jax.Array:
    """Clip each client's delta from the global model to an L2 ball
    (reference: norm_diff_clipping_defense.py)."""
    delta = updates - global_vec[None, :]
    norms = jnp.linalg.norm(delta, axis=1, keepdims=True)
    factor = jnp.minimum(1.0, norm_bound / jnp.maximum(norms, 1e-12))
    return global_vec[None, :] + delta * factor


def cclip(
    updates: jax.Array,
    weights: jax.Array,
    tau: float = 10.0,
    iters: int = 3,
) -> jax.Array:
    """Centered clipping (reference: cclip_defense.py): iteratively move a
    center v by clipped client deviations."""
    w = weights / jnp.sum(weights)

    def body(_, v):
        delta = updates - v[None, :]
        norms = jnp.linalg.norm(delta, axis=1, keepdims=True)
        factor = jnp.minimum(1.0, tau / jnp.maximum(norms, 1e-12))
        return v + (w[:, None] * delta * factor).sum(0)

    v0 = (w[:, None] * updates).sum(0)
    return jax.lax.fori_loop(0, iters, body, v0)


def robust_learning_rate(
    updates: jax.Array, global_vec: jax.Array, threshold: int, server_lr: float = 1.0
) -> jax.Array:
    """Sign-vote robust LR (reference: robust_learning_rate_defense.py):
    per-coordinate, if |sum of client update signs| < threshold flip the lr."""
    delta = updates - global_vec[None, :]
    sign_sum = jnp.abs(jnp.sum(jnp.sign(delta), axis=0))
    lr = jnp.where(sign_sum >= threshold, server_lr, -server_lr)
    return global_vec + lr * jnp.mean(delta, axis=0)


def weak_dp(
    aggregate: jax.Array, key: jax.Array, stddev: float = 0.002
) -> jax.Array:
    """Add small Gaussian noise to the aggregate (reference:
    weak_dp_defense.py)."""
    return aggregate + stddev * jax.random.normal(key, aggregate.shape, aggregate.dtype)


def soteria_mask(
    feature_fn, x: jax.Array, prune_percentile: float = 1.0
) -> jax.Array:
    """Soteria representation-pruning mask (reference: ``soteria_defense.py``,
    Sun et al. CVPR'21 "Provable defense against privacy leakage").

    For each feature ``r_f`` of the defended representation layer, compute the
    leakage ratio ``||dr_f/dx|| / |r_f|`` and zero out the features in the
    lowest ``prune_percentile`` percent — those are the ones a gradient-
    inversion attacker relies on most cheaply.

    The reference builds the Jacobian with a Python loop of per-feature
    ``backward()`` calls (``soteria_defense.py:54-63``); here it's ONE
    ``jax.jacrev`` — the full [d_r, x_dim] Jacobian in a single fused program.

    ``feature_fn``: x → representation [d_r]. Returns a 0/1 mask [d_r] to be
    multiplied into the defended layer's gradient before sharing.
    """
    r = feature_fn(x)
    jac = jax.jacrev(feature_fn)(x)  # [d_r, *x.shape]
    jac = jac.reshape(r.shape[0], -1)
    ratio = jnp.linalg.norm(jac, axis=1) / jnp.maximum(jnp.abs(r), 1e-12)
    thresh = jnp.percentile(ratio, prune_percentile)
    return (ratio >= thresh).astype(jnp.float32)


def apply_soteria(defended_layer_grad: jax.Array, mask: jax.Array) -> jax.Array:
    """Apply the Soteria mask to the defended (fc) layer's gradient
    (reference: ``soteria_defense.py:78``). Grad shape [d_r, ...] or [d_r]."""
    return defended_layer_grad * mask.reshape(
        (mask.shape[0],) + (1,) * (defended_layer_grad.ndim - 1)
    )


def wbc_perturb(
    param_vec: jax.Array,
    grad: jax.Array,
    old_grad: jax.Array,
    key: jax.Array,
    pert_strength: float = 1.0,
    learning_rate: float = 0.1,
) -> jax.Array:
    """FL-WBC "White Blood Cell" client-side perturbation (reference:
    ``wbc_defense.py``, Sun et al. NeurIPS'21).

    The attack effect on parameters persists in the subspace where the
    gradient barely changes between batches; WBC injects Laplace noise into
    exactly the coordinates where ``|grad - old_grad|`` is smaller than the
    sampled noise — perturbing the attack-carrying subspace while leaving
    well-learned coordinates alone (``wbc_defense.py:59-70``).
    """
    grad_diff = jnp.abs(grad - old_grad)
    # Laplace(0, b) via inverse-CDF of uniform
    u = jax.random.uniform(
        key, param_vec.shape, minval=-0.499999, maxval=0.499999
    )
    noise = -pert_strength * jnp.sign(u) * jnp.log1p(-2.0 * jnp.abs(u))
    noise = jnp.where(grad_diff > jnp.abs(noise), 0.0, noise)
    return param_vec + learning_rate * noise


def multikrum_weighted(
    updates: jax.Array, weights: jax.Array, byzantine_count: int, m: int
) -> jax.Array:
    """Multi-Krum then weighted average of the survivors (reference
    krum_defense.py:20-23 averages selected with sample weights)."""
    scores = krum_scores(updates, byzantine_count)
    _, sel = jax.lax.top_k(-scores, m)
    w = weights[sel]
    w = w / jnp.sum(w)
    return (w[:, None] * updates[sel]).sum(0)
