"""Runtime log daemon — background shipping of run logs to a sink.

reference: ``core/mlops/mlops_runtime_log_daemon.py:14-362`` —
MLOpsRuntimeLogProcessor tails ``fedml-run-{run}-edge-{edge}.log``, keeps a
per-run uploaded-line index in ``log-config.yaml``, and POSTs batches of at
most ``FED_LOG_LINE_NUMS_PER_UPLOADING`` lines every
``FED_LOG_UPLOAD_FREQUENCY`` seconds to the MLOps log server;
MLOpsRuntimeLogDaemon is the process-wide registry that starts/stops one
processor per (run, edge).

TPU re-grounding: same tail → index → batch → ship loop, but the shipping
target is a pluggable *sink* instead of a hard-coded HTTPS endpoint, because
a TPU pod job usually wants logs on shared storage (GCS/NFS) rather than a
SaaS ingest. Three sinks ship built-in:

- ``dir:<path>``  — append batches to ``<path>/run_<id>_edge_<id>.log``
  (the shared-filesystem path a multi-host pod actually uses);
- ``http(s)://…`` — POST a JSON body ``{run_id, edge_id, logs: [...]}``
  (wire-compatible shape with the reference's uploader);
- a Python callable ``sink(run_id, edge_id, lines) -> bool``.

The daemon runs as a daemon *thread*, not a multiprocessing.Process like the
reference: log shipping is IO-bound and the host side of a TPU program must
not fork after the runtime initialises (fork-after-XLA-init deadlocks), so a
thread is the correct TPU-host design. Upload state is a JSON index file, so
a restarted process resumes where the last upload stopped — the same
resume-by-line-index contract as the reference's ``log-config.yaml``.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple, Union

logger = logging.getLogger("fedml_tpu.mlops.log_daemon")

Sink = Union[str, Callable[[str, int, List[str]], bool]]

# reference: FED_LOG_LINE_NUMS_PER_UPLOADING / FED_LOG_UPLOAD_FREQUENCY
# (mlops_runtime_log_daemon.py:15-16)
MAX_LINES_PER_BATCH = 1000
MAX_BYTES_PER_CYCLE = 8 * 1024 * 1024
DEFAULT_UPLOAD_INTERVAL_S = 1.0


def _ship_to_dir(dest_dir: str, run_id: str, edge_id: int,
                 lines: List[str]) -> bool:
    os.makedirs(dest_dir, exist_ok=True)
    path = os.path.join(dest_dir, f"run_{run_id}_edge_{edge_id}.log")
    with open(path, "a") as f:
        f.writelines(line if line.endswith("\n") else line + "\n"
                     for line in lines)
    return True


def _ship_to_http(url: str, run_id: str, edge_id: int,
                  lines: List[str]) -> bool:
    """POST the reference uploader's body shape ({run_id, edge_id, logs})."""
    import urllib.request

    body = json.dumps(
        {"run_id": run_id, "edge_id": edge_id, "logs": lines}
    ).encode()
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"}
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return 200 <= resp.status < 300
    except Exception as e:  # pragma: no cover - network-specific
        logger.warning("log upload to %s failed: %s", url, e)
        return False


class LogProcessor:
    """Tail one run's log file and ship new lines to the sink.

    reference: MLOpsRuntimeLogProcessor (mlops_runtime_log_daemon.py:14-250)
    — one instance per (run_id, edge_id), resumable via a line index.
    """

    def __init__(self, log_path: str, run_id: str, edge_id: int, sink: Sink,
                 index_dir: Optional[str] = None,
                 upload_interval_s: float = DEFAULT_UPLOAD_INTERVAL_S):
        self.log_path = log_path
        self.run_id = str(run_id)
        self.edge_id = int(edge_id)
        self.sink = sink
        self.upload_interval_s = upload_interval_s
        self.index_path = os.path.join(
            index_dir or os.path.dirname(os.path.abspath(log_path)),
            f".log_index_{self.run_id}_{self.edge_id}.json",
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- index persistence (reference: load_log_config/save_log_config) -----

    def _load_index(self) -> int:
        """Uploaded byte offset (the reference tracks a line index; bytes
        make resume O(new data) instead of a full re-read per cycle)."""
        try:
            with open(self.index_path) as f:
                return int(json.load(f).get("uploaded_offset", 0))
        except (OSError, ValueError):
            return 0

    def _save_index(self, offset: int) -> None:
        tmp = self.index_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"uploaded_offset": offset}, f)
        os.replace(tmp, self.index_path)

    # -- shipping -----------------------------------------------------------

    def _ship(self, lines: List[str]) -> bool:
        if callable(self.sink):
            return bool(self.sink(self.run_id, self.edge_id, lines))
        if self.sink.startswith(("http://", "https://")):
            return _ship_to_http(self.sink, self.run_id, self.edge_id, lines)
        dest = self.sink[4:] if self.sink.startswith("dir:") else self.sink
        return _ship_to_dir(dest, self.run_id, self.edge_id, lines)

    def poll_once(self) -> int:
        """One tail→batch→ship cycle; returns the number of lines shipped.

        Only complete (newline-terminated) lines are consumed: a line the
        writer is mid-way through stays unshipped until its newline lands,
        so no line is ever shipped truncated. Reads seek to the uploaded
        offset — O(new data) per cycle, not O(file).
        """
        if not os.path.exists(self.log_path):
            return 0
        offset = self._load_index()
        if offset > os.path.getsize(self.log_path):
            # the file was truncated/rotated under us: start over
            logger.warning("log %s shrank below offset %d; resetting",
                           self.log_path, offset)
            offset = 0
            self._save_index(0)
        with open(self.log_path, "rb") as f:
            f.seek(offset)
            # cap per-cycle reads so attaching to a huge backlog doesn't
            # spike host memory; the offset loop catches up next cycles
            chunk = f.read(MAX_BYTES_PER_CYCLE)
        end = chunk.rfind(b"\n")
        if end < 0:
            return 0
        raw_lines = chunk[: end + 1].splitlines(True)
        shipped = 0
        while shipped < len(raw_lines):
            raw = raw_lines[shipped: shipped + MAX_LINES_PER_BATCH]
            batch = [b.decode(errors="replace") for b in raw]
            if not self._ship(batch):
                break  # sink down: retry from the same offset next cycle
            shipped += len(raw)
            offset += sum(len(b) for b in raw)
            self._save_index(offset)
        return shipped

    # -- thread lifecycle ---------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:  # keep the daemon alive on sink errors
                logger.warning("log processor cycle failed: %s", e)
            self._stop.wait(self.upload_interval_s)
        while self.poll_once():  # final drain, across read-cap cycles
            pass

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"log-daemon-{self.run_id}", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None


class MLOpsRuntimeLogDaemon:
    """Process-wide registry of log processors.

    reference: MLOpsRuntimeLogDaemon (mlops_runtime_log_daemon.py:253-362) —
    ``get_instance(args)`` singleton with start/stop per (run, edge).
    """

    _instance: Optional["MLOpsRuntimeLogDaemon"] = None
    _lock = threading.Lock()

    def __init__(self, sink: Sink):
        self.sink = sink
        self._processors: Dict[Tuple[str, int], LogProcessor] = {}

    @classmethod
    def get_instance(cls, sink: Sink) -> "MLOpsRuntimeLogDaemon":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls(sink)
            return cls._instance

    @classmethod
    def reset_instance(cls) -> None:
        with cls._lock:
            if cls._instance is not None:
                cls._instance.stop_all()
            cls._instance = None

    def start_log_processor(self, run_id: str, edge_id: int,
                            log_path: str, **kw) -> LogProcessor:
        key = (str(run_id), int(edge_id))
        if key not in self._processors:
            proc = LogProcessor(log_path, run_id, edge_id, self.sink, **kw)
            proc.start()
            self._processors[key] = proc
        return self._processors[key]

    def stop_log_processor(self, run_id: str, edge_id: int) -> None:
        proc = self._processors.pop((str(run_id), int(edge_id)), None)
        if proc is not None:
            proc.stop()

    def stop_all(self) -> None:
        for key in list(self._processors):
            self.stop_log_processor(*key)
