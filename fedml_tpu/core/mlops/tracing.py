"""Federation-wide distributed tracing: cross-process causal spans, clock
alignment, round critical-path extraction, and a crash flight recorder.

reference: Dapper (Sigelman et al., 2010) for the span/context model and
FedScale (Lai et al., 2022) for per-client latency attribution. The PR 2
telemetry plane answers "how long" (histograms); this module answers
"WHERE" — one round is ONE causal trace spanning the server, every cohort
client, and all swarm worker processes, decomposing the opaque p99
``traffic.dispatch_ready_s`` scalar into admission wait, fold-queue wait,
fold, store lookup, wire encode (server side) and decode, local train,
upload (client side).

Three planes live here:

- **Recording** (:class:`Tracer`): per-``(run_id, rank)`` span recorder,
  owned by :class:`~fedml_tpu.core.world.WorldScope` (``world.trace``) so
  handler code never touches a process singleton without a run
  discriminator (graftiso I002). Spans are emitted as ``trace_span`` JSONL
  records through the PR 2 sink; a W3C-traceparent-style context
  ``(run_id, round, span_id, parent)`` rides ``Message`` headers
  (``Message.MSG_ARG_KEY_TRACE``) so causality survives grpc/mqtt/loopback,
  the retry/dedup layer (retries become span EVENTS, dedup drops become
  annotations — never duplicate spans), and the delta delivery plane.
  Zero-cost when disabled: every entry point is one ``bool`` check that
  returns a shared no-op object; nothing on the fused path ever syncs.
- **Flight recorder**: a bounded ring of the most recent spans/events per
  world, flushed to ``flight_<run>_rank_<rank>.json`` on world shutdown,
  atexit (which covers the preemption-drain exit 75), and explicitly
  before the PR 12 ``kill_server(phase, round)`` fault hook fires — so a
  SIGKILL'd server leaves a post-mortem naming the exact protocol phase it
  died in, and the merge tool can recover the dead process's span tail
  that the write-behind JSONL buffer lost.
- **Analysis** (pure functions; ``fedml_tpu trace`` is the CLI face):
  merge per-process span files, align clocks — NTP-style offset estimation
  from monotonic send/recv timestamp pairs piggybacked on the PR 12
  heartbeat exchange, wall-clock anchoring as the fallback — extract the
  per-round critical path and straggler attribution, and export Chrome
  trace-event JSON loadable in Perfetto.
"""

from __future__ import annotations

import atexit
import glob
import json
import os
import threading
import time
import zlib
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..containers import BoundedDict

TRACE_VERSION = 1

# span-record JSONL kind (rides the PR 2 sink next to round_record et al.)
SPAN_KIND = "trace_span"
CLOCK_KIND = "trace_clock"

FLIGHT_RING_CAPACITY = 256

# inter-span gaps on the critical path below this are float noise, not a
# network/wait segment worth naming
_GAP_EPSILON_S = 1e-6


# ---------------------------------------------------------------------------
# Trace context — the wire-propagated causal identity
# ---------------------------------------------------------------------------


class TraceContext:
    """W3C-traceparent-style context ``(run_id, round, span_id, parent)``.

    Serialized as a compact 4-element JSON list inside the ``Message``
    header params, so it survives every transport (the header rides the
    length-prefixed JSON frame) and the payload-store offload path
    untouched."""

    __slots__ = ("run_id", "round_idx", "span_id", "parent")

    def __init__(self, run_id: str, round_idx: int, span_id: str,
                 parent: Optional[str] = None):
        self.run_id = str(run_id)
        self.round_idx = int(round_idx)
        self.span_id = str(span_id)
        self.parent = parent

    def to_wire(self) -> list:
        return [self.run_id, self.round_idx, self.span_id, self.parent]

    @classmethod
    def from_wire(cls, value) -> Optional["TraceContext"]:
        """Parse a header value; malformed contexts are dropped, never
        raised — a traced world must interoperate with an untraced one."""
        try:
            run_id, round_idx, span_id, parent = value
            return cls(str(run_id), int(round_idx), str(span_id),
                       None if parent is None else str(parent))
        except (TypeError, ValueError):
            return None

    def child(self, span_id: str) -> "TraceContext":
        return TraceContext(self.run_id, self.round_idx, span_id,
                            parent=self.span_id)

    def __repr__(self) -> str:  # pragma: no cover - debug only
        return (f"TraceContext(run={self.run_id}, round={self.round_idx}, "
                f"span={self.span_id}, parent={self.parent})")


# ---------------------------------------------------------------------------
# Null objects — the zero-cost-disabled face
# ---------------------------------------------------------------------------


class _NullSpan:
    """Shared no-op span: one allocation per process, every method a pass."""

    __slots__ = ()
    span_id = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def end(self) -> None:
        pass

    def event(self, name: str, **attrs) -> None:
        pass

    def annotate(self, key: str, value) -> None:
        pass

    def context(self) -> Optional[TraceContext]:
        return None


_NULL_SPAN = _NullSpan()
# the public face for call sites that gate span creation themselves
# (e.g. "only when the incoming message carried a context")
NULL_SPAN = _NULL_SPAN


# ---------------------------------------------------------------------------
# Clock-offset estimation (NTP-style, from heartbeat probe pairs)
# ---------------------------------------------------------------------------


class ClockOffsetEstimator:
    """Estimate the offset between a local and a peer monotonic clock from
    ``(t_send, t_peer_recv, t_peer_send, t_recv)`` probe pairs.

    Per pair (all seconds, sender clock for t_send/t_recv, peer clock for
    the middle two): ``offset = ((t_peer_recv - t_send) +
    (t_peer_send - t_recv)) / 2`` and ``delay = (t_recv - t_send) -
    (t_peer_send - t_peer_recv)``. The estimate keeps the minimum-delay
    pair inside a sliding window — asymmetric queuing inflates high-delay
    pairs, so the tightest round-trip is the most trustworthy sample
    (classic NTP clock filtering). ``uncertainty = delay / 2`` bounds the
    unknowable path asymmetry.
    """

    def __init__(self, window: int = 64):
        self._pairs: deque = deque(maxlen=int(window))
        self._lock = threading.Lock()

    def add_pair(self, t_send: float, t_peer_recv: float,
                 t_peer_send: float, t_recv: float) -> Tuple[float, float]:
        offset = ((t_peer_recv - t_send) + (t_peer_send - t_recv)) / 2.0
        delay = max(0.0, (t_recv - t_send) - (t_peer_send - t_peer_recv))
        with self._lock:
            self._pairs.append((delay, offset))
        return offset, delay

    @property
    def n(self) -> int:
        with self._lock:
            return len(self._pairs)

    def estimate(self) -> Optional[Tuple[float, float]]:
        """``(offset_s, uncertainty_s)`` from the min-delay pair, or None
        before the first probe."""
        with self._lock:
            if not self._pairs:
                return None
            delay, offset = min(self._pairs, key=lambda p: p[0])
        return offset, delay / 2.0


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------


class _Span:
    """An open span. Context-manager or explicit :meth:`end`; emits its
    record exactly once (idempotent end — a with-block around an explicit
    end must not double-emit)."""

    __slots__ = ("tracer", "name", "span_id", "parent", "round_idx",
                 "client", "t0_mono", "ts_wall", "events", "annot",
                 "_done")

    def __init__(self, tracer: "Tracer", name: str, span_id: str,
                 parent: Optional[str], round_idx: int,
                 client: Optional[int]):
        self.tracer = tracer
        self.name = name
        self.span_id = span_id
        self.parent = parent
        self.round_idx = round_idx
        self.client = client
        self.t0_mono = time.monotonic()
        self.ts_wall = time.time()
        self.events: List[Dict[str, Any]] = []
        self.annot: Dict[str, Any] = {}
        self._done = False

    def __enter__(self) -> "_Span":
        return self

    def __exit__(self, *exc) -> bool:
        self.end()
        return False

    def event(self, name: str, **attrs) -> None:
        """A point-in-time event inside this span (e.g. a send retry)."""
        e = {"name": name, "t": time.monotonic() - self.t0_mono}
        if attrs:
            e.update(attrs)
        self.events.append(e)

    def annotate(self, key: str, value) -> None:
        self.annot[key] = value

    def context(self) -> TraceContext:
        """The context a child (possibly across the wire) continues from."""
        return TraceContext(self.tracer.run_id, self.round_idx,
                            self.span_id)

    def end(self) -> None:
        if self._done:
            return
        self._done = True
        dur = time.monotonic() - self.t0_mono
        self.tracer._finish_span(self, dur)


class Tracer:
    """Per-(run_id, rank) span recorder + flight recorder.

    Access from serving-plane code goes through ``world.trace`` — the
    module-level index exists for construction and the pre-SIGKILL flush,
    both keyed by run identity."""

    # process index of tracers — always accessed through the (run_id,
    # rank) discriminator, mirroring telemetry's scope registry
    _tracers: Dict[Tuple[str, int], "Tracer"] = {}
    _tracers_lock = threading.Lock()

    def __init__(self, run_id: str, rank: int):
        self.run_id = str(run_id)
        self.rank = int(rank)
        self.pid = os.getpid()
        self.enabled = False
        self.sample = 1.0
        self.flight_dir = ""
        self._lock = threading.Lock()
        self._seq = 0
        self._tls = threading.local()
        self._ring: deque = deque(maxlen=FLIGHT_RING_CAPACITY)
        self._last_phase: Optional[Dict[str, Any]] = None
        # per-peer clock filters, LRU-bounded (graftmem M001): a root
        # probing 100k clients would otherwise pin one estimator each
        self._estimators: Dict[int, ClockOffsetEstimator] = BoundedDict(
            1024, lru=True, name="trace.clock_estimators")
        self._atexit_armed = False

    # -- configuration -------------------------------------------------------

    def configure(self, args) -> "Tracer":
        """Apply a run's tracing knobs (idempotent; called by WorldScope
        construction so every comm manager wires the same way)."""
        self.enabled = bool(getattr(args, "enable_tracing", False))
        raw_sample = getattr(args, "trace_sample", None)
        self.sample = (1.0 if raw_sample is None
                       else max(0.0, min(1.0, float(raw_sample))))
        self.flight_dir = str(
            getattr(args, "trace_dir", "")
            or getattr(args, "tracking_dir", "")
            or ".fedml_tpu_runs")
        if self.enabled and not self._atexit_armed:
            # atexit covers normal exit AND the preemption-drain exit 75
            # (sys.exit runs atexit hooks); SIGKILL is the flight
            # recorder's explicit pre-kill flush's business
            atexit.register(self.flush_flight, "atexit")
            self._atexit_armed = True
        return self

    def sampled(self, round_idx: int) -> bool:
        """Deterministic per-round sampling decision: a hash of
        ``(run_id, round)`` — no RNG (graftrep D002), and every process
        that asks about the same round agrees without coordination."""
        if not self.enabled:
            return False
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        h = zlib.crc32(f"{self.run_id}:{int(round_idx)}".encode("utf-8"))
        return (h / 4294967296.0) < self.sample

    # -- span recording ------------------------------------------------------

    def _next_id(self) -> str:
        with self._lock:
            self._seq += 1
            return f"{self.rank}.{self.pid}.{self._seq}"

    def span(self, name: str, round_idx: Optional[int] = None,
             parent: Optional[str] = None,
             ctx: Optional[TraceContext] = None,
             client: Optional[int] = None):
        """Open a span. ``ctx`` continues a wire-carried context (the new
        span's parent is ``ctx.span_id``); ``parent`` overrides explicitly;
        otherwise the innermost open span on this thread (or an adopted
        context) is the parent."""
        if not self.enabled:
            return _NULL_SPAN
        if ctx is not None:
            parent = ctx.span_id
            if round_idx is None:
                round_idx = ctx.round_idx
        elif parent is None:
            cur = self.current_context()
            if cur is not None:
                parent = cur.span_id
                if round_idx is None:
                    round_idx = cur.round_idx
        s = _Span(self, name, self._next_id(), parent,
                  -1 if round_idx is None else int(round_idx), client)
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(s)
        return s

    def record_span(self, name: str, t0_mono: float, dur_s: float,
                    round_idx: Optional[int] = None,
                    parent: Optional[str] = None,
                    ctx: Optional[TraceContext] = None,
                    client: Optional[int] = None,
                    **annot) -> Optional[str]:
        """Emit an already-measured span (e.g. fold-queue wait, computed
        retroactively from the enqueue timestamp). Returns its span id."""
        if not self.enabled:
            return None
        if ctx is not None:
            parent = ctx.span_id
            if round_idx is None:
                round_idx = ctx.round_idx
        now = time.monotonic()
        rec = {
            "kind": SPAN_KIND, "v": TRACE_VERSION, "run": self.run_id,
            "rank": self.rank, "pid": self.pid, "span": self._next_id(),
            "parent": parent, "name": name,
            "round": -1 if round_idx is None else int(round_idx),
            "ts": time.time() - (now - t0_mono), "mono": t0_mono,
            "dur": float(dur_s),
        }
        if client is not None:
            rec["client"] = int(client)
        if annot:
            rec["annot"] = dict(annot)
        self._emit(rec)
        return rec["span"]

    def _finish_span(self, s: _Span, dur: float) -> None:
        stack = getattr(self._tls, "stack", None)
        if stack and s in stack:
            stack.remove(s)
        rec = {
            "kind": SPAN_KIND, "v": TRACE_VERSION, "run": self.run_id,
            "rank": self.rank, "pid": self.pid, "span": s.span_id,
            "parent": s.parent, "name": s.name, "round": s.round_idx,
            "ts": s.ts_wall, "mono": s.t0_mono, "dur": float(dur),
        }
        if s.client is not None:
            rec["client"] = int(s.client)
        if s.events:
            rec["events"] = s.events
        if s.annot:
            rec["annot"] = s.annot
        self._emit(rec)

    # -- ambient context (wire receive path) ---------------------------------

    def adopt(self, ctx: Optional[TraceContext]) -> None:
        """Set the thread's ambient context (the comm manager calls this
        with the incoming message's wire context before dispatching to
        handlers, so spans opened inside — and messages sent from — the
        handler continue the sender's trace)."""
        if not self.enabled:
            return
        self._tls.adopted = ctx

    def current_context(self) -> Optional[TraceContext]:
        """Innermost open span on this thread, else the adopted wire
        context, else None."""
        if not self.enabled:
            return None
        stack = getattr(self._tls, "stack", None)
        if stack:
            return stack[-1].context()
        return getattr(self._tls, "adopted", None)

    def event(self, name: str, **attrs) -> None:
        """A point event: attached to the innermost open span when one
        exists (a send retry inside an upload span), otherwise noted in
        the flight-recorder ring only — never a standalone span, so
        retries/dedup drops can NEVER duplicate spans."""
        if not self.enabled:
            return
        stack = getattr(self._tls, "stack", None)
        if stack:
            stack[-1].event(name, **attrs)
            return
        note = {"kind": "trace_event", "run": self.run_id,
                "rank": self.rank, "name": name,
                "mono": time.monotonic()}
        if attrs:
            note.update(attrs)
        with self._lock:
            self._ring.append(note)

    # -- clock probes --------------------------------------------------------

    def clock_probe(self, peer: int, t_send: float, t_peer_recv: float,
                    t_peer_send: float,
                    t_recv: float) -> Optional[Tuple[float, float]]:
        """Feed one heartbeat probe pair; returns the refreshed
        ``(offset_s, uncertainty_s)`` estimate toward ``peer`` and emits a
        ``trace_clock`` record so the merge tool can align this process's
        monotonic timeline onto the peer's."""
        with self._lock:
            est = self._estimators.get(int(peer))
            if est is None:
                est = self._estimators[int(peer)] = ClockOffsetEstimator()
        est.add_pair(t_send, t_peer_recv, t_peer_send, t_recv)
        out = est.estimate()
        if out is not None and self.enabled:
            self._emit({
                "kind": CLOCK_KIND, "v": TRACE_VERSION, "run": self.run_id,
                "rank": self.rank, "pid": self.pid, "peer": int(peer),
                "offset_s": out[0], "uncertainty_s": out[1], "n": est.n,
            })
        return out

    def clock_offset(self, peer: int) -> Optional[Tuple[float, float]]:
        with self._lock:
            est = self._estimators.get(int(peer))
        return None if est is None else est.estimate()

    # -- flight recorder -----------------------------------------------------

    def note_phase(self, phase: str, round_idx: int) -> None:
        """Mark the protocol phase the world is entering — the post-mortem
        names the LAST mark, which is exactly the phase a no-drain SIGKILL
        died in (pairs with FaultPlan.kill_server)."""
        if not self.enabled:
            return
        mark = {"phase": str(phase), "round": int(round_idx),
                "mono": time.monotonic(), "ts": time.time()}
        with self._lock:
            self._last_phase = mark
            self._ring.append({"kind": "trace_phase", **mark})

    def flush_flight(self, reason: str = "") -> Optional[str]:
        """Write the flight-recorder post-mortem JSON (ring of recent
        spans/events, still-open spans, the last phase mark) and drain the
        write-behind JSONL sink. Safe to call repeatedly; the newest call
        wins the file. Returns the path (None when tracing is off)."""
        if not self.enabled:
            return None
        with self._lock:
            ring = list(self._ring)
            last_phase = dict(self._last_phase) if self._last_phase else None
        open_spans = []
        stack = getattr(self._tls, "stack", None)
        if stack:
            now = time.monotonic()
            for s in stack:
                open_spans.append({
                    "span": s.span_id, "parent": s.parent, "name": s.name,
                    "round": s.round_idx, "ts": s.ts_wall, "mono": s.t0_mono,
                    "dur": now - s.t0_mono, "open": True,
                })
        post = {
            "kind": "flight_recorder", "v": TRACE_VERSION,
            "run": self.run_id, "rank": self.rank, "pid": self.pid,
            "reason": str(reason), "time": time.time(),
            "last_phase": last_phase, "open_spans": open_spans,
            "ring": ring,
        }
        path = flight_path(self.flight_dir, self.run_id, self.rank)
        try:
            os.makedirs(self.flight_dir or ".", exist_ok=True)
            tmp = f"{path}.tmp.{self.pid}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(post, f)
            os.replace(tmp, path)
        except OSError:  # pragma: no cover - post-mortem must never raise
            return None
        # the main sink's buffered tail must also survive the crash window
        from fedml_tpu.core import mlops

        mlops.flush()
        return path

    # -- emission ------------------------------------------------------------

    def _emit(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            self._ring.append(rec)
        # ride the PR 2 JSONL sink (a no-op when tracking is off — the
        # flight-recorder ring still captures for the post-mortem)
        from fedml_tpu.core import mlops

        mlops._emit(dict(rec))


def tracer_for(run_id: str, rank: int = 0) -> Tracer:
    """The (run_id, rank)-keyed tracer — created disabled on first ask;
    :meth:`Tracer.configure` (via WorldScope construction) arms it."""
    key = (str(run_id), int(rank))
    with Tracer._tracers_lock:
        t = Tracer._tracers.get(key)
        if t is None:
            t = Tracer._tracers[key] = Tracer(key[0], key[1])
        return t


def flight_path(flight_dir: str, run_id: str, rank: int) -> str:
    return os.path.join(flight_dir or ".",
                        f"flight_{run_id}_rank_{int(rank)}.json")


# ---------------------------------------------------------------------------
# Analysis plane — pure functions over span/clock records
# ---------------------------------------------------------------------------


def collect_trace_files(trace_dir: str,
                        run_id: Optional[str] = None) -> List[str]:
    """Every span-bearing file in a directory: run JSONL sinks plus flight
    recorder post-mortems (sorted — merge determinism starts here)."""
    pats = ["run_*.jsonl", "flight_*.json"]
    if run_id:
        pats = [f"run_{run_id}_edge_*.jsonl", f"flight_{run_id}_rank_*.json"]
    out: List[str] = []
    for pat in pats:
        out.extend(glob.glob(os.path.join(trace_dir, pat)))
    return sorted(out)


def read_trace(paths: Sequence[str]) -> Tuple[List[dict], List[dict]]:
    """Load ``(spans, clocks)`` from JSONL sinks and flight-recorder JSON.

    Flight-recorder rings recover the span tail a SIGKILL'd process's
    write-behind buffer lost; spans present in both sources dedupe on
    their globally-unique ``(rank, pid, span)`` id, so merging a crashed
    run never double-counts."""
    spans: Dict[Tuple, dict] = {}
    clocks: List[dict] = []

    def take(rec: dict) -> None:
        kind = rec.get("kind")
        if kind == SPAN_KIND and "span" in rec:
            spans.setdefault(
                (rec.get("rank"), rec.get("pid"), rec["span"]), rec)
        elif kind == CLOCK_KIND:
            clocks.append(rec)

    for path in paths:
        try:
            if path.endswith(".jsonl"):
                with open(path, encoding="utf-8") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            take(json.loads(line))
                        except ValueError:
                            continue  # torn tail of a crashed writer
            else:
                with open(path, encoding="utf-8") as f:
                    post = json.load(f)
                for rec in post.get("ring", []):
                    rec = dict(rec)
                    rec.setdefault("rank", post.get("rank"))
                    rec.setdefault("pid", post.get("pid"))
                    take(rec)
                for rec in post.get("open_spans", []):
                    rec = dict(rec, kind=SPAN_KIND, run=post.get("run"),
                               rank=post.get("rank"), pid=post.get("pid"))
                    take(rec)
        except (OSError, ValueError):
            continue
    ordered = sorted(spans.values(),
                     key=lambda r: (r.get("rank", 0), r.get("pid", 0),
                                    r.get("mono", 0.0), r.get("span", "")))
    clocks.sort(key=lambda r: (r.get("rank", 0), r.get("pid", 0),
                               r.get("n", 0)))
    return ordered, clocks


def _proc_key(rec: dict) -> Tuple[int, int]:
    return int(rec.get("rank", 0)), int(rec.get("pid", 0))


def align_clocks(spans: Sequence[dict],
                 clocks: Sequence[dict]) -> Dict[Tuple[int, int], float]:
    """Per-process offsets that map each process's monotonic timeline onto
    a shared reference (the server process's monotonic clock).

    Primary source: heartbeat probe estimates (``trace_clock`` records —
    ``offset_s`` maps the recording process's clock onto its peer's, and
    the peer is the server). Fallback for processes that never exchanged a
    probe (swarm sim devices, the server itself): wall-clock anchoring —
    each span carries both ``ts`` (epoch) and ``mono``, so the median of
    ``ts - mono`` per process rebases everything onto the wall clock,
    then onto the server's monotonic frame. Single-host soaks share a wall
    clock, which is exactly the case the fallback serves."""
    procs: Dict[Tuple[int, int], List[float]] = {}
    for rec in spans:
        if "ts" in rec and "mono" in rec:
            procs.setdefault(_proc_key(rec), []).append(
                float(rec["ts"]) - float(rec["mono"]))
    anchors = {k: sorted(v)[len(v) // 2] for k, v in procs.items()}
    if not anchors:
        return {}
    server_proc = min(anchors,
                      key=lambda k: (k[0], -len(procs[k]), k[1]))
    server_anchor = anchors[server_proc]
    # newest probe estimate per process (records are emitted in order)
    probe: Dict[Tuple[int, int], float] = {}
    for rec in clocks:
        probe[_proc_key(rec)] = float(rec.get("offset_s", 0.0))
    offsets: Dict[Tuple[int, int], float] = {}
    for key, anchor in anchors.items():
        if key == server_proc:
            offsets[key] = 0.0
        elif key in probe:
            offsets[key] = probe[key]
        else:
            offsets[key] = anchor - server_anchor
    return offsets


def merge_trace(spans: Sequence[dict],
                clocks: Sequence[dict] = ()) -> Dict[str, Any]:
    """Merge per-process spans into one clock-aligned trace.

    Deterministic: identical inputs produce a byte-identical structure
    (stable sort keys, no wall-clock reads). Spans whose parent is missing
    after flight-recorder recovery are counted as ``orphans`` — a clean
    killed-and-recovered chaos run must merge with zero."""
    offsets = align_clocks(spans, clocks)
    merged: List[dict] = []
    index: Dict[str, dict] = {}
    for rec in spans:
        off = offsets.get(_proc_key(rec), 0.0)
        t0 = float(rec.get("mono", 0.0)) + off
        m = dict(rec)
        m["t0"] = t0
        m["t1"] = t0 + float(rec.get("dur", 0.0))
        merged.append(m)
        index[str(rec.get("span"))] = m
    if merged:
        base = min(m["t0"] for m in merged)
        for m in merged:
            m["t0"] -= base
            m["t1"] -= base
    merged.sort(key=lambda m: (m["t0"], str(m.get("span"))))
    orphans = sorted(str(m.get("span")) for m in merged
                     if m.get("parent") and str(m["parent"]) not in index)
    rounds = sorted({int(m.get("round", -1)) for m in merged
                     if int(m.get("round", -1)) >= 0})
    return {"v": TRACE_VERSION, "spans": merged, "orphans": orphans,
            "rounds": rounds,
            "procs": sorted({_proc_key(m) for m in merged})}


def critical_path(merged: Dict[str, Any],
                  round_idx: int) -> List[Dict[str, Any]]:
    """The round's gating causal chain: walk parent links back from the
    latest-finishing terminal span of the round, emitting one segment per
    span plus ``transit`` segments for inter-span gaps (network + peer
    scheduling). Empty only when the round has no spans at all."""
    spans = [m for m in merged.get("spans", [])
             if int(m.get("round", -1)) == int(round_idx)]
    if not spans:
        return []
    index = {str(m.get("span")): m for m in spans}
    terminal = max(spans, key=lambda m: (m["t1"], str(m.get("span"))))
    chain: List[dict] = []
    cur: Optional[dict] = terminal
    seen = set()
    while cur is not None and str(cur.get("span")) not in seen:
        seen.add(str(cur.get("span")))
        chain.append(cur)
        parent = cur.get("parent")
        cur = index.get(str(parent)) if parent else None
    chain.reverse()
    path: List[Dict[str, Any]] = []
    prev: Optional[dict] = None
    for m in chain:
        if prev is not None:
            gap = m["t0"] - prev["t1"]
            if gap > _GAP_EPSILON_S:
                path.append({"name": "transit", "dur_s": gap,
                             "rank": m.get("rank"),
                             "from": prev.get("name"),
                             "to": m.get("name")})
        seg = {"name": m.get("name"), "dur_s": float(m.get("dur", 0.0)),
               "rank": m.get("rank"), "span": m.get("span")}
        if m.get("client") is not None:
            seg["client"] = m["client"]
        path.append(seg)
        prev = m
    return path


def critical_path_shares(merged: Dict[str, Any]) -> Dict[str, float]:
    """Aggregate critical-path time by segment name over every round —
    the 'where do the gating milliseconds go' distribution."""
    totals: Dict[str, float] = {}
    for r in merged.get("rounds", []):
        for seg in critical_path(merged, r):
            totals[seg["name"]] = (totals.get(seg["name"], 0.0)
                                   + float(seg["dur_s"]))
    return totals


def straggler_attribution(merged: Dict[str, Any],
                          k: int = 5) -> List[Dict[str, Any]]:
    """Top-k clients by attributed wait: per round, a client's chain-end
    lateness relative to the round's fastest client chain (the FedScale
    framing — who gates, not who averages worst), summed over rounds."""
    by_round: Dict[int, Dict[int, float]] = {}
    for m in merged.get("spans", []):
        client = m.get("client")
        r = int(m.get("round", -1))
        if client is None or r < 0:
            continue
        ends = by_round.setdefault(r, {})
        c = int(client)
        ends[c] = max(ends.get(c, 0.0), float(m["t1"]))
    waits: Dict[int, float] = {}
    rounds_gated: Dict[int, int] = {}
    for r, ends in by_round.items():
        if len(ends) < 2:
            continue
        fastest = min(ends.values())
        slowest = max(ends, key=lambda c: ends[c])
        for c, t1 in ends.items():
            waits[c] = waits.get(c, 0.0) + (t1 - fastest)
        rounds_gated[slowest] = rounds_gated.get(slowest, 0) + 1
    top = sorted(waits, key=lambda c: (-waits[c], c))[:int(k)]
    return [{"client": c, "wait_s": waits[c],
             "rounds_gated": rounds_gated.get(c, 0)} for c in top]


def dispatch_ready_from_trace(
        merged: Dict[str, Any]) -> Tuple[float, int]:
    """Sum of traced server-side dispatch→ready segments per folded
    update: the histogram's window opens at the enqueue stamp, and
    ``queue_wait + fold`` cover it additively (the admission span overlaps
    the pre-enqueue part of the receive path), so their sum must reconcile
    with the measured ``traffic.dispatch_ready_s`` total within 5%
    (acceptance gate). Folds the histogram never observed — stale or
    undecodable updates, annotated ``outcome`` — are excluded. Returns
    ``(total_seconds, folds)``."""
    spans = merged.get("spans", [])
    index = {str(m.get("span")): m for m in spans}
    total = 0.0
    folds = 0
    for m in spans:
        if m.get("name") != "fold":
            continue
        if (m.get("annot") or {}).get("outcome") in ("stale",
                                                     "undecodable"):
            continue
        folds += 1
        total += float(m.get("dur", 0.0))
        cur = index.get(str(m.get("parent")))
        if cur is not None and cur.get("name") == "queue_wait":
            total += float(cur.get("dur", 0.0))
    return total, folds


def to_chrome(merged: Dict[str, Any]) -> Dict[str, Any]:
    """Chrome trace-event JSON (Perfetto-loadable): one complete ('X')
    event per span, processes keyed by federation rank."""
    events: List[dict] = []
    for rank, pid in merged.get("procs", []):
        events.append({
            "ph": "M", "name": "process_name", "pid": rank, "tid": 0,
            "args": {"name": (f"server rank {rank}" if rank == 0
                              else f"client rank {rank}") + f" (pid {pid})"},
        })
    for m in merged.get("spans", []):
        args: Dict[str, Any] = {"round": m.get("round"),
                                "span": m.get("span")}
        if m.get("client") is not None:
            args["client"] = m["client"]
        if m.get("annot"):
            args.update(m["annot"])
        ev = {
            "ph": "X", "name": m.get("name"),
            "cat": f"round_{m.get('round')}",
            "pid": int(m.get("rank", 0)), "tid": int(m.get("pid", 0)),
            "ts": round(m["t0"] * 1e6, 3),
            "dur": round(float(m.get("dur", 0.0)) * 1e6, 3),
            "args": args,
        }
        events.append(ev)
        for e in m.get("events", []) or []:
            events.append({
                "ph": "i", "name": e.get("name"), "s": "t",
                "pid": int(m.get("rank", 0)), "tid": int(m.get("pid", 0)),
                "ts": round((m["t0"] + float(e.get("t", 0.0))) * 1e6, 3),
                "args": {k: v for k, v in e.items()
                         if k not in ("name", "t")},
            })
    return {"traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"run": (merged.get("spans") or [{}])[0].get(
                "run", ""), "format": "fedml_tpu.tracing"}}


def read_postmortem(flight_dir: str, run_id: str,
                    rank: int = 0) -> Optional[Dict[str, Any]]:
    """Load a flight-recorder post-mortem, if one was flushed."""
    path = flight_path(flight_dir, run_id, rank)
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
