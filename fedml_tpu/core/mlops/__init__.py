"""MLOps-lite: event tracing, metrics, and system stats.

reference: ``core/mlops/`` (2,217 LoC) — MLOpsProfilerEvent emitting
{run_id, edge_id, event_name, started/ended_time} to MQTT + wandb
(mlops_profiler_event.py:9-126), MLOpsMetrics status/metrics topics
(mlops_metrics.py:18-303), SysStats (system_stats.py:8-165), and the
``mlops.event/log/log_round_info`` facade (core/mlops/__init__.py:71-385).

TPU re-design: the platform plane (open.fedml.ai MQTT/HTTP agents) is
replaced by pluggable local sinks — python logging, a JSONL event file, and
wandb when importable — plus ``jax.profiler`` trace capture for device-level
profiling. Event names used by the runtimes are kept from the reference
(train / agg / comm_c2s / server.wait) so dashboards translate 1:1.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger("fedml_tpu.mlops")

# write-behind sink bounds: a flush happens when the buffer holds this many
# events regardless of the interval knob, so a burst can never grow the
# buffer unboundedly between interval ticks
BUFFER_EVENT_LIMIT = 256


class MLOpsStore:
    """Process-wide sink registry (reference: MLOpsStore at __init__.py:46).

    The JSONL sink is write-behind: ``_emit`` appends to ``_buffer`` and the
    emitting thread drains it to disk when ``flush_interval_s`` has elapsed
    since the last drain (or the buffer hits :data:`BUFFER_EVENT_LIMIT`, or
    someone calls :func:`flush`). ``flush_interval_s == 0`` restores the
    legacy syscall-per-event behavior. Zero-loss is guaranteed through the
    atexit-registered :func:`close` — including the preemption-drain exit 75
    path, which leaves via ``sys.exit`` and therefore runs atexit hooks.
    """

    _sink_lock = threading.Lock()
    enabled: bool = False
    run_id: str = "0"
    edge_id: int = 0
    jsonl_path: Optional[str] = None
    _jsonl_file = None
    _buffer: List[str] = []
    flush_interval_s: float = 0.5
    _last_flush: float = 0.0
    use_wandb: bool = False
    _wandb = None
    _atexit_registered: bool = False


def init(args) -> None:
    """reference: mlops.init(args) — binds run/edge ids, opens sinks."""
    if MLOpsStore._jsonl_file is not None:
        # re-init (tests, bench's post-measurement tracked pass): never leak
        # the previous run's file handle
        close()
    MLOpsStore.enabled = bool(getattr(args, "enable_tracking", False))
    MLOpsStore.run_id = str(getattr(args, "run_id", "0"))
    MLOpsStore.edge_id = int(getattr(args, "rank", 0))
    MLOpsStore.jsonl_path = None  # never point at a previous run's file
    MLOpsStore.use_wandb = False
    raw_interval = getattr(args, "tracking_flush_s", None)
    MLOpsStore.flush_interval_s = (
        0.5 if raw_interval is None else max(0.0, float(raw_interval)))
    with MLOpsStore._sink_lock:
        MLOpsStore._buffer = []
        MLOpsStore._last_flush = time.monotonic()
    if MLOpsStore.enabled:
        out_dir = str(getattr(args, "tracking_dir", "") or ".fedml_tpu_runs")
        os.makedirs(out_dir, exist_ok=True)
        MLOpsStore.jsonl_path = os.path.join(
            out_dir, f"run_{MLOpsStore.run_id}_edge_{MLOpsStore.edge_id}.jsonl"
        )
        MLOpsStore._jsonl_file = open(MLOpsStore.jsonl_path, "a")
        if bool(getattr(args, "enable_wandb", False)):
            try:
                import wandb

                MLOpsStore._wandb = wandb
                MLOpsStore.use_wandb = True
            except ImportError:
                logger.warning("wandb requested but not importable; skipping")
    from . import telemetry

    telemetry.init(args)
    if not MLOpsStore._atexit_registered:
        # durability: short runs must not lose their JSONL tail, and a
        # --profile_rounds window or --metrics_file configured WITHOUT
        # tracking still needs its trace stopped / exposition flushed when
        # the interpreter exits — so the hook registers regardless of
        # enable_tracking
        atexit.register(close)
        MLOpsStore._atexit_registered = True


def close() -> None:
    """Flush telemetry and close the JSONL sink (atexit-registered).

    Runs even when tracking is off: an open ``--profile_rounds`` trace must
    be stopped and a ``--metrics_file`` exposition force-written whether or
    not a JSONL sink exists."""
    from . import telemetry

    try:
        telemetry.close()  # summary event must land before the file shuts
    except Exception:  # pragma: no cover - shutdown must never raise
        logger.exception("telemetry close failed")
    with MLOpsStore._sink_lock:
        f, MLOpsStore._jsonl_file = MLOpsStore._jsonl_file, None
        pending, MLOpsStore._buffer = MLOpsStore._buffer, []
    if f is not None:
        try:
            if pending:
                f.write("".join(pending))
            f.flush()
            f.close()
        except OSError:
            pass


def flush() -> None:
    """Drain the write-behind buffer to disk now (shutdown paths, readers
    of the live file, and the flight recorder's post-mortem flush)."""
    with MLOpsStore._sink_lock:
        _flush_locked()


def _flush_locked() -> None:
    if MLOpsStore._jsonl_file is None or not MLOpsStore._buffer:
        MLOpsStore._last_flush = time.monotonic()
        return
    pending, MLOpsStore._buffer = MLOpsStore._buffer, []
    try:
        MLOpsStore._jsonl_file.write("".join(pending))
        MLOpsStore._jsonl_file.flush()
    except OSError:  # pragma: no cover - disk-full etc.; keep serving
        pass
    MLOpsStore._last_flush = time.monotonic()


def _emit(record: Dict[str, Any]) -> None:
    if not MLOpsStore.enabled:
        return
    record.setdefault("run_id", MLOpsStore.run_id)
    record.setdefault("edge_id", MLOpsStore.edge_id)
    record.setdefault("time", time.time())
    with MLOpsStore._sink_lock:
        if MLOpsStore._jsonl_file is not None:
            MLOpsStore._buffer.append(json.dumps(record) + "\n")
            now = time.monotonic()
            if (len(MLOpsStore._buffer) >= BUFFER_EVENT_LIMIT
                    or now - MLOpsStore._last_flush
                    >= MLOpsStore.flush_interval_s):
                _flush_locked()
    logger.debug("mlops: %s", record)


def event(event_name: str, event_started: bool = True,
          event_value: Optional[str] = None) -> None:
    """reference: mlops.event(...) → MLOpsProfilerEvent.log_event_started/
    ended; scenario code wraps train/agg/comm_c2s/server.wait phases."""
    _emit({
        "kind": "event",
        "event_name": event_name,
        "phase": "started" if event_started else "ended",
        "event_value": event_value,
    })


def log(metrics: Dict[str, Any], step: Optional[int] = None) -> None:
    """reference: mlops.log — scalar metrics (also to wandb when enabled)."""
    _emit({"kind": "metrics", "step": step, **metrics})
    if MLOpsStore.use_wandb:
        MLOpsStore._wandb.log(metrics, step=step)


def log_round_info(round_index: int, total_rounds: int) -> None:
    """reference: mlops.log_round_info (core/mlops/__init__.py:354-384)."""
    _emit({"kind": "round_info", "round_index": round_index,
           "total_rounds": total_rounds})


def log_training_status(status: str) -> None:
    _emit({"kind": "client_status", "status": status})


def log_aggregation_status(status: str) -> None:
    _emit({"kind": "server_status", "status": status})


def device_stats() -> list:
    """Per-accelerator memory stats (the reference's nvidia-smi fields,
    ``system_stats.py`` gpu_* — here from the jax backend's allocator)."""
    out = []
    try:
        import jax

        for d in jax.devices():
            stats = {}
            try:
                stats = d.memory_stats() or {}
            except Exception:
                pass
            used = int(stats.get("bytes_in_use", 0))
            limit = int(stats.get("bytes_limit", 0))
            out.append({
                "device": str(d),
                "kind": getattr(d, "device_kind", "?"),
                "mem_used_mb": round(used / 1e6, 1),
                "mem_limit_mb": round(limit / 1e6, 1),
                "mem_util": round(used / limit, 4) if limit else None,
                "peak_mb": round(
                    int(stats.get("peak_bytes_in_use", 0)) / 1e6, 1
                ),
            })
    except Exception:
        pass
    return out


def log_sys_perf() -> None:
    """reference: SysStats via psutil/nvidia (system_stats.py:8-165) —
    host CPU/mem plus per-device HBM utilization."""
    entry = {"kind": "sys_perf", "devices": device_stats()}
    try:
        import psutil

        p = psutil.Process()
        entry.update({
            "cpu_percent": psutil.cpu_percent(interval=None),
            "mem_rss_mb": p.memory_info().rss / 1e6,
            "mem_percent": psutil.virtual_memory().percent,
        })
    except ImportError:
        pass
    _emit(entry)


class MLOpsProfilerEvent:
    """Span helper (reference: mlops_profiler_event.py) + context manager."""

    def __init__(self, name: str):
        self.name = name
        self.t0 = 0.0

    def __enter__(self):
        self.t0 = time.perf_counter()
        event(self.name, event_started=True)
        return self

    def __exit__(self, *exc):
        event(self.name, event_started=False,
              event_value=f"{time.perf_counter() - self.t0:.6f}s")
        return False


def profile_trace(log_dir: str):
    """Device-level profiling: jax.profiler trace context (the TPU-native
    replacement for the reference's wandb latency spans)."""
    import jax

    return jax.profiler.trace(log_dir)


def read_events(path: Optional[str] = None) -> List[Dict[str, Any]]:
    """Load a run's JSONL event log (test/debug helper)."""
    p = path or MLOpsStore.jsonl_path
    if p is not None and p == MLOpsStore.jsonl_path:
        flush()  # reading the live sink: drain the write-behind buffer first
    if p is None or not os.path.exists(p):
        return []
    with open(p) as f:
        return [json.loads(line) for line in f if line.strip()]


def phase_totals(events: List[Dict[str, Any]]) -> tuple:
    """Sum ``round_record`` phase durations over an event list.

    Returns ``({phase: total_seconds}, record_count)`` — the per-phase
    breakdown bench legs attach to BENCH_*.json."""
    totals: Dict[str, float] = {}
    n = 0
    for e in events:
        if e.get("kind") != "round_record":
            continue
        n += 1
        for name, dur in (e.get("phases") or {}).items():
            totals[name] = totals.get(name, 0.0) + float(dur)
    return totals, n
