"""Remote run-config fetch with local caching.

reference: ``core/mlops/mlops_configs.py:14-137`` — MLOpsConfigs singleton
POSTs ``{"config_name": ["mqtt_config", "s3_config", ...]}`` to
``…/fedmlOpsServer/configs/fetch`` (per config_version release/test/dev/
local) and hands the returned transport endpoints to the agents.

TPU re-grounding: the fetch contract is kept — named config sections
resolved from a remote source at run start — but the source is a URI that
covers how pod jobs actually receive config: ``http(s)://`` endpoints, plain
file paths / ``file://`` URIs (shared filesystem), or an env-var override.
Every successful fetch is cached to disk and the cache is the fallback when
the source is unreachable, so a transient control-plane outage does not keep
a pod from (re)starting — the failure-recovery behavior the reference's
agents get from retrying MQTT/S3 config fetches.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger("fedml_tpu.mlops.remote_config")

ENV_CONFIG_URI = "FEDML_TPU_CONFIG_URI"
DEFAULT_CACHE_DIR = ".fedml_tpu_runs"
CACHE_FILE = "remote_config_cache.json"

# reference: json_params config_name list (mlops_configs.py:79,96,113)
DEFAULT_SECTIONS = ["mqtt_config", "s3_config", "ml_ops_config"]


class RemoteConfig:
    """Singleton fetch-with-cache (reference: MLOpsConfigs.get_instance)."""

    _instance: Optional["RemoteConfig"] = None
    _lock = threading.Lock()

    def __init__(self, uri: Optional[str] = None,
                 cache_dir: str = DEFAULT_CACHE_DIR):
        self.uri = uri or os.environ.get(ENV_CONFIG_URI, "")
        self.cache_dir = cache_dir
        self.cache_path = os.path.join(cache_dir, CACHE_FILE)

    @classmethod
    def get_instance(cls, uri: Optional[str] = None,
                     cache_dir: str = DEFAULT_CACHE_DIR) -> "RemoteConfig":
        """Return the process-wide env-configured instance, or a fresh
        standalone one when explicit parameters are passed — an explicit
        ``uri``/``cache_dir`` must not silently repoint unrelated callers,
        and must not be silently ignored because an instance already exists."""
        if uri is not None or cache_dir != DEFAULT_CACHE_DIR:
            return cls(uri, cache_dir)
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls(uri, cache_dir)
            return cls._instance

    @classmethod
    def reset_instance(cls) -> None:
        with cls._lock:
            cls._instance = None

    # -- sources ------------------------------------------------------------

    def _fetch_raw(self) -> Dict[str, Any]:
        uri = self.uri
        if not uri:
            raise FileNotFoundError("no config URI set (FEDML_TPU_CONFIG_URI)")
        if uri.startswith(("http://", "https://")):
            import urllib.request

            req = urllib.request.Request(
                uri, headers={"Accept": "application/json"}
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                return json.loads(resp.read().decode())
        path = uri[7:] if uri.startswith("file://") else uri
        with open(path) as f:
            return json.load(f)

    # -- cache --------------------------------------------------------------

    def _save_cache(self, cfg: Dict[str, Any]) -> None:
        os.makedirs(self.cache_dir, exist_ok=True)
        tmp = self.cache_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"fetched_at": time.time(), "config": cfg}, f)
        os.replace(tmp, self.cache_path)

    def _load_cache(self) -> Optional[Dict[str, Any]]:
        try:
            with open(self.cache_path) as f:
                return json.load(f)["config"]
        except (OSError, ValueError, KeyError):
            return None

    # -- public API ---------------------------------------------------------

    def fetch_configs(
        self, sections: Optional[List[str]] = None
    ) -> Dict[str, Any]:
        """Resolve the named sections (reference: fetch_all_configs returning
        (mqtt_config, s3_config, mlops_config, docker_config)).

        Remote first; disk cache on failure; raises only when both miss.
        """
        sections = sections or DEFAULT_SECTIONS
        try:
            cfg = self._fetch_raw()
            # the reference's endpoint nests payload under data
            cfg = cfg.get("data", cfg) if isinstance(cfg, dict) else cfg
            self._save_cache(cfg)
        except Exception as e:
            cached = self._load_cache()
            if cached is None:
                raise RuntimeError(
                    f"remote config fetch failed ({e}) and no cache exists"
                ) from e
            logger.warning("remote config unreachable (%s); using cache", e)
            cfg = cached
        return {name: cfg.get(name, {}) for name in sections}


def fetch_configs(uri: Optional[str] = None,
                  sections: Optional[List[str]] = None,
                  cache_dir: str = DEFAULT_CACHE_DIR) -> Dict[str, Any]:
    """Module-level convenience mirroring MLOpsConfigs.fetch_all_configs."""
    return RemoteConfig.get_instance(uri, cache_dir).fetch_configs(sections)
