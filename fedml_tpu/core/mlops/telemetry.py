"""Round telemetry plane: metrics registry, phase spans, profiler windows.

The reference ships a 2,217-LoC MLOps plane (MLOpsProfilerEvent spans,
MLOpsMetrics, SysStats) whose unit of observation is a *message* — fine for
an actor federation, blind for this port where PR 1 collapsed a whole FedAvg
round into one donated XLA dispatch. The unit of observation here is the
**round** (or the Cheetah step): where inside it time goes (sample / gather /
train / aggregate / device wait), how long dispatch→ready takes on the fused
path, how HBM grows, and how often XLA recompiles.

Three layers, all process-wide:

- :class:`MetricsRegistry` — counters, gauges, and fixed-bucket histograms
  with p50/p95/p99 interpolation. Counter bumps are a dict update under a
  lock (always on — the comm plane counts bytes/messages whether or not a
  run is tracked). Rendered as Prometheus text exposition to
  ``--metrics_file``.
- **RoundRecord** — one structured JSONL event per round: phase span
  durations, dispatch→``block_until_ready`` latency (fused path), HBM
  used/peak from :func:`device_stats`, examples processed, a rounds/s EMA,
  and compile events (via ``jax.monitoring`` listeners, which also count
  persistent-compilation-cache hits/misses).
- **Profiler windows** — ``--profile_rounds N:M`` opens a ``jax.profiler``
  trace for rounds [N, M) and closes it after, no code changes in the run.

Zero-cost contract: with tracking disabled, :func:`begin_round` returns
``None`` after one boolean check, :func:`phase` returns a shared no-op
context manager, and the fused round path performs NO extra host sync
(``block_until_ready`` only runs under an active record) — pinned by
``tests/test_telemetry.py``.
"""

from __future__ import annotations

import bisect
import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------

# latency buckets in seconds: 100 µs .. 2 min, the dispatch-to-superround span
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

# peak bf16 FLOPs/s per chip by device kind (public spec sheets) — the MFU
# denominator for the Cheetah runner's live estimate (bench.py keeps its own
# copy because its parent process must never import this package's deps)
PEAK_BF16_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


class Histogram:
    """Fixed-bucket histogram with interpolated quantiles."""

    __slots__ = ("buckets", "counts", "count", "sum")

    def __init__(self, buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # +1 overflow bucket
        self.count = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        self.counts[bisect.bisect_left(self.buckets, value)] += 1
        self.count += 1
        self.sum += value

    def quantile(self, q: float) -> Optional[float]:
        """Linear interpolation inside the bucket holding quantile ``q``."""
        if self.count == 0:
            return None
        target = q * self.count
        acc = 0.0
        lo = 0.0
        for i, c in enumerate(self.counts):
            hi = self.buckets[i] if i < len(self.buckets) else self.buckets[-1]
            if c and acc + c >= target:
                if i >= len(self.buckets):  # overflow: no upper bound
                    return max(hi, self.sum / self.count)
                return lo + (hi - lo) * ((target - acc) / c)
            acc += c
            lo = hi
        return self.buckets[-1]

    def summary(self) -> Dict[str, Any]:
        return {
            "count": self.count,
            "sum": round(self.sum, 6),
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
        }


class MetricsRegistry:
    """Process-wide counters / gauges / histograms (thread-safe)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, Histogram] = {}

    # -- write side ---------------------------------------------------------
    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value

    def gauge_set(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def observe(self, name: str, value: float,
                buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(buckets)
            h.observe(float(value))

    # -- read side ----------------------------------------------------------
    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.summary() for k, h in self._hists.items()},
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()

    # -- Prometheus text exposition ----------------------------------------
    @staticmethod
    def _prom_name(name: str) -> str:
        safe = "".join(c if (c.isalnum() or c == "_") else "_" for c in name)
        return f"fedml_{safe}"

    def render_prometheus(self) -> str:
        """Text exposition: counters/gauges as single samples, histograms as
        cumulative ``_bucket{le=...}`` series only — a histogram family must
        not mix in summary-style quantile samples or expfmt parsers reject
        the whole file (quantiles stay available via ``snapshot()`` and
        ``histogram_quantile()`` server-side)."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {
                k: (h.buckets, list(h.counts), h.count, h.sum)
                for k, h in self._hists.items()
            }
        lines: List[str] = []
        for name, v in sorted(counters.items()):
            pn = self._prom_name(name) + "_total"
            lines += [f"# TYPE {pn} counter", f"{pn} {v:g}"]
        for name, v in sorted(gauges.items()):
            pn = self._prom_name(name)
            lines += [f"# TYPE {pn} gauge", f"{pn} {v:g}"]
        for name, (buckets, counts, count, total) in sorted(hists.items()):
            pn = self._prom_name(name)
            lines.append(f"# TYPE {pn} histogram")
            acc = 0
            for le, c in zip(buckets, counts):
                acc += c
                lines.append(f'{pn}_bucket{{le="{le:g}"}} {acc}')
            lines.append(f'{pn}_bucket{{le="+Inf"}} {count}')
            lines.append(f"{pn}_sum {total:g}")
            lines.append(f"{pn}_count {count}")
        return "\n".join(lines) + "\n"


_REG = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REG


def counter_inc(name: str, value: float = 1.0) -> None:
    _REG.inc(name, value)


def gauge_set(name: str, value: float) -> None:
    _REG.gauge_set(name, value)


def observe(name: str, value: float) -> None:
    _REG.observe(name, value)


# ---------------------------------------------------------------------------
# Run-scoped telemetry (the serving plane's world-keyed metrics facade)
# ---------------------------------------------------------------------------


class TelemetryScope:
    """A run identity's view of a metrics registry.

    Serving-plane handler/worker code bumps counters through the scope
    carried on its :class:`~fedml_tpu.core.world.WorldScope`
    (``self.world.telemetry.counter_inc(...)``) instead of the module
    helpers — the process-wide registry is then reachable from a handler
    only through an explicit run discriminator (graftiso I002,
    docs/graftiso.md). In a single-tenant process the default scope wraps
    the process-global registry, so every existing counter name, the
    Prometheus exposition, and ``fedml_tpu top`` are unchanged; the
    multi-tenant serving plane installs dedicated per-run registries via
    :func:`install_scope` without touching a single call site.
    """

    __slots__ = ("run_id", "registry")

    def __init__(self, run_id: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.run_id = run_id
        self.registry = registry if registry is not None else MetricsRegistry()

    def counter_inc(self, name: str, value: float = 1.0) -> None:
        self.registry.inc(name, value)

    def gauge_set(self, name: str, value: float) -> None:
        self.registry.gauge_set(name, value)

    def observe(self, name: str, value: float,
                buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> None:
        self.registry.observe(name, value, buckets)

    def counter(self, name: str) -> float:
        return self.registry.counter(name)

    def snapshot(self) -> Dict[str, Any]:
        return self.registry.snapshot()


_DEFAULT_SCOPE = TelemetryScope(run_id=None, registry=_REG)

# dedicated per-run scopes (multi-tenant serving): run_id -> scope.
# Accessed only through scope_for/install_scope with the run discriminator.
_SCOPES: Dict[str, TelemetryScope] = {}
_SCOPES_LOCK = threading.Lock()


def default_scope() -> TelemetryScope:
    """The process-global scope (wraps the module registry)."""
    return _DEFAULT_SCOPE


def scope_for(run_id: Optional[str] = None) -> TelemetryScope:
    """The telemetry scope for a run identity.

    Returns the process-global default unless a dedicated scope was
    installed for ``run_id`` (:func:`install_scope` — the multi-tenant
    hook), so single-tenant behavior is bitwise what it always was."""
    if run_id is None:
        return _DEFAULT_SCOPE
    with _SCOPES_LOCK:
        return _SCOPES.get(str(run_id), _DEFAULT_SCOPE)


def install_scope(run_id: str) -> TelemetryScope:
    """Create (or return) a dedicated registry-backed scope for a run —
    the multi-tenant serving plane's per-tenant metrics namespace."""
    with _SCOPES_LOCK:
        scope = _SCOPES.get(str(run_id))
        if scope is None:
            scope = _SCOPES[str(run_id)] = TelemetryScope(run_id=str(run_id))
        return scope


def uninstall_scope(run_id: str) -> None:
    with _SCOPES_LOCK:
        _SCOPES.pop(str(run_id), None)


# ---------------------------------------------------------------------------
# Process state + init
# ---------------------------------------------------------------------------


class _State:
    enabled: bool = False
    metrics_file: Optional[str] = None
    profiler: Optional["ProfilerWindow"] = None
    ema_rounds_per_sec: Optional[float] = None
    last_metrics_write: float = 0.0
    metrics_write_interval_s: float = 2.0


_TLS = threading.local()  # .record — the in-flight RoundRecord, if any

# guards _State's mutable run-state (EMA, metrics-file throttle) and the
# metrics tmp-file write: cross-silo rounds close on a comm receive thread
# while close()/atexit and the sys-perf sampler touch the same state
# (graftlint G005)
_STATE_LOCK = threading.Lock()


def enabled() -> bool:
    return _State.enabled


def set_enabled(flag: bool) -> None:
    """Test / embedding hook; normal runs go through :func:`init`."""
    _State.enabled = bool(flag)


def init(args) -> None:
    """Configure the plane from a run's args (called by ``mlops.init``)."""
    _State.enabled = bool(getattr(args, "enable_tracking", False))
    _State.metrics_file = str(getattr(args, "metrics_file", "") or "") or None
    _State.ema_rounds_per_sec = None
    _State.last_metrics_write = 0.0
    _TLS.record = None
    spec = str(getattr(args, "profile_rounds", "") or "")
    if spec:
        log_dir = (str(getattr(args, "profile_dir", "") or "")
                   or str(getattr(args, "tracking_dir", "") or "")
                   or ".fedml_tpu_runs")
        _State.profiler = ProfilerWindow.parse(spec, log_dir)
    else:
        _State.profiler = None
    if _State.enabled:
        install_jax_listeners()


def close() -> None:
    """Flush-and-summarise hook (run at ``mlops`` shutdown, before the JSONL
    sink closes): force the metrics file out and emit one summary event with
    the full registry snapshot so ``fedml cache`` / post-mortems can read
    compile-cache hit rates from the run log alone."""
    prof = _State.profiler
    if prof is not None and prof.active:
        prof.force_stop()
    if _State.enabled:
        from . import _emit

        _emit({"kind": "telemetry_summary", "metrics": _REG.snapshot(),
               "rounds_per_sec_ema": _State.ema_rounds_per_sec})
    write_metrics_file(force=True)


def write_metrics_file(force: bool = False) -> Optional[str]:
    """Write the Prometheus exposition to ``--metrics_file`` (throttled).

    The throttle check-and-set and the tmp-file write/replace both run under
    ``_STATE_LOCK``: two threads racing the same ``.tmp`` path would corrupt
    the exposition file."""
    path = _State.metrics_file
    if path is None:
        return None
    import os

    now = time.monotonic()
    with _STATE_LOCK:
        if (not force and now - _State.last_metrics_write
                < _State.metrics_write_interval_s):
            return None
        _State.last_metrics_write = now
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(_REG.render_prometheus())
        os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# jax.monitoring listeners: compile events + compilation-cache hit/miss
# ---------------------------------------------------------------------------

_LISTENERS_INSTALLED = False

_EVENT_COUNTERS = {
    "/jax/compilation_cache/cache_hits": "jax.compilation_cache.hits",
    "/jax/compilation_cache/cache_misses": "jax.compilation_cache.misses",
}


def install_jax_listeners() -> bool:
    """Count XLA compiles and persistent-cache hits/misses into the registry.

    ``jax.monitoring`` has no unregister API, so this installs once per
    process; the listeners only touch the registry (no jax state). The
    install-once latch is checked AND flipped under ``_STATE_LOCK``
    (graftiso I001): two runs initialising on different threads — the
    multi-tenant shape — must not both register and double-count every
    compile."""
    global _LISTENERS_INSTALLED
    try:
        from jax import monitoring
    except ImportError:  # pragma: no cover - jax is a hard dep in practice
        return False

    def on_event(event: str, **kw) -> None:
        name = _EVENT_COUNTERS.get(event)
        if name is not None:
            _REG.inc(name)

    def on_duration(event: str, duration_secs: float, **kw) -> None:
        if event == "/jax/core/compile/backend_compile_duration":
            _REG.inc("jax.compiles")
            _REG.observe("jax.compile.seconds", duration_secs)
        elif event == "/jax/compilation_cache/compile_time_saved_sec":
            _REG.inc("jax.compilation_cache.time_saved_s", duration_secs)

    with _STATE_LOCK:
        if _LISTENERS_INSTALLED:
            return True
        monitoring.register_event_listener(on_event)
        monitoring.register_event_duration_secs_listener(on_duration)
        _LISTENERS_INSTALLED = True
    return True


# ---------------------------------------------------------------------------
# Phase spans
# ---------------------------------------------------------------------------


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "t0", "record")

    def __init__(self, name: str, record: bool = True):
        self.name = name
        self.t0 = 0.0
        self.record = record

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self.t0
        if self.record:
            rec = getattr(_TLS, "record", None)
            if rec is not None:
                rec.phases[self.name] = rec.phases.get(self.name, 0.0) + dt
        _REG.observe(f"phase.{self.name}.seconds", dt)
        return False


def phase(name: str, record: bool = True):
    """Span context manager: attributes its duration to the in-flight
    RoundRecord (if any) and the ``phase.<name>.seconds`` histogram.
    A shared no-op when tracking is disabled.

    ``record=False`` keeps the histogram but stays out of the RoundRecord —
    for sub-spans nested inside a recorded phase (the mesh engine's
    placement spans run inside the sp base's sample/prep spans), whose
    double-counted time would push a record's phase sum past its wall."""
    if not _State.enabled:
        return _NULL_SPAN
    return _Span(name, record)


# ---------------------------------------------------------------------------
# RoundRecord lifecycle
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class RoundRecord:
    """One round's (or one Cheetah step's) structured telemetry."""

    round_idx: int
    fused: bool = False
    superround: bool = False
    phases: Dict[str, float] = dataclasses.field(default_factory=dict)
    wall_s: float = 0.0
    dispatch_latency_s: Optional[float] = None  # dispatch → block_until_ready
    examples: Optional[float] = None
    train_loss: Optional[float] = None
    rounds_per_sec_ema: Optional[float] = None
    hbm_used_mb: Optional[float] = None
    hbm_peak_mb: Optional[float] = None
    compiles: int = 0
    # lazy device scalars realized at end_round (one sync, tracking-on only)
    lazy: Dict[str, Any] = dataclasses.field(default_factory=dict)
    t0: float = 0.0
    _compiles0: float = 0.0

    def to_event(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d.pop("lazy", None)
        d.pop("t0", None)
        d.pop("_compiles0", None)
        d["phases"] = {k: round(v, 6) for k, v in self.phases.items()}
        d["wall_s"] = round(self.wall_s, 6)
        return {"kind": "round_record", **d}


def current_record() -> Optional[RoundRecord]:
    return getattr(_TLS, "record", None)


def record_lazy(name: str, value: Any) -> None:
    """Stash a device scalar on the in-flight record; realized (ONE host
    sync) at :func:`end_round`. No-op without an active record."""
    rec = getattr(_TLS, "record", None)
    if rec is not None:
        rec.lazy[name] = value


def begin_round(round_idx: int, fused: bool = False,
                superround: bool = False) -> Optional[RoundRecord]:
    """Open a RoundRecord; ``None`` (after one bool check) when disabled."""
    if not _State.enabled:
        return None
    rec = RoundRecord(round_idx=int(round_idx), fused=fused,
                      superround=superround)
    rec.t0 = time.perf_counter()
    rec._compiles0 = _REG.counter("jax.compiles")
    _TLS.record = rec
    return rec


def _update_ema(inst_rounds_per_sec: float) -> float:
    with _STATE_LOCK:  # read-modify-write shared with comm-thread rounds
        prev = _State.ema_rounds_per_sec
        ema = (inst_rounds_per_sec if prev is None
               else 0.9 * prev + 0.1 * inst_rounds_per_sec)
        _State.ema_rounds_per_sec = ema
        return ema


def _hbm_fields(rec: RoundRecord) -> None:
    from . import device_stats

    stats = device_stats()
    if stats:
        rec.hbm_used_mb = stats[0].get("mem_used_mb")
        rec.hbm_peak_mb = stats[0].get("peak_mb")


def _realize(value: Any) -> Optional[float]:
    if value is None:
        return None
    try:
        import numpy as np

        return float(np.asarray(value))
    except Exception:
        return None


def end_round(rec: Optional[RoundRecord],
              train_loss: Any = None, wall_s: Optional[float] = None) -> None:
    """Close a RoundRecord: realize lazy device scalars (the one host sync
    tracking buys), stamp HBM + EMA + compile count, emit the JSONL event,
    bump registry counters, and maybe refresh the metrics file."""
    if rec is None:
        return
    from . import _emit

    rec.wall_s = (time.perf_counter() - rec.t0) if wall_s is None else wall_s
    rec.train_loss = _realize(train_loss if train_loss is not None
                              else rec.lazy.get("train_loss"))
    rec.examples = _realize(rec.lazy.get("examples"))
    rec.compiles = int(_REG.counter("jax.compiles") - rec._compiles0)
    rec.rounds_per_sec_ema = _update_ema(1.0 / max(rec.wall_s, 1e-9))
    _hbm_fields(rec)
    _TLS.record = None
    _REG.inc("rounds.total")
    if rec.examples:
        _REG.inc("examples.total", rec.examples)
    _REG.observe("round.wall.seconds", rec.wall_s)
    _emit(rec.to_event())
    write_metrics_file()


def emit_superround(start_round: int, k: int, wall_s: float,
                    scan_metrics: Dict[str, Any]) -> None:
    """One RoundRecord per scanned round, unpacked host-side from the scan's
    stacked per-round outputs (``train_loss[k]``, ``examples[k]``). The scan
    is one device program, so per-round wall/phase attribution is the scan
    wall divided evenly — honest about what a fused superround can know."""
    if not _State.enabled:
        return
    import numpy as np

    from . import _emit

    losses = np.asarray(scan_metrics.get("train_loss"))
    ex = scan_metrics.get("examples")
    ex = None if ex is None else np.asarray(ex)
    per = wall_s / max(k, 1)
    hbm_probe = RoundRecord(round_idx=-1)
    _hbm_fields(hbm_probe)
    for j in range(k):
        rec = RoundRecord(round_idx=start_round + j, fused=True,
                          superround=True)
        rec.wall_s = per
        rec.phases = {"superround_scan": per}
        rec.train_loss = float(losses[j]) if losses.shape else float(losses)
        rec.examples = None if ex is None else float(ex[j])
        rec.rounds_per_sec_ema = _update_ema(1.0 / max(per, 1e-9))
        rec.hbm_used_mb = hbm_probe.hbm_used_mb
        rec.hbm_peak_mb = hbm_probe.hbm_peak_mb
        _REG.inc("rounds.total")
        if rec.examples:
            _REG.inc("examples.total", rec.examples)
        _REG.observe("round.wall.seconds", per)
        _emit(rec.to_event())
    write_metrics_file()


# ---------------------------------------------------------------------------
# Profiler windows (--profile_rounds N:M)
# ---------------------------------------------------------------------------


def _start_trace(log_dir: str) -> None:  # monkeypatchable in tests
    import jax

    jax.profiler.start_trace(log_dir)


def _stop_trace() -> None:
    import jax

    jax.profiler.stop_trace()


class ProfilerWindow:
    """``jax.profiler`` trace over rounds [start, stop) — device-level truth
    (op timelines, HBM traffic) for the window the host-side spans flag."""

    def __init__(self, start_round: int, stop_round: int, log_dir: str):
        self.start_round = int(start_round)
        self.stop_round = int(stop_round)
        self.log_dir = log_dir
        self.active = False
        self.done = False

    @classmethod
    def parse(cls, spec: str, log_dir: str) -> "ProfilerWindow":
        """``"N:M"`` traces rounds [N, M); bare ``"N"`` traces round N."""
        lo, _, hi = str(spec).partition(":")
        start = int(lo)
        stop = int(hi) if hi else start + 1
        if stop <= start:
            raise ValueError(
                f"profile_rounds expects N:M with M > N, got {spec!r}")
        return cls(start, stop, log_dir)

    def on_round_start(self, round_idx: int) -> None:
        if (not self.done and not self.active
                and self.start_round <= round_idx < self.stop_round):
            _start_trace(self.log_dir)
            self.active = True

    def on_round_end(self, round_idx: int) -> None:
        if self.active and round_idx + 1 >= self.stop_round:
            self.force_stop()

    def force_stop(self) -> None:
        if self.active:
            _stop_trace()
            self.active = False
            self.done = True

    def intersects(self, lo: int, hi: int) -> bool:
        """Does [lo, hi) overlap the (not yet finished) window?"""
        return (not self.done and lo < self.stop_round
                and hi > self.start_round)


def on_round_start(round_idx: int) -> None:
    p = _State.profiler
    if p is not None:
        p.on_round_start(round_idx)


def on_round_end(round_idx: int) -> None:
    p = _State.profiler
    if p is not None:
        p.on_round_end(round_idx)


def profiler_blocks_chunk(lo: int, hi: int) -> bool:
    """True when a K-round scan over [lo, hi) would swallow a profiler
    boundary — the chunker then falls back to single rounds so the trace
    starts/stops exactly on the requested rounds."""
    p = _State.profiler
    return p is not None and p.intersects(lo, hi)


# ---------------------------------------------------------------------------
# Periodic host/device sampler (daemon thread)
# ---------------------------------------------------------------------------


class SysPerfSampler:
    """Periodic ``log_sys_perf()`` on a daemon thread: host CPU/RSS + HBM
    time series for long runs, no calls sprinkled through scenario code."""

    def __init__(self, interval_s: float):
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "SysPerfSampler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="sys-perf-sampler", daemon=True
            )
            self._thread.start()
        return self

    def _run(self) -> None:
        from . import log_sys_perf

        while not self._stop.wait(self.interval_s):
            try:
                log_sys_perf()
            except Exception:  # sampling must never kill a run
                pass

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None


def start_sys_perf_sampler(args) -> Optional[SysPerfSampler]:
    """Start the sampler when tracking is on and ``--sys_perf_interval_s``
    is positive; else ``None`` (the runner calls this unconditionally)."""
    interval = float(getattr(args, "sys_perf_interval_s", 0.0) or 0.0)
    if not _State.enabled or interval <= 0:
        return None
    return SysPerfSampler(interval).start()


# ---------------------------------------------------------------------------
# MFU estimate (Cheetah)
# ---------------------------------------------------------------------------


def flops_per_token(n_params: int, seq_len: int, n_layers: int,
                    d_model: int) -> float:
    """Model FLOPs per token, fwd+bwd (PaLM appendix B convention)."""
    return 6.0 * n_params + 12.0 * seq_len * n_layers * d_model


def mfu_estimate(tokens_per_sec: float, flops_per_tok: float,
                 device_kind: str, n_chips: int = 1) -> Optional[float]:
    peak = PEAK_BF16_FLOPS.get(str(device_kind))
    if not peak or n_chips <= 0:
        return None
    return (tokens_per_sec * flops_per_tok) / (peak * n_chips)
