"""In-process loopback comm backend — the first-class test fixture the
reference never had (SURVEY.md §4: "No fake/in-memory comm backend exists...
the new framework should make an in-process loopback backend a first-class
test fixture").

All ranks of a named "world" share a broker of queues; each rank's
``handle_receive_message`` drains its own queue on a thread-blocking get.
Serialization is exercised for fidelity (messages cross rank boundaries as
bytes, exactly like the network backends).
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, List

from .base_com_manager import BaseCommunicationManager, CommunicationConstants, Observer
from .message import Message


class _Broker:
    """Shared mailbox set for one world (keyed by world name)."""

    _worlds: Dict[str, "_Broker"] = {}
    _lock = threading.Lock()

    def __init__(self):
        # NOT a defaultdict: concurrent first-touch of the same rank from two
        # sender threads races ``__missing__`` — both build a Queue, the
        # second dict store wins, and anything put into (or drained from) the
        # losing instance is silently gone. A receiver that grabbed the loser
        # then waits forever: this was the intermittent multi-hour
        # dryrun_multichip wedge (r4 VERDICT weak #6).
        self._qlock = threading.Lock()
        self.queues: Dict[int, "queue.Queue[bytes]"] = {}

    def queue_for(self, rank: int) -> "queue.Queue[bytes]":
        """Lock-protected get-or-create: one Queue instance per rank, ever."""
        with self._qlock:
            q = self.queues.get(rank)
            if q is None:
                q = self.queues[rank] = queue.Queue()
            return q

    @classmethod
    def get(cls, world: str) -> "_Broker":
        with cls._lock:
            if world not in cls._worlds:
                cls._worlds[world] = cls()
            return cls._worlds[world]

    @classmethod
    def reset(cls, world: str) -> None:
        with cls._lock:
            cls._worlds.pop(world, None)


class LoopbackCommManager(BaseCommunicationManager):
    def __init__(self, rank: int, world_size: int, world: str = "default"):
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.world = world
        self.broker = _Broker.get(world)
        # shared with the receive thread (graftlint G005) — same discipline
        # as the network backends: locked observer snapshot, Event liveness
        self._observers: List[Observer] = []
        self._obs_lock = threading.Lock()
        self._stop_evt = threading.Event()

    def send_message(self, msg: Message) -> None:
        self.broker.queue_for(msg.get_receiver_id()).put(msg.serialize())

    def add_observer(self, observer: Observer) -> None:
        with self._obs_lock:
            self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        with self._obs_lock:
            if observer in self._observers:
                self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        # synthetic connection-ready event, like the MQTT/GRPC backends
        self._notify(
            Message(CommunicationConstants.MSG_TYPE_CONNECTION_IS_READY,
                    self.rank, self.rank)
        )
        q = self.broker.queue_for(self.rank)
        while not self._stop_evt.is_set():
            try:
                data = q.get(timeout=0.1)
            except queue.Empty:
                continue
            from .delivery import safe_deserialize

            msg = safe_deserialize(data, "loopback")
            if msg is not None:
                self._notify(msg)

    def stop_receive_message(self) -> None:
        self._stop_evt.set()

    def _notify(self, msg: Message) -> None:
        with self._obs_lock:
            observers = list(self._observers)
        for obs in observers:
            obs.receive_message(msg.get_type(), msg)
