"""Direct-tensor wire format — the TRPC-role transport (r4 VERDICT #10).

reference: ``core/distributed/communication/trpc/trpc_comm_manager.py:25-176``
— PyTorch TensorPipe RPC with ``set_device_map`` so tensors move
device-to-device without host serialization. A TPU pod has no CUDA-direct
DCN path (cross-host device transfer is the XLA collectives' job over
ICI/DCN meshes), so the role this module covers is the part that remains on
the FL message plane: moving LARGE host tensors between processes with as
few copies and codec passes as possible.

The default ``Message`` body is an npz (a zip container): every array is
deflate-scanned and copied through the zip writer, and ``np.load`` copies
again on read. The RAW frame format here writes one JSON header
(dtype/shape per tensor) plus the tensors' raw bytes, and decodes to
ZERO-COPY numpy views over the received buffer — the receive path does no
per-element work at all. ``fedml_tpu.Comm/SendStream`` (grpc_backend)
streams these bodies in bounded chunks so a GB-scale weight blob never
needs a single contiguous gRPC message buffer — the pinned-host-staging
analog. Measured by ``tools/bench_tensor_transport.py`` →
``TENSOR_TRANSPORT_BENCH.json``.
"""

from __future__ import annotations

import json
import math
from typing import List, Sequence, Union

import numpy as np

RAW_MAGIC = b"FTT1"


def encode_frame_parts(
        arrays: Sequence[np.ndarray]) -> List[Union[bytes, memoryview]]:
    """[arrays] → the body PIECES [RAW_MAGIC, u32 header_len, JSON header,
    frame, frame, ...] — callers join them together with their own prefix
    so the whole wire payload is assembled in ONE pass (Message.serialize
    does exactly that; a naive encode-then-concat would copy a GB-scale
    blob twice).

    Already-C-contiguous arrays ride as MEMORYVIEWS over their own buffers
    — zero data copies on encode; the single pass that touches bytes is the
    caller's join/socket write. Only non-contiguous inputs pay a
    materializing ``ascontiguousarray``.

    No alignment padding: the body rides behind a variable-length message
    prefix anyway, so in-body alignment cannot survive to the receive
    buffer — numpy accepts unaligned views (ALIGNED=False)."""
    metas = []
    frames: List[Union[bytes, memoryview]] = []
    off = 0
    for a in arrays:
        a = np.asarray(a)
        # record the TRUE shape before ascontiguousarray, which promotes
        # 0-d scalars to (1,) — the npz path preserves () and so must we
        shape = list(a.shape)
        if not a.flags.c_contiguous:
            a = np.ascontiguousarray(a)
        metas.append({"dtype": a.dtype.str, "shape": shape, "off": off})
        # flat byte view, zero-copy (read-only arrays export read-only
        # views; join/write only ever reads)
        frames.append(memoryview(a).cast("B") if a.nbytes else b"")
        off += a.nbytes
    header = json.dumps(metas).encode("utf-8")
    return [RAW_MAGIC, len(header).to_bytes(4, "big"), header, *frames]


def encode_frames(arrays: Sequence[np.ndarray]) -> bytes:
    """Standalone body: the joined :func:`encode_frame_parts`."""
    return b"".join(encode_frame_parts(arrays))


def decode_frames(buf: Union[bytes, memoryview]) -> List[np.ndarray]:
    """RAW body → list of ZERO-COPY numpy views over ``buf``.

    The views are read-only (the buffer is immutable bytes); consumers that
    mutate must copy — FL aggregation stacks/averages into fresh arrays
    anyway, so the hot path never pays a receive-side copy."""
    view = memoryview(buf)
    if view[:4] != RAW_MAGIC:
        raise ValueError("not a raw tensor-frame body")
    if len(view) < 8:
        raise ValueError("truncated tensor frame (no header length)")
    hlen = int.from_bytes(view[4:8], "big")
    if 8 + hlen > len(view):
        raise ValueError(
            f"truncated tensor frame (header wants {hlen} bytes, body has "
            f"{len(view) - 8})"
        )
    try:
        metas = json.loads(bytes(view[8:8 + hlen]).decode("utf-8"))
    except ValueError as e:  # bit-flipped header bytes: clean error, not
        raise ValueError(    # a raw JSONDecodeError/UnicodeDecodeError
            f"corrupt tensor frame header: {e}"
        ) from None
    if not isinstance(metas, list):
        raise ValueError("corrupt tensor frame header: not a tensor list")
    base = 8 + hlen
    out = []
    for i, m in enumerate(metas):
        # validate the header's dtype/shape/off BEFORE touching the buffer:
        # a truncated or corrupt frame must surface as a clean error (the
        # receive loop counts + drops it), not a confusing np.frombuffer /
        # reshape failure mid-decode
        try:
            dt = np.dtype(m["dtype"])
            shape = [int(s) for s in m["shape"]]
            off = int(m["off"])
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(
                f"corrupt tensor frame header (tensor {i}): {e}"
            ) from None
        if off < 0 or any(s < 0 for s in shape):
            raise ValueError(
                f"corrupt tensor frame header (tensor {i}: negative "
                "offset/shape)"
            )
        # arbitrary-precision Python ints: np.prod would wrap in int64 and
        # an adversarial shape like [2**40, 2**40] could slip past the
        # bounds check below with a garbage (even negative) byte count
        n = math.prod(shape) if shape else 1
        start = base + off
        end = start + n * dt.itemsize
        if end > len(view):
            raise ValueError(
                f"truncated tensor frame (tensor {i}: needs bytes "
                f"[{start}, {end}) of a {len(view)}-byte body)"
            )
        frame = view[start:end]
        out.append(np.frombuffer(frame, dtype=dt).reshape(shape))
    return out


def is_raw_body(body: Union[bytes, memoryview]) -> bool:
    return bytes(body[:4]) == RAW_MAGIC


def iter_chunks(payload: Union[bytes, memoryview],
                chunk_bytes: int = 4 * 1024 * 1024):
    """Bounded-size chunks for the streaming RPC (no monolithic buffer)."""
    view = memoryview(payload)
    for i in range(0, len(view), chunk_bytes):
        yield bytes(view[i:i + chunk_bytes])
