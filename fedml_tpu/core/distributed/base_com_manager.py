"""Abstract communication manager + observer contract.

reference: ``core/distributed/communication/base_com_manager.py:7-25`` and
``observer.py:4-7`` — send_message / add_observer / handle_receive_message /
stop_receive_message, with observers receiving (msg_type, msg_params).
"""

from __future__ import annotations

import abc

from .message import Message


class Observer(abc.ABC):
    @abc.abstractmethod
    def receive_message(self, msg_type: str, msg: Message) -> None:
        ...


class BaseCommunicationManager(abc.ABC):
    @abc.abstractmethod
    def send_message(self, msg: Message) -> None:
        ...

    @abc.abstractmethod
    def add_observer(self, observer: Observer) -> None:
        ...

    @abc.abstractmethod
    def remove_observer(self, observer: Observer) -> None:
        ...

    @abc.abstractmethod
    def handle_receive_message(self) -> None:
        """Block in the receive loop until stopped."""
        ...

    @abc.abstractmethod
    def stop_receive_message(self) -> None:
        ...


class CommunicationConstants:
    """reference: communication/constants.py:1-11."""

    MSG_TYPE_CONNECTION_IS_READY = "connection_ready"
    # the client liveness-status type lives HERE (not only in the cross-silo
    # message_define) because the transport layer itself speaks it: the MQTT
    # last-will publishes an OFFLINE status on the sender's behalf, and the
    # transport must not import FSM-layer protocol modules (graftproto P003
    # pins every use site to a define-class constant)
    MSG_TYPE_CLIENT_STATUS = "c2s_client_status"
    MSG_CLIENT_STATUS_OFFLINE = "OFFLINE"
    MSG_CLIENT_STATUS_IDLE = "IDLE"
    GRPC_BASE_PORT = 8890
    TCP_BASE_PORT = 8950
