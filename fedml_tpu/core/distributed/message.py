"""Typed message with array payloads.

Reference: ``core/distributed/communication/message.py:5-82`` — a JSON dict of
string params plus *pickled torch tensors* under MSG_ARG_KEY_MODEL_PARAMS.
TPU re-design: payloads are flat numpy array lists (a pytree's canonical leaf
order), serialized with ``np.savez`` + a JSON header — no pickle on the wire
(untrusted peers can't execute code via payloads), no torch.
"""

from __future__ import annotations

import io
import json
from typing import Any, Dict, List, Optional

import numpy as np


class Message:
    # keys mirrored from the reference (message.py:12-34)
    MSG_ARG_KEY_TYPE = "msg_type"
    MSG_ARG_KEY_SENDER = "sender"
    MSG_ARG_KEY_RECEIVER = "receiver"
    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
    MSG_ARG_KEY_CLIENT_STATUS = "client_status"
    MSG_ARG_KEY_ROUND_IDX = "round_idx"
    MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
    # at-least-once delivery header (core/distributed/delivery.py): per-
    # sender monotonic sequence + sender epoch identify wire duplicates;
    # the payload digest rejects corrupt bodies before any handler runs
    MSG_ARG_KEY_SEQ = "_seq"
    MSG_ARG_KEY_EPOCH = "_epoch"
    MSG_ARG_KEY_PAYLOAD_SHA256 = "_sha256"
    # W3C-traceparent-style causal context (core/mlops/tracing.py): a
    # compact [run_id, round, span_id, parent] list stamped by the comm
    # manager on send and adopted on receive — rides the JSON header, so
    # it survives every transport, the payload-store offload, and the
    # retry/dedup layer unchanged (a retried frame carries the SAME
    # context: never a duplicate span)
    MSG_ARG_KEY_TRACE = "_trace"

    def __init__(self, type: str = "default", sender_id: int = 0, receiver_id: int = 0):
        self.type = str(type)
        self.sender_id = int(sender_id)
        self.receiver_id = int(receiver_id)
        self.msg_params: Dict[str, Any] = {
            Message.MSG_ARG_KEY_TYPE: self.type,
            Message.MSG_ARG_KEY_SENDER: self.sender_id,
            Message.MSG_ARG_KEY_RECEIVER: self.receiver_id,
        }
        self.arrays: List[np.ndarray] = []  # canonical-order pytree leaves

    # -- reference API (message.py:36-75) -----------------------------------
    def init(self, msg_params: Dict[str, Any]) -> None:
        self.msg_params = dict(msg_params)
        self.type = str(msg_params.get(Message.MSG_ARG_KEY_TYPE, self.type))
        self.sender_id = int(msg_params.get(Message.MSG_ARG_KEY_SENDER, 0))
        self.receiver_id = int(msg_params.get(Message.MSG_ARG_KEY_RECEIVER, 0))

    def get_sender_id(self) -> int:
        return self.sender_id

    def get_receiver_id(self) -> int:
        return self.receiver_id

    def add_params(self, key: str, value: Any) -> None:
        self.msg_params[key] = value

    def get_params(self) -> Dict[str, Any]:
        return self.msg_params

    def add(self, key: str, value: Any) -> None:
        self.msg_params[key] = value

    def get(self, key: str, default: Any = None) -> Any:
        return self.msg_params.get(key, default)

    def get_type(self) -> str:
        return str(self.msg_params[Message.MSG_ARG_KEY_TYPE])

    # -- array payloads ------------------------------------------------------
    def set_arrays(self, arrays: List[np.ndarray]) -> None:
        self.arrays = [np.asarray(a) for a in arrays]

    def get_arrays(self, copy: bool = False) -> List[np.ndarray]:
        """The payload arrays, in canonical pytree-leaf order.

        **Treat the result as READ-ONLY unless ``copy=True``.** On a
        received message the arrays may be zero-copy views over the wire
        buffer (``grpc_wire_format=raw`` decodes with ``np.frombuffer``
        over immutable bytes — in-place mutation raises ``ValueError``),
        while the npz path happens to return writable copies. That
        asymmetry is a wire-format detail, not API surface: code that
        mutates received arrays works or crashes depending on a transport
        flag. ``copy=True`` returns fresh writable arrays on every call —
        the explicit opt-in for consumers that must mutate in place.
        (FL aggregation stacks/averages into new arrays, so the hot path
        never needs the copy.)
        """
        if copy:
            return [np.array(a) for a in self.arrays]
        return self.arrays

    # -- wire format ---------------------------------------------------------
    # "npz" (default, self-describing zip) or "raw" — the direct-tensor
    # frame format (tensor_transport.py): one encode copy, ZERO-copy decode
    # views. deserialize() sniffs the body magic, so mixed-format worlds
    # interoperate (npz bodies start with the zip magic "PK").
    wire_format = "npz"

    # fault-injection hook (core/distributed/faults.py `corrupt()` rules):
    # when set, serialize() computes the TRUE payload digest and then flips
    # one byte of the encoded frame — the receiver's integrity check must
    # reject the message. Never set outside the fault harness.
    corrupt_on_wire = False

    def serialize(self) -> bytes:
        from .delivery import arrays_digest

        if self.arrays:
            # digest of the arrays (not the encoded body): the same header
            # value verifies an inline body AND a payload-store blob after
            # the arrays moved by reference (comm_manager offload)
            self.msg_params[Message.MSG_ARG_KEY_PAYLOAD_SHA256] = \
                arrays_digest(self.arrays)
        header = json.dumps(self.msg_params).encode("utf-8")
        prefix = [len(header).to_bytes(4, "big"), header]
        if self.wire_format == "raw" and self.arrays:
            from .tensor_transport import encode_frame_parts

            # single-pass assembly: one join over prefix + frame pieces
            frame = b"".join(prefix + encode_frame_parts(self.arrays))
        else:
            buf = io.BytesIO()
            np.savez(buf, *self.arrays)
            frame = b"".join(prefix + [buf.getvalue()])
        if self.corrupt_on_wire:
            frame = bytearray(frame)
            # flip a byte mid-body for payload messages (defeats the array
            # digest), or a header byte for control messages (defeats the
            # JSON parse) — either way the receiver must reject the frame
            body_start = 4 + len(header)
            idx = (body_start + (len(frame) - body_start) // 2
                   if self.arrays else 4)
            frame[idx] ^= 0xFF
            frame = bytes(frame)
        # transport-agnostic wire accounting (counters are always-on):
        # every backend serializes exactly once per send, so this is THE
        # per-direction comm.bytes number the delta-delivery bench pins
        from ..mlops import telemetry

        telemetry.counter_inc("comm.bytes_sent", len(frame))
        telemetry.counter_inc("comm.frames_sent")
        return frame

    @staticmethod
    def deserialize(data: bytes, verify: bool = True) -> "Message":
        hlen = int.from_bytes(data[:4], "big")
        header = json.loads(data[4 : 4 + hlen].decode("utf-8"))
        msg = Message()
        msg.init(header)
        body = memoryview(data)[4 + hlen:]
        if len(body):
            from .tensor_transport import decode_frames, is_raw_body

            if is_raw_body(body):
                msg.arrays = decode_frames(body)
            else:
                with np.load(io.BytesIO(bytes(body))) as z:
                    msg.arrays = [z[k] for k in z.files]
        if verify and msg.arrays:
            msg.verify_payload()
        return msg

    def verify_payload(self) -> None:
        """Check the arrays against the header digest (when present).
        Raises :class:`delivery.PayloadCorruptError` on mismatch — receive
        loops turn that into a counted drop, and the at-least-once sender
        re-delivers a clean copy."""
        from .delivery import PayloadCorruptError, arrays_digest

        want = self.msg_params.get(Message.MSG_ARG_KEY_PAYLOAD_SHA256)
        if want is None:
            return  # pre-digest peer: nothing to verify
        got = arrays_digest(self.arrays)
        if got != want:
            raise PayloadCorruptError(
                f"payload checksum mismatch for {self.type!r} "
                f"{self.sender_id}->{self.receiver_id}: "
                f"expected {want[:12]}…, got {got[:12]}…"
            )

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"Message(type={self.type!r}, {self.sender_id}->{self.receiver_id}, "
            f"{len(self.arrays)} arrays)"
        )
