"""FedMLAlgorithmFlow — declarative algorithm-flow DSL.

reference: ``core/distributed/flow/fedml_flow.py:20-295`` (FedMLAlgorithmFlow:
a declarative sequence of (flow_name, executor_task) pairs compiled into
message handlers; neighbor liveness handshake before start; ONCE/FINISH tags)
and ``fedml_executor.py`` (FedMLExecutor holds params/ids).

Semantics preserved: every node declares the SAME flow sequence; ``build()``
compiles it into handlers on the node's comm manager; a step runs on the
nodes whose role matches, consuming the previous step's ``Params`` and
shipping its returned ``Params`` to the next step's nodes. ``ONCE`` steps run
only in the first pass; the flow loops until a ``FINISH``-tagged step
completes. The liveness handshake (all nodes ONLINE before the first step)
mirrors fedml_flow.py's neighbor handshake.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from ... import constants
from ..alg_frame import Params
from .base_com_manager import CommunicationConstants
from .comm_manager import FedMLCommManager
from .message import Message

logger = logging.getLogger(__name__)

FLOW_TAG_ONCE = "ONCE"
FLOW_TAG_REPEAT = "REPEAT"
FLOW_TAG_FINISH = "FINISH"

ROLE_SERVER = "server"  # rank 0
ROLE_CLIENT = "client"  # ranks 1..N


class FedMLExecutor:
    """Task host bound to one node (reference: fedml_executor.py)."""

    def __init__(self, id: int = 0, neighbor_id_list: Optional[List[int]] = None):
        self.id = id
        self.neighbor_id_list = neighbor_id_list or []
        self.params: Optional[Params] = None

    def get_params(self) -> Optional[Params]:
        return self.params

    def set_params(self, params: Optional[Params]) -> None:
        self.params = params


class _FlowStep:
    def __init__(self, name: str, method: Callable, role: str, tag: str):
        self.name = name
        self.method = method
        self.role = role
        self.tag = tag


class FedMLAlgorithmFlow(FedMLCommManager):
    MSG_TYPE_FLOW = "flow_step"
    MSG_TYPE_READY = "flow_node_ready"
    ARG_STEP = "step_idx"
    ARG_PASS = "pass_idx"

    def __init__(self, args, executor: FedMLExecutor, rank: int = 0,
                 size: int = 0, backend: str = constants.COMM_BACKEND_LOOPBACK):
        super().__init__(args, None, rank, size, backend)
        self.executor = executor
        self.flows: List[_FlowStep] = []
        self._ready = set()
        self._built = False
        self.pass_idx = 0
        self.done = threading.Event()
        self._lock = threading.Lock()

    # -- DSL -----------------------------------------------------------------
    def add_flow(self, name: str, executor_task: Callable, role: str,
                 flow_tag: str = FLOW_TAG_REPEAT) -> "FedMLAlgorithmFlow":
        """reference: fedml_flow.py ``add_flow(flow_name, executor_task)``;
        role says which nodes run the step (server=rank0, client=ranks>0)."""
        if self._built:
            raise RuntimeError("add_flow after build()")
        self.flows.append(_FlowStep(name, executor_task, role, flow_tag))
        return self

    def build(self) -> "FedMLAlgorithmFlow":
        if not self.flows:
            raise ValueError("empty flow")
        self._built = True
        return self

    # -- handlers ------------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            CommunicationConstants.MSG_TYPE_CONNECTION_IS_READY,
            self._on_connection_ready,
        )
        self.register_message_receive_handler(self.MSG_TYPE_READY, self._on_ready)
        self.register_message_receive_handler(self.MSG_TYPE_FLOW, self._on_flow)

    def _on_connection_ready(self, msg: Message) -> None:
        # liveness handshake: everyone announces to rank 0
        ready = Message(self.MSG_TYPE_READY, self.rank, 0)
        self.send_message(ready)

    def _on_ready(self, msg: Message) -> None:
        if self.rank != 0:
            return
        start = False
        with self._lock:
            self._ready.add(msg.get_sender_id())
            if len(self._ready) == self.size:
                start = True
        if start:
            logger.info("flow: all %d nodes ready, starting", self.size)
            self._dispatch_step(0, Params(), 0)

    def _targets(self, step: _FlowStep) -> List[int]:
        return [0] if step.role == ROLE_SERVER else list(range(1, self.size))

    def _dispatch_step(self, step_idx: int, params: Params, pass_idx: int,
                       targets: Optional[List[int]] = None) -> None:
        step = self.flows[step_idx]
        payload = _params_to_message_fields(params)
        for target in (targets if targets is not None else self._targets(step)):
            m = Message(self.MSG_TYPE_FLOW, self.rank, target)
            m.add(self.ARG_STEP, step_idx)
            m.add(self.ARG_PASS, pass_idx)
            m.add("header", payload[0])
            m.set_arrays(payload[1])
            self.send_message(m)

    def _on_flow(self, msg: Message) -> None:
        if msg.get("final"):
            self.executor.set_params(
                _params_from_message_fields(msg.get("header"), msg.get_arrays())
            )
            with self._lock:
                # drop the readiness roster (graftmem M001): one entry per
                # sender, and a finished flow never consults it again
                self._ready.clear()
            self.done.set()
            self.finish()
            return
        step_idx = int(msg.get(self.ARG_STEP))
        pass_idx = int(msg.get(self.ARG_PASS))
        step = self.flows[step_idx]
        if self.rank not in self._targets(step):
            return
        params = _params_from_message_fields(msg.get("header"), msg.get_arrays())
        self.executor.set_params(params)
        out = step.method(self.executor)
        out = out if out is not None else Params()

        if step.tag == FLOW_TAG_FINISH:
            logger.info("flow: FINISH at %r (rank %d)", step.name, self.rank)
            if self.rank == 0:
                # propagate final params to everyone, then stop
                header, arrays = _params_to_message_fields(out)
                for r in range(1, self.size):
                    m = Message(self.MSG_TYPE_FLOW, self.rank, r)
                    m.add("final", True)
                    m.add("header", header)
                    m.set_arrays(arrays)
                    self.send_message(m)
            with self._lock:
                self._ready.clear()
            self.done.set()
            self.finish()
            return

        # advance: each node ships its own result to the next step's nodes;
        # the next step's handler runs once per arriving message (reference
        # behavior: flows chain handler→handler, the receiving executor
        # accumulates across senders).
        next_idx = step_idx + 1
        next_pass = pass_idx
        if next_idx >= len(self.flows):
            # wrap: skip ONCE steps after the first pass
            next_pass += 1
            next_idx = 0
            while self.flows[next_idx].tag == FLOW_TAG_ONCE:
                next_idx += 1
        next_role = self.flows[next_idx].role
        if step.role == ROLE_SERVER:
            self._dispatch_step(next_idx, out, next_pass)  # fan out
        elif next_role == ROLE_SERVER:
            self._dispatch_step(next_idx, out, next_pass, targets=[0])
        else:
            # client → client: each node continues with its own params
            self._dispatch_step(next_idx, out, next_pass, targets=[self.rank])


def _params_to_message_fields(params: Params):
    """Params → (json-able header, array list). Arrays are extracted."""
    header: Dict = {}
    arrays: List[np.ndarray] = []
    for k in list(params.keys()):
        v = getattr(params, k)
        if isinstance(v, (np.ndarray,)) or hasattr(v, "__array__"):
            header[k] = {"__array__": len(arrays)}
            arrays.append(np.asarray(v))
        else:
            header[k] = v
    return header, arrays


def _params_from_message_fields(header, arrays) -> Params:
    p = Params()
    for k, v in (header or {}).items():
        if isinstance(v, dict) and "__array__" in v:
            p.add(k, arrays[v["__array__"]])
        else:
            p.add(k, v)
    return p
