"""Idempotent at-least-once delivery primitives for the message plane.

reference: none — the reference's transports are fire-and-forget (its only
retry is gRPC's implicit reconnect; a duplicated or replayed
``MSG_TYPE_C2S_SEND_MODEL`` double-counts a client in the aggregator).
Production FL needs *effectively-once* message handling built from two
halves:

- **at-least-once** (sender): every logical message carries a per-sender
  monotonic sequence number and a sender epoch (regenerated at process
  start, strictly increasing across restarts); transient send failures are
  retried under :class:`RetryPolicy` (exponential backoff + jitter, bounded
  budget). Retries re-send the SAME sequence number — that is what makes
  them recognizable as duplicates.
- **at-most-once** (receiver): :class:`DedupWindow` drops wire duplicates
  (same sender/epoch/seq), messages from a superseded sender epoch (a
  restarted sender never re-uses its predecessor's numbering), and —
  together with the payload checksum in :mod:`message` — corrupt payloads.

Transports raise :class:`TransientSendError` for failures worth retrying;
anything else propagates (the cross-silo server's ``_send_or_mark_dead``
keeps handling hard-dead peers). All recovery events are telemetry
counters: ``comm.send_retries``, ``comm.send_failures``,
``comm.dedup_drops``, ``comm.stale_epoch_drops``, ``comm.corrupt_payloads``.
"""

from __future__ import annotations

import hashlib
import random
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, Optional, Set, Tuple

import numpy as np

from ..containers import BoundedDict


def safe_deserialize(data: bytes, transport: str = "comm"):
    """Decode wire bytes defensively: a frame that fails to parse or whose
    payload checksum mismatches is counted (``comm.corrupt_payloads``) and
    dropped (returns None) instead of killing the receive loop. The
    at-least-once sender re-delivers a clean copy."""
    import logging

    from ..mlops import telemetry
    from .message import Message

    try:
        return Message.deserialize(data)
    except Exception as e:  # noqa: BLE001 — any decode failure is a drop
        telemetry.counter_inc("comm.corrupt_payloads")
        logging.getLogger(__name__).warning(
            "%s: corrupt frame (%d bytes) dropped: %s", transport,
            len(data), e,
        )
        return None


class TransientSendError(ConnectionError):
    """A send failure the at-least-once layer should retry (peer briefly
    unreachable, injected fault, broker blip). Non-transient errors keep
    their own types and propagate."""


class PayloadCorruptError(ValueError):
    """Deserialized payload failed its integrity checksum."""


# ---------------------------------------------------------------------------
# payload digests
# ---------------------------------------------------------------------------


def arrays_digest(arrays) -> str:
    """Canonical sha256 over an array list: dtype + shape + C-order bytes
    per array. Wire-format independent — the same digest verifies an inline
    npz body, a raw tensor frame, and a payload-store blob."""
    h = hashlib.sha256()
    for a in arrays:
        a = np.ascontiguousarray(a)
        h.update(str(a.dtype.str).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# sender side: retry with backoff + jitter
# ---------------------------------------------------------------------------


@dataclass
class RetryPolicy:
    """Bounded exponential backoff with decorrelated jitter.

    ``max_attempts`` counts RE-sends (0 disables retrying); the first send
    is always made. Backoff for attempt k (1-based) is
    ``min(base * 2**(k-1), max_s)`` scaled by a uniform jitter in
    ``[1 - jitter, 1]`` so synchronized clients don't retry in lockstep.
    """

    max_attempts: int = 4
    base_s: float = 0.05
    max_s: float = 2.0
    jitter: float = 0.5

    @classmethod
    def from_args(cls, args) -> "RetryPolicy":
        return cls(
            max_attempts=int(getattr(args, "comm_retry_max_attempts", 4)),
            base_s=float(getattr(args, "comm_retry_backoff_s", 0.05)),
            max_s=float(getattr(args, "comm_retry_backoff_max_s", 2.0)),
        )

    def backoff_s(self, attempt: int, rng: Optional[random.Random] = None) \
            -> float:
        expo = min(self.base_s * (2.0 ** max(attempt - 1, 0)), self.max_s)
        r = (rng or random).uniform(1.0 - self.jitter, 1.0)
        return expo * r

    def call(self, fn, *, is_transient, on_retry=None):
        """Run ``fn`` with the policy. ``is_transient(exc) -> bool`` decides
        retryability; ``on_retry(attempt, exc)`` observes each re-send."""
        attempt = 0
        while True:
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 — classifier decides
                if not is_transient(e) or attempt >= self.max_attempts:
                    raise
                attempt += 1
                if on_retry is not None:
                    on_retry(attempt, e)
                time.sleep(self.backoff_s(attempt))


# ---------------------------------------------------------------------------
# receiver side: dedup window
# ---------------------------------------------------------------------------


class DedupWindow:
    """Per-sender (epoch, seq) dedup with a bounded memory window.

    ``accept(sender, epoch, seq)`` returns the verdict:

    - ``"accept"`` — first sighting; the seq is recorded.
    - ``"duplicate"`` — same epoch, already-seen seq (a retry or an
      injected duplication) — the handler must NOT run.
    - ``"stale_epoch"`` — the sender has since restarted with a newer
      epoch; its previous life's stragglers are dropped.

    A NEWER epoch resets the sender's window (a restarted sender starts
    its numbering over). The window keeps the last ``window`` seqs per
    sender; seqs older than the window floor are treated as duplicates —
    with monotonic senders a seq that far behind can only be a replay.
    Thread-safe: delayed-delivery timers and multi-threaded transports may
    deliver concurrently with the receive loop.

    The sender map itself is LRU-bounded (graftmem M001): at a million
    clients an unbounded per-sender map is a slow OOM. Evicting the
    coldest sender only weakens dedup for a sender silent past
    ``max_senders`` other senders' traffic — its next message re-enters
    as ``"accept"``, which the round-index guards upstream already
    tolerate (the same rebuild path a server restart takes).
    """

    def __init__(self, window: int = 4096, max_senders: int = 65536):
        self.window = max(int(window), 1)
        self._lock = threading.Lock()
        # sender -> (epoch, seen-set, fifo of seqs); LRU over senders
        self._senders: Dict[int, Tuple[int, Set[int], Deque[int]]] = \
            BoundedDict(max(int(max_senders), 1), lru=True,
                        name="delivery.dedup_senders")

    def accept(self, sender: int, epoch: int, seq: int) -> str:
        sender, epoch, seq = int(sender), int(epoch), int(seq)
        with self._lock:
            cur = self._senders.get(sender)
            if cur is None or epoch > cur[0]:
                seen: Set[int] = {seq}
                fifo: Deque[int] = deque([seq])
                self._senders[sender] = (epoch, seen, fifo)
                return "accept"
            cur_epoch, seen, fifo = cur
            if epoch < cur_epoch:
                return "stale_epoch"
            if seq in seen:
                return "duplicate"
            if fifo and len(fifo) >= self.window and seq < min(fifo):
                # below the window floor: cannot distinguish from a replay —
                # reject (senders are monotonic; a live message is never
                # `window` sends behind)
                return "duplicate"
            seen.add(seq)
            fifo.append(seq)
            while len(fifo) > self.window:
                seen.discard(fifo.popleft())
            return "accept"


# ---------------------------------------------------------------------------
# sender identity
# ---------------------------------------------------------------------------


class SenderStamp:
    """Per-process sender identity: a strictly-increasing epoch (wall-clock
    nanoseconds at construction — a restart always epoch-supersedes the
    previous life) + a monotonic per-message sequence counter."""

    def __init__(self, epoch: Optional[int] = None):
        self.epoch = int(epoch) if epoch is not None else time.time_ns()
        self._seq = 0
        self._lock = threading.Lock()

    def next_seq(self) -> int:
        with self._lock:
            self._seq += 1
            return self._seq
