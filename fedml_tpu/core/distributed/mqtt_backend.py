"""MQTT comm backend: broker-mediated DCN message plane.

reference: ``core/distributed/communication/mqtt/mqtt_comm_manager.py`` +
``mqtt_s3/mqtt_s3_multi_clients_comm_manager.py:20-352`` — the production
Octopus/Beehive transport: per-rank topics on a broker, JSON control
messages, last-will for liveness, S3 for bulk payloads.

TPU-native composition: this backend carries ONLY control traffic (the same
no-pickle ``Message`` wire bytes, base64 over MQTT); bulk model payloads ride
the payload-by-reference store (``payload_store.py``), which IS the S3 split
— configure ``payload_store_dir`` and every oversized array list stays off
the broker. Liveness: a retained last-will publishes the OFFLINE status the
server manager already understands.

``paho-mqtt`` is an optional dependency (not staged on TPU pods); importing
this module without it raises at construction with a clear message, exactly
like the reference degrades without its broker config.
"""

from __future__ import annotations

import base64
import logging
import queue
import threading
import time
from typing import List

from ..mlops import telemetry
from .base_com_manager import BaseCommunicationManager, CommunicationConstants, Observer
from .message import Message

logger = logging.getLogger(__name__)


class MqttCommManager(BaseCommunicationManager):
    """Per-rank topic scheme: ``fedml/<run_id>/<rank>``."""

    def __init__(self, host: str, port: int, rank: int, world_size: int,
                 run_id: str = "0", keepalive: int = 60, qos: int = 1,
                 subscribe_retries: int = 5,
                 subscribe_timeout_s: float = 6.0):
        try:
            import paho.mqtt.client as mqtt
        except ImportError as e:
            raise RuntimeError(
                "the MQTT backend needs paho-mqtt (pip install paho-mqtt); "
                "on broker-less pods use GRPC or LOOPBACK — with "
                "payload_store_dir they cover the MQTT+S3 design"
            ) from e
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.run_id = str(run_id)
        self.qos = int(qos)
        self.host = str(host)
        self.port = int(port)
        self.subscribe_retries = int(subscribe_retries)
        self.subscribe_timeout_s = float(subscribe_timeout_s)
        self._queue: "queue.Queue[bytes]" = queue.Queue()
        # shared with the paho network thread and the receive thread
        # (graftlint G005): observers snapshot under a lock, loop liveness
        # is an Event instead of a cross-thread bool
        self._observers: List[Observer] = []
        self._obs_lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._subscribed = threading.Event()
        # set on either outcome (subscribed OR refused) so waiters wake
        # immediately on a definitive broker refusal
        self._conn_resolved = threading.Event()
        # written by the paho thread strictly BEFORE _conn_resolved.set();
        # read strictly AFTER .wait() — the Event is the happens-before edge
        self._connect_error = None
        client_id = f"fedml-{run_id}-{rank}"
        try:  # paho-mqtt >= 2.0 requires the callback API version up front
            self._client = mqtt.Client(
                mqtt.CallbackAPIVersion.VERSION1, client_id=client_id
            )
        except AttributeError:  # paho-mqtt 1.x
            self._client = mqtt.Client(client_id=client_id)
        # MQTT last-will: the broker publishes OFFLINE for us if we vanish —
        # the server's liveness handler treats it like a graceful departure
        will = Message(
            CommunicationConstants.MSG_TYPE_CLIENT_STATUS, self.rank, 0
        )
        will.add(Message.MSG_ARG_KEY_CLIENT_STATUS,
                 CommunicationConstants.MSG_CLIENT_STATUS_OFFLINE)
        self._client.will_set(
            self._topic(0), base64.b64encode(will.serialize()), qos=qos,
            retain=False,
        )
        self._client.on_message = self._on_mqtt_message
        # (re)subscribe in on_connect: paho auto-reconnects after a broker
        # blip but does NOT restore subscriptions on a clean session
        def _on_connect(client, userdata, flags, rc, *a):
            # rc is an int in paho 1.x, a ReasonCode in 2.x; nonzero/failure
            # means the broker refused us (bad auth) — surface it instead of
            # declaring readiness on a dead connection
            refused = (rc != 0) if isinstance(rc, int) else rc.is_failure
            if refused:
                self._connect_error = f"mqtt broker refused connection: {rc}"
                logger.error(self._connect_error)
                self._conn_resolved.set()
                return
            client.subscribe(self._topic(self.rank), qos=self.qos)
            self._subscribed.set()
            self._conn_resolved.set()

        self._client.on_connect = _on_connect
        self._client.connect(host, int(port), keepalive)
        self._client.loop_start()
        logger.info("mqtt backend: rank %d on %s:%d", rank, host, port)

    def _topic(self, rank: int) -> str:
        return f"fedml/{self.run_id}/{rank}"

    def _on_mqtt_message(self, client, userdata, msg) -> None:
        data = base64.b64decode(msg.payload)
        telemetry.counter_inc("comm.mqtt.messages_received")
        telemetry.counter_inc("comm.mqtt.bytes_received", len(data))
        self._queue.put(data)

    def send_message(self, msg: Message) -> None:
        payload = msg.serialize()
        telemetry.counter_inc("comm.mqtt.messages_sent")
        telemetry.counter_inc("comm.mqtt.bytes_sent", len(payload))
        info = self._client.publish(
            self._topic(msg.get_receiver_id()),
            base64.b64encode(payload), qos=self.qos,
        )
        # paho queues on a down broker and republishes after reconnect —
        # count those as retries so flaky-broker runs are visible
        if getattr(info, "rc", 0) != 0:
            telemetry.counter_inc("comm.mqtt.send_retries")

    def add_observer(self, observer: Observer) -> None:
        with self._obs_lock:
            self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        with self._obs_lock:
            if observer in self._observers:
                self._observers.remove(observer)

    def _await_subscribe(self) -> None:
        """Block until our SUBSCRIBE is acknowledged, with bounded
        reconnect retries + backoff.

        The pre-robustness behavior — one hard 30 s wait, then "proceeding
        anyway" — either wedged a run on a slow broker or silently dropped
        the peers' ONLINE handshakes (brokers drop publishes to
        subscriber-less topics). Now each unconfirmed window triggers a
        counted reconnect attempt (``comm.mqtt.subscribe_retries``); when
        the budget is spent the raise tells the operator exactly what to
        check instead of a bare timeout."""
        from .delivery import RetryPolicy

        backoff = RetryPolicy(base_s=0.5, max_s=5.0)
        for attempt in range(self.subscribe_retries + 1):
            if self._conn_resolved.wait(timeout=self.subscribe_timeout_s):
                if self._connect_error is not None:
                    # a broker REFUSAL (bad auth/ACL) is not transient —
                    # retrying the same credentials cannot succeed
                    raise ConnectionError(
                        f"{self._connect_error} — check the broker "
                        f"credentials/ACL for client fedml-{self.run_id}-"
                        f"{self.rank} at {self.host}:{self.port}"
                    )
                if self._subscribed.is_set():
                    return
            if attempt >= self.subscribe_retries:
                break
            telemetry.counter_inc("comm.mqtt.subscribe_retries")
            logger.warning(
                "mqtt backend: subscribe unconfirmed after %.1fs — "
                "reconnect attempt %d/%d", self.subscribe_timeout_s,
                attempt + 1, self.subscribe_retries,
            )
            self._conn_resolved.clear()
            try:
                self._client.reconnect()
            except Exception as e:  # noqa: BLE001 — retried with backoff
                logger.warning("mqtt backend: reconnect failed: %s", e)
            time.sleep(backoff.backoff_s(attempt + 1))
        raise ConnectionError(
            f"mqtt backend: subscribe to {self._topic(self.rank)} "
            f"unconfirmed after {self.subscribe_retries} reconnect "
            f"attempts (~{self.subscribe_timeout_s * (self.subscribe_retries + 1):.0f}s) "
            f"— is the broker at {self.host}:{self.port} reachable from "
            "this host (DNS/firewall), and does it allow this client id? "
            "On broker-less pods use the GRPC or LOOPBACK backend."
        )

    def handle_receive_message(self) -> None:
        # don't declare readiness before our SUBSCRIBE is acknowledged:
        # brokers drop publishes to subscriber-less topics, so an early
        # ONLINE handshake from a peer would vanish
        self._await_subscribe()
        self._notify(
            Message(CommunicationConstants.MSG_TYPE_CONNECTION_IS_READY,
                    self.rank, self.rank)
        )
        while not self._stop_evt.is_set():
            try:
                data = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            from .delivery import safe_deserialize

            msg = safe_deserialize(data, "mqtt")
            if msg is not None:
                self._notify(msg)

    def stop_receive_message(self) -> None:
        self._stop_evt.set()
        try:
            self._client.loop_stop()
            self._client.disconnect()
        except Exception:
            pass

    def _notify(self, msg: Message) -> None:
        with self._obs_lock:
            observers = list(self._observers)
        for obs in observers:
            obs.receive_message(msg.get_type(), msg)
