"""Payload-by-reference bulk channel: control/data split for the message plane.

reference: the production Octopus/Beehive transports split small control
messages from bulk model payloads — MQTT carries JSON control, S3 carries the
tensors, and the message holds the S3 key
(``communication/mqtt_s3/mqtt_s3_multi_clients_comm_manager.py:20-352``,
``communication/s3/remote_storage.py:18-183``).

TPU-native re-design: one ``PayloadStore`` abstraction over a shared
filesystem directory (NFS / GCS-FUSE in production pods, a tmp dir in tests).
Arrays are written once as an npz blob with an atomic rename; the wire
message carries only the key, so a 1 GB model never rides the control
channel. The npz format matches ``Message``'s inline body — no pickle in
either path.
"""

from __future__ import annotations

import hashlib
import io
import logging
import os
import time
import uuid
from typing import List, Optional

import numpy as np

from ..mlops import telemetry

logger = logging.getLogger(__name__)

# message param carrying the reference (absent = inline payload)
PAYLOAD_REF_KEY = "__payload_ref__"

# URL-safe object keys only: '?', '#', '%', '/' etc. would address a
# DIFFERENT object over HTTP than the same key in the directory store
import re  # noqa: E402

HTTP_KEY_RE = re.compile(r"[A-Za-z0-9_\-][A-Za-z0-9._\-]*\Z")


class PayloadStore:
    """npz blobs under a shared directory, addressed by opaque keys."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        path = os.path.abspath(os.path.join(self.root, key))
        if not path.startswith(self.root + os.sep):
            raise ValueError(f"payload key escapes the store root: {key!r}")
        return path

    def new_key(self, hint: str = "payload") -> str:
        safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in hint)
        return f"{safe}-{uuid.uuid4().hex}.npz"

    def put(self, key: str, arrays: List[np.ndarray]) -> str:
        """Write atomically (tmp + rename): a reader never sees a torn blob."""
        buf = io.BytesIO()
        np.savez(buf, *[np.asarray(a) for a in arrays])
        path = self._path(key)
        tmp = f"{path}.tmp-{uuid.uuid4().hex}"
        with open(tmp, "wb") as f:
            f.write(buf.getvalue())
        os.replace(tmp, path)
        return key

    def put_dedup(self, arrays: List[np.ndarray]) -> str:
        """Content-addressed put: a broadcast of the same model to N peers
        writes ONE blob (key = sha256 of the serialized payload), not N."""
        buf = io.BytesIO()
        np.savez(buf, *[np.asarray(a) for a in arrays])
        data = buf.getvalue()
        key = f"cas-{hashlib.sha256(data).hexdigest()}.npz"
        path = self._path(key)
        if os.path.exists(path):
            # refresh the TTL clock: a dedup hit on a near-expired blob must
            # not leave an in-flight reference pointing at a sweep target
            telemetry.counter_inc("payload_store.dedup_hits")
            try:
                os.utime(path, None)
            except OSError:
                pass
        else:
            telemetry.counter_inc("payload_store.puts")
            telemetry.counter_inc("payload_store.put_bytes", len(data))
            tmp = f"{path}.tmp-{uuid.uuid4().hex}"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        return key

    def sweep(self, max_age_seconds: float = 3600.0) -> int:
        """Drop blobs older than the TTL (content-addressed blobs are shared
        by many readers, so delete-on-read is wrong; age is the contract)."""
        cutoff = time.time() - max_age_seconds
        dropped = 0
        try:
            names = os.listdir(self.root)
        except OSError:
            return 0
        for name in names:
            path = os.path.join(self.root, name)
            try:
                if os.path.getmtime(path) < cutoff:
                    os.remove(path)
                    dropped += 1
            except OSError:
                continue
        if dropped:
            telemetry.counter_inc("payload_store.swept", dropped)
        return dropped

    def get(self, key: str, delete: bool = False) -> List[np.ndarray]:
        path = self._path(key)
        with open(path, "rb") as f:
            data = f.read()
        telemetry.counter_inc("payload_store.gets")
        telemetry.counter_inc("payload_store.get_bytes", len(data))
        with np.load(io.BytesIO(data)) as z:
            arrays = [z[k] for k in z.files]
        if delete:
            try:
                os.remove(path)
            except OSError:
                pass
        return arrays

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except OSError:
            pass


class HttpPayloadStore(PayloadStore):
    """Object-store backend: the same PayloadStore contract over HTTP
    PUT/GET/DELETE against a base URL.

    reference: ``communication/s3/remote_storage.py:18-183`` (boto3
    put_object/get_object) — the role here is the same bulk channel for
    cross-org Octopus where no shared filesystem exists. Any object gateway
    that accepts ``PUT <base>/<key>`` / ``GET`` / ``DELETE`` works: S3/GCS
    presigned-URL proxies, nginx with dav_methods, MinIO, a plain WebDAV
    share. Auth rides in ``headers`` (e.g. a bearer token); TTL/sweeping is
    the store's lifecycle policy, so :meth:`sweep` is a logged no-op.
    """

    def __init__(self, base_url: str, headers: Optional[dict] = None,
                 timeout_s: float = 30.0, dedup_refresh_age_s: float = 300.0):
        self.base_url = base_url.rstrip("/")
        self.headers = dict(headers or {})
        self.timeout_s = float(timeout_s)
        # dedup HEAD hits on blobs older than this re-PUT to refresh the
        # gateway's lifecycle clock (see put_dedup)
        self.dedup_refresh_age_s = float(dedup_refresh_age_s)
        self._warned_no_age = False

    def _url(self, key: str) -> str:
        if not HTTP_KEY_RE.match(key):
            raise ValueError(f"bad payload key: {key!r}")
        return f"{self.base_url}/{key}"

    def _request(self, method: str, key: str, body: Optional[bytes] = None):
        import urllib.request

        req = urllib.request.Request(
            self._url(key), data=body, method=method,
            headers={"Content-Type": "application/octet-stream",
                     **self.headers},
        )
        return urllib.request.urlopen(req, timeout=self.timeout_s)

    def _serialize(self, arrays: List[np.ndarray]) -> bytes:
        buf = io.BytesIO()
        np.savez(buf, *[np.asarray(a) for a in arrays])
        return buf.getvalue()

    def put(self, key: str, arrays: List[np.ndarray]) -> str:
        with self._request("PUT", key, self._serialize(arrays)):
            pass
        return key

    def put_dedup(self, arrays: List[np.ndarray]) -> str:
        data = self._serialize(arrays)
        key = f"cas-{hashlib.sha256(data).hexdigest()}.npz"
        # HEAD probe: a broadcast of one model to N peers uploads once. Any
        # HTTP error (404, 405/501 no-HEAD gateways, 403 PUT-scoped auth)
        # just means "can't confirm it exists" — fall through to PUT, whose
        # own failure is the one that matters.
        import urllib.error

        try:
            with self._request("HEAD", key) as resp:
                # TTL refresh on dedup hit (directory store utimes here): if
                # the gateway runs an age-based lifecycle and the blob is
                # already old — or its age is unknowable (no Last-Modified)
                # — re-PUT to reset its clock, otherwise a just-sent message
                # could reference a sweep target. Fresh blobs skip the upload.
                age = self._age_seconds(resp)
                if age is not None and age < self.dedup_refresh_age_s:
                    telemetry.counter_inc("payload_store.dedup_hits")
                    return key
                if age is None and not self._warned_no_age:
                    # correctness over bandwidth, but never silently: a
                    # gateway that omits Last-Modified re-uploads every
                    # dedup hit
                    self._warned_no_age = True
                    logger.warning(
                        "object gateway sends no Last-Modified on HEAD: "
                        "put_dedup re-uploads on every hit (dedup degraded)")
        except urllib.error.HTTPError:
            pass
        telemetry.counter_inc("payload_store.puts")
        telemetry.counter_inc("payload_store.put_bytes", len(data))
        with self._request("PUT", key, data):
            pass
        return key

    @staticmethod
    def _age_seconds(resp) -> Optional[float]:
        """Blob age from a HEAD response's Last-Modified, None if absent."""
        lm = resp.headers.get("Last-Modified") if resp.headers else None
        if not lm:
            return None
        from email.utils import parsedate_to_datetime

        try:
            return max(0.0, time.time() - parsedate_to_datetime(lm).timestamp())
        except (TypeError, ValueError):
            return None

    def get(self, key: str, delete: bool = False) -> List[np.ndarray]:
        # normalise transport/decode failures to OSError: callers (the comm
        # managers' receive loops) drop a message on OSError instead of
        # dying, and the directory store's failures are all OSError already
        try:
            with self._request("GET", key) as resp:
                data = resp.read()
            telemetry.counter_inc("payload_store.gets")
            telemetry.counter_inc("payload_store.get_bytes", len(data))
            with np.load(io.BytesIO(data)) as z:
                arrays = [z[k] for k in z.files]
        except OSError:
            raise
        except Exception as e:
            raise OSError(f"payload fetch/decode failed for {key}: {e}") from e
        if delete:
            self.delete(key)
        return arrays

    def delete(self, key: str) -> None:
        import urllib.error

        try:
            with self._request("DELETE", key):
                pass
        except urllib.error.HTTPError:
            pass

    def sweep(self, max_age_seconds: float = 3600.0) -> int:
        # called per over-limit send by the comm managers — debug, not info
        logger.debug("HttpPayloadStore.sweep: no-op (object-store TTL is "
                     "the gateway's lifecycle policy)")
        return 0


def store_from_args(args) -> Optional[PayloadStore]:
    """YAML/args surface:

    - ``payload_store_dir``: directory path, or an http(s) base URL for the
      object-gateway backend
    - ``payload_store_timeout_s``: HTTP request timeout (default 30)
    - ``payload_store_headers``: dict of extra request headers (auth etc.)
    - ``payload_store_auth_token``: shorthand for a bearer token; the
      ``FEDML_TPU_PAYLOAD_TOKEN`` env var works too (env wins, so secrets
      can stay out of the YAML)
    """
    root = str(getattr(args, "payload_store_dir", "") or "")
    if not root:
        return None
    if root.startswith(("http://", "https://")):
        headers = dict(getattr(args, "payload_store_headers", None) or {})
        token = (os.environ.get("FEDML_TPU_PAYLOAD_TOKEN")
                 or getattr(args, "payload_store_auth_token", None))
        if token:
            headers.setdefault("Authorization", f"Bearer {token}")
        return HttpPayloadStore(
            root, headers=headers,
            timeout_s=float(getattr(args, "payload_store_timeout_s", 30.0)),
        )
    return PayloadStore(root)
