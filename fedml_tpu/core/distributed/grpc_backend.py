"""gRPC comm backend for cross-host FL (DCN message plane).

reference: ``core/distributed/communication/grpc/grpc_comm_manager.py:30-177``
— one gRPC server per node at base_port+rank, static CSV ip table, 1 GB max
message, pickled Message in a proto bytes field. Differences here:
- no protoc/codegen: a generic bytes-in/bytes-out unary handler (the wire
  format is ``Message.serialize`` — JSON header + npz arrays, no pickle)
- a persistent channel per peer (the reference dials a fresh channel per send)
"""

from __future__ import annotations

import csv
import logging
import queue
import threading
from concurrent import futures
from typing import Dict, List, Optional, Tuple

import grpc

from ..mlops import telemetry
from .base_com_manager import BaseCommunicationManager, CommunicationConstants, Observer
from .message import Message

logger = logging.getLogger(__name__)

# transient status codes worth re-sending: a peer mid-restart (crash-drop
# recovery, rolling deploy) costs backoff + a counter bump instead of a
# dead round. Retried sends re-use the same delivery header (seq/epoch),
# so the receiver's dedup window recognizes any duplicate the retry
# creates. RESOURCE_EXHAUSTED is deliberately NOT here: its common cause
# (message over the peer's size limit) is permanent and must fail fast.
TRANSIENT_STATUS_CODES = (grpc.StatusCode.UNAVAILABLE,)

MAX_MESSAGE_BYTES = 1024 * 1024 * 1024  # 1 GB, reference parity
_SERVICE = "fedml_tpu.Comm"
_METHOD = f"/{_SERVICE}/Send"

_GRPC_OPTIONS = [
    ("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
    ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES),
]


def load_ip_config(path: str) -> Dict[int, str]:
    """CSV ``receiver_id,ip`` (reference: grpc_ipconfig.csv)."""
    table: Dict[int, str] = {}
    with open(path) as f:
        for row in csv.reader(f):
            if not row or row[0].strip().lower() in ("receiver_id", ""):
                continue
            table[int(row[0])] = row[1].strip()
    return table


class GRPCCommManager(BaseCommunicationManager):
    def __init__(
        self,
        host: str,
        port: int,
        rank: int,
        world_size: int,
        ip_config: Optional[Dict[int, str]] = None,
        ip_config_path: str = "",
        base_port: int = CommunicationConstants.GRPC_BASE_PORT,
        wire_format: str = "npz",
        stream_threshold_bytes: int = 8 * 1024 * 1024,
        retry_policy=None,
    ):
        from .delivery import RetryPolicy

        self.retry_policy = retry_policy or RetryPolicy()
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.base_port = int(base_port)
        # "raw" = the direct-tensor frame format (tensor_transport.py), the
        # TRPC-role fast path: zero-copy decode + chunked streaming for
        # payloads past stream_threshold_bytes (no monolithic gRPC buffer)
        self.wire_format = str(wire_format)
        self.stream_threshold = int(stream_threshold_bytes)
        if ip_config is None and ip_config_path:
            ip_config = load_ip_config(ip_config_path)
        self.ip_config = ip_config or {i: "127.0.0.1" for i in range(world_size)}
        # shared with the receive thread (graftlint G005): the observer list
        # is snapshotted under its own lock, loop liveness is an Event — a
        # plain bool write from stop_receive_message() has no happens-before
        # edge with the loop's read
        self._observers: List[Observer] = []
        self._obs_lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._queue: "queue.Queue[bytes]" = queue.Queue()
        self._channels: Dict[int, grpc.Channel] = {}
        self._stubs: Dict[int, grpc.UnaryUnaryMultiCallable] = {}
        self._stream_stubs: Dict[int, grpc.StreamUnaryMultiCallable] = {}
        self._lock = threading.Lock()

        def handle_send(request: bytes, context) -> bytes:
            telemetry.counter_inc("comm.grpc.messages_received")
            telemetry.counter_inc("comm.grpc.bytes_received", len(request))
            self._queue.put(request)
            return b"ok"

        def handle_send_stream(request_iter, context) -> bytes:
            # bounded reassembly: the unary path is capped by the channel's
            # max_receive_message_length, so the stream must enforce the
            # same ceiling — otherwise any peer on the insecure channel
            # could grow server memory without limit in a single RPC
            chunks: List[bytes] = []
            total = 0
            for chunk in request_iter:
                total += len(chunk)
                if total > MAX_MESSAGE_BYTES:
                    telemetry.counter_inc("comm.grpc.stream_overflows")
                    context.abort(
                        grpc.StatusCode.RESOURCE_EXHAUSTED,
                        f"streamed payload exceeds {MAX_MESSAGE_BYTES} "
                        "bytes",
                    )
                chunks.append(chunk)
            data = b"".join(chunks)
            telemetry.counter_inc("comm.grpc.messages_received")
            telemetry.counter_inc("comm.grpc.bytes_received", len(data))
            self._queue.put(data)
            return b"ok"

        handlers = grpc.method_handlers_generic_handler(
            _SERVICE,
            {
                "Send": grpc.unary_unary_rpc_method_handler(
                    handle_send,
                    request_deserializer=None,  # raw bytes through
                    response_serializer=None,
                ),
                "SendStream": grpc.stream_unary_rpc_method_handler(
                    handle_send_stream,
                    request_deserializer=None,
                    response_serializer=None,
                ),
            },
        )
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=8), options=_GRPC_OPTIONS
        )
        self._server.add_generic_rpc_handlers((handlers,))
        bind = f"{host}:{port}"
        # grpc returns 0 (not an exception) when the bind fails — an
        # unchecked 0 means a server that silently never receives
        if self._server.add_insecure_port(bind) == 0:
            raise OSError(f"grpc backend: could not bind {bind}")
        self._server.start()
        logger.info("grpc backend: rank %d serving at %s", rank, bind)

    def _ensure_channel(self, receiver_id: int) -> None:
        if receiver_id not in self._stubs:
            target = (
                f"{self.ip_config[receiver_id]}:{self.base_port + receiver_id}"
            )
            ch = grpc.insecure_channel(target, options=_GRPC_OPTIONS)
            self._channels[receiver_id] = ch
            self._stubs[receiver_id] = ch.unary_unary(
                _METHOD, request_serializer=None, response_deserializer=None
            )
            self._stream_stubs[receiver_id] = ch.stream_unary(
                f"/{_SERVICE}/SendStream",
                request_serializer=None, response_deserializer=None,
            )

    def _stub(self, receiver_id: int) -> grpc.UnaryUnaryMultiCallable:
        with self._lock:
            self._ensure_channel(receiver_id)
            return self._stubs[receiver_id]

    def _stream_stub(self, receiver_id: int) -> grpc.StreamUnaryMultiCallable:
        with self._lock:
            self._ensure_channel(receiver_id)
            return self._stream_stubs[receiver_id]

    def send_message(self, msg: Message) -> None:
        msg.wire_format = self.wire_format
        payload = msg.serialize()
        telemetry.counter_inc("comm.grpc.messages_sent")
        telemetry.counter_inc("comm.grpc.bytes_sent", len(payload))

        def _once() -> None:
            if len(payload) > self.stream_threshold:
                from .tensor_transport import iter_chunks

                self._stream_stub(msg.get_receiver_id())(
                    iter_chunks(payload), timeout=300
                )
            else:
                self._stub(msg.get_receiver_id())(payload, timeout=300)

        def _transient(e: Exception) -> bool:
            code = e.code() if hasattr(e, "code") else None
            return (isinstance(e, grpc.RpcError)
                    and code in TRANSIENT_STATUS_CODES)

        try:
            # exponential backoff + jitter under a bounded budget
            # (delivery.RetryPolicy) — replaces the old single-UNAVAILABLE
            # retry; a peer that stays down past the budget still raises so
            # _send_or_mark_dead can declare it dead
            self.retry_policy.call(
                _once,
                is_transient=_transient,
                on_retry=lambda attempt, e: telemetry.counter_inc(
                    "comm.grpc.send_retries"
                ),
            )
        except grpc.RpcError:
            telemetry.counter_inc("comm.grpc.send_failures")
            raise

    def add_observer(self, observer: Observer) -> None:
        with self._obs_lock:
            self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        with self._obs_lock:
            if observer in self._observers:
                self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        self._notify(
            Message(CommunicationConstants.MSG_TYPE_CONNECTION_IS_READY,
                    self.rank, self.rank)
        )
        while not self._stop_evt.is_set():
            try:
                data = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            from .delivery import safe_deserialize

            msg = safe_deserialize(data, "grpc")
            if msg is not None:
                self._notify(msg)

    def stop_receive_message(self) -> None:
        self._stop_evt.set()
        self._server.stop(grace=0.5)
        with self._lock:
            for ch in self._channels.values():
                ch.close()
            self._channels.clear()
            self._stubs.clear()

    def _notify(self, msg: Message) -> None:
        with self._obs_lock:
            observers = list(self._observers)
        for obs in observers:
            obs.receive_message(msg.get_type(), msg)
