"""gRPC comm backend for cross-host FL (DCN message plane).

reference: ``core/distributed/communication/grpc/grpc_comm_manager.py:30-177``
— one gRPC server per node at base_port+rank, static CSV ip table, 1 GB max
message, pickled Message in a proto bytes field. Differences here:
- no protoc/codegen: a generic bytes-in/bytes-out unary handler (the wire
  format is ``Message.serialize`` — raw zero-copy tensor frames by default,
  npz as the self-describing fallback; no pickle either way)
- a persistent channel per peer (the reference dials a fresh channel per send)
- rank→port multiplexing (``grpc_ranks_per_port``): N ranks share ONE
  port / gRPC server per process (:class:`_SharedGrpcServer` routes frames
  by the header's receiver id), lifting the port-per-rank cap that bounded
  how many device processes one machine could host in the swarm harness
"""

from __future__ import annotations

import csv
import json
import logging
import queue
import threading
from concurrent import futures
from typing import Dict, List, Optional, Tuple

import grpc

from ..mlops import telemetry
from .base_com_manager import BaseCommunicationManager, CommunicationConstants, Observer
from .message import Message

logger = logging.getLogger(__name__)


def port_for_rank(base_port: int, rank: int, ranks_per_port: int = 1) -> int:
    """The one rank→port mapping both bind and dial use.

    ``ranks_per_port=1`` is the legacy port-per-rank layout
    (``base_port + rank``). With N > 1, blocks of N consecutive client
    ranks share a port: rank 0 (the server) keeps ``base_port``, ranks
    ``1..N`` map to ``base_port + 1``, ``N+1..2N`` to ``base_port + 2`` —
    matching the swarm harness's contiguous rank-block process assignment,
    so each device-host process binds exactly one port however many device
    ranks it hosts."""
    n = max(int(ranks_per_port), 1)
    return int(base_port) + (int(rank) + n - 1) // n

# transient status codes worth re-sending: a peer mid-restart (crash-drop
# recovery, rolling deploy) costs backoff + a counter bump instead of a
# dead round. Retried sends re-use the same delivery header (seq/epoch),
# so the receiver's dedup window recognizes any duplicate the retry
# creates. RESOURCE_EXHAUSTED is deliberately NOT here: its common cause
# (message over the peer's size limit) is permanent and must fail fast.
TRANSIENT_STATUS_CODES = (grpc.StatusCode.UNAVAILABLE,)

MAX_MESSAGE_BYTES = 1024 * 1024 * 1024  # 1 GB, reference parity
_SERVICE = "fedml_tpu.Comm"
_METHOD = f"/{_SERVICE}/Send"

_GRPC_OPTIONS = [
    ("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
    ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES),
]


def load_ip_config(path: str) -> Dict[int, str]:
    """CSV ``receiver_id,ip`` (reference: grpc_ipconfig.csv)."""
    table: Dict[int, str] = {}
    with open(path) as f:
        for row in csv.reader(f):
            if not row or row[0].strip().lower() in ("receiver_id", ""):
                continue
            table[int(row[0])] = row[1].strip()
    return table


def _peek_receiver(data: bytes) -> Optional[int]:
    """The frame header's receiver id, parsed without touching the body
    (the routing key for multiplexed ranks). None on any parse failure —
    the frame still gets delivered somewhere so the receive loop's
    corrupt-frame accounting sees it."""
    try:
        hlen = int.from_bytes(data[:4], "big")
        header = json.loads(bytes(data[4:4 + hlen]).decode("utf-8"))
        return int(header[Message.MSG_ARG_KEY_RECEIVER])
    except Exception:  # noqa: BLE001 — any malformed header: no route
        return None


class _SharedGrpcServer:
    """ONE gRPC server per (host, port), shared by every local rank bound
    there. Each rank registers its raw-bytes receive queue; inbound frames
    route by the header's receiver id. With ``grpc_ranks_per_port=1``
    exactly one rank registers per server and routing short-circuits, so
    the legacy layout pays nothing for the capability."""

    _registry: Dict[str, "_SharedGrpcServer"] = {}
    _registry_lock = threading.Lock()

    @classmethod
    def acquire(cls, host: str, port: int, rank: int,
                q: "queue.Queue[bytes]") -> "_SharedGrpcServer":
        """Get-or-create the server for (host, port) AND register
        ``rank``'s queue in one registry-lock critical section — a
        concurrent last-rank release can never stop the server between
        the lookup and the registration."""
        key = f"{host}:{port}"
        with cls._registry_lock:
            srv = cls._registry.get(key)
            if srv is None:
                # the constructor raises OSError on bind failure; the
                # entry is only inserted after it returns, so a failed
                # bind leaves no registry garbage
                srv = cls(host, port, key)
                cls._registry[key] = srv
            srv._register(rank, q)
            return srv

    @classmethod
    def server_count(cls) -> int:
        with cls._registry_lock:
            return len(cls._registry)

    def __init__(self, host: str, port: int, key: str):
        self.key = key
        self._routes_lock = threading.Lock()
        self._routes: Dict[int, "queue.Queue[bytes]"] = {}

        def handle_send(request: bytes, context) -> bytes:
            telemetry.counter_inc("comm.grpc.messages_received")
            telemetry.counter_inc("comm.grpc.bytes_received", len(request))
            self._route(request)
            return b"ok"

        def handle_send_stream(request_iter, context) -> bytes:
            # bounded reassembly: the unary path is capped by the channel's
            # max_receive_message_length, so the stream must enforce the
            # same ceiling — otherwise any peer on the insecure channel
            # could grow server memory without limit in a single RPC
            chunks: List[bytes] = []
            total = 0
            for chunk in request_iter:
                total += len(chunk)
                if total > MAX_MESSAGE_BYTES:
                    telemetry.counter_inc("comm.grpc.stream_overflows")
                    context.abort(
                        grpc.StatusCode.RESOURCE_EXHAUSTED,
                        f"streamed payload exceeds {MAX_MESSAGE_BYTES} "
                        "bytes",
                    )
                chunks.append(chunk)
            data = b"".join(chunks)
            telemetry.counter_inc("comm.grpc.messages_received")
            telemetry.counter_inc("comm.grpc.bytes_received", len(data))
            self._route(data)
            return b"ok"

        handlers = grpc.method_handlers_generic_handler(
            _SERVICE,
            {
                "Send": grpc.unary_unary_rpc_method_handler(
                    handle_send,
                    request_deserializer=None,  # raw bytes through
                    response_serializer=None,
                ),
                "SendStream": grpc.stream_unary_rpc_method_handler(
                    handle_send_stream,
                    request_deserializer=None,
                    response_serializer=None,
                ),
            },
        )
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=8), options=_GRPC_OPTIONS
        )
        self._server.add_generic_rpc_handlers((handlers,))
        bind = f"{host}:{port}"
        # grpc returns 0 (not an exception) when the bind fails — an
        # unchecked 0 means a server that silently never receives.
        # (acquire() holds the registry lock and inserts the entry only
        # after this constructor returns, so raising here is clean.)
        if self._server.add_insecure_port(bind) == 0:
            raise OSError(f"grpc backend: could not bind {bind}")
        self._server.start()
        logger.info("grpc backend: serving at %s", bind)

    def _register(self, rank: int, q: "queue.Queue[bytes]") -> None:
        """Called by acquire() under the registry lock (lock order:
        registry → routes, same as release)."""
        with self._routes_lock:
            if rank in self._routes:
                raise ValueError(
                    f"grpc backend: rank {rank} already registered on "
                    f"{self.key} — two managers for one rank on one port"
                )
            self._routes[rank] = q

    def release(self, rank: int) -> None:
        """Unregister a rank; the LAST rank out stops the server."""
        with self._registry_lock:
            with self._routes_lock:
                self._routes.pop(rank, None)
                empty = not self._routes
            if empty:
                self._registry.pop(self.key, None)
        if empty:
            self._server.stop(grace=0.5)

    def _route(self, data: bytes) -> None:
        with self._routes_lock:
            if len(self._routes) == 1:
                q = next(iter(self._routes.values()))
            else:
                receiver = _peek_receiver(data)
                q = self._routes.get(receiver)
                if q is None:
                    # unknown/garbled receiver: deliver to the lowest rank
                    # so the frame is still counted (corrupt) or logged
                    # (misrouted) by a real receive loop instead of
                    # vanishing
                    telemetry.counter_inc("comm.grpc.misrouted_frames")
                    if not self._routes:
                        return
                    q = self._routes[min(self._routes)]
        q.put(data)


class GRPCCommManager(BaseCommunicationManager):
    def __init__(
        self,
        host: str,
        port: int,
        rank: int,
        world_size: int,
        ip_config: Optional[Dict[int, str]] = None,
        ip_config_path: str = "",
        base_port: int = CommunicationConstants.GRPC_BASE_PORT,
        wire_format: str = "raw",
        stream_threshold_bytes: int = 8 * 1024 * 1024,
        retry_policy=None,
        ranks_per_port: int = 1,
    ):
        from .delivery import RetryPolicy

        self.retry_policy = retry_policy or RetryPolicy()
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.base_port = int(base_port)
        # rank→port multiplexing: dial peers through the same mapping the
        # bind side used (port_for_rank); 1 = legacy port-per-rank
        self.ranks_per_port = max(int(ranks_per_port), 1)
        # "raw" = the direct-tensor frame format (tensor_transport.py), the
        # TRPC-role fast path: zero-copy decode + chunked streaming for
        # payloads past stream_threshold_bytes (no monolithic gRPC buffer)
        self.wire_format = str(wire_format)
        self.stream_threshold = int(stream_threshold_bytes)
        if ip_config is None and ip_config_path:
            ip_config = load_ip_config(ip_config_path)
        self.ip_config = ip_config or {i: "127.0.0.1" for i in range(world_size)}
        # shared with the receive thread (graftlint G005): the observer list
        # is snapshotted under its own lock, loop liveness is an Event — a
        # plain bool write from stop_receive_message() has no happens-before
        # edge with the loop's read
        self._observers: List[Observer] = []
        self._obs_lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._queue: "queue.Queue[bytes]" = queue.Queue()
        self._channels: Dict[int, grpc.Channel] = {}
        self._stubs: Dict[int, grpc.UnaryUnaryMultiCallable] = {}
        self._stream_stubs: Dict[int, grpc.StreamUnaryMultiCallable] = {}
        self._lock = threading.Lock()
        # bind through the shared-server registry: ranks mapped to the same
        # (host, port) share ONE gRPC server, frames route by receiver id
        # (acquire registers atomically; ValueError on a duplicate rank)
        self._shared = _SharedGrpcServer.acquire(
            host, port, self.rank, self._queue)
        logger.info("grpc backend: rank %d receiving at %s:%d "
                    "(ranks_per_port=%d)", rank, host, port,
                    self.ranks_per_port)

    def _ensure_channel(self, receiver_id: int) -> None:
        if receiver_id not in self._stubs:
            target = (
                f"{self.ip_config[receiver_id]}:"
                f"{port_for_rank(self.base_port, receiver_id, self.ranks_per_port)}"
            )
            ch = grpc.insecure_channel(target, options=_GRPC_OPTIONS)
            self._channels[receiver_id] = ch
            self._stubs[receiver_id] = ch.unary_unary(
                _METHOD, request_serializer=None, response_deserializer=None
            )
            self._stream_stubs[receiver_id] = ch.stream_unary(
                f"/{_SERVICE}/SendStream",
                request_serializer=None, response_deserializer=None,
            )

    def _stub(self, receiver_id: int) -> grpc.UnaryUnaryMultiCallable:
        with self._lock:
            self._ensure_channel(receiver_id)
            return self._stubs[receiver_id]

    def _stream_stub(self, receiver_id: int) -> grpc.StreamUnaryMultiCallable:
        with self._lock:
            self._ensure_channel(receiver_id)
            return self._stream_stubs[receiver_id]

    def _evict_channel(self, receiver_id: int) -> None:
        """Drop the cached channel/stubs for a peer whose connection just
        failed: the next ``send_message`` re-dials from scratch. A peer
        process that died and was RESTARTED on the same port must never be
        reached through the old process's connection state — eviction on
        connection error is what makes a reconnecting client land cleanly
        on the restarted server (docs/robustness.md)."""
        with self._lock:
            ch = self._channels.pop(receiver_id, None)
            self._stubs.pop(receiver_id, None)
            self._stream_stubs.pop(receiver_id, None)
        if ch is not None:
            telemetry.counter_inc("comm.grpc.channel_evictions")
            try:
                ch.close()
            except Exception:  # noqa: BLE001 — already-broken channel
                pass

    def send_message(self, msg: Message) -> None:
        msg.wire_format = self.wire_format
        payload = msg.serialize()
        telemetry.counter_inc("comm.grpc.messages_sent")
        telemetry.counter_inc("comm.grpc.bytes_sent", len(payload))

        def _once() -> None:
            if len(payload) > self.stream_threshold:
                from .tensor_transport import iter_chunks

                self._stream_stub(msg.get_receiver_id())(
                    iter_chunks(payload), timeout=300
                )
            else:
                self._stub(msg.get_receiver_id())(payload, timeout=300)

        def _transient(e: Exception) -> bool:
            code = e.code() if hasattr(e, "code") else None
            return (isinstance(e, grpc.RpcError)
                    and code in TRANSIENT_STATUS_CODES)

        def _on_retry(attempt: int, e: Exception) -> None:
            telemetry.counter_inc("comm.grpc.send_retries")
            # rebuild the connection between attempts: the peer may have
            # been killed and restarted on the same port, and its old
            # channel must not be retried into
            self._evict_channel(msg.get_receiver_id())

        try:
            # exponential backoff + jitter under a bounded budget
            # (delivery.RetryPolicy) — replaces the old single-UNAVAILABLE
            # retry; a peer that stays down past the budget still raises so
            # _send_or_mark_dead can declare it dead
            self.retry_policy.call(
                _once,
                is_transient=_transient,
                on_retry=_on_retry,
            )
        except grpc.RpcError:
            telemetry.counter_inc("comm.grpc.send_failures")
            # evict here too: the NEXT send (a later round, a resync
            # attempt) starts with a fresh dial instead of a dead channel
            self._evict_channel(msg.get_receiver_id())
            raise

    def add_observer(self, observer: Observer) -> None:
        with self._obs_lock:
            self._observers.append(observer)

    def remove_observer(self, observer: Observer) -> None:
        with self._obs_lock:
            if observer in self._observers:
                self._observers.remove(observer)

    def handle_receive_message(self) -> None:
        self._notify(
            Message(CommunicationConstants.MSG_TYPE_CONNECTION_IS_READY,
                    self.rank, self.rank)
        )
        while not self._stop_evt.is_set():
            try:
                data = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            from .delivery import safe_deserialize

            msg = safe_deserialize(data, "grpc")
            if msg is not None:
                self._notify(msg)

    def stop_receive_message(self) -> None:
        self._stop_evt.set()
        # unregister from the shared server; the last rank out stops it
        self._shared.release(self.rank)
        with self._lock:
            for ch in self._channels.values():
                ch.close()
            self._channels.clear()
            self._stubs.clear()
            self._stream_stubs.clear()

    def _notify(self, msg: Message) -> None:
        with self._obs_lock:
            observers = list(self._observers)
        for obs in observers:
            obs.receive_message(msg.get_type(), msg)
