"""``fedml_tpu.core.distributed`` — message plane for cross-silo FL."""

from .base_com_manager import (
    BaseCommunicationManager,
    CommunicationConstants,
    Observer,
)
from .comm_manager import FedMLCommManager
from .message import Message

__all__ = [
    "BaseCommunicationManager",
    "CommunicationConstants",
    "Observer",
    "FedMLCommManager",
    "Message",
]
