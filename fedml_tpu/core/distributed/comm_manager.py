"""FedMLCommManager — the event-driven actor base every cross-silo node
subclasses.

reference: ``core/distributed/fedml_comm_manager.py:11-135`` — an Observer
holding a handler registry keyed by message type; ``run()`` blocks in the
backend's receive loop; ``_init_manager`` is the backend factory. Preserved
contract: register_message_receive_handler / send_message / finish. Backends:
LOOPBACK (in-process test fixture) and GRPC; the reference's MQTT/S3/TRPC
transports collapse into these two (SURVEY.md §5 "Distributed communication
backend": one DCN message plane instead of five broker stacks).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, Optional

from ... import constants
from .base_com_manager import BaseCommunicationManager, Observer
from .message import Message

logger = logging.getLogger(__name__)

MessageHandler = Callable[[Message], None]


class FedMLCommManager(Observer):
    def __init__(self, args, comm=None, rank: int = 0, size: int = 0,
                 backend: str = constants.COMM_BACKEND_LOOPBACK):
        from ..world import WorldScope
        from .delivery import DedupWindow, RetryPolicy, SenderStamp

        self.args = args
        self.size = int(size)
        self.rank = int(rank)
        self.backend = backend
        self.com_manager: Optional[BaseCommunicationManager] = comm
        self.message_handler_dict: Dict[str, MessageHandler] = {}
        self._thread: Optional[threading.Thread] = None
        # the explicit owner of this participant's run state (graftiso,
        # docs/graftiso.md): telemetry scope, payload store, and the
        # thread/timer registry the shutdown path drains — keyed by
        # (run_id, rank) so tenant A's teardown can never touch tenant B
        self.world = WorldScope.for_args(args, rank=self.rank)
        # payload-by-reference mode (reference MQTT+S3 split): arrays above
        # the inline limit ride the world-keyed store, not the control
        # channel
        self.payload_store = self.world.payload_store
        self.payload_inline_limit = int(
            getattr(args, "payload_inline_limit_bytes", 1 * 1024 * 1024)
        )
        # idempotent at-least-once delivery (delivery.py): every outbound
        # message is stamped (sender epoch + monotonic seq) ONCE, so a
        # retried send is a recognizable wire duplicate; inbound duplicates
        # and stale-epoch stragglers are dropped before any FSM handler
        # (a retried C2S_SEND_MODEL must never double-count a client)
        self._stamp = SenderStamp()
        self._retry_policy = RetryPolicy.from_args(args)
        self._dedup = DedupWindow(
            window=int(getattr(args, "comm_dedup_window", 4096))
        )
        if self.com_manager is None:
            self._init_manager()
        self.com_manager.add_observer(self)

    # -- registry (reference :52-63) ----------------------------------------
    def register_comm_manager(self, comm_manager: BaseCommunicationManager):
        # setup-phase setter: callers install the backend before run()/
        # run_async() starts the receive loop, so no concurrent reader exists
        self.com_manager = comm_manager  # graftlint: disable=G005

    def register_message_receive_handler(
        self, msg_type: str, handler: MessageHandler
    ) -> None:
        self.message_handler_dict[str(msg_type)] = handler

    def register_message_receive_handlers(self) -> None:
        """Subclasses register their FSM edges here (called by run())."""

    # -- loop (reference :25-50) --------------------------------------------
    def run(self) -> None:
        self.register_message_receive_handlers()
        self.com_manager.handle_receive_message()
        logger.info("rank %d comm loop exited", self.rank)

    def run_async(self) -> threading.Thread:
        """Run the receive loop on a daemon thread (test/process embedding)."""
        self.register_message_receive_handlers()
        self._thread = threading.Thread(
            target=self.com_manager.handle_receive_message, daemon=True
        )
        # tethered to the world: finish() → world.shutdown() joins it
        self.world.register_thread(self._thread)
        self._thread.start()
        return self._thread

    def bump_epoch(self) -> None:
        """Start a fresh delivery epoch (new SenderStamp: new epoch, seq
        from 0). A client RE-HOMING to a sibling edge calls this before
        replaying its cached update: the stamp's seq counter is shared
        across receivers, so by re-home time the cached update's original
        seq sits far below the adoptive edge's window floor — a fresh
        window would misclassify the replay as a duplicate. Under a NEW
        epoch the adoptive edge's window resets and accepts it, while the
        old (live, merely partitioned) edge still holds the ORIGINAL
        stamped copy and dedups any straggler of it — both sides pinned in
        tests/test_delivery.py."""
        from .delivery import SenderStamp

        self._stamp = SenderStamp()

    def send_message(self, message: Message) -> None:
        from .delivery import TransientSendError, arrays_digest
        from .payload_store import PAYLOAD_REF_KEY

        # stamp ONCE per logical message (idempotent across retries and
        # across callers that re-send the same Message object)
        if message.get(Message.MSG_ARG_KEY_SEQ) is None:
            message.add(Message.MSG_ARG_KEY_SEQ, self._stamp.next_seq())
            message.add(Message.MSG_ARG_KEY_EPOCH, self._stamp.epoch)
        # causal trace context (docs/tracing.md): the innermost open span
        # on this thread — or the context adopted from the message being
        # handled — rides the header, so the receiver's spans continue
        # THIS trace. Handlers that stamped an explicit context (fan-out
        # dispatch) win; like the seq stamp, it survives retries unchanged.
        if (self.world.trace.enabled
                and message.get(Message.MSG_ARG_KEY_TRACE) is None):
            ctx = self.world.trace.current_context()
            if ctx is not None:
                message.add(Message.MSG_ARG_KEY_TRACE, ctx.to_wire())
        if (
            self.payload_store is not None
            and message.arrays
            and sum(a.nbytes for a in message.arrays) > self.payload_inline_limit
        ):
            # content-addressed: an N-client broadcast of the same model
            # writes one blob; stale blobs age out via TTL sweep
            self.world.telemetry.counter_inc("comm.payload_offloads")
            self.world.telemetry.counter_inc(
                "comm.payload_offload_bytes",
                sum(a.nbytes for a in message.arrays),
            )
            # digest of the arrays BEFORE they leave the message: the
            # receiver re-verifies after the store fetch (and re-fetches
            # once on mismatch — a torn blob read must not reach the FSM)
            message.add(Message.MSG_ARG_KEY_PAYLOAD_SHA256,
                        arrays_digest(message.arrays))
            key = self.payload_store.put_dedup(message.arrays)
            message.add(PAYLOAD_REF_KEY, key)
            message.set_arrays([])
            self.payload_store.sweep(
                float(getattr(self.args, "payload_ttl_seconds", 3600.0))
            )
        try:
            self._retry_policy.call(
                lambda: self.com_manager.send_message(message),
                is_transient=lambda e: isinstance(e, TransientSendError),
                on_retry=lambda attempt, e: (
                    self.world.telemetry.counter_inc("comm.send_retries"),
                    # a retry is an EVENT inside the enclosing span (the
                    # upload/dispatch that is retrying) — never a new span,
                    # so retried frames can't duplicate trace nodes
                    self.world.trace.event(
                        "send_retry", attempt=attempt,
                        msg_type=message.get_type()),
                    logger.info(
                        "rank %d: transient send failure for %r (%s) — "
                        "retry %d", self.rank, message.get_type(), e, attempt,
                    ),
                ),
            )
        except Exception:
            self.world.telemetry.counter_inc("comm.send_failures")
            raise

    def receive_message(self, msg_type: str, msg: Message) -> None:
        from .delivery import PayloadCorruptError
        from .payload_store import PAYLOAD_REF_KEY

        ref = msg.get(PAYLOAD_REF_KEY)
        if ref:
            self.world.telemetry.counter_inc("comm.payload_fetches")
            if self.payload_store is None:
                # fail HERE, loudly — otherwise the handler sees an empty
                # array list and dies far away in tree_unflatten
                logger.error(
                    "rank %d: message %r carries payload reference %r but "
                    "this node has no payload_store_dir configured — "
                    "dropping message", self.rank, msg_type, ref,
                )
                return
            try:
                # blobs are content-addressed and shared across recipients —
                # never consumed on read; the sender's TTL sweep reclaims
                # them. A fetch whose digest mismatches the header (torn
                # read, corrupted blob) is re-fetched once, then dropped.
                msg.set_arrays(self._fetch_verified(str(ref), msg))
            except OSError as e:
                logger.error(
                    "rank %d: payload blob %r for %r is gone (%s) — likely "
                    "TTL-swept before delivery; raise payload_ttl_seconds. "
                    "Dropping message.", self.rank, ref, msg_type, e,
                )
                return
            except PayloadCorruptError as e:
                self.world.telemetry.counter_inc("comm.corrupt_payloads")
                logger.error(
                    "rank %d: payload blob %r for %r failed its checksum "
                    "after re-fetch (%s) — dropping message",
                    self.rank, ref, msg_type, e,
                )
                return
        # at-most-once: drop wire duplicates (sender retries, injected
        # duplication) and stale-epoch stragglers before the handler runs.
        # Recorded only AFTER the payload fetch succeeded — a message
        # dropped for a missing/corrupt blob must NOT consume its seq, or
        # the sender's re-delivery of the same logical message would be
        # misclassified as a duplicate and the contribution lost for good
        seq = msg.get(Message.MSG_ARG_KEY_SEQ)
        if seq is not None:
            verdict = self._dedup.accept(
                msg.get_sender_id(), int(msg.get(
                    Message.MSG_ARG_KEY_EPOCH, 0)), int(seq),
            )
            if verdict == "duplicate":
                self.world.telemetry.counter_inc("comm.dedup_drops")
                # the drop is an ANNOTATION on the receive timeline, not a
                # span: the original delivery already owns the trace node
                self.world.trace.event(
                    "dedup_drop", msg_type=str(msg_type),
                    sender=msg.get_sender_id(), seq=int(seq))
                logger.info(
                    "rank %d: duplicate %r from %d (seq %s) dropped",
                    self.rank, msg_type, msg.get_sender_id(), seq,
                )
                return
            if verdict == "stale_epoch":
                self.world.telemetry.counter_inc("comm.stale_epoch_drops")
                self.world.trace.event(
                    "stale_epoch_drop", msg_type=str(msg_type),
                    sender=msg.get_sender_id())
                logger.info(
                    "rank %d: stale-epoch %r from %d dropped (sender "
                    "restarted)", self.rank, msg_type, msg.get_sender_id(),
                )
                return
        handler = self.message_handler_dict.get(str(msg_type))
        if handler is None:
            logger.debug("rank %d: no handler for %r", self.rank, msg_type)
            return
        if self.world.trace.enabled:
            # adopt the sender's causal context for the handler's duration:
            # spans opened inside — and messages sent from — the handler
            # continue the sender's trace across the process boundary
            from ..mlops.tracing import TraceContext

            wire_ctx = TraceContext.from_wire(
                msg.get(Message.MSG_ARG_KEY_TRACE))
            self.world.trace.adopt(wire_ctx)
            try:
                handler(msg)
            finally:
                self.world.trace.adopt(None)
            return
        handler(msg)

    def _fetch_verified(self, ref: str, msg: Message):
        """Payload-store fetch with integrity verification + one re-fetch."""
        from .delivery import PayloadCorruptError, arrays_digest

        want = msg.get(Message.MSG_ARG_KEY_PAYLOAD_SHA256)
        for attempt in range(2):
            arrays = self.payload_store.get(ref)
            if want is None or arrays_digest(arrays) == want:
                return arrays
            if attempt == 0:
                self.world.telemetry.counter_inc("comm.payload_refetches")
                logger.warning(
                    "rank %d: payload blob %r failed checksum — "
                    "re-fetching once", self.rank, ref,
                )
        raise PayloadCorruptError(
            f"payload blob {ref!r} digest mismatch after re-fetch "
            f"(expected {str(want)[:12]}…)"
        )

    def finish(self) -> None:
        """Stop the loop (reference :57-60 calls MPI Abort; we just stop),
        then drain the world scope: cancel registered timers and join
        registered worker threads — rank-scoped, so one participant's
        teardown never touches another's (idempotent; a worker driving its
        own shutdown is skipped, not self-joined)."""
        self.com_manager.stop_receive_message()
        self.world.shutdown()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    # -- backend factory (reference :72-133) --------------------------------
    def _init_manager(self) -> None:
        if self.backend == constants.COMM_BACKEND_LOOPBACK:
            from .loopback import LoopbackCommManager

            world = str(getattr(self.args, "run_id", "default"))
            self.com_manager = LoopbackCommManager(self.rank, self.size, world)
        elif self.backend == constants.COMM_BACKEND_GRPC:
            from .base_com_manager import CommunicationConstants
            from .grpc_backend import GRPCCommManager, port_for_rank

            base_port = int(
                getattr(self.args, "comm_port", CommunicationConstants.GRPC_BASE_PORT)
            )
            ranks_per_port = int(
                getattr(self.args, "grpc_ranks_per_port", 1) or 1)
            self.com_manager = GRPCCommManager(
                host=str(getattr(self.args, "comm_host", "0.0.0.0")),
                port=port_for_rank(base_port, self.rank, ranks_per_port),
                rank=self.rank,
                world_size=self.size,
                ip_config_path=str(getattr(self.args, "grpc_ipconfig_path", "")),
                base_port=base_port,
                # TRPC-role fast path (tensor_transport.py): raw zero-copy
                # frames + chunked streaming for bulk tensors is the
                # DEFAULT since ISSUE 9; "npz" stays as the explicit
                # self-describing fallback (mixed worlds interoperate —
                # decode sniffs the body magic)
                wire_format=str(getattr(self.args, "grpc_wire_format", "raw")),
                stream_threshold_bytes=int(getattr(
                    self.args, "grpc_stream_threshold_bytes", 8 * 1024 * 1024
                )),
                retry_policy=self._retry_policy,
                ranks_per_port=ranks_per_port,
            )
        elif self.backend == constants.COMM_BACKEND_MQTT:
            from .mqtt_backend import MqttCommManager

            self.com_manager = MqttCommManager(
                host=str(getattr(self.args, "mqtt_host", "127.0.0.1")),
                port=int(getattr(self.args, "mqtt_port", 1883)),
                rank=self.rank,
                world_size=self.size,
                run_id=str(getattr(self.args, "run_id", "0")),
                subscribe_retries=int(
                    getattr(self.args, "mqtt_subscribe_retries", 5)
                ),
                subscribe_timeout_s=float(
                    getattr(self.args, "mqtt_subscribe_timeout_s", 6.0)
                ),
            )
        else:
            raise ValueError(
                f"unsupported comm backend {self.backend!r}; "
                f"known: {constants.COMM_BACKENDS}"
            )
        # fault injection (SURVEY §5 upgrade — the reference has none):
        # a FaultPlan on args wraps the transport so recovery paths are
        # testable deterministically; production FSMs stay unaware
        plan = getattr(self.args, "fault_plan", None)
        if plan is not None:
            from .faults import FaultyComm

            self.com_manager = FaultyComm(self.com_manager, plan, self.rank)
