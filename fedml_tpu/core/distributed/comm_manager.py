"""FedMLCommManager — the event-driven actor base every cross-silo node
subclasses.

reference: ``core/distributed/fedml_comm_manager.py:11-135`` — an Observer
holding a handler registry keyed by message type; ``run()`` blocks in the
backend's receive loop; ``_init_manager`` is the backend factory. Preserved
contract: register_message_receive_handler / send_message / finish. Backends:
LOOPBACK (in-process test fixture) and GRPC; the reference's MQTT/S3/TRPC
transports collapse into these two (SURVEY.md §5 "Distributed communication
backend": one DCN message plane instead of five broker stacks).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, Optional

from ... import constants
from .base_com_manager import BaseCommunicationManager, Observer
from .message import Message

logger = logging.getLogger(__name__)

MessageHandler = Callable[[Message], None]


class FedMLCommManager(Observer):
    def __init__(self, args, comm=None, rank: int = 0, size: int = 0,
                 backend: str = constants.COMM_BACKEND_LOOPBACK):
        from .payload_store import store_from_args

        self.args = args
        self.size = int(size)
        self.rank = int(rank)
        self.backend = backend
        self.com_manager: Optional[BaseCommunicationManager] = comm
        self.message_handler_dict: Dict[str, MessageHandler] = {}
        self._thread: Optional[threading.Thread] = None
        # payload-by-reference mode (reference MQTT+S3 split): arrays above
        # the inline limit ride the shared store, not the control channel
        self.payload_store = store_from_args(args)
        self.payload_inline_limit = int(
            getattr(args, "payload_inline_limit_bytes", 1 * 1024 * 1024)
        )
        if self.com_manager is None:
            self._init_manager()
        self.com_manager.add_observer(self)

    # -- registry (reference :52-63) ----------------------------------------
    def register_comm_manager(self, comm_manager: BaseCommunicationManager):
        # setup-phase setter: callers install the backend before run()/
        # run_async() starts the receive loop, so no concurrent reader exists
        self.com_manager = comm_manager  # graftlint: disable=G005

    def register_message_receive_handler(
        self, msg_type: str, handler: MessageHandler
    ) -> None:
        self.message_handler_dict[str(msg_type)] = handler

    def register_message_receive_handlers(self) -> None:
        """Subclasses register their FSM edges here (called by run())."""

    # -- loop (reference :25-50) --------------------------------------------
    def run(self) -> None:
        self.register_message_receive_handlers()
        self.com_manager.handle_receive_message()
        logger.info("rank %d comm loop exited", self.rank)

    def run_async(self) -> threading.Thread:
        """Run the receive loop on a daemon thread (test/process embedding)."""
        self.register_message_receive_handlers()
        self._thread = threading.Thread(
            target=self.com_manager.handle_receive_message, daemon=True
        )
        self._thread.start()
        return self._thread

    def send_message(self, message: Message) -> None:
        from ..mlops import telemetry
        from .payload_store import PAYLOAD_REF_KEY

        if (
            self.payload_store is not None
            and message.arrays
            and sum(a.nbytes for a in message.arrays) > self.payload_inline_limit
        ):
            # content-addressed: an N-client broadcast of the same model
            # writes one blob; stale blobs age out via TTL sweep
            telemetry.counter_inc("comm.payload_offloads")
            telemetry.counter_inc(
                "comm.payload_offload_bytes",
                sum(a.nbytes for a in message.arrays),
            )
            key = self.payload_store.put_dedup(message.arrays)
            message.add(PAYLOAD_REF_KEY, key)
            message.set_arrays([])
            self.payload_store.sweep(
                float(getattr(self.args, "payload_ttl_seconds", 3600.0))
            )
        self.com_manager.send_message(message)

    def receive_message(self, msg_type: str, msg: Message) -> None:
        from ..mlops import telemetry
        from .payload_store import PAYLOAD_REF_KEY

        ref = msg.get(PAYLOAD_REF_KEY)
        if ref:
            telemetry.counter_inc("comm.payload_fetches")
            if self.payload_store is None:
                # fail HERE, loudly — otherwise the handler sees an empty
                # array list and dies far away in tree_unflatten
                logger.error(
                    "rank %d: message %r carries payload reference %r but "
                    "this node has no payload_store_dir configured — "
                    "dropping message", self.rank, msg_type, ref,
                )
                return
            try:
                # blobs are content-addressed and shared across recipients —
                # never consumed on read; the sender's TTL sweep reclaims them
                msg.set_arrays(self.payload_store.get(str(ref)))
            except OSError as e:
                logger.error(
                    "rank %d: payload blob %r for %r is gone (%s) — likely "
                    "TTL-swept before delivery; raise payload_ttl_seconds. "
                    "Dropping message.", self.rank, ref, msg_type, e,
                )
                return
        handler = self.message_handler_dict.get(str(msg_type))
        if handler is None:
            logger.debug("rank %d: no handler for %r", self.rank, msg_type)
            return
        handler(msg)

    def finish(self) -> None:
        """Stop the loop (reference :57-60 calls MPI Abort; we just stop)."""
        self.com_manager.stop_receive_message()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    # -- backend factory (reference :72-133) --------------------------------
    def _init_manager(self) -> None:
        if self.backend == constants.COMM_BACKEND_LOOPBACK:
            from .loopback import LoopbackCommManager

            world = str(getattr(self.args, "run_id", "default"))
            self.com_manager = LoopbackCommManager(self.rank, self.size, world)
        elif self.backend == constants.COMM_BACKEND_GRPC:
            from .base_com_manager import CommunicationConstants
            from .grpc_backend import GRPCCommManager

            base_port = int(
                getattr(self.args, "comm_port", CommunicationConstants.GRPC_BASE_PORT)
            )
            self.com_manager = GRPCCommManager(
                host=str(getattr(self.args, "comm_host", "0.0.0.0")),
                port=base_port + self.rank,
                rank=self.rank,
                world_size=self.size,
                ip_config_path=str(getattr(self.args, "grpc_ipconfig_path", "")),
                base_port=base_port,
                # TRPC-role fast path (tensor_transport.py): raw zero-copy
                # frames + chunked streaming for bulk tensors
                wire_format=str(getattr(self.args, "grpc_wire_format", "npz")),
                stream_threshold_bytes=int(getattr(
                    self.args, "grpc_stream_threshold_bytes", 8 * 1024 * 1024
                )),
            )
        elif self.backend == constants.COMM_BACKEND_MQTT:
            from .mqtt_backend import MqttCommManager

            self.com_manager = MqttCommManager(
                host=str(getattr(self.args, "mqtt_host", "127.0.0.1")),
                port=int(getattr(self.args, "mqtt_port", 1883)),
                rank=self.rank,
                world_size=self.size,
                run_id=str(getattr(self.args, "run_id", "0")),
            )
        else:
            raise ValueError(
                f"unsupported comm backend {self.backend!r}; "
                f"known: {constants.COMM_BACKENDS}"
            )
        # fault injection (SURVEY §5 upgrade — the reference has none):
        # a FaultPlan on args wraps the transport so recovery paths are
        # testable deterministically; production FSMs stay unaware
        plan = getattr(self.args, "fault_plan", None)
        if plan is not None:
            from .faults import FaultyComm

            self.com_manager = FaultyComm(self.com_manager, plan, self.rank)
