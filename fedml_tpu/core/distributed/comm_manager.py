"""FedMLCommManager — the event-driven actor base every cross-silo node
subclasses.

reference: ``core/distributed/fedml_comm_manager.py:11-135`` — an Observer
holding a handler registry keyed by message type; ``run()`` blocks in the
backend's receive loop; ``_init_manager`` is the backend factory. Preserved
contract: register_message_receive_handler / send_message / finish. Backends:
LOOPBACK (in-process test fixture) and GRPC; the reference's MQTT/S3/TRPC
transports collapse into these two (SURVEY.md §5 "Distributed communication
backend": one DCN message plane instead of five broker stacks).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, Optional

from ... import constants
from .base_com_manager import BaseCommunicationManager, Observer
from .message import Message

logger = logging.getLogger(__name__)

MessageHandler = Callable[[Message], None]


class FedMLCommManager(Observer):
    def __init__(self, args, comm=None, rank: int = 0, size: int = 0,
                 backend: str = constants.COMM_BACKEND_LOOPBACK):
        self.args = args
        self.size = int(size)
        self.rank = int(rank)
        self.backend = backend
        self.com_manager: Optional[BaseCommunicationManager] = comm
        self.message_handler_dict: Dict[str, MessageHandler] = {}
        self._thread: Optional[threading.Thread] = None
        if self.com_manager is None:
            self._init_manager()
        self.com_manager.add_observer(self)

    # -- registry (reference :52-63) ----------------------------------------
    def register_comm_manager(self, comm_manager: BaseCommunicationManager):
        self.com_manager = comm_manager

    def register_message_receive_handler(
        self, msg_type: str, handler: MessageHandler
    ) -> None:
        self.message_handler_dict[str(msg_type)] = handler

    def register_message_receive_handlers(self) -> None:
        """Subclasses register their FSM edges here (called by run())."""

    # -- loop (reference :25-50) --------------------------------------------
    def run(self) -> None:
        self.register_message_receive_handlers()
        self.com_manager.handle_receive_message()
        logger.info("rank %d comm loop exited", self.rank)

    def run_async(self) -> threading.Thread:
        """Run the receive loop on a daemon thread (test/process embedding)."""
        self.register_message_receive_handlers()
        self._thread = threading.Thread(
            target=self.com_manager.handle_receive_message, daemon=True
        )
        self._thread.start()
        return self._thread

    def send_message(self, message: Message) -> None:
        self.com_manager.send_message(message)

    def receive_message(self, msg_type: str, msg: Message) -> None:
        handler = self.message_handler_dict.get(str(msg_type))
        if handler is None:
            logger.debug("rank %d: no handler for %r", self.rank, msg_type)
            return
        handler(msg)

    def finish(self) -> None:
        """Stop the loop (reference :57-60 calls MPI Abort; we just stop)."""
        self.com_manager.stop_receive_message()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    # -- backend factory (reference :72-133) --------------------------------
    def _init_manager(self) -> None:
        if self.backend == constants.COMM_BACKEND_LOOPBACK:
            from .loopback import LoopbackCommManager

            world = str(getattr(self.args, "run_id", "default"))
            self.com_manager = LoopbackCommManager(self.rank, self.size, world)
        elif self.backend == constants.COMM_BACKEND_GRPC:
            from .base_com_manager import CommunicationConstants
            from .grpc_backend import GRPCCommManager

            base_port = int(
                getattr(self.args, "comm_port", CommunicationConstants.GRPC_BASE_PORT)
            )
            self.com_manager = GRPCCommManager(
                host=str(getattr(self.args, "comm_host", "0.0.0.0")),
                port=base_port + self.rank,
                rank=self.rank,
                world_size=self.size,
                ip_config_path=str(getattr(self.args, "grpc_ipconfig_path", "")),
                base_port=base_port,
            )
        else:
            raise ValueError(
                f"unsupported comm backend {self.backend!r}; "
                f"known: {constants.COMM_BACKENDS}"
            )
