"""Deterministic fault injection for the message plane.

reference: none — SURVEY.md §5 records the reference has **no fault
injection** harness (its only failure tooling is MQTT last-will + fail-stop
``MPI.Abort``). This module is the upgrade the blueprint calls for: system
faults (lost messages, delays, crashed peers, duplicated and corrupted
frames) injected AT THE TRANSPORT, so every recovery path — round
deadlines, straggler revival, OFFLINE handling, LightSecAgg dropout
tolerance, retry/dedup/checksum delivery — is testable deterministically,
with the production FSMs completely unaware.

``FaultyComm`` wraps any ``BaseCommunicationManager`` (loopback/gRPC/MQTT)
and applies a ``FaultPlan``:

- ``drop(sender, receiver, round)`` — a specific message class vanishes;
- ``delay(sender, receiver, round, seconds)`` — link latency, delivered
  from a daemon timer thread (the sender's thread is NEVER stalled — a
  delayed link must not block the server FSM's unrelated sends);
- ``crash(rank, after_sends)`` — the wrapped node stops sending AND
  receiving after its Nth send (0 = dead from the start), like a killed
  process (its queue goes dark, not its python object);
- ``loss(p, seed, visible=False)`` — seeded Bernoulli message loss.
  ``visible=True`` models a transport whose sender SEES the failure (a
  refused TCP write, a gRPC UNAVAILABLE): the send raises
  :class:`delivery.TransientSendError`, which the at-least-once layer
  retries with backoff. The default models silent loss (QoS-0 broadcast);
- ``duplicate(p, seed, sender, receiver, round)`` — seeded wire
  duplication: the SAME stamped message is delivered twice, exercising the
  receiver's dedup window;
- ``corrupt(p, seed, sender, receiver, round)`` — seeded payload
  corruption: a bit-flipped copy is delivered AND the send raises
  ``TransientSendError`` (the loopback analog of a receiver checksum NACK),
  so the retry layer re-delivers a clean copy while the receiver drops the
  corrupt one;
- ``partition(ranks, start_s, duration_s)`` — a network partition: for the
  window ``[start_s, start_s + duration_s)`` (measured from the wrapper's
  construction) every message CROSSING the boundary between ``ranks`` and
  the rest of the world fails with a VISIBLE
  :class:`delivery.TransientSendError` in both directions — the
  at-least-once layer backs off and re-delivers once the partition heals;
- ``straggle(rank, seconds, round)`` — a straggling sender: every message
  ``rank`` sends (optionally only for one round) is delivered ``seconds``
  late, modelling a slow client whose round contribution misses the
  cohort deadline (``--round_deadline_s`` folds it via the staleness
  path — docs/robustness.md "Partial cohorts under deadline");
- ``kill_server(phase, round)`` — arms the server-side kill switch: the
  cross-silo server SIGKILLs its own process (no drain, no atexit — the
  true crash) when its protocol reaches ``phase`` ∈ {``pre_fold``,
  ``mid_fold``, ``post_commit``} of round ``round``. The chaos harness
  restarts it with ``--resume auto`` and the surviving clients resync;
- ``kill_edge(phase, round)`` — the edge-aggregator analog, attached to
  the EDGE's own plan: the edge fail-stops in-process via
  :meth:`FaultyComm.kill` (sends vanish, receive loop goes dark, the
  entry buffer dies unshipped) when its protocol reaches ``phase``
  (pre_fold = a client update arrives; mid_fold = summary built but not
  sent; post_commit = summary sent). Its orphaned clients heartbeat-miss
  and re-home (docs/robustness.md "Edge tier failure domains").

Rules match on the Message header only (sender/receiver/round), never on
payloads, so injection composes with compression/encryption layers.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from .base_com_manager import BaseCommunicationManager, Observer
from .delivery import TransientSendError
from .message import Message


@dataclass
class FaultPlan:
    """Declarative fault schedule; all rules are optional and compose."""

    drops: List[dict] = field(default_factory=list)
    delays: List[dict] = field(default_factory=list)
    duplicates: List[dict] = field(default_factory=list)
    corrupts: List[dict] = field(default_factory=list)
    partitions: List[dict] = field(default_factory=list)
    crash_rank: Optional[int] = None
    crash_after_sends: int = 0
    loss_p: float = 0.0
    loss_seed: int = 0
    loss_visible: bool = False
    # server kill switch (consumed by cross_silo/server_manager.py, not by
    # the transport wrapper): SIGKILL the server process at this protocol
    # phase of this round
    kill_phase: Optional[str] = None
    kill_round: int = -1
    # edge kill switch (consumed by hierarchy/edge_manager.py): in-process
    # fail-stop of the edge aggregator at this protocol phase — attach to
    # the edge's OWN plan (the hook carries no rank)
    edge_kill_phase: Optional[str] = None
    edge_kill_round: int = -1

    KILL_PHASES = ("pre_fold", "mid_fold", "post_commit")

    def drop(self, sender: Optional[int] = None,
             receiver: Optional[int] = None,
             round_idx: Optional[int] = None) -> "FaultPlan":
        self.drops.append(
            {"sender": sender, "receiver": receiver, "round": round_idx}
        )
        return self

    def delay(self, seconds: float, sender: Optional[int] = None,
              receiver: Optional[int] = None,
              round_idx: Optional[int] = None) -> "FaultPlan":
        self.delays.append(
            {"sender": sender, "receiver": receiver, "round": round_idx,
             "seconds": seconds}
        )
        return self

    def crash(self, rank: int, after_sends: int = 0) -> "FaultPlan":
        self.crash_rank = rank
        self.crash_after_sends = after_sends
        return self

    def loss(self, p: float, seed: int = 0,
             visible: bool = False) -> "FaultPlan":
        self.loss_p = float(p)
        self.loss_seed = int(seed)
        self.loss_visible = bool(visible)
        return self

    def duplicate(self, p: float = 1.0, seed: int = 0,
                  sender: Optional[int] = None,
                  receiver: Optional[int] = None,
                  round_idx: Optional[int] = None) -> "FaultPlan":
        self.duplicates.append(
            {"sender": sender, "receiver": receiver, "round": round_idx,
             "p": float(p), "seed": int(seed)}
        )
        return self

    def corrupt(self, p: float = 1.0, seed: int = 0,
                sender: Optional[int] = None,
                receiver: Optional[int] = None,
                round_idx: Optional[int] = None) -> "FaultPlan":
        self.corrupts.append(
            {"sender": sender, "receiver": receiver, "round": round_idx,
             "p": float(p), "seed": int(seed)}
        )
        return self

    def partition(self, ranks: Sequence[int], start_s: float = 0.0,
                  duration_s: float = 1.0) -> "FaultPlan":
        """Bidirectional visible loss between ``ranks`` and everyone else
        for ``[start_s, start_s + duration_s)`` after wrapper construction.
        Apply the SAME rule to every endpoint's plan — each side refuses
        its own crossing sends, so the cut is symmetric."""
        self.partitions.append(
            {"ranks": frozenset(int(r) for r in ranks),
             "start_s": float(start_s), "duration_s": float(duration_s)}
        )
        return self

    def straggle(self, rank: int, seconds: float,
                 round_idx: Optional[int] = None) -> "FaultPlan":
        """Everything ``rank`` sends (optionally just for one round)
        arrives ``seconds`` late — sugar over :meth:`delay` naming the
        straggler scenario the deadline/late-fold plane is built for."""
        return self.delay(seconds, sender=int(rank), round_idx=round_idx)

    def kill_server(self, phase: str, round_idx: int = 0) -> "FaultPlan":
        """Arm the server kill switch: SIGKILL at ``phase`` of
        ``round_idx`` (pre_fold = the round's first update arrives;
        mid_fold = cohort collected, nothing committed; post_commit =
        checkpoint + ledger durable, broadcast not yet sent)."""
        if phase not in self.KILL_PHASES:
            raise ValueError(
                f"kill_server phase must be one of {self.KILL_PHASES}, "
                f"got {phase!r}"
            )
        self.kill_phase = str(phase)
        self.kill_round = int(round_idx)
        return self

    def kill_edge(self, phase: str, round_idx: int = -1) -> "FaultPlan":
        """Arm the edge kill switch: the edge aggregator fail-stops
        in-process (``FaultyComm.kill`` — sends vanish, receive loop goes
        dark, the entry buffer is never drained) when ITS protocol
        reaches ``phase`` at replica version ``round_idx`` — or at the
        first time ``phase`` is reached when ``round_idx`` is -1."""
        if phase not in self.KILL_PHASES:
            raise ValueError(
                f"kill_edge phase must be one of {self.KILL_PHASES}, "
                f"got {phase!r}"
            )
        self.edge_kill_phase = str(phase)
        self.edge_kill_round = int(round_idx)
        return self

    def maybe_kill_edge(self, phase: str, round_idx: int) -> bool:
        """True exactly when the armed edge kill matches (phase, round).
        Unlike :meth:`maybe_kill_server` this returns instead of
        SIGKILLing — the edge manager performs the in-process fail-stop
        itself (and latches, so the switch fires once)."""
        if self.edge_kill_phase != phase:
            return False
        return (self.edge_kill_round < 0
                or self.edge_kill_round == int(round_idx))

    def maybe_kill_server(self, phase: str, round_idx: int) -> None:
        """SIGKILL this process if the switch is armed for (phase, round).
        Called by the server manager at its protocol-phase hook points —
        a true fail-stop: no drain, no checkpoint, no atexit."""
        if self.kill_phase == phase and self.kill_round == int(round_idx):
            import logging
            import os
            import signal

            logging.getLogger(__name__).warning(
                "fault injection: SIGKILL at %s of round %d", phase,
                round_idx,
            )
            os.kill(os.getpid(), signal.SIGKILL)


def _matches(rule: dict, msg: Message) -> bool:
    if rule.get("sender") is not None and msg.get_sender_id() != rule["sender"]:
        return False
    if (rule.get("receiver") is not None
            and msg.get_receiver_id() != rule["receiver"]):
        return False
    if rule.get("round") is not None:
        msg_round = msg.get(Message.MSG_ARG_KEY_ROUND_IDX)
        if msg_round is None or int(msg_round) != rule["round"]:
            return False
    return True


class FaultyComm(BaseCommunicationManager):
    """Transport wrapper applying a :class:`FaultPlan` on the send path."""

    def __init__(self, inner: BaseCommunicationManager, plan: FaultPlan,
                 rank: Optional[int] = None):
        self.inner = inner
        self.plan = plan
        self.rank = rank if rank is not None else getattr(inner, "rank", -1)
        self._sends = 0
        self._crashed = False
        self._rng = np.random.RandomState(plan.loss_seed)
        # per-rule seeded streams: each probabilistic rule draws from its
        # own RandomState so matrices reproduce regardless of rule order
        self._dup_rngs = [np.random.RandomState(r["seed"])
                          for r in plan.duplicates]
        self._cor_rngs = [np.random.RandomState(r["seed"])
                          for r in plan.corrupts]
        self._lock = threading.Lock()
        # pending delay timers (graftiso I005): cancelled on stop so an
        # injected link delay can never deliver into a torn-down node
        self._timers: List[threading.Timer] = []
        # partition windows are measured from wrapper construction — every
        # endpoint of a world is wrapped at startup, so the windows align
        # to within process-start skew
        self._t0 = time.monotonic()

    # -- fault logic --------------------------------------------------------

    def _partitioned(self, msg: Message) -> bool:
        """Whether an active partition separates sender and receiver."""
        if not self.plan.partitions:
            return False
        now = time.monotonic() - self._t0
        snd, rcv = msg.get_sender_id(), msg.get_receiver_id()
        for rule in self.plan.partitions:
            if not (rule["start_s"] <= now
                    < rule["start_s"] + rule["duration_s"]):
                continue
            if (snd in rule["ranks"]) != (rcv in rule["ranks"]):
                return True
        return False

    def _send_verdict(self, msg: Message) -> str:
        """One of: deliver | drop | lose_visible."""
        with self._lock:
            if self._crashed:
                return "drop"
            # after_sends=0 means crashed-from-the-start: no send ever leaves
            if (self.plan.crash_rank == self.rank
                    and self._sends >= self.plan.crash_after_sends):
                self._crashed = True
                self.inner.stop_receive_message()  # the process is gone
                return "drop"
            self._sends += 1
            if self.plan.loss_p > 0 and self._rng.rand() < self.plan.loss_p:
                return ("lose_visible" if self.plan.loss_visible
                        else "drop")
        if self._partitioned(msg):
            # a refused write, not silence: the sender's at-least-once
            # layer backs off and re-delivers after the partition heals
            return "partitioned"
        if any(_matches(r, msg) for r in self.plan.drops):
            return "drop"
        return "deliver"

    def _rule_hits(self, msg: Message, rules: List[dict],
                   rngs: List[np.random.RandomState]) -> bool:
        """Whether any matching probabilistic rule fires. Every MATCHING
        rule draws (under the lock) even when it misses, so the stream
        position depends only on the matched-message sequence."""
        hit = False
        with self._lock:
            for rule, rng in zip(rules, rngs):
                if _matches(rule, msg) and rng.rand() < rule["p"]:
                    hit = True
        return hit

    def kill(self) -> None:
        """Externally declare this node dead (tests/harnesses): every
        subsequent send vanishes and the receive loop goes dark — the
        in-process analog of SIGKILLing the wrapped endpoint, usable at a
        deterministic point (e.g. right after a ledger commit) instead of
        an Nth-send trigger."""
        with self._lock:
            self._crashed = True
        self.inner.stop_receive_message()

    # -- BaseCommunicationManager -------------------------------------------

    def send_message(self, msg: Message) -> None:
        verdict = self._send_verdict(msg)
        if verdict == "drop":
            return
        if verdict == "lose_visible":
            raise TransientSendError(
                f"injected loss: {msg.get_type()!r} "
                f"{msg.get_sender_id()}->{msg.get_receiver_id()}"
            )
        if verdict == "partitioned":
            raise TransientSendError(
                f"injected partition: {msg.get_type()!r} "
                f"{msg.get_sender_id()}->{msg.get_receiver_id()}"
            )
        delay_s = 0.0
        for rule in self.plan.delays:
            if _matches(rule, msg):
                delay_s = max(delay_s, float(rule["seconds"]))
        corrupt = self._rule_hits(msg, self.plan.corrupts, self._cor_rngs)
        duplicate = self._rule_hits(msg, self.plan.duplicates, self._dup_rngs)
        if corrupt:
            # deliver the damaged frame, then surface a NACK to the sender:
            # the retry layer re-sends a clean copy (same seq — the receiver
            # dropped the corrupt one before dedup recorded it)
            self._deliver(msg, delay_s, corrupt=True)
            raise TransientSendError(
                f"injected corruption: {msg.get_type()!r} "
                f"{msg.get_sender_id()}->{msg.get_receiver_id()}"
            )
        self._deliver(msg, delay_s)
        if duplicate:
            self._deliver(msg, delay_s)

    def _deliver(self, msg: Message, delay_s: float,
                 corrupt: bool = False) -> None:
        """Hand the message to the wrapped transport — immediately, or from
        a daemon timer thread after ``delay_s``. The caller's thread never
        sleeps: a delayed link stalls only its own messages, not the
        sender FSM's unrelated sends."""
        if delay_s <= 0:
            self._transmit(msg, corrupt)
            return
        t = threading.Timer(delay_s, self._transmit, args=(msg, corrupt))
        t.daemon = True
        with self._lock:
            self._timers = [x for x in self._timers if x.is_alive()]
            self._timers.append(t)
        t.start()

    def _transmit(self, msg: Message, corrupt: bool) -> None:
        with self._lock:
            if self._crashed:
                return  # a timer racing the crash: the process is gone
        if corrupt:
            # corrupt a COPY: the caller's Message instance is re-sent
            # verbatim by the retry layer (and possibly by a concurrent
            # delayed timer) — it must never carry the corruption flag
            damaged = Message()
            damaged.init(msg.get_params())
            damaged.arrays = list(msg.arrays)
            damaged.wire_format = msg.wire_format
            damaged.corrupt_on_wire = True
            self.inner.send_message(damaged)
        else:
            self.inner.send_message(msg)

    def add_observer(self, observer: Observer) -> None:
        self.inner.add_observer(observer)

    def remove_observer(self, observer: Observer) -> None:
        self.inner.remove_observer(observer)

    def handle_receive_message(self) -> None:
        self.inner.handle_receive_message()

    def stop_receive_message(self) -> None:
        with self._lock:
            timers, self._timers = self._timers, []
        for t in timers:
            t.cancel()
        self.inner.stop_receive_message()
