"""Deterministic fault injection for the message plane.

reference: none — SURVEY.md §5 records the reference has **no fault
injection** harness (its only failure tooling is MQTT last-will + fail-stop
``MPI.Abort``). This module is the upgrade the blueprint calls for: system
faults (lost messages, delays, crashed peers) injected AT THE TRANSPORT, so
every recovery path — round deadlines, straggler revival, OFFLINE handling,
LightSecAgg dropout tolerance — is testable deterministically, with the
production FSMs completely unaware.

``FaultyComm`` wraps any ``BaseCommunicationManager`` (loopback/gRPC/MQTT)
and applies a ``FaultPlan``:

- ``drop(sender, receiver, round)`` — a specific message class vanishes;
- ``delay(sender, receiver, seconds)`` — link latency;
- ``crash(rank, after_sends)`` — the wrapped node stops sending AND
  receiving after its Nth send (0 = dead from the start), like a killed
  process (its queue goes dark, not its python object);
- ``loss(p, seed)`` — seeded Bernoulli message loss, reproducible.

Rules match on the Message header only (sender/receiver/round), never on
payloads, so injection composes with compression/encryption layers.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from .base_com_manager import BaseCommunicationManager, Observer
from .message import Message


@dataclass
class FaultPlan:
    """Declarative fault schedule; all rules are optional and compose."""

    drops: List[dict] = field(default_factory=list)
    delays: List[dict] = field(default_factory=list)
    crash_rank: Optional[int] = None
    crash_after_sends: int = 0
    loss_p: float = 0.0
    loss_seed: int = 0

    def drop(self, sender: Optional[int] = None,
             receiver: Optional[int] = None,
             round_idx: Optional[int] = None) -> "FaultPlan":
        self.drops.append(
            {"sender": sender, "receiver": receiver, "round": round_idx}
        )
        return self

    def delay(self, seconds: float, sender: Optional[int] = None,
              receiver: Optional[int] = None) -> "FaultPlan":
        self.delays.append(
            {"sender": sender, "receiver": receiver, "seconds": seconds}
        )
        return self

    def crash(self, rank: int, after_sends: int = 0) -> "FaultPlan":
        self.crash_rank = rank
        self.crash_after_sends = after_sends
        return self

    def loss(self, p: float, seed: int = 0) -> "FaultPlan":
        self.loss_p = float(p)
        self.loss_seed = int(seed)
        return self


def _matches(rule: dict, msg: Message) -> bool:
    if rule.get("sender") is not None and msg.get_sender_id() != rule["sender"]:
        return False
    if (rule.get("receiver") is not None
            and msg.get_receiver_id() != rule["receiver"]):
        return False
    if rule.get("round") is not None:
        msg_round = msg.get(Message.MSG_ARG_KEY_ROUND_IDX)
        if msg_round is None or int(msg_round) != rule["round"]:
            return False
    return True


class FaultyComm(BaseCommunicationManager):
    """Transport wrapper applying a :class:`FaultPlan` on the send path."""

    def __init__(self, inner: BaseCommunicationManager, plan: FaultPlan,
                 rank: Optional[int] = None):
        self.inner = inner
        self.plan = plan
        self.rank = rank if rank is not None else getattr(inner, "rank", -1)
        self._sends = 0
        self._crashed = False
        self._rng = np.random.RandomState(plan.loss_seed)
        self._lock = threading.Lock()

    # -- fault logic --------------------------------------------------------

    def _should_drop(self, msg: Message) -> bool:
        with self._lock:
            if self._crashed:
                return True
            # after_sends=0 means crashed-from-the-start: no send ever leaves
            if (self.plan.crash_rank == self.rank
                    and self._sends >= self.plan.crash_after_sends):
                self._crashed = True
                self.inner.stop_receive_message()  # the process is gone
                return True
            self._sends += 1
            if self.plan.loss_p > 0 and self._rng.rand() < self.plan.loss_p:
                return True
        return any(_matches(r, msg) for r in self.plan.drops)

    # -- BaseCommunicationManager -------------------------------------------

    def send_message(self, msg: Message) -> None:
        if self._should_drop(msg):
            return
        for rule in self.plan.delays:
            if _matches(rule, msg):
                time.sleep(float(rule["seconds"]))
        self.inner.send_message(msg)

    def add_observer(self, observer: Observer) -> None:
        self.inner.add_observer(observer)

    def remove_observer(self, observer: Observer) -> None:
        self.inner.remove_observer(observer)

    def handle_receive_message(self) -> None:
        self.inner.handle_receive_message()

    def stop_receive_message(self) -> None:
        self.inner.stop_receive_message()
