"""Non-IID data partitioning.

Re-implements the reference's Dirichlet (LDA) partitioner
(``python/fedml/core/data/noniid_partition.py:6-124``) and the homogeneous
splitter used by the dataset loaders (``data/cifar10/data_loader.py`` homo
branch). Host-side numpy: partitioning happens once at load time, device code
only ever sees the resulting packed arrays.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np


def non_iid_partition_with_dirichlet_distribution(
    label_list: np.ndarray,
    client_num: int,
    classes: int,
    alpha: float,
    task: str = "classification",
    seed: int = 0,
) -> Dict[int, np.ndarray]:
    """Partition sample indices among clients with a per-class Dirichlet draw.

    Reference semantics (noniid_partition.py:6-69): for each class, draw
    proportions ~ Dir(alpha) over clients, capped so no client exceeds N/num
    samples on average, and assign that class's (shuffled) indices by the
    proportions. Smaller alpha → more skew.
    """
    rng = np.random.RandomState(seed)
    net_dataidx_map: Dict[int, List[int]] = {i: [] for i in range(client_num)}
    idx_batch: List[List[int]] = [[] for _ in range(client_num)]
    N = label_list.shape[0]

    for k in range(classes):
        if task == "segmentation":
            # labels are per-sample sets of present classes
            idx_k = np.asarray(
                [i for i, labels in enumerate(label_list) if k in labels]
            )
        else:
            idx_k = np.where(label_list == k)[0]
        rng.shuffle(idx_k)
        proportions = rng.dirichlet(np.repeat(alpha, client_num))
        # cap: clients already at average size get 0 share (reference :101-103)
        proportions = np.array(
            [
                p * (len(idx_j) < N / client_num)
                for p, idx_j in zip(proportions, idx_batch)
            ]
        )
        s = proportions.sum()
        if s == 0:
            proportions = np.repeat(1.0 / client_num, client_num)
        else:
            proportions = proportions / s
        cuts = (np.cumsum(proportions) * len(idx_k)).astype(int)[:-1]
        for j, split in enumerate(np.split(idx_k, cuts)):
            idx_batch[j].extend(split.tolist())

    for i in range(client_num):
        rng.shuffle(idx_batch[i])
        net_dataidx_map[i] = np.asarray(idx_batch[i], dtype=np.int64)
    return net_dataidx_map


def homo_partition(
    total_num: int, client_num: int, seed: int = 0
) -> Dict[int, np.ndarray]:
    """IID partition: shuffle and split evenly (reference homo branch)."""
    rng = np.random.RandomState(seed)
    idxs = rng.permutation(total_num)
    return {
        i: np.asarray(part, dtype=np.int64)
        for i, part in enumerate(np.array_split(idxs, client_num))
    }


def record_data_stats(
    label_list: np.ndarray, net_dataidx_map: Dict[int, np.ndarray], task="classification"
) -> Dict[int, Dict[int, int]]:
    """Per-client class histogram (reference: noniid_partition.py:72-96)."""
    stats: Dict[int, Dict[int, int]] = {}
    for client, idxs in net_dataidx_map.items():
        if task == "segmentation":
            unq: Dict[int, int] = {}
            for i in idxs:
                for c in label_list[i]:
                    unq[int(c)] = unq.get(int(c), 0) + 1
        else:
            vals, counts = np.unique(label_list[idxs], return_counts=True)
            unq = {int(v): int(c) for v, c in zip(vals, counts)}
        stats[client] = unq
    return stats


def pack_partitions(
    data: np.ndarray,
    labels: np.ndarray,
    net_dataidx_map: Dict[int, np.ndarray],
    max_samples: int | None = None,
):
    """Pack per-client shards into dense ``[clients, max_samples, ...]`` arrays
    plus a sample-count vector.

    This is the TPU-native data residency layout (SURVEY.md §7 "Heterogeneous
    per-client data residency"): static shapes for jit, masks for ragged
    client sizes; shards then shard directly over a ``clients`` mesh axis.
    """
    client_num = len(net_dataidx_map)
    counts = np.array([len(net_dataidx_map[i]) for i in range(client_num)])
    cap = int(max_samples or counts.max())
    x = np.zeros((client_num, cap) + data.shape[1:], dtype=data.dtype)
    y = np.zeros((client_num, cap) + labels.shape[1:], dtype=labels.dtype)
    for i in range(client_num):
        idxs = net_dataidx_map[i][:cap]
        x[i, : len(idxs)] = data[idxs]
        y[i, : len(idxs)] = labels[idxs]
    return x, y, np.minimum(counts, cap)
