"""Heterogeneous client workload scheduling.

Re-implements the reference's workload scheduler
(``python/fedml/core/schedule/scheduler.py:4-183`` — branch-and-bound DP
assignment of per-client runtimes to devices, with ``np.array_split`` as the
fallback used by fedavg_seq / the NCCL simulator at
``simulation/nccl/base_framework/Server.py:124``).

Host-side: schedules are computed between rounds from recorded runtimes, then
materialised as *padded static-shape* schedule arrays (the trick that survives
jit — reference precedent ``Server.py:126-128``).
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np


def uniform_schedule(client_ids: np.ndarray, num_workers: int) -> List[np.ndarray]:
    """Fallback: even split (reference fallback np.array_split)."""
    return [np.asarray(a) for a in np.array_split(client_ids, num_workers)]


def lpt_schedule(
    client_ids: np.ndarray, runtimes: np.ndarray, num_workers: int
) -> List[np.ndarray]:
    """Longest-Processing-Time-first makespan minimisation.

    Equivalent role to the reference's branch-and-bound `DP_schedule` (min-max
    device runtime) with a 4/3-approximation at O(n log n) — appropriate since
    the reference's exact search call sites are commented out anyway
    (SURVEY.md §2.4).
    """
    order = np.argsort(-np.asarray(runtimes))
    loads = np.zeros(num_workers)
    buckets: List[List[int]] = [[] for _ in range(num_workers)]
    for i in order:
        j = int(np.argmin(loads))
        buckets[j].append(int(client_ids[i]))
        loads[j] += runtimes[i]
    return [np.asarray(b, dtype=np.int64) for b in buckets]


def pad_schedules(
    schedules: List[np.ndarray], pad_value: int = -1
) -> Tuple[np.ndarray, np.ndarray]:
    """Pad ragged per-worker schedules to ``[workers, max_len]`` + mask.

    Static shape for jit; masked slots are skipped on-device (reference
    precedent: padded schedule tensors broadcast at Server.py:126-128).
    """
    max_len = max((len(s) for s in schedules), default=0)
    out = np.full((len(schedules), max(max_len, 1)), pad_value, dtype=np.int64)
    mask = np.zeros_like(out, dtype=np.float32)
    for i, s in enumerate(schedules):
        out[i, : len(s)] = s
        mask[i, : len(s)] = 1.0
    return out, mask
