"""Heterogeneous client workload scheduling.

Re-implements the reference's workload scheduler
(``python/fedml/core/schedule/scheduler.py:4-183`` — branch-and-bound DP
assignment of per-client runtimes to devices, with ``np.array_split`` as the
fallback used by fedavg_seq / the NCCL simulator at
``simulation/nccl/base_framework/Server.py:124``).

Host-side: schedules are computed between rounds from recorded runtimes, then
materialised as *padded static-shape* schedule arrays (the trick that survives
jit — reference precedent ``Server.py:126-128``).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


def uniform_schedule(client_ids: np.ndarray, num_workers: int) -> List[np.ndarray]:
    """Fallback: even split (reference fallback np.array_split)."""
    return [np.asarray(a) for a in np.array_split(client_ids, num_workers)]


def lpt_schedule(
    client_ids: np.ndarray, runtimes: np.ndarray, num_workers: int
) -> List[np.ndarray]:
    """Longest-Processing-Time-first makespan minimisation.

    Equivalent role to the reference's branch-and-bound `DP_schedule` (min-max
    device runtime) with a 4/3-approximation at O(n log n) — appropriate since
    the reference's exact search call sites are commented out anyway
    (SURVEY.md §2.4).
    """
    order = np.argsort(-np.asarray(runtimes))
    loads = np.zeros(num_workers)
    buckets: List[List[int]] = [[] for _ in range(num_workers)]
    for i in order:
        j = int(np.argmin(loads))
        buckets[j].append(int(client_ids[i]))
        loads[j] += runtimes[i]
    return [np.asarray(b, dtype=np.int64) for b in buckets]


def pad_schedules(
    schedules: List[np.ndarray], pad_value: int = -1
) -> Tuple[np.ndarray, np.ndarray]:
    """Pad ragged per-worker schedules to ``[workers, max_len]`` + mask.

    Static shape for jit; masked slots are skipped on-device (reference
    precedent: padded schedule tensors broadcast at Server.py:126-128).
    """
    max_len = max((len(s) for s in schedules), default=0)
    out = np.full((len(schedules), max(max_len, 1)), pad_value, dtype=np.int64)
    mask = np.zeros_like(out, dtype=np.float32)
    for i, s in enumerate(schedules):
        out[i, : len(s)] = s
        mask[i, : len(s)] = 1.0
    return out, mask


def branch_and_bound_schedule(
    workloads: np.ndarray,
    speeds: np.ndarray,
    memory_caps: Optional[np.ndarray] = None,
    beam: int = 4096,
) -> Tuple[np.ndarray, float]:
    """Makespan-minimizing assignment of workloads to heterogeneous workers.

    reference: ``core/schedule/scheduler.py:4-183`` — best-first
    branch-and-bound: workloads sorted descending; the frontier expands the
    partial assignment with the smallest current makespan; a worker whose
    accumulated cost would exceed its memory cap is pruned. Re-design:
    iterative heap frontier (the reference recurses, which overflows Python's
    stack beyond ~1000 expansions) with a ``beam`` bound that falls back to
    greedy completion if the frontier would explode — same optimum on small
    instances, graceful degradation on big ones.

    ``speeds[j]``: cost multiplier of worker j (reference's ``constraints``);
    ``memory_caps[j]``: max accumulated cost (None = unbounded).
    Returns (assignment [n] worker ids in the ORIGINAL workload order,
    makespan).
    """
    import heapq

    w = np.asarray(workloads, np.float64)
    y = np.asarray(speeds, np.float64)
    n, k = len(w), len(y)
    if n == 0:
        return np.zeros(0, np.int32), 0.0
    caps = (
        np.full(k, np.inf) if memory_caps is None
        else np.asarray(memory_caps, np.float64)
    )
    order = np.argsort(w)[::-1]
    ws = w[order]

    # frontier entries: (makespan, tiebreak, next_idx, costs tuple, assign tuple)
    counter = 0
    frontier = [(0.0, 0, 0, tuple(0.0 for _ in range(k)), ())]
    best = None
    while frontier:
        makespan, _, idx, costs, assign = heapq.heappop(frontier)
        if idx == n:
            best = (assign, makespan)
            break
        if len(frontier) > beam:
            # complete greedily (LPT on remaining) from this best node; if
            # the greedy completion hits a cap, fall through to exact
            # expansion of this node — other frontier nodes may still
            # complete, so infeasibility here is NOT global infeasibility
            costs_l = list(costs)
            assign_l = list(assign)
            feasible = True
            for i in range(idx, n):
                options = [
                    c + y[jj] * ws[i] if c + y[jj] * ws[i] <= caps[jj]
                    else np.inf
                    for jj, c in enumerate(costs_l)
                ]
                j = int(np.argmin(options))
                if not np.isfinite(options[j]):
                    feasible = False
                    break
                costs_l[j] += y[j] * ws[i]
                assign_l.append(j)
            if feasible:
                best = (tuple(assign_l), max(costs_l))
                break
        seen_states = set()  # symmetry breaking: identical (cost, speed,
        # cap) workers produce identical subtrees — expand only one
        for j in range(k):
            sym_key = (costs[j], y[j], caps[j])
            if sym_key in seen_states:
                continue
            seen_states.add(sym_key)
            cost_j = costs[j] + y[j] * ws[idx]
            if cost_j > caps[j]:
                continue
            new_costs = costs[:j] + (cost_j,) + costs[j + 1:]
            counter += 1
            heapq.heappush(frontier, (
                max(makespan, cost_j), counter, idx + 1, new_costs,
                assign + (j,),
            ))
    if best is None:
        raise ValueError(
            "no feasible schedule under the given memory caps"
        )
    assign_sorted, makespan = best
    out = np.zeros(n, np.int32)
    out[order] = np.asarray(assign_sorted, np.int32)
    return out, float(makespan)
