"""Topology managers for decentralized FL.

Re-implements the reference's ``python/fedml/core/distributed/topology/``
(``BaseTopologyManager`` abstract at base_topology_manager.py:4-22,
``SymmetricTopologyManager`` ring-with-neighbors at
symmetric_topology_manager.py:7-80, ``AsymmetricTopologyManager`` directed
graphs at asymmetric_topology_manager.py:7-108).

The topology is exported as a dense row-stochastic mixing matrix ``W [n, n]``
— the TPU-native representation: one gossip round for all nodes is then a
single matmul ``W @ params_stack`` on the MXU (instead of per-node neighbor
message loops).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import List

import numpy as np


class BaseTopologyManager(ABC):
    @abstractmethod
    def generate_topology(self) -> None: ...

    def get_in_neighbor_idx_list(self, index: int) -> List[int]:
        return [j for j in range(self.n) if self.topology[index, j] > 0 and j != index]

    def get_out_neighbor_idx_list(self, index: int) -> List[int]:
        return [j for j in range(self.n) if self.topology[j, index] > 0 and j != index]

    def get_in_neighbor_weights(self, index: int) -> np.ndarray:
        return self.topology[index]

    def get_out_neighbor_weights(self, index: int) -> np.ndarray:
        return self.topology[:, index]

    def mixing_matrix(self) -> np.ndarray:
        """Row-stochastic W for one-matmul gossip."""
        return np.asarray(self.topology, dtype=np.float32)


class SymmetricTopologyManager(BaseTopologyManager):
    """Ring with `neighbor_num` symmetric neighbors, uniform weights
    (reference: symmetric_topology_manager.py — ring + random undirected
    edges, here deterministic ring-k for reproducibility)."""

    def __init__(self, n: int, neighbor_num: int = 2):
        if neighbor_num % 2 != 0:
            raise ValueError("neighbor_num must be even (k/2 each side of ring)")
        self.n = n
        self.neighbor_num = neighbor_num
        self.topology = np.zeros((n, n), dtype=np.float32)

    def generate_topology(self) -> None:
        n, k = self.n, self.neighbor_num
        A = np.eye(n, dtype=np.float32)
        # offsets beyond n//2 wrap onto already-set edges; capping keeps the
        # requested degree meaningful for small rings (n=2 still mixes)
        for off in range(1, min(k // 2, n // 2) + 1):
            for i in range(n):
                A[i, (i + off) % n] = 1.0
                A[i, (i - off) % n] = 1.0
        self.topology = A / A.sum(axis=1, keepdims=True)


class AsymmetricTopologyManager(BaseTopologyManager):
    """Directed ring + random extra out-edges (reference:
    asymmetric_topology_manager.py)."""

    def __init__(self, n: int, out_neighbor_num: int = 2, seed: int = 0):
        self.n = n
        self.out_neighbor_num = min(out_neighbor_num, n - 1)
        self.seed = seed
        self.topology = np.zeros((n, n), dtype=np.float32)

    def generate_topology(self) -> None:
        rng = np.random.RandomState(self.seed)
        n = self.n
        A = np.eye(n, dtype=np.float32)
        for i in range(n):
            ring = (i + 1) % n
            A[i, ring] = 1.0  # directed ring
            pool = [j for j in range(n) if j != i and j != ring]
            n_extra = max(min(self.out_neighbor_num - 1, len(pool)), 0)
            extra = rng.choice(pool, n_extra, replace=False)
            A[i, extra] = 1.0
        self.topology = A / A.sum(axis=1, keepdims=True)
