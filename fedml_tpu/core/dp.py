"""Differential privacy mechanisms on pytrees.

Re-founds the reference's ``python/fedml/core/differential_privacy/`` (Laplace
& Gaussian mechanisms, ``FedPrivacyMechanism`` CDP/LDP wrapper,
``fed_privacy_mechanism.py:4-20``) as pure JAX: explicit PRNG keys, one fused
noise-add per leaf, jit/vmap-compatible so LDP can be vmapped over the client
axis on-device.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

PyTree = Any


def _tree_keys(key: jax.Array, tree: PyTree) -> PyTree:
    leaves, treedef = jax.tree.flatten(tree)
    keys = jax.random.split(key, len(leaves))
    return jax.tree.unflatten(treedef, list(keys))


def _add_tree_noise(tree: PyTree, key: jax.Array, sampler, scale: float) -> PyTree:
    """One fused noise-add per leaf with per-leaf derived keys."""
    keys = _tree_keys(key, tree)
    return jax.tree.map(
        lambda x, k: x
        + sampler(k, x.shape, jnp.result_type(x, jnp.float32)).astype(x.dtype)
        * scale,
        tree,
        keys,
    )


class LaplaceMechanism:
    """Laplace noise with scale sensitivity/epsilon (reference:
    differential_privacy/mechanisms/laplace.py)."""

    def __init__(self, epsilon: float, sensitivity: float = 1.0):
        if epsilon <= 0:
            raise ValueError("epsilon must be positive")
        self.scale = sensitivity / epsilon

    def add_noise(self, tree: PyTree, key: jax.Array) -> PyTree:
        return _add_tree_noise(
            tree, key, lambda k, s, d: jax.random.laplace(k, s, dtype=d), self.scale
        )


class GaussianMechanism:
    """(epsilon, delta)-DP Gaussian noise, sigma = s*sqrt(2 ln(1.25/delta))/eps
    (reference: differential_privacy/mechanisms/gaussian.py classic bound)."""

    def __init__(self, epsilon: float, delta: float, sensitivity: float = 1.0):
        if not (0 < epsilon) or not (0 < delta < 1):
            raise ValueError("need epsilon > 0 and 0 < delta < 1")
        self.sigma = sensitivity * math.sqrt(2.0 * math.log(1.25 / delta)) / epsilon

    def add_noise(self, tree: PyTree, key: jax.Array) -> PyTree:
        return _add_tree_noise(
            tree, key, lambda k, s, d: jax.random.normal(k, s, dtype=d), self.sigma
        )


def clip_tree_by_global_norm(tree: PyTree, max_norm: float) -> PyTree:
    """L2-clip the whole update (standard DP-FL sensitivity bound)."""
    from ..utils.tree import global_norm, tree_scale

    norm = global_norm(tree)
    factor = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return tree_scale(tree, factor)


class FedPrivacyMechanism:
    """CDP/LDP dispatch wrapper (reference: fed_privacy_mechanism.py:4-20).

    - ``dp_type="ldp"``: each client clips + noises its own update
      (:meth:`randomize`, vmap-able over the clients axis).
    - ``dp_type="cdp"``: per-client contributions are clipped BEFORE
      aggregation (:meth:`clip_client_updates` — this is what bounds the
      sensitivity the noise is calibrated to), then the server noises the
      aggregate (:meth:`randomize_global`, noise only, no clipping).
    """

    def __init__(
        self,
        epsilon: float,
        delta: float = 1e-5,
        sensitivity: float = 1.0,
        mechanism_type: str = "laplace",
        dp_type: str = "cdp",
        clip_norm: float = 0.0,
    ):
        mechanism_type = mechanism_type.lower()
        if mechanism_type == "laplace":
            self.mechanism = LaplaceMechanism(epsilon, sensitivity)
        elif mechanism_type == "gaussian":
            self.mechanism = GaussianMechanism(epsilon, delta, sensitivity)
        else:
            raise ValueError(f"unknown DP mechanism {mechanism_type!r}")
        if dp_type not in ("cdp", "ldp"):
            raise ValueError(f"dp_type must be cdp|ldp, got {dp_type!r}")
        self.dp_type = dp_type
        self.clip_norm = clip_norm

    @classmethod
    def from_args(cls, args) -> "FedPrivacyMechanism":
        return cls(
            epsilon=args.epsilon,
            delta=args.delta,
            sensitivity=args.sensitivity,
            mechanism_type=args.mechanism_type,
            dp_type=args.dp_type,
            clip_norm=getattr(args, "dp_clip_norm", 0.0) or 0.0,
        )

    def randomize(self, tree: PyTree, key: jax.Array) -> PyTree:
        """LDP: clip + noise one client's own update."""
        if self.clip_norm > 0:
            tree = clip_tree_by_global_norm(tree, self.clip_norm)
        return self.mechanism.add_noise(tree, key)

    def clip_client_updates(self, stacked: PyTree, global_params: PyTree) -> PyTree:
        """CDP sensitivity bound: clip each client's delta from the global
        model to ``clip_norm`` (leading axis of ``stacked`` = clients)."""
        if self.clip_norm <= 0:
            return stacked

        def one(client_tree):
            delta = jax.tree.map(jnp.subtract, client_tree, global_params)
            delta = clip_tree_by_global_norm(delta, self.clip_norm)
            return jax.tree.map(jnp.add, global_params, delta)

        return jax.vmap(one)(stacked)

    def randomize_global(self, tree: PyTree, key: jax.Array) -> PyTree:
        """CDP: noise the aggregate. No clipping here — clipping the aggregate
        would not bound per-client sensitivity (it must happen per client via
        :meth:`clip_client_updates`) and would distort the global update."""
        return self.mechanism.add_noise(tree, key)
