"""Aggregation kernels — the TPU-native replacement for the reference's
``python/fedml/ml/aggregator/agg_operator.py:4-29`` (``FedMLAggOperator.agg``,
an O(params × clients) Python dict loop).

Design: client updates live *stacked* — every leaf carries a leading
``[num_clients]`` axis — so aggregation is one ``tensordot`` per leaf that XLA
fuses and tiles onto the MXU, and the same arrays shard directly over a
``clients`` mesh axis for the mesh-parallel simulator (aggregation then rides
ICI as a weighted ``psum``).
"""

from __future__ import annotations

from typing import Any, List, Sequence

import jax
import jax.numpy as jnp

PyTree = Any


def stack_trees(trees: Sequence[PyTree]) -> PyTree:
    """Stack a list of identically-shaped pytrees along a new leading axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


def unstack_tree(stacked: PyTree, num: int) -> List[PyTree]:
    """Inverse of :func:`stack_trees`."""
    return [jax.tree.map(lambda x: x[i], stacked) for i in range(num)]


def weighted_average(stacked: PyTree, weights: jax.Array) -> PyTree:
    """Weighted mean over the leading (clients) axis of every leaf.

    ``weights`` are unnormalised sample counts (reference semantics:
    ``agg_operator.py:23-29`` divides by total training number). A zero weight
    sum (e.g. a fully-masked cohort) yields a zero aggregate, not NaN —
    callers that can hit that case should keep the previous global model.
    """
    w = weights / jnp.maximum(jnp.sum(weights), 1e-12)

    def _leaf(x):
        return jnp.tensordot(w.astype(x.dtype), x, axes=1)

    return jax.tree.map(_leaf, stacked)


def masked_weighted_average(
    stacked: PyTree, weights: jax.Array, mask: jax.Array
) -> PyTree:
    """Weighted mean where ``mask`` (0/1 per client) disables padded slots.

    Padded cohort slots are how dynamic client sampling becomes static-shaped
    under jit (SURVEY.md §7 "Hard parts": fixed cohort + padded schedules).
    """
    w = weights * mask
    return weighted_average(stacked, w)


class FedMLAggOperator:
    """API-compatible facade (reference: ``FedMLAggOperator.agg``).

    The reference implements only FedAvg-style weighted averaging here and
    raises for other optimizers; server-side optimizers (FedOpt/FedNova) apply
    optax transforms to the pseudo-gradient in the simulation layer.
    """

    @staticmethod
    def agg(args, stacked: PyTree, weights: jax.Array) -> PyTree:
        return weighted_average(stacked, weights)


def fednova_normalized_direction(
    global_params: PyTree, stacked: PyTree, tau: jax.Array
) -> PyTree:
    """Per-client normalized direction (w_g - w_i)/tau_i, leaf-wise.

    The single definition shared by the unfused round loop and the fused
    round engine — FedNova's fused-vs-unfused parity depends on both paths
    computing this identically.
    """
    return jax.tree.map(
        lambda g, s: (g[None] - s) / tau.reshape((-1,) + (1,) * (s.ndim - 1)),
        global_params,
        stacked,
    )


def pseudo_gradient(w_global: PyTree, w_aggregated: PyTree) -> PyTree:
    """Server pseudo-gradient: g = w_global - avg(w_clients).

    This is the quantity FedOpt-family server optimizers step on
    (reference: ``simulation/sp/fedopt/fedopt_api.py`` set_model_global_grads).
    """
    return jax.tree.map(jnp.subtract, w_global, w_aggregated)
