from .aggregate import FedMLAggOperator, stack_trees, unstack_tree, weighted_average  # noqa: F401
from .partition import (  # noqa: F401
    homo_partition,
    non_iid_partition_with_dirichlet_distribution,
)
