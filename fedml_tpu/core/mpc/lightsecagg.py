"""LightSecAgg: Lagrange-Coded-Computing secure aggregation primitives.

Re-founds the reference's ``core/mpc/lightsecagg.py:1-205`` (LCC mask
encode/decode over a prime field, modular inverse, model quantize/dequantize)
for the TPU stack. Design split (SURVEY.md §7 "Finite-field math on TPU"):

- **Share encode/decode** (tiny [U×N] Lagrange matrices, needs exact mod-p
  int arithmetic with modular inverses): host-side numpy int64 / object ints.
  TPU int64 support is gated and the MXU does not do exact wide-int matmul,
  so running these µs-scale matrices on device would buy nothing.
- **Masking / unmasking / field sums** (O(model) elementwise): int32 jnp with
  p = 2**15 - 19 < 2**15 so a+b and a·b never overflow int32 — these run
  fused on device next to the models they protect.

Protocol parameters follow the paper/reference: N clients, T privacy
threshold, U target survivors, T < U ≤ N; masks are split into U−T chunks and
coded with T random chunks so any U aggregate shares reconstruct the sum of
surviving masks while ≤T colluders learn nothing.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

FIELD_P = 2**15 - 19  # same prime family as the reference (fits int32 products)


# ---------------------------------------------------------------------------
# Modular helpers (host-side, exact)
# ---------------------------------------------------------------------------
def mod_inverse(a: int, p: int = FIELD_P) -> int:
    """Fermat inverse a^(p-2) mod p (reference: modular inverse via ext-gcd)."""
    return pow(int(a) % p, p - 2, p)


def lagrange_coeffs(
    alpha_s: Sequence[int], beta_s: Sequence[int], p: int = FIELD_P
) -> np.ndarray:
    """U[i][j]: Lagrange basis l_j(alpha_i) mod p — evaluate the polynomial
    interpolating values at points ``beta_s`` at points ``alpha_s``
    (reference: ``gen_Lagrange_coeffs``)."""
    num_alpha, num_beta = len(alpha_s), len(beta_s)
    U = np.zeros((num_alpha, num_beta), dtype=np.int64)
    for i, a in enumerate(alpha_s):
        for j in range(num_beta):
            num, den = 1, 1
            for k in range(num_beta):
                if k == j:
                    continue
                num = (num * (a - beta_s[k])) % p
                den = (den * (beta_s[j] - beta_s[k])) % p
            U[i, j] = (num * mod_inverse(den, p)) % p
    return U


def lcc_encode(X: np.ndarray, alpha_s, beta_s, p: int = FIELD_P) -> np.ndarray:
    """Encode U chunks [U, m] → N shares [N, m]
    (reference: ``LCC_encoding_with_points``)."""
    W = lagrange_coeffs(alpha_s, beta_s, p)  # [N, U]
    return (W % p) @ (X.astype(np.int64) % p) % p


def lcc_decode(
    shares: np.ndarray, eval_points, target_points, p: int = FIELD_P
) -> np.ndarray:
    """Decode U shares [U, m] at eval_points → values at target_points
    (reference: ``LCC_decoding_with_points``)."""
    W = lagrange_coeffs(target_points, eval_points, p)
    return (W % p) @ (shares.astype(np.int64) % p) % p


# ---------------------------------------------------------------------------
# Quantization float ⇄ field (reference: transform_tensor_to_finite / back)
# ---------------------------------------------------------------------------
def quantize_to_field(
    vec: np.ndarray, q_bits: int = 8, p: int = FIELD_P
) -> np.ndarray:
    """Fixed-point quantize: round(x·2^q) mod p; negatives wrap to upper half."""
    scaled = np.round(np.asarray(vec, np.float64) * (1 << q_bits)).astype(np.int64)
    return np.mod(scaled, p)


def dequantize_from_field(
    fvec: np.ndarray, q_bits: int = 8, p: int = FIELD_P
) -> np.ndarray:
    """Inverse: values > p/2 are negatives."""
    x = np.asarray(fvec, np.int64) % p
    x = np.where(x > p // 2, x - p, x)
    return (x.astype(np.float64) / (1 << q_bits)).astype(np.float32)


# ---------------------------------------------------------------------------
# Mask lifecycle
# ---------------------------------------------------------------------------
def pad_len(d: int, chunks: int) -> int:
    return int(-(-d // chunks) * chunks)


def mask_encoding(
    d: int, N: int, U: int, T: int, rng: np.random.RandomState,
    p: int = FIELD_P,
) -> Tuple[np.ndarray, np.ndarray]:
    """Generate a local mask z [d] and its N encoded shares [N, d_pad/(U-T)].

    reference: ``mask_encoding`` — split z into U−T chunks, append T random
    chunks, LCC-encode at points α_1..α_N from values at β_1..β_U.
    """
    chunks = U - T
    dp = pad_len(d, chunks)
    z = rng.randint(0, p, size=dp).astype(np.int64)
    m = dp // chunks
    sub = z.reshape(chunks, m)
    noise = rng.randint(0, p, size=(T, m)).astype(np.int64)
    X = np.concatenate([sub, noise], axis=0)  # [U, m]
    alpha_s = list(range(1, N + 1))
    beta_s = list(range(N + 1, N + 1 + U))
    shares = lcc_encode(X, alpha_s, beta_s, p)  # [N, m]
    return z[:d], shares


def aggregate_shares(
    received: List[np.ndarray], p: int = FIELD_P
) -> np.ndarray:
    """Client-side: sum of the shares received from surviving clients."""
    out = np.zeros_like(received[0])
    for s in received:
        out = (out + s) % p
    return out


def decode_aggregate_mask(
    agg_shares: List[np.ndarray], survivor_points: List[int],
    d: int, N: int, U: int, T: int, p: int = FIELD_P,
) -> np.ndarray:
    """Server-side: U aggregate shares (evaluations at α_j for surviving j) →
    Σ z_i over survivors [d] (reference: aggregate_models_in_finite +
    LCC_decoding)."""
    chunks = U - T
    beta_s = list(range(N + 1, N + 1 + U))
    shares = np.stack(agg_shares[:U]).astype(np.int64)  # [U, m]
    vals = lcc_decode(shares, survivor_points[:U], beta_s[:chunks], p)  # [chunks, m]
    return vals.reshape(-1)[:d]


# ---------------------------------------------------------------------------
# On-device field ops (int32-safe since p < 2**15)
# ---------------------------------------------------------------------------
def model_masking(quantized: jnp.ndarray, mask: jnp.ndarray, p: int = FIELD_P):
    """(model + z) mod p — elementwise, runs on TPU next to the model."""
    return jnp.mod(quantized.astype(jnp.int32) + mask.astype(jnp.int32), p)


def model_unmasking(masked_sum: jnp.ndarray, mask_sum: jnp.ndarray, p: int = FIELD_P):
    """(Σ masked − Σ z) mod p."""
    return jnp.mod(masked_sum.astype(jnp.int32) - mask_sum.astype(jnp.int32), p)


def field_sum(stack: jnp.ndarray, p: int = FIELD_P):
    """Σ over clients axis mod p. int32 accumulation is safe for N < 2**16."""
    return jnp.mod(jnp.sum(stack.astype(jnp.int64), axis=0), p).astype(jnp.int32)
