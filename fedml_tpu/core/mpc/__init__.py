"""``fedml_tpu.core.mpc`` — secure multi-party computation primitives."""

from . import lightsecagg

__all__ = ["lightsecagg"]
