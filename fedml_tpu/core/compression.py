"""Gradient/weight compression kernels.

Re-implements the reference's ``python/fedml/utils/compression.py:9-281``
(TopK, EF-TopK with residual error feedback, uniform quantization, QSGD) as
pure JAX on flat vectors: ``jax.lax.top_k`` rides the VPU, all functions are
jit/vmap-compatible so per-client compression runs on-device along the clients
axis.

Each compressor exposes ``compress(vec, ...) -> (payload, aux)`` and
``decompress(payload, aux) -> vec`` with static output shapes (k is a static
int), as required under jit.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class TopKPayload(NamedTuple):
    values: jax.Array
    indices: jax.Array
    dim: int  # static original length


def topk_compress(vec: jax.Array, k: int) -> TopKPayload:
    """Keep the k largest-magnitude entries (reference: TopKCompressor)."""
    _, idx = jax.lax.top_k(jnp.abs(vec), k)
    return TopKPayload(values=vec[idx], indices=idx, dim=vec.shape[0])


def topk_decompress(payload: TopKPayload) -> jax.Array:
    return jnp.zeros((payload.dim,), payload.values.dtype).at[payload.indices].set(
        payload.values
    )


def ef_topk_compress(
    vec: jax.Array, residual: jax.Array, k: int
) -> Tuple[TopKPayload, jax.Array]:
    """Error-feedback TopK (reference: EFTopKCompressor — compensate with the
    residual from the previous round, emit top-k, carry the rest forward)."""
    compensated = vec + residual
    payload = topk_compress(compensated, k)
    new_residual = compensated - topk_decompress(payload)
    return payload, new_residual


class QSGDPayload(NamedTuple):
    norm: jax.Array
    signed_levels: jax.Array  # int16: sign folded into the quantization level
    s: int


def qsgd_compress(vec: jax.Array, key: jax.Array, s: int = 256) -> QSGDPayload:
    """QSGD stochastic quantization to s levels (reference: QSGDCompressor).

    q_i = norm * (sl_i / s) where sl_i = sign(v_i)·round_stoch(|v_i|/norm·s) —
    unbiased: E[decompress(compress(v))] = v. The sign is folded into an int16
    level so the payload is 2 bytes/element (vs 4 uncompressed) for s ≤ 2**15.
    """
    if s > (1 << 15) - 1:
        raise ValueError(f"s={s} overflows the int16 signed-level encoding")
    norm = jnp.linalg.norm(vec)
    safe_norm = jnp.maximum(norm, 1e-12)
    scaled = jnp.abs(vec) / safe_norm * s
    floor = jnp.floor(scaled)
    prob = scaled - floor
    rnd = jax.random.uniform(key, vec.shape)
    levels = floor + (rnd < prob)
    signed = (jnp.sign(vec) * levels).astype(jnp.int16)
    return QSGDPayload(norm=norm, signed_levels=signed, s=s)


def qsgd_decompress(payload: QSGDPayload) -> jax.Array:
    return (
        payload.norm * payload.signed_levels.astype(payload.norm.dtype)
        / payload.s
    )


class QuantizePayload(NamedTuple):
    q: jax.Array
    scale: jax.Array
    zero: jax.Array


def uniform_quantize(vec: jax.Array, bits: int = 8) -> QuantizePayload:
    """Deterministic uniform affine quantization (reference:
    QuantizationCompressor)."""
    lo, hi = jnp.min(vec), jnp.max(vec)
    qmax = (1 << bits) - 1
    scale = jnp.maximum(hi - lo, 1e-12) / qmax
    q = jnp.clip(jnp.round((vec - lo) / scale), 0, qmax).astype(jnp.uint8 if bits <= 8 else jnp.int32)
    return QuantizePayload(q=q, scale=scale, zero=lo)


def uniform_dequantize(payload: QuantizePayload) -> jax.Array:
    return payload.q.astype(payload.scale.dtype) * payload.scale + payload.zero


# ---------------------------------------------------------------------------
# Wire codec: compressed client->server updates for the message plane
# ---------------------------------------------------------------------------
class UpdateCodec:
    """Codec for C2S model updates on the cross-silo message plane.

    reference: the fedavg_seq message hook compresses each client update
    before it rides MPI (``utils/compression.py:9-281`` wired through
    ``simulation/mpi/fedavg_seq``). Here the client encodes the DELTA between
    its trained params and the round's broadcast global (deltas are sparse/
    low-entropy where raw params are not); the server reconstructs
    ``global + delta``. EF-TopK carries the per-client residual across
    rounds, so dropped mass is re-injected instead of lost.

    ``args.compression`` ∈ {"", "topk", "eftopk", "qsgd", "quantize"};
    ``args.compression_ratio`` (top-k fraction), ``args.quantize_bits``,
    ``args.qsgd_levels``.
    """

    META_KEY = "__compression__"

    def __init__(self, args):
        self.scheme = str(getattr(args, "compression", "") or "").lower()
        self.ratio = float(getattr(args, "compression_ratio", 0.1))
        self.bits = int(getattr(args, "quantize_bits", 8))
        self.levels = int(getattr(args, "qsgd_levels", 256))
        self.seed = int(getattr(args, "random_seed", 0))
        self._residual = None  # EF-TopK state (client side)

    def enabled(self) -> bool:
        return self.scheme in ("topk", "eftopk", "qsgd", "quantize")

    def encode(self, global_vec, new_vec, round_idx: int = 0):
        """-> (arrays, meta) for the wire. Inputs are 1-D jax/np vectors."""
        import numpy as np

        delta = jnp.asarray(new_vec) - jnp.asarray(global_vec)
        dim = int(delta.shape[0])
        meta = {"scheme": self.scheme, "dim": dim}
        if self.scheme in ("topk", "eftopk"):
            k = max(1, int(dim * self.ratio))
            meta["k"] = k
            if self.scheme == "eftopk":
                if self._residual is None or self._residual.shape != delta.shape:
                    self._residual = jnp.zeros_like(delta)
                payload, self._residual = ef_topk_compress(
                    delta, self._residual, k
                )
            else:
                payload = topk_compress(delta, k)
            arrays = [np.asarray(payload.values),
                      np.asarray(payload.indices).astype(np.int32)]
        elif self.scheme == "qsgd":
            key = jax.random.fold_in(jax.random.PRNGKey(self.seed), round_idx)
            payload = qsgd_compress(delta, key, self.levels)
            meta["s"] = self.levels
            arrays = [np.asarray(payload.norm).reshape(1),
                      np.asarray(payload.signed_levels)]
        elif self.scheme == "quantize":
            payload = uniform_quantize(delta, self.bits)
            meta["bits"] = self.bits
            arrays = [np.asarray(payload.q),
                      np.asarray(payload.scale).reshape(1),
                      np.asarray(payload.zero).reshape(1)]
        else:
            raise ValueError(f"unknown compression scheme {self.scheme!r}")
        return arrays, meta

    @staticmethod
    def decode(global_vec, arrays, meta):
        """Reconstruct the client's new vector from the wire payload."""
        scheme = meta["scheme"]
        dim = int(meta["dim"])
        if scheme in ("topk", "eftopk"):
            payload = TopKPayload(
                values=jnp.asarray(arrays[0]),
                indices=jnp.asarray(arrays[1]), dim=dim,
            )
            delta = topk_decompress(payload)
        elif scheme == "qsgd":
            payload = QSGDPayload(
                norm=jnp.asarray(arrays[0])[0],
                signed_levels=jnp.asarray(arrays[1]), s=int(meta["s"]),
            )
            delta = qsgd_decompress(payload)
        elif scheme == "quantize":
            payload = QuantizePayload(
                q=jnp.asarray(arrays[0]),
                scale=jnp.asarray(arrays[1])[0],
                zero=jnp.asarray(arrays[2])[0],
            )
            delta = uniform_dequantize(payload)
        else:
            raise ValueError(f"unknown compression scheme {scheme!r}")
        return jnp.asarray(global_vec) + delta
