"""Algorithm frame: the user-override seam.

Mirrors the reference's ``core/alg_frame/`` ABCs —
``ClientTrainer`` (client_trainer.py:4-39), ``ServerAggregator``
(server_aggregator.py:7-42), ``Params``/``Context`` (params.py:1-30,
context.py:5-8) — the one abstraction the survey says to copy verbatim as a
*seam* (SURVEY.md §7 "Async FSM vs SPMD lockstep"). JAX adaptation: model
parameters are explicit pytrees, and a trainer may expose a *pure* local-train
function so the SPMD runtimes can ``vmap``/``shard_map`` it; the imperative
``train`` method remains for message-driven runtimes (cross-silo).
"""

from __future__ import annotations

import abc
from typing import Any, Dict, Optional

PyTree = Any


class Params:
    """Dict-like argument bag (reference: core/alg_frame/params.py)."""

    def __init__(self, **kwargs):
        self.__dict__.update(kwargs)

    def add(self, name: str, value: Any) -> "Params":
        self.__dict__[name] = value
        return self

    def get(self, name: str, default: Any = None) -> Any:
        return self.__dict__.get(name, default)

    def keys(self):
        return self.__dict__.keys()

    def __contains__(self, name: str) -> bool:
        return name in self.__dict__


class Context(Params):
    """Process-wide singleton Params (reference: context.py + singleton.py)."""

    _instance: Optional["Context"] = None

    def __new__(cls, *a, **kw):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance


class ClientTrainer(abc.ABC):
    """Local-training operator bound to one model + one client's data.

    Contract preserved from the reference (client_trainer.py:4-39):
    get/set_model_params, train, optional test, on_before/after hooks.
    """

    def __init__(self, model, args=None):
        self.model = model  # ModelBundle
        self.args = args
        self.id = 0
        self.model_params: Optional[PyTree] = None
        self.local_train_fn = None  # pure fn for SPMD runtimes (may be None)

    def set_id(self, trainer_id: int) -> None:
        self.id = trainer_id

    def get_model_params(self) -> PyTree:
        return self.model_params

    def set_model_params(self, model_parameters: PyTree) -> None:
        self.model_params = model_parameters

    def on_before_local_training(self, train_data, device, args) -> None:
        pass

    @abc.abstractmethod
    def train(self, train_data, device, args) -> Dict[str, Any]:
        ...

    def on_after_local_training(self, train_data, device, args) -> None:
        pass

    def test(self, test_data, device, args):
        """Default eval: the shared jit'd pass over (x, y) test arrays.
        Trainers with a ModelBundle-shaped ``self.model`` get this for free."""
        if self.model is None or self.model_params is None:
            return None
        from ..ml.evaluate import make_eval_fn

        x, y = test_data
        return make_eval_fn(self.model)(self.model_params, x, y)


class ServerAggregator(abc.ABC):
    """Aggregation operator (reference: server_aggregator.py:7-42)."""

    def __init__(self, model, args=None):
        self.model = model
        self.args = args
        self.id = 0
        self.model_params: Optional[PyTree] = None

    def set_id(self, aggregator_id: int) -> None:
        self.id = aggregator_id

    def get_model_params(self) -> PyTree:
        return self.model_params

    def set_model_params(self, model_parameters: PyTree) -> None:
        self.model_params = model_parameters

    def on_before_aggregation(self, raw_client_model_or_grad_list):
        return raw_client_model_or_grad_list

    def aggregate(self, raw_client_model_or_grad_list) -> PyTree:
        """Default: weighted average (reference defers to FedMLAggOperator)."""
        from .aggregate import stack_trees, weighted_average
        import jax.numpy as jnp

        weights = jnp.asarray([float(n) for n, _ in raw_client_model_or_grad_list])
        stacked = stack_trees([p for _, p in raw_client_model_or_grad_list])
        return weighted_average(stacked, weights)

    def on_after_aggregation(self, aggregated_model_or_grad: PyTree) -> PyTree:
        return aggregated_model_or_grad

    @abc.abstractmethod
    def test(self, test_data, device, args):
        ...

    def test_all(self, train_data_local_dict, test_data_local_dict, device, args) -> bool:
        return True
