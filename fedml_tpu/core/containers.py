"""Bounded containers for serving-plane state (graftmem M001/M002).

Every dict a handler can grow by a sender/round-derived key must be
bounded (docs/graftmem.md): :class:`BoundedDict` is the substrate — a
``dict`` subclass (JSON-serializable, ``isinstance(dict)``-true, so read
sites and reports never change) with a hard capacity, oldest-first
eviction (optionally LRU — reads refresh recency), and per-container
occupancy accounting published to the ``mem.*`` telemetry family the
swarm leak witness (``fedml_tpu swarm --leak_check``) gates on:

- ``mem.<name>.occupancy`` (gauge): live entry count after each write;
- ``mem.<name>.evictions`` (counter): entries dropped by the bound.

Capacities are deliberately generous — orders of magnitude above any
live working set, so eviction only ever removes state that a retry path
can rebuild (an evicted dedup sender re-enters as "accept"; an evicted
committed-round entry re-folds at worst one stale replay, which the
round-index guard then drops). The bound converts "slow OOM at a million
clients" into "bounded memory with a documented, recoverable worst case".
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterable, Optional, Tuple

_TELEMETRY = None
_TELEMETRY_LOCK = threading.Lock()


def _telemetry():
    """Lazy telemetry import: containers must be importable from anywhere
    (including mlops itself) without an import cycle."""
    global _TELEMETRY
    if _TELEMETRY is None:
        with _TELEMETRY_LOCK:
            if _TELEMETRY is None:
                from .mlops import telemetry as _t

                _TELEMETRY = _t
    return _TELEMETRY


class BoundedDict(dict):
    """A dict with a hard capacity and oldest-first (insertion-order or
    LRU) eviction.

    ``name`` (optional) publishes ``mem.<name>.occupancy`` /
    ``mem.<name>.evictions`` after every mutating write. Not internally
    locked — callers guard it with the same lock that guarded the plain
    dict it replaces, exactly like ``dict``.
    """

    def __init__(self, capacity: int, *, lru: bool = False, name: str = "",
                 seed: Optional[Dict] = None):
        super().__init__()
        if int(capacity) < 1:
            raise ValueError(f"BoundedDict capacity must be >= 1, "
                             f"got {capacity}")
        self.capacity = int(capacity)
        self.lru = bool(lru)
        self.name = str(name)
        self.evictions = 0
        if seed:
            self.update(seed)

    # -- mutation (every write funnels through __setitem__) ------------------

    def __setitem__(self, key, value) -> None:
        if self.lru and super().__contains__(key):
            super().__delitem__(key)  # reinsert at the recent end
        super().__setitem__(key, value)
        self._trim()

    def setdefault(self, key, default=None):
        if super().__contains__(key):
            self._touch(key)
            return super().__getitem__(key)
        self[key] = default
        return default

    def update(self, other=(), **kw) -> None:  # type: ignore[override]
        items: Iterable[Tuple[Any, Any]]
        if hasattr(other, "items"):
            items = other.items()
        else:
            items = other
        for k, v in items:
            self[k] = v
        for k, v in kw.items():
            self[k] = v

    # -- reads (LRU refreshes recency) ---------------------------------------

    def __getitem__(self, key):
        self._touch(key)
        return super().__getitem__(key)

    def get(self, key, default=None):
        if super().__contains__(key):
            self._touch(key)
            return super().__getitem__(key)
        return default

    # -- internals -----------------------------------------------------------

    def _touch(self, key) -> None:
        if self.lru and super().__contains__(key):
            value = super().pop(key)
            super().__setitem__(key, value)

    def _trim(self) -> None:
        evicted = 0
        while len(self) > self.capacity:
            oldest = next(iter(self))
            super().__delitem__(oldest)
            evicted += 1
        if evicted:
            self.evictions += evicted
        self._account(evicted)

    def _account(self, evicted: int) -> None:
        if not self.name:
            return
        tel = _telemetry()
        tel.gauge_set(f"mem.{self.name}.occupancy", float(len(self)))
        if evicted:
            tel.counter_inc(f"mem.{self.name}.evictions", float(evicted))
