"""Durable run ledger + preemption-safe resume.

reference: none — SURVEY.md §5 records the reference has essentially no
checkpoint/resume (models are in-memory state dicts or per-round S3
artifacts; a killed run restarts from round 0). Production FL treats device
churn and server preemption as the steady state (Bonawitz et al., MLSys
2019), so this module makes "kill -9 anywhere, restart, converge to the same
params" a first-class, testable invariant:

- :class:`RunLedger` — an append-only JSONL file beside the Orbax
  checkpoints. One line per *committed* round boundary (round index,
  covering checkpoint step, sampled cohort, contribution counts), each line
  self-checksummed so a torn write at crash time is detected and dropped on
  read instead of poisoning the resume.
- :class:`PreemptionGuard` — a process-wide SIGTERM/SIGINT latch. The
  handler only sets an Event; training loops drain the in-flight round,
  commit checkpoint + ledger, and raise :class:`PreemptionError`, which
  entry points convert into :data:`EXIT_PREEMPTED` (75, EX_TEMPFAIL:
  "preempted, resumable") so schedulers can tell a preemption from a crash.
- ``resume_mode`` / ``checkpoint_cadence`` — the one parser for the
  ``--resume auto|never|require`` and ``--checkpoint_rounds N`` knobs shared
  by the sp/mesh engines and the cross-silo server.

Recovery events (resumes, preemptions, committed rounds) flow through the
telemetry registry as ``run.*`` counters.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import signal
import threading
from typing import Any, Dict, List, Optional, Sequence

from .mlops import telemetry

logger = logging.getLogger(__name__)

LEDGER_FILENAME = "run_ledger.jsonl"

# EX_TEMPFAIL: the conventional "transient failure, retry me" exit status —
# distinct from a crash (nonzero) and from success, so a supervisor can
# restart with --resume auto instead of paging someone
EXIT_PREEMPTED = 75


class PreemptionError(RuntimeError):
    """Raised by a training loop that drained and committed after SIGTERM/
    SIGINT. Carries the last committed round so callers can log it."""

    def __init__(self, last_round: int, message: str = ""):
        super().__init__(
            message or f"preempted after committing round {last_round} — "
            f"resumable with --resume auto (exit {EXIT_PREEMPTED})"
        )
        self.last_round = int(last_round)


def resume_mode(args) -> str:
    """Normalize ``args.resume`` to ``auto | never | require``.

    Back-compat: the pre-ledger schema typed ``resume`` as a bool; True
    maps to ``auto``, False to ``never``.
    """
    raw = getattr(args, "resume", "auto")
    if isinstance(raw, bool):
        return "auto" if raw else "never"
    mode = str(raw).strip().lower()
    if mode in ("", "auto", "true", "1", "yes"):
        return "auto"
    if mode in ("never", "false", "0", "no", "off"):
        return "never"
    if mode in ("require", "required", "must"):
        return "require"
    raise ValueError(
        f"resume must be auto|never|require, got {raw!r}"
    )


def checkpoint_cadence(args) -> int:
    """Rounds between checkpoint commits: ``--checkpoint_rounds`` wins,
    then the legacy ``checkpoint_every_rounds``, else every round."""
    for key in ("checkpoint_rounds", "checkpoint_every_rounds"):
        n = int(getattr(args, key, 0) or 0)
        if n > 0:
            return n
    return 1


# ---------------------------------------------------------------------------
# Durable ledger
# ---------------------------------------------------------------------------


def _line_digest(payload: Dict[str, Any]) -> str:
    body = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(body.encode("utf-8")).hexdigest()[:16]


class RunLedger:
    """Append-only JSONL record of committed round boundaries.

    Every line is ``{...payload..., "sha": <sha256[:16] of the payload>}``
    and is flushed + fsync'd before ``commit_round`` returns — after a crash
    the file's valid prefix IS the set of rounds that durably completed.
    Read-side, any line that fails to parse or whose checksum mismatches
    (a torn write at kill time) ends the valid prefix; everything after it
    is ignored. The ledger is advisory metadata next to the Orbax
    checkpoint: the checkpoint holds the params, the ledger holds the round
    history (cohorts, contribution counts) that makes recovery *auditable*
    — two runs are provably the same federation iff their ledgers diff
    clean.
    """

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._lock = threading.Lock()

    @classmethod
    def for_checkpoint_dir(cls, ckpt_dir: str) -> "RunLedger":
        return cls(os.path.join(os.path.abspath(ckpt_dir), LEDGER_FILENAME))

    # -- append side --------------------------------------------------------

    def _append(self, payload: Dict[str, Any]) -> None:
        payload = dict(payload)
        payload["sha"] = _line_digest(
            {k: v for k, v in payload.items() if k != "sha"}
        )
        line = json.dumps(payload, sort_keys=True, separators=(",", ":"))
        # write+flush under the lock (append order stays serialized), fsync
        # OUTSIDE it (graftproto P009): fsync can stall for tens of ms on a
        # busy disk and the comm/FSM thread must not hold the ledger lock
        # through it. fsync on the still-open fd durably covers this line
        # (and anything a concurrent appender wrote after it) before
        # commit_round returns, so the durability contract is unchanged.
        with self._lock:
            f = open(self.path, "a", encoding="utf-8")
            try:
                f.write(line + "\n")
                f.flush()
            except Exception:
                f.close()
                raise
        try:
            os.fsync(f.fileno())
        finally:
            f.close()

    def ensure_meta(self, **meta: Any) -> Dict[str, Any]:
        """Write the run_meta head line once; return the (existing or new)
        meta. A resumed run re-uses the original meta — a MISMATCH on the
        identity keys (seed, world) means the operator pointed a different
        federation at this ledger, which would silently corrupt the round
        history, so it raises."""
        existing = self.meta()
        if existing is not None:
            for key in ("seed", "world"):
                if key in meta and key in existing and \
                        existing[key] != meta[key]:
                    raise RuntimeError(
                        f"ledger {self.path}: run_meta mismatch on "
                        f"{key!r} (ledger={existing[key]!r}, "
                        f"run={meta[key]!r}) — this checkpoint dir belongs "
                        "to a different federation; use a fresh dir"
                    )
            return existing
        payload = {"kind": "run_meta", "version": 1, **meta}
        self._append(payload)
        return payload

    def commit_round(
        self,
        round_idx: int,
        ckpt_step: Optional[int] = None,
        cohort: Optional[Sequence[int]] = None,
        **extra: Any,
    ) -> Dict[str, Any]:
        """Durably record one committed round boundary."""
        payload: Dict[str, Any] = {
            "kind": "round",
            "round": int(round_idx),
            "ckpt_step": None if ckpt_step is None else int(ckpt_step),
            "cohort": None if cohort is None else [int(c) for c in cohort],
        }
        for k, v in extra.items():
            payload[k] = v
        self._append(payload)
        telemetry.counter_inc("run.rounds_committed")
        return payload

    # -- read side ----------------------------------------------------------

    def entries(self) -> List[Dict[str, Any]]:
        """The valid prefix of the ledger (torn/corrupt tail dropped)."""
        out: List[Dict[str, Any]] = []
        if not os.path.exists(self.path):
            return out
        with open(self.path, "r", encoding="utf-8") as f:
            for raw in f:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    payload = json.loads(raw)
                except (ValueError, TypeError):
                    logger.warning(
                        "ledger %s: torn/corrupt line after %d entries — "
                        "treating it as the crash point", self.path, len(out)
                    )
                    break
                sha = payload.pop("sha", None)
                if sha != _line_digest(payload):
                    logger.warning(
                        "ledger %s: checksum mismatch after %d entries — "
                        "treating it as the crash point", self.path, len(out)
                    )
                    break
                out.append(payload)
        return out

    def meta(self) -> Optional[Dict[str, Any]]:
        for e in self.entries():
            if e.get("kind") == "run_meta":
                return e
        return None

    def rounds(self) -> List[Dict[str, Any]]:
        return [e for e in self.entries() if e.get("kind") == "round"]

    def last_round(self) -> Optional[int]:
        rs = self.rounds()
        return None if not rs else int(rs[-1]["round"])

    def cohort_for(self, round_idx: int) -> Optional[List[int]]:
        """The recorded cohort of a committed round (newest record wins —
        a resumed run may legitimately re-commit the crash-round)."""
        for e in reversed(self.rounds()):
            if int(e["round"]) == int(round_idx):
                c = e.get("cohort")
                return None if c is None else [int(x) for x in c]
        return None


# ---------------------------------------------------------------------------
# Preemption guard
# ---------------------------------------------------------------------------


class PreemptionGuard:
    """Process-wide SIGTERM/SIGINT latch with drain semantics.

    The signal handler ONLY sets an Event — no I/O, no exit — so the
    training loop finishes (drains) the in-flight round, commits checkpoint
    + ledger at a consistent boundary, and exits with the distinct
    "preempted, resumable" status. A second signal while already draining
    escalates: the original handler is restored and the signal re-raised,
    so a stuck drain can still be killed.

    Tests trigger preemption without real signals via :meth:`request`.
    """

    def __init__(self):
        self._evt = threading.Event()
        self._installed = False
        self._prev: Dict[int, Any] = {}
        self._lock = threading.Lock()

    def install(self, signals: Sequence[int] = (signal.SIGTERM,
                                                signal.SIGINT)) -> bool:
        """Install handlers (idempotent). Returns False off the main thread
        (signal.signal raises there) — callers on comm threads simply run
        without signal-driven preemption, keeping :meth:`request` usable."""
        with self._lock:
            if self._installed:
                return True
            try:
                for sig in signals:
                    self._prev[sig] = signal.signal(sig, self._on_signal)
            except ValueError:  # not the main thread
                self._prev.clear()
                return False
            self._installed = True
            return True

    def _on_signal(self, signum, frame) -> None:
        if self._evt.is_set():
            # second signal: the drain is stuck or the operator means NOW —
            # restore the original disposition and re-deliver
            prev = self._prev.get(signum, signal.SIG_DFL)
            signal.signal(signum, prev)
            os.kill(os.getpid(), signum)
            return
        telemetry.counter_inc("run.preempt_signals")
        self._evt.set()
        logger.warning(
            "preemption signal %d: draining the in-flight round, then "
            "committing checkpoint + ledger (exit %d)", signum,
            EXIT_PREEMPTED,
        )

    def request(self, *_a) -> None:
        """Programmatic preemption (tests, embedding runtimes)."""
        self._evt.set()

    def requested(self) -> bool:
        return self._evt.is_set()

    def reset(self) -> None:
        self._evt.clear()

    def uninstall(self) -> None:
        with self._lock:
            for sig, prev in self._prev.items():
                try:
                    signal.signal(sig, prev)
                except ValueError:
                    pass
            self._prev.clear()
            self._installed = False


_GUARD = PreemptionGuard()


def preemption_guard() -> PreemptionGuard:
    """The process-wide guard (one handler install, many consumers)."""
    return _GUARD
