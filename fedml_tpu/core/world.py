"""World scope: the explicit owner of a federation's per-run state.

reference: none — the reference binds one federation to one ``runner.py``
process and keeps its MLOps state in module globals (PAPER.md), so
"which federation owns this counter/thread/blob" never has to be asked.
This repo is heading for M concurrent federations in one process (ROADMAP
"many worlds, one process, one mesh"), where that question is THE
correctness question: any mutable run state reachable from a message
handler outside an explicitly-scoped world object is a cross-tenant leak.

:class:`WorldScope` is that object. One scope per federation participant
— keyed by ``(run_id, rank)``, the same identity the loopback broker and
the run ledger already use — owning:

- the **telemetry scope** (:class:`~fedml_tpu.core.mlops.telemetry.
  TelemetryScope`): handler/worker code bumps counters through
  ``world.telemetry``, never through the process-global registry
  directly. Single-tenant processes get the process-global default, so
  every existing counter and ``fedml_tpu top`` keep working unchanged.
- the **payload store** (the bulk channel's world-keyed end): built once
  per world from the run's args instead of ambiently inside each comm
  manager.
- the **thread/timer registry + shutdown hooks**: every worker thread or
  timer a federation starts registers here, and :meth:`shutdown` cancels
  timers, runs hooks, and joins threads — so killing world A can never
  orphan (or, worse, share) world B's workers. This is the runtime
  contract behind graftiso I005; the swarm/chaos harnesses additionally
  assert no non-daemon thread leaks a soak (``thread_snapshot`` /
  ``leaked_threads``).

``tools/graftiso`` statically enforces the discipline this module exists
for (docs/graftiso.md): I001 no module-global mutable state written from
handler code, I002 no unscoped process-wide registry access, I005 every
federation thread tethered to its scope's shutdown path.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Set, Tuple

from .mlops import telemetry, tracing


class WorldScope:
    """Per-(run, rank) ownership root for a federation participant's
    mutable serving-plane state."""

    # process index of live scopes — advisory (introspection + the
    # multi-tenant serving plane's lookup), always accessed through the
    # (run_id, rank) discriminator; entries are replaced, never implicitly
    # shut down (the owning manager drives its own lifecycle)
    _scopes: Dict[Tuple[str, int], "WorldScope"] = {}
    _scopes_lock = threading.Lock()

    def __init__(self, run_id: str, rank: int, args=None):
        self.run_id = str(run_id)
        self.rank = int(rank)
        # single-tenant default: the process-global registry — every
        # existing counter name and `fedml_tpu top` keep working. The
        # multi-tenant PR installs per-run scopes via
        # telemetry.install_scope(run_id) without touching call sites.
        self.telemetry = telemetry.scope_for(self.run_id)
        # per-world span recorder + flight recorder (docs/tracing.md):
        # handler code opens spans through ``world.trace`` — the same
        # (run_id, rank) discriminator as everything else this scope owns.
        # Disabled (a shared null-span per call site) unless the run's
        # args arm it.
        self.trace = tracing.tracer_for(self.run_id, self.rank)
        if args is not None:
            self.trace.configure(args)
        # world-keyed bulk channel (reference MQTT+S3 split): one store
        # per world, built from the run's args at construction — handlers
        # never read ambient config to find it
        self.payload_store = None
        if args is not None:
            from .distributed.payload_store import store_from_args

            self.payload_store = store_from_args(args)
        self._lock = threading.Lock()
        self._threads: List[threading.Thread] = []
        self._timers: List[threading.Timer] = []
        self._hooks: List[Callable[[], None]] = []
        self._closed = False
        if self.trace.enabled:
            # the flight recorder's ring lands on every orderly teardown
            # too (finish() → shutdown()), not just atexit/fault paths
            self.add_shutdown(lambda: self.trace.flush_flight("shutdown"))

    # -- registry ------------------------------------------------------------

    @classmethod
    def for_args(cls, args, rank: Optional[int] = None) -> "WorldScope":
        """Build (and index) the scope for a run's args. A re-construction
        under the same (run_id, rank) replaces the index entry — the
        previous owner keeps its reference and its own shutdown."""
        run_id = str(getattr(args, "run_id", "0") or "0")
        r = int(rank if rank is not None else getattr(args, "rank", 0))
        scope = cls(run_id, r, args=args)
        with cls._scopes_lock:
            cls._scopes[(run_id, r)] = scope
        return scope

    @classmethod
    def get(cls, run_id: str, rank: int) -> Optional["WorldScope"]:
        """The live scope for (run_id, rank), if one is indexed."""
        with cls._scopes_lock:
            return cls._scopes.get((str(run_id), int(rank)))

    @classmethod
    def release(cls, run_id: str, rank: int) -> None:
        """Drop (and shut down) the indexed scope for (run_id, rank)."""
        with cls._scopes_lock:
            scope = cls._scopes.pop((str(run_id), int(rank)), None)
        if scope is not None:
            scope.shutdown()

    # -- thread / lifecycle registry -----------------------------------------

    def register_thread(self, thread: threading.Thread) -> threading.Thread:
        """Tether a worker thread to this world: :meth:`shutdown` joins it.
        Returns the thread for chaining. Registering on an already-closed
        scope cannot be honored (nothing will drain the list again) — it
        is logged loudly instead of silently losing the tether."""
        with self._lock:
            if self._closed:
                closed = True
            else:
                closed = False
                self._threads = [t for t in self._threads if t.is_alive()]
                self._threads.append(thread)
        if closed:
            import logging

            logging.getLogger(__name__).warning(
                "world (%s, %d): thread %r registered after shutdown — "
                "the scope cannot join it", self.run_id, self.rank,
                thread.name,
            )
        return thread

    def register_timer(self, timer: threading.Timer) -> threading.Timer:
        """Tether a one-shot timer: :meth:`shutdown` cancels anything
        still pending. Fired timers are pruned on each registration. A
        timer registered after shutdown (a callback racing the teardown
        and re-arming) is cancelled immediately — the scope's contract is
        that nothing it owns fires past :meth:`shutdown`."""
        with self._lock:
            if self._closed:
                closed = True
            else:
                closed = False
                self._timers = [t for t in self._timers if t.is_alive()]
                self._timers.append(timer)
        if closed:
            timer.cancel()
        return timer

    def add_shutdown(self, hook: Callable[[], None]) -> None:
        """Run ``hook`` during :meth:`shutdown` (before joining threads) —
        the place for Event.set / queue-poison steps that unblock workers."""
        with self._lock:
            self._hooks.append(hook)

    def shutdown(self, timeout_s: float = 5.0) -> None:
        """Cancel registered timers, run shutdown hooks, join registered
        threads (skipping the calling thread — a worker may drive its own
        world's shutdown), and drop this scope from the process index so
        a long-lived multi-run process never accumulates closed scopes.
        Idempotent; never raises."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            timers, self._timers = self._timers, []
            hooks, self._hooks = self._hooks, []
            threads, self._threads = self._threads, []
        for t in timers:
            t.cancel()
        for hook in hooks:
            try:
                hook()
            except Exception:  # pragma: no cover - shutdown must not raise
                pass
        me = threading.current_thread()
        for t in threads:
            if t is me:
                continue
            try:
                t.join(timeout_s)
            except RuntimeError:
                pass  # registered but never started — nothing to drain
        with type(self)._scopes_lock:
            if type(self)._scopes.get((self.run_id, self.rank)) is self:
                type(self)._scopes.pop((self.run_id, self.rank))

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed


# ---------------------------------------------------------------------------
# Thread-leak witnesses (the runtime half of graftiso I005): the swarm and
# chaos soaks snapshot the process's threads at start and fail if a
# non-daemon thread outlives world shutdown.
# ---------------------------------------------------------------------------


def thread_snapshot() -> Set[threading.Thread]:
    """The Thread objects alive right now (object identity, NOT idents —
    CPython recycles thread idents, which would let a leaked thread
    silently reuse a snapshot-era id and evade the gate)."""
    return set(threading.enumerate())


def leaked_threads(snapshot: Set[threading.Thread],
                   join_grace_s: float = 2.0) -> List[str]:
    """Names of NON-DAEMON threads alive now that were not in ``snapshot``.

    Daemon threads die with the process and are the world registry's
    business (joined by :meth:`WorldScope.shutdown`); a leaked non-daemon
    thread wedges interpreter exit — the soak harnesses fail on it. A
    short SHARED grace deadline absorbs workers that are mid-exit."""
    import time

    leaked = [t for t in threading.enumerate()
              if t not in snapshot and not t.daemon and t.is_alive()
              and t is not threading.current_thread()]
    deadline = time.monotonic() + join_grace_s
    for t in leaked:
        t.join(max(deadline - time.monotonic(), 0.0))
    return [t.name for t in leaked if t.is_alive()]
