"""Cross-device FL server over model-artifact files.

reference: ``cross_device/server_mnn/fedml_aggregator.py:16-213`` (aggregate
at :63: read device ``.mnn`` files → tensors → weighted average → write back)
and ``server_mnn/utils.py:11-50`` (``read_mnn_as_tensor_dict`` /
``write_tensor_dict_to_mnn``). Artifact format here: ``.npz`` of named leaves.

The message FSM is the cross-silo server's (same S2C_INIT/SYNC/FINISH
protocol, ``cross_device/server_mnn/FedMLServerManager`` mirrors the Octopus
one) — devices are clients whose model payloads are artifact files rather
than inline arrays.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, Optional

import jax
import numpy as np

from ..ml.aggregator import create_server_aggregator
from ..ml.evaluate import make_eval_fn

logger = logging.getLogger(__name__)


def write_tensor_dict_to_artifact(tensor_dict: Dict[str, np.ndarray],
                                  path: str) -> None:
    """reference: write_tensor_dict_to_mnn (server_mnn/utils.py:31-50).

    Atomic: written to a temp file then os.replace'd, so devices polling the
    artifact (by existence or mtime) never observe a half-written archive —
    the publish is a single filesystem event.
    """
    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp.npz"  # .npz suffix: np.savez writes exactly here
    np.savez(tmp, **{k: np.asarray(v) for k, v in tensor_dict.items()})
    os.replace(tmp, path)


def read_artifact_as_tensor_dict(path: str) -> Dict[str, np.ndarray]:
    """reference: read_mnn_as_tensor_dict (server_mnn/utils.py:11-29)."""
    with np.load(path) as z:
        return {k: z[k] for k in z.files}


def params_to_tensor_dict(params) -> Dict[str, np.ndarray]:
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    return {
        "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path):
            np.asarray(leaf)
        for path, leaf in flat
    }


def tensor_dict_to_params(template, tensor_dict: Dict[str, np.ndarray]):
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in flat:
        key = "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
        leaves.append(np.asarray(tensor_dict[key]).reshape(np.shape(leaf)))
    return jax.tree_util.tree_unflatten(treedef, leaves)


class ServerMNN:
    """Artifact-file FL server (reference: ServerMNN, cross_device/mnn_server.py).

    Runs rounds against a directory devices upload into:
    - publishes the global model to ``global_model_file_path``
    - each round, ingests ``client_*.npz`` uploads (+ a ``.samples`` sidecar
      for the weight), weighted-averages, re-publishes, evaluates.
    An ``upload_dir`` poll stands in for the MQTT+S3 transport on a pod with
    no broker; the aggregation math matches fedml_aggregator.py:63-91.
    """

    def __init__(self, args, device, dataset, model, server_aggregator=None):
        self.args = args
        self.ds = dataset
        self.bundle = model
        self.aggregator = server_aggregator or create_server_aggregator(model, args)
        self.global_model_file_path = str(
            getattr(args, "global_model_file_path", "")
            or os.path.join(".", "global_model.npz")
        )
        self.upload_dir = str(
            getattr(args, "device_upload_dir", "") or "./device_uploads"
        )
        self.global_params = model.init(
            jax.random.PRNGKey(int(getattr(args, "random_seed", 0)))
        )
        self.aggregator.set_model_params(self.global_params)
        self.evaluate = make_eval_fn(model)
        self.round_idx = 0
        self.final_metrics: Optional[dict] = None

    def publish_global_model(self) -> str:
        write_tensor_dict_to_artifact(
            params_to_tensor_dict(self.global_params), self.global_model_file_path
        )
        return self.global_model_file_path

    def ingest_uploads(self) -> list:
        """Collect (num_samples, params) from device artifact uploads."""
        out = []
        if not os.path.isdir(self.upload_dir):
            return out
        for fn in sorted(os.listdir(self.upload_dir)):
            if not fn.endswith(".npz"):
                continue
            path = os.path.join(self.upload_dir, fn)
            td = read_artifact_as_tensor_dict(path)
            params = tensor_dict_to_params(self.global_params, td)
            sidecar = path[:-4] + ".samples"
            n = 1.0
            if os.path.exists(sidecar):
                with open(sidecar) as f:
                    n = float(f.read().strip() or 1.0)
            out.append((n, params))
        return out

    def run_one_round(self) -> Optional[dict]:
        """publish → devices train (out of band) → ingest → aggregate → eval."""
        from ..core.aggregate import stack_trees, weighted_average
        import jax.numpy as jnp

        uploads = self.ingest_uploads()
        if not uploads:
            logger.info("cross_device: no uploads in %s", self.upload_dir)
            return None
        uploads = self.aggregator.on_before_aggregation(uploads)
        weights = jnp.asarray([n for n, _ in uploads])
        stacked = stack_trees([p for _, p in uploads])
        agg = weighted_average(stacked, weights)
        agg = self.aggregator.on_after_aggregation(agg)
        self.global_params = agg
        self.aggregator.set_model_params(agg)
        self.publish_global_model()
        self.round_idx += 1
        if self.ds is not None:
            self.final_metrics = self.evaluate(
                agg, self.ds.test_x, self.ds.test_y
            )
            logger.info("cross_device round %d: acc=%.4f", self.round_idx,
                        self.final_metrics["test_acc"])
        return self.final_metrics

    def run(self):
        """Round loop: each round consumes whatever uploads are present."""
        self.publish_global_model()
        rounds = int(getattr(self.args, "comm_round", 1))
        for _ in range(rounds):
            self.run_one_round()
        return self.final_metrics
