"""Cross-device LightSecAgg over model-artifact files.

reference: ``cross_device/server_mnn_lsa/`` (859 LoC — the BeeHive artifact
server + LightSecAgg: devices upload MASKED models; the server reconstructs
only the aggregate). Artifact analog of the MQTT+S3 transport, mirroring
``cross_silo/lightsecagg``'s math (one shared ``core/mpc/lightsecagg``
kernel set):

round phases, all files under ``upload_dir``:

1. server publishes the global model (``ServerMNN.publish_global_model``)
2. every device writes its LCC-encoded mask shares:  ``shares_{d}.npz``
   holding rows for ALL peers (the reference routes shares through the
   server/broker as opaque payloads — a shared directory is the same trust
   model: shares are field-random without T+1 collusion)
3. surviving devices write masked quantized models: ``masked_{d}.npz``
4. after the server names the survivor set (``survivors.json``), each
   surviving device sums the share-rows addressed to it from survivors and
   writes ``aggshare_{d}.npz``
5. the server field-sums the masked models, LCC-decodes Σz from any U
   aggregate shares, unmasks, dequantizes → the average — individual
   updates are never visible to anyone.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..core.mpc import lightsecagg as lsa
from ..utils.tree import tree_flatten_to_vector, tree_unflatten_from_vector
from .server import ServerMNN

logger = logging.getLogger(__name__)


class DeviceLSA:
    """The device side of the artifact LSA flow (reference: the MNN device's
    LightSecAgg client; here it doubles as the test/demo harness)."""

    def __init__(self, device_id: int, upload_dir: str, N: int, U: int, T: int,
                 q_bits: int = 8, seed: int = 0):
        self.d_id = int(device_id)
        self.dir = upload_dir
        self.N, self.U, self.T = N, U, T
        self.q_bits = q_bits
        self.rng = np.random.RandomState(1000 + seed * 131 + device_id)
        self._z: Optional[np.ndarray] = None

    def write_shares(self, dim: int) -> None:
        """Phase 2: generate mask, encode, publish the share rows."""
        self._z, shares = lsa.mask_encoding(
            dim, self.N, self.U, self.T, self.rng
        )
        np.savez(os.path.join(self.dir, f"shares_{self.d_id}.npz"),
                 shares=shares)

    def write_masked_model(self, vec: np.ndarray, n_samples: float) -> None:
        """Phase 3: upload (quantized model + z) mod p."""
        q = np.asarray(lsa.quantize_to_field(vec, self.q_bits))
        masked = np.asarray(lsa.model_masking(
            jnp.asarray(q, jnp.int32), jnp.asarray(self._z, jnp.int32)
        ))
        np.savez(os.path.join(self.dir, f"masked_{self.d_id}.npz"),
                 masked=masked, n=np.asarray([n_samples]))

    def write_aggregate_share(self, survivors: List[int]) -> None:
        """Phase 4: sum the rows addressed to me from surviving peers."""
        rows = []
        for s in survivors:
            with np.load(os.path.join(self.dir, f"shares_{s}.npz")) as z:
                rows.append(z["shares"][self.d_id])
        agg = lsa.aggregate_shares(rows)
        np.savez(os.path.join(self.dir, f"aggshare_{self.d_id}.npz"), agg=agg)


class ServerMNNLSA(ServerMNN):
    """Artifact FL server that only ever sees masked models.

    ``args``: ``lsa_privacy_guarantee`` (T), ``lsa_surviving_threshold`` (U,
    default N-1), ``lsa_quantize_bits``.
    """

    def __init__(self, args, device, dataset, model, server_aggregator=None):
        super().__init__(args, device, dataset, model, server_aggregator)
        self.N = int(getattr(args, "client_num_in_total", 1))
        self.T = int(getattr(args, "lsa_privacy_guarantee", 1))
        self.U = int(getattr(args, "lsa_surviving_threshold", 0)) or max(
            self.T + 1, self.N - 1
        )
        # q_bits must leave headroom in the 2**15-19 field: values scale
        # by 2**q_bits and N of them sum before unmasking
        self.q_bits = int(getattr(args, "lsa_quantize_bits", 8))
        vec, self._treedef, self._shapes = tree_flatten_to_vector(
            self.global_params
        )
        self._dim = int(vec.shape[0])

    # -- round phases --------------------------------------------------------
    def list_masked_uploads(self) -> Dict[int, np.ndarray]:
        out = {}
        if not os.path.isdir(self.upload_dir):
            return out
        for fn in sorted(os.listdir(self.upload_dir)):
            if fn.startswith("masked_") and fn.endswith(".npz"):
                d_id = int(fn[len("masked_"):-len(".npz")])
                with np.load(os.path.join(self.upload_dir, fn)) as z:
                    out[d_id] = z["masked"].astype(np.int64)
        return out

    def publish_survivors(self, survivors: List[int]) -> None:
        with open(os.path.join(self.upload_dir, "survivors.json"), "w") as f:
            json.dump(sorted(survivors), f)

    def reconstruct(self, masked: Dict[int, np.ndarray]) -> np.ndarray:
        """Field-sum survivors' masked models, decode Σz, unmask, dequantize."""
        survivors = sorted(masked)
        masked_sum = np.zeros(self._dim, np.int64)
        for d_id in survivors:
            masked_sum = (masked_sum + masked[d_id]) % lsa.FIELD_P
        # any U survivors' aggregate shares suffice
        agg_shares, points = [], []
        for d_id in survivors:
            path = os.path.join(self.upload_dir, f"aggshare_{d_id}.npz")
            if not os.path.exists(path):
                continue
            with np.load(path) as z:
                agg_shares.append(z["agg"].astype(np.int64))
            points.append(d_id + 1)  # α_j = device index + 1
            if len(agg_shares) == self.U:
                break
        if len(agg_shares) < self.U:
            raise RuntimeError(
                f"LSA needs {self.U} aggregate shares, got {len(agg_shares)}"
            )
        mask_sum = lsa.decode_aggregate_mask(
            agg_shares, points, self._dim, self.N, self.U, self.T
        )
        clear = np.asarray(lsa.model_unmasking(
            jnp.asarray(masked_sum % lsa.FIELD_P, jnp.int32),
            jnp.asarray(mask_sum % lsa.FIELD_P, jnp.int32),
        ))
        return lsa.dequantize_from_field(clear, self.q_bits) / max(
            len(survivors), 1
        )

    def run_one_round(self) -> Optional[dict]:
        """Two poll phases, like the broker flow: (a) enough masked uploads →
        name the survivor set and wait for aggregate shares; (b) U aggregate
        shares present → reconstruct and advance the round."""
        masked = self.list_masked_uploads()
        if len(masked) < max(self.U, 1):
            logger.info(
                "cross_device lsa: %d/%d masked uploads — waiting",
                len(masked), self.U,
            )
            return None
        survivors_file = os.path.join(self.upload_dir, "survivors.json")
        if not os.path.exists(survivors_file):
            self.publish_survivors(sorted(masked))
            return None  # devices now compute their aggregate shares
        n_agg = sum(
            1 for fn in os.listdir(self.upload_dir)
            if fn.startswith("aggshare_")
        )
        if n_agg < self.U:
            logger.info(
                "cross_device lsa: %d/%d aggregate shares — waiting",
                n_agg, self.U,
            )
            return None
        avg = self.reconstruct(masked)
        self.global_params = tree_unflatten_from_vector(
            jnp.asarray(avg, jnp.float32), self._treedef, self._shapes
        )
        self.aggregator.set_model_params(self.global_params)
        self.publish_global_model()
        self.round_idx += 1
        # consume the round's artifacts
        for fn in os.listdir(self.upload_dir):
            if fn.startswith(("masked_", "aggshare_", "shares_")) or (
                fn == "survivors.json"
            ):
                try:
                    os.remove(os.path.join(self.upload_dir, fn))
                except OSError:
                    pass
        if self.ds is not None:
            self.final_metrics = self.evaluate(
                self.global_params, self.ds.test_x, self.ds.test_y
            )
            logger.info(
                "cross_device lsa round %d: acc=%.4f", self.round_idx,
                self.final_metrics["test_acc"],
            )
        return self.final_metrics
