"""``fedml_tpu.cross_device`` — the Beehive pillar (server side).

reference: ``cross_device/server_mnn/`` (ServerMNN + FedMLAggregator, 783 LoC)
— an FL server whose model artifact is a file phones train on; aggregation
reads device-uploaded artifacts into tensors, averages, writes back.

Per SURVEY.md §7 stage 9, the MNN C++ engine itself is out of scope on a TPU
pod (and closed-source in the reference, ``android/README.md``); what is kept
is the *server-side protocol*: artifact-file model exchange behind the comm
abstraction, so edge servers aggregate device uploads. Artifacts are ``.npz``
leaf files (documented compatibility surface replacing ``.mnn``).
"""

from .server import ServerMNN, read_artifact_as_tensor_dict, write_tensor_dict_to_artifact
from .server_lsa import DeviceLSA, ServerMNNLSA

__all__ = [
    "ServerMNN",
    "ServerMNNLSA",
    "DeviceLSA",
    "read_artifact_as_tensor_dict",
    "write_tensor_dict_to_artifact",
]
