"""``fedml_tpu.data`` — federated dataset loading.

Public surface mirrors the reference (``fedml.data.load``,
``python/fedml/data/data_loader.py:30-330``): ``load(args)`` returns
``(dataset, class_num)``; here ``dataset`` is a packed :class:`FedDataset`
instead of dicts of torch DataLoaders (see ``fed_dataset.py`` for why).
"""

from __future__ import annotations

import logging
from typing import Tuple

import numpy as np

from ..core.partition import (
    homo_partition,
    non_iid_partition_with_dirichlet_distribution,
    pack_partitions,
)
from .datasets import REGISTRY, DatasetSpec, load_raw
from .fed_dataset import FedDataset, pad_cap_to_batch_multiple

logger = logging.getLogger(__name__)

__all__ = ["load", "FedDataset", "REGISTRY", "DatasetSpec"]


def _try_natural_partition(name: str, cache_dir: str, spec: DatasetSpec):
    """Naturally-partitioned on-disk loaders — LEAF JSON and Google-TFF h5
    (None when files aren't staged)."""
    if name == "femnist":
        from .leaf import try_load_leaf_femnist

        return try_load_leaf_femnist(cache_dir)
    if name == "fed_cifar100":
        from .tff_h5 import try_load_fed_cifar100

        return try_load_fed_cifar100(cache_dir)
    if name == "fed_shakespeare":
        from .tff_h5 import try_load_fed_shakespeare

        tff = try_load_fed_shakespeare(cache_dir)
        if tff is not None:
            return tff
        from .leaf import try_load_leaf_shakespeare

        return try_load_leaf_shakespeare(cache_dir, spec.seq_len)
    if name == "shakespeare":
        from .leaf import try_load_leaf_shakespeare

        return try_load_leaf_shakespeare(cache_dir, spec.seq_len)
    if name == "stackoverflow_nwp":
        from .real_readers import try_load_stackoverflow_nwp

        return try_load_stackoverflow_nwp(cache_dir, seq_len=spec.seq_len)
    if name == "stackoverflow_lr":
        from .real_readers import try_load_stackoverflow_lr

        return try_load_stackoverflow_lr(
            cache_dir, vocab_size=spec.sample_shape[0], tag_size=spec.class_num
        )
    if name == "ILSVRC2012":
        from .real_readers import try_load_imagenet

        return try_load_imagenet(cache_dir, image_hw=spec.sample_shape[:2])
    if spec.task == "detection" and spec.sample_shape[0] >= 128:
        # real-resolution detection keys read staged COCO-format data
        # (annotations json + images dir); synthetic fallback otherwise
        from .real_readers import try_load_coco_detection

        return try_load_coco_detection(
            cache_dir, image_hw=spec.sample_shape[:2],
            num_classes=spec.class_num,
        )
    if name in ("gld23k", "gld160k"):
        from .real_readers import try_load_landmarks

        return try_load_landmarks(
            cache_dir, name=name, image_hw=spec.sample_shape[:2]
        )
    return None


def load(args) -> Tuple[FedDataset, int]:
    """Load + partition + pack a federated dataset per ``args``.

    Reference dispatch analog: data_loader.py:30 ``load`` → per-dataset
    ``load_partition_data_*``. Partitioning: ``hetero`` = Dirichlet LDA over
    labels (core/data/noniid_partition.py), ``homo`` = shuffled even split.
    """
    name = args.dataset
    if name not in REGISTRY:
        raise ValueError(
            f"unknown dataset {name!r}; known: {sorted(REGISTRY)}"
        )
    spec = REGISTRY[name]
    client_num = int(getattr(args, "client_num_in_total", 0) or spec.default_clients)
    n_train = client_num * spec.train_per_client
    seed = int(getattr(args, "random_seed", 0))
    cache_dir = getattr(args, "data_cache_dir", "./data_cache")

    # LEAF datasets carry a NATURAL per-author partition when staged on disk
    # (reference: data_loader.py dispatches femnist/shakespeare to LEAF JSON
    # loaders) — use it and let the file define the client count
    natural = _try_natural_partition(name, cache_dir, spec)
    if natural is not None:
        client_xs, client_ys, ex, ey = natural
        # real LEAF partitions are heavily skewed; the packed layout's cap is
        # the LARGEST client, so bound per-client samples or the dense
        # [clients, cap, ...] array explodes (shakespeare: some authors have
        # tens of thousands of windows)
        max_per = int(getattr(args, "leaf_max_samples_per_client", 2048))
        capped = sum(1 for cx in client_xs if len(cx) > max_per)
        if capped:
            logger.warning(
                "data: %s — subsampling %d/%d LEAF clients to "
                "leaf_max_samples_per_client=%d (packed cap bound)",
                name, capped, len(client_xs), max_per,
            )
            client_xs = [cx[:max_per] for cx in client_xs]
            client_ys = [cy[:max_per] for cy in client_ys]
        tx = np.concatenate(client_xs)
        ty = np.concatenate(client_ys)
        idx_map, start = {}, 0
        for cid, cx in enumerate(client_xs):
            idx_map[cid] = np.arange(start, start + len(cx))
            start += len(cx)
        if int(getattr(args, "client_num_in_total", 0) or 0) not in (
            0, len(client_xs),
        ):
            logger.warning(
                "data: %s LEAF files define %d clients; overriding "
                "client_num_in_total=%s", name, len(client_xs),
                args.client_num_in_total,
            )
        args.client_num_in_total = len(client_xs)
        x, y, counts = pack_partitions(tx, ty, idx_map)
        ds = FedDataset(
            train_x=x, train_y=y, train_counts=counts.astype(np.int32),
            test_x=ex, test_y=ey, class_num=spec.class_num, task=spec.task,
            meta={"vocab_size": spec.vocab_size, "seq_len": spec.seq_len,
                  "name": name, "natural_partition": True},
        )
        ds = pad_cap_to_batch_multiple(ds, int(getattr(args, "batch_size", 32)))
        logger.info(
            "data: %s (LEAF) clients=%d cap=%d train=%d test=%d",
            name, ds.client_num, ds.cap, ds.train_data_num, ds.test_data_num,
        )
        return ds, spec.class_num

    tx, ty, ex, ey, real_files = load_raw(
        spec, cache_dir, n_train, spec.test_total, seed
    )

    # --- partition ---------------------------------------------------------
    method = getattr(args, "partition_method", "hetero")
    if spec.task == "classification" and method == "hetero":
        idx_map = non_iid_partition_with_dirichlet_distribution(
            ty, client_num, spec.class_num, float(args.partition_alpha), seed=seed
        )
    else:
        # text/tagpred datasets are naturally partitioned per author in the
        # reference (LEAF); synthetic equivalent: even split
        idx_map = homo_partition(tx.shape[0], client_num, seed=seed)

    x, y, counts = pack_partitions(tx, ty, idx_map)
    ds = FedDataset(
        train_x=x,
        train_y=y,
        train_counts=counts.astype(np.int32),
        test_x=ex,
        test_y=ey,
        class_num=spec.class_num,
        task=spec.task,
        meta={"vocab_size": spec.vocab_size, "seq_len": spec.seq_len,
              "name": name, "real_files": real_files},
    )
    ds = pad_cap_to_batch_multiple(ds, int(getattr(args, "batch_size", 32)))
    logger.info(
        "data: %s clients=%d cap=%d train=%d test=%d classes=%d task=%s",
        name, ds.client_num, ds.cap, ds.train_data_num, ds.test_data_num,
        ds.class_num, ds.task,
    )
    return ds, spec.class_num
