"""Dataset registry: shapes, class counts, and sources.

Mirrors the catalogue handled by the reference's dispatch
(``python/fedml/data/data_loader.py:30-330``): MNIST, FEMNIST, shakespeare
(LEAF + Google), fed_cifar100, stackoverflow lr/nwp, CIFAR-10/100, CINIC-10,
ImageNet, Landmarks. Two sources per dataset:

- **on-disk real data** in ``args.data_cache_dir`` (MNIST IDX files, CIFAR
  python pickles) — used when present;
- **deterministic synthetic fallback** with the real shapes/class counts —
  class-conditional Gaussian images and Markov-chain token streams, so models
  *learn* (convergence tests are meaningful) without any network egress.
  The reference instead auto-downloads (``data/mnist/data_loader.py``
  ``download_mnist``, S3 URL at ``data/constants.py:24``); a TPU pod build
  cannot assume egress, so synthetic-by-default is a deliberate change.
"""

from __future__ import annotations

import gzip
import logging
import os
import pickle
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class DatasetSpec:
    name: str
    sample_shape: Tuple[int, ...]
    class_num: int
    task: str  # classification | nwp | tagpred | segmentation | regression
    #           | node_clf | link_pred
    default_clients: int
    train_per_client: int  # synthetic samples per client
    test_total: int
    vocab_size: int = 0  # text tasks
    seq_len: int = 0
    n_nodes: int = 0  # graph tasks: padded node count (packed dense block)
    n_feats: int = 0  # graph tasks: node feature width


REGISTRY = {
    # vision
    "synthetic": DatasetSpec("synthetic", (60,), 10, "classification", 30, 40, 400),
    "mnist": DatasetSpec("mnist", (28, 28, 1), 10, "classification", 1000, 60, 2000),
    "femnist": DatasetSpec("femnist", (28, 28, 1), 62, "classification", 200, 100, 4000),
    "cifar10": DatasetSpec("cifar10", (32, 32, 3), 10, "classification", 100, 500, 2000),
    "cifar100": DatasetSpec("cifar100", (32, 32, 3), 100, "classification", 100, 500, 2000),
    "cinic10": DatasetSpec("cinic10", (32, 32, 3), 10, "classification", 100, 500, 2000),
    "fed_cifar100": DatasetSpec(
        "fed_cifar100", (32, 32, 3), 100, "classification", 500, 100, 2000
    ),
    "ILSVRC2012": DatasetSpec(
        "ILSVRC2012", (224, 224, 3), 1000, "classification", 100, 16, 256
    ),
    "gld23k": DatasetSpec("gld23k", (224, 224, 3), 203, "classification", 233, 16, 256),
    "gld160k": DatasetSpec("gld160k", (224, 224, 3), 2028, "classification", 100, 16, 256),
    # text — char LM (LEAF shakespeare vocab: 80 printable chars + pad,
    # reference model/nlp/rnn.py RNN_OriginalFedAvg embeds 90)
    "shakespeare": DatasetSpec(
        "shakespeare", (80,), 90, "nwp", 100, 50, 500, vocab_size=90, seq_len=80
    ),
    "fed_shakespeare": DatasetSpec(
        "fed_shakespeare", (80,), 90, "nwp", 100, 50, 500, vocab_size=90, seq_len=80
    ),
    "stackoverflow_nwp": DatasetSpec(
        "stackoverflow_nwp", (20,), 10004, "nwp", 200, 50, 500, vocab_size=10004, seq_len=20
    ),
    # multilabel bag-of-words tag prediction (10k vocab → 500 tags)
    "stackoverflow_lr": DatasetSpec(
        "stackoverflow_lr", (10000,), 500, "tagpred", 200, 30, 400
    ),
    # semantic segmentation (reference: simulation/mpi/fedseg — pascal_voc /
    # cityscapes loaders at data/{pascal_voc_augmented,cityscapes}/); synthetic
    # fallback keeps the per-pixel label geometry at toy resolution
    "pascal_voc": DatasetSpec(
        "pascal_voc", (32, 32, 3), 21, "segmentation", 20, 40, 200
    ),
    "cityscapes": DatasetSpec(
        "cityscapes", (32, 32, 3), 19, "segmentation", 20, 40, 200
    ),
    # adversarial-FL fixture (reference: data/edge_case_examples) — plain
    # CIFAR-10 shapes; poisoning is applied by the attack layer, not the data.
    "edge_case_examples": DatasetSpec(
        "edge_case_examples", (32, 32, 3), 10, "classification", 100, 200, 1000
    ),
    # FedCV detection (reference: python/app/fedcv/object_detection —
    # YOLOv5/coco128; dense CenterNet-style targets here, see
    # models/detection.py). classification + segmentation FedCV tasks ride
    # the standard vision datasets above.
    "coco128_det": DatasetSpec(
        "coco128_det", (32, 32, 3), 6, "detection", 8, 40, 160
    ),
    # real-resolution detection (reference trains YOLOv5 at 640px on
    # coco128): 224px images through the native host pipeline + a deeper
    # CenterNet — row 75's "32x32 toy" objection closes here
    "fedcv_det224": DatasetSpec(
        "fedcv_det224", (224, 224, 3), 6, "detection", 4, 16, 32
    ),
    # test-budget variant: same resolution/task, half the per-client volume
    # (one XLA:CPU 224px conv round costs minutes on a 1-core host)
    "fedcv_det224_mini": DatasetSpec(
        "fedcv_det224_mini", (224, 224, 3), 6, "detection", 4, 8, 16
    ),
    # full COCO category space for staged real data (80 classes; the reader
    # maps sparse COCO category ids to contiguous classes in sorted order)
    "coco_det": DatasetSpec(
        "coco_det", (224, 224, 3), 80, "detection", 8, 32, 64
    ),
    # Healthcare / FLamby family (reference: python/app/healthcare/*) —
    # tabular & imaging tasks mapped onto their natural task types
    "fed_heart_disease": DatasetSpec(
        "fed_heart_disease", (13,), 2, "classification", 4, 40, 160
    ),
    "fed_isic2019": DatasetSpec(
        "fed_isic2019", (32, 32, 3), 8, "classification", 6, 60, 240
    ),
    "fed_tcga_brca": DatasetSpec(
        "fed_tcga_brca", (39,), 1, "regression", 6, 40, 160
    ),
    # FedNLP task family (reference: python/app/fednlp/{seq_tagging,
    # span_extraction,seq2seq}); text_classification rides the standard
    # classification datasets
    "fednlp_seq_tagging": DatasetSpec(
        "fednlp_seq_tagging", (24,), 9, "seq_tagging", 8, 48, 192,
        vocab_size=128, seq_len=24,
    ),
    "fednlp_span_extraction": DatasetSpec(
        "fednlp_span_extraction", (32,), 32, "span_extraction", 8, 48, 192,
        vocab_size=64, seq_len=32,
    ),
    # seq2seq as a prefix-LM: [src ; SEP ; tgt] packed, loss masked to the
    # target region via pad id 0 (the TPU-idiomatic decoder-only framing)
    "fednlp_seq2seq": DatasetSpec(
        "fednlp_seq2seq", (33,), 32, "nwp", 8, 48, 192,
        vocab_size=32, seq_len=33,
    ),
    # graphs — FedGraphNN family (reference: python/app/fedgraphnn/*);
    # packed dense blocks [N, F+N+1] (models/gnn.py), generated in
    # data/graphs.py. sample_shape = (n_nodes, n_feats + n_nodes + 1).
    "moleculenet_clf": DatasetSpec(
        "moleculenet_clf", (24, 8 + 24 + 1), 2, "classification", 8, 48, 192,
        n_nodes=24, n_feats=8,
    ),
    "moleculenet_reg": DatasetSpec(
        "moleculenet_reg", (24, 8 + 24 + 1), 1, "regression", 8, 48, 192,
        n_nodes=24, n_feats=8,
    ),
    "social_graph_clf": DatasetSpec(
        "social_graph_clf", (32, 4 + 32 + 1), 3, "classification", 8, 48, 192,
        n_nodes=32, n_feats=4,
    ),
    "ego_node_clf": DatasetSpec(
        "ego_node_clf", (32, 16 + 32 + 1), 5, "node_clf", 8, 32, 128,
        n_nodes=32, n_feats=16,
    ),
    "ego_link_pred": DatasetSpec(
        "ego_link_pred", (32, 16 + 32 + 1), 4, "link_pred", 8, 32, 128,
        n_nodes=32, n_feats=16,
    ),
    "recsys_link_pred": DatasetSpec(
        "recsys_link_pred", (48, 16 + 48 + 1), 6, "link_pred", 8, 24, 96,
        n_nodes=48, n_feats=16,
    ),
}


# ---------------------------------------------------------------------------
# Real on-disk loaders (no downloads; used when files are already cached)
# ---------------------------------------------------------------------------
def _read_idx(path: str) -> Optional[np.ndarray]:
    opener = gzip.open if path.endswith(".gz") else open
    try:
        with opener(path, "rb") as f:
            magic = int.from_bytes(f.read(4), "big")
            ndim = magic & 0xFF
            dims = [int.from_bytes(f.read(4), "big") for _ in range(ndim)]
            data = np.frombuffer(f.read(), dtype=np.uint8)
            return data.reshape(dims)
    except (OSError, ValueError):
        return None


def try_load_mnist(cache_dir: str):
    """MNIST from standard IDX files if present under ``cache_dir/MNIST`` or
    ``cache_dir`` (reference auto-downloads these; we only read)."""
    names = {
        "train_x": ("train-images-idx3-ubyte", "train-images.idx3-ubyte"),
        "train_y": ("train-labels-idx1-ubyte", "train-labels.idx1-ubyte"),
        "test_x": ("t10k-images-idx3-ubyte", "t10k-images.idx3-ubyte"),
        "test_y": ("t10k-labels-idx1-ubyte", "t10k-labels.idx1-ubyte"),
    }
    out = {}
    for key, candidates in names.items():
        arr = None
        for base in candidates:
            for sub in ("", "MNIST", "mnist"):
                for ext in ("", ".gz"):
                    p = os.path.join(cache_dir, sub, base + ext)
                    if os.path.exists(p):
                        arr = _read_idx(p)
                        break
                if arr is not None:
                    break
            if arr is not None:
                break
        if arr is None:
            out[key] = None
        else:
            out[key] = arr
    if out["test_x"] is None or out["test_y"] is None:
        return None
    if out["train_x"] is not None and out["train_y"] is None:
        return None  # images without labels: clean synthetic fallback
    if out["train_x"] is None:
        # t10k-split fallback: the only real MNIST this pod carries is the
        # 10k test set (the reference checkout ships its cross-device
        # example's data/MNIST/raw WITHOUT train-images-idx3-ubyte, and the
        # pod has zero egress). Train on the first 8k REAL digits, evaluate
        # on the held-out 2k — real data, reduced protocol; the repro
        # harness surfaces the deviation as protocol="mnist_t10k_split".
        logger.warning(
            "mnist: train-images missing; splitting the REAL t10k set "
            "8000 train / 2000 test (protocol deviation, logged in output)"
        )
        ex_all = out["test_x"].astype(np.float32)[..., None] / 255.0
        ey_all = out["test_y"].astype(np.int32)
        return (ex_all[:8000], ey_all[:8000], ex_all[8000:], ey_all[8000:],
                "mnist_t10k_split")
    tx = out["train_x"].astype(np.float32)[..., None] / 255.0
    ex = out["test_x"].astype(np.float32)[..., None] / 255.0
    return tx, out["train_y"].astype(np.int32), ex, out["test_y"].astype(np.int32)


def try_load_cifar(cache_dir: str, name: str):
    """CIFAR-10/100 from the standard python pickle batches if present."""
    if name == "cifar10":
        sub, train_files, test_file, label_key = (
            "cifar-10-batches-py",
            [f"data_batch_{i}" for i in range(1, 6)],
            "test_batch",
            b"labels",
        )
    else:
        sub, train_files, test_file, label_key = (
            "cifar-100-python",
            ["train"],
            "test",
            b"fine_labels",
        )
    root = os.path.join(cache_dir, sub)
    if not os.path.isdir(root):
        return None
    try:
        xs, ys = [], []
        for fn in train_files:
            with open(os.path.join(root, fn), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(d[b"data"])
            ys.extend(d[label_key])
        with open(os.path.join(root, test_file), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        tx = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        ex = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return (
            tx.astype(np.float32) / 255.0,
            np.asarray(ys, dtype=np.int32),
            ex.astype(np.float32) / 255.0,
            np.asarray(d[label_key], dtype=np.int32),
        )
    except (OSError, KeyError, ValueError):
        return None


# ---------------------------------------------------------------------------
# Synthetic generators (deterministic, learnable)
# ---------------------------------------------------------------------------
def synth_classification(spec: DatasetSpec, n_train: int, n_test: int, seed: int):
    """Class-conditional Gaussian data: x = prototype[y] + noise.

    Linearly separable enough that LR/CNN/ResNet reach high accuracy —
    preserving the reference's "tiny-config real training" smoke pattern
    (SURVEY.md §4) without downloads.
    """
    rng = np.random.RandomState(seed)
    dim = int(np.prod(spec.sample_shape))
    protos = rng.randn(spec.class_num, dim).astype(np.float32)

    def make(n, rng):
        y = rng.randint(0, spec.class_num, size=n).astype(np.int32)
        x = protos[y] * 0.5 + rng.randn(n, dim).astype(np.float32) * 0.8
        return x.reshape((n,) + spec.sample_shape), y

    tx, ty = make(n_train, rng)
    ex, ey = make(n_test, rng)
    return tx, ty, ex, ey


def synth_tagpred(spec: DatasetSpec, n_train: int, n_test: int, seed: int):
    """Multilabel bag-of-words: sparse count vectors, tags linearly linked to
    active vocabulary blocks (stackoverflow_lr analog)."""
    rng = np.random.RandomState(seed)
    dim = spec.sample_shape[0]
    proj = rng.randn(dim, spec.class_num).astype(np.float32) * 0.3

    def make(n, rng):
        x = (rng.rand(n, dim) < (8.0 / dim)).astype(np.float32) * (
            1.0 + rng.rand(n, dim).astype(np.float32)
        )
        logits = x @ proj
        thresh = np.quantile(logits, 0.99, axis=1, keepdims=True)
        y = (logits >= thresh).astype(np.float32)
        return x, y

    tx, ty = make(n_train, rng)
    ex, ey = make(n_test, rng)
    return tx, ty, ex, ey


def synth_nwp(spec: DatasetSpec, n_train: int, n_test: int, seed: int):
    """Token sequences from a peaked Markov chain over the real vocab size, so
    next-word prediction is learnable well above chance."""
    rng = np.random.RandomState(seed)
    V, L = spec.vocab_size, spec.seq_len
    # each token has a handful of likely successors
    succ = rng.randint(0, V, size=(V, 4))

    def make(n, rng):
        seqs = np.zeros((n, L), dtype=np.int32)
        tok = rng.randint(0, V, size=n)
        for t in range(L):
            seqs[:, t] = tok
            choice = rng.randint(0, 4, size=n)
            follow = succ[tok, choice]
            rand = rng.randint(0, V, size=n)
            use_rand = rng.rand(n) < 0.1
            tok = np.where(use_rand, rand, follow)
        return seqs

    tx = make(n_train, rng)
    ex = make(n_test, rng)
    # y = x shifted left (predict next token); last target = 0 (masked pad id 0)
    def shift(x):
        y = np.zeros_like(x)
        y[:, :-1] = x[:, 1:]
        return y

    return tx, shift(tx), ex, shift(ex)


def synth_seq_tagging(spec: DatasetSpec, n_train: int, n_test: int, seed: int):
    """Per-token tags: a token's tag is its vocab block, EXCEPT after a
    trigger token, which shifts the next tag by one — so context (the BiLSTM)
    beats a per-token lookup. Padding tail labeled -1."""
    rng = np.random.RandomState(seed)
    V, L, C = spec.vocab_size, spec.seq_len, spec.class_num
    block = max(1, V // C)
    trigger = 0  # token id 0 is the trigger

    def make(n, rng):
        x = rng.randint(1, V, size=(n, L)).astype(np.int32)
        x[rng.rand(n, L) < 0.15] = trigger
        base = np.minimum(x // block, C - 1)
        prev_trigger = np.zeros_like(x, dtype=bool)
        prev_trigger[:, 1:] = x[:, :-1] == trigger
        y = np.where(prev_trigger, (base + 1) % C, base).astype(np.int32)
        # ragged lengths: tail beyond each sample's length is padding
        lengths = rng.randint(L // 2, L + 1, size=n)
        pad = np.arange(L)[None, :] >= lengths[:, None]
        y[pad] = -1
        return x, y

    tx, ty = make(n_train, rng)
    ex, ey = make(n_test, rng)
    return tx, ty, ex, ey


def synth_span_extraction(spec: DatasetSpec, n_train: int, n_test: int, seed: int):
    """QA-style pointer task: context tokens come from the low half of the
    vocab, one contiguous answer span from the high half; y = (start, end)."""
    rng = np.random.RandomState(seed)
    V, L = spec.vocab_size, spec.seq_len
    half = V // 2

    def make(n, rng):
        x = rng.randint(1, half, size=(n, L)).astype(np.int32)
        starts = rng.randint(0, L - 4, size=n)
        lens = rng.randint(1, 5, size=n)
        ends = np.minimum(starts + lens - 1, L - 1)
        for i in range(n):
            x[i, starts[i]: ends[i] + 1] = rng.randint(
                half, V, size=ends[i] - starts[i] + 1
            )
        y = np.stack([starts, ends], axis=1).astype(np.int32)
        return x, y

    tx, ty = make(n_train, rng)
    ex, ey = make(n_test, rng)
    return tx, ty, ex, ey


def synth_seq2seq(spec: DatasetSpec, n_train: int, n_test: int, seed: int):
    """Prefix-LM seq2seq: src is random tokens, tgt is src reversed,
    packed [src ; SEP ; tgt]. NWP targets are 0 (masked) everywhere except
    the target region — the loss trains only the seq2seq mapping."""
    rng = np.random.RandomState(seed)
    V, L = spec.vocab_size, spec.seq_len
    src_len = (L - 1) // 2
    sep = V - 1

    def make(n, rng):
        src = rng.randint(1, V - 1, size=(n, src_len)).astype(np.int32)
        tgt = src[:, ::-1]
        x = np.concatenate(
            [src, np.full((n, 1), sep, np.int32), tgt], axis=1
        )
        y = np.zeros_like(x)
        # predict tgt tokens from the position before each (SEP predicts
        # tgt[0]); everything else is pad-masked
        y[:, src_len: src_len + src_len] = tgt
        return x, y

    tx, ty = make(n_train, rng)
    ex, ey = make(n_test, rng)
    return tx, ty, ex, ey


def synth_segmentation(spec: DatasetSpec, n_train: int, n_test: int, seed: int):
    """Images of colored rectangles; labels = class id per pixel (background
    0). Learnable: each class has a distinct mean color."""
    rng = np.random.RandomState(seed)
    H, W, _ = spec.sample_shape
    C = spec.class_num
    protos = rng.rand(C, 3).astype(np.float32) * 2 - 1

    def make(n, rng):
        x = rng.randn(n, H, W, 3).astype(np.float32) * 0.3
        y = np.zeros((n, H, W), np.int32)
        for i in range(n):
            for _ in range(rng.randint(1, 4)):
                c = rng.randint(1, C)
                h0, w0 = rng.randint(0, H - 8), rng.randint(0, W - 8)
                dh, dw = rng.randint(6, 14), rng.randint(6, 14)
                y[i, h0:h0 + dh, w0:w0 + dw] = c
                x[i, h0:h0 + dh, w0:w0 + dw] += protos[c]
        return x, y

    tx, ty = make(n_train, rng)
    ex, ey = make(n_test, rng)
    return tx, ty, ex, ey


def synth_regression(spec: DatasetSpec, n_train: int, n_test: int, seed: int):
    """Tabular regression (fed_tcga_brca survival analog): y = x·w + ε."""
    rng = np.random.RandomState(seed)
    dim = int(np.prod(spec.sample_shape))
    w = rng.randn(dim).astype(np.float32) / np.sqrt(dim)

    def make(n, rng):
        x = rng.randn(n, dim).astype(np.float32)
        y = (x @ w + rng.randn(n).astype(np.float32) * 0.1).astype(np.float32)
        return x.reshape((n,) + spec.sample_shape), y

    tx, ty = make(n_train, rng)
    ex, ey = make(n_test, rng)
    return tx, ty, ex, ey


def synth_detection(spec: DatasetSpec, n_train: int, n_test: int, seed: int):
    """Images with 1-3 colored rectangles; dense stride-4 CenterNet-style
    targets (models/detection.py layout): per-cell one-hot class heatmap ++
    normalized (h, w) ++ center mask. Class = rectangle color prototype."""
    rng = np.random.RandomState(seed)
    H, W, _ = spec.sample_shape
    C = spec.class_num
    Hs, Ws = H // 4, W // 4
    protos = rng.rand(C, 3).astype(np.float32) * 2 - 1

    # rectangle sizes scale with resolution (32px keeps the original 6-14px
    # range; 224px draws 14-56px objects)
    lo = max(H // 16, 6)
    hi = max(H // 4, 14)

    def make(n, rng):
        x = rng.randn(n, H, W, 3).astype(np.float32) * 0.3
        y = np.zeros((n, Hs, Ws, C + 3), np.float32)
        for i in range(n):
            for _ in range(rng.randint(1, 4)):
                c = rng.randint(0, C)
                dh, dw = rng.randint(lo, hi), rng.randint(lo, hi)
                h0 = rng.randint(0, H - dh)
                w0 = rng.randint(0, W - dw)
                x[i, h0:h0 + dh, w0:w0 + dw] += protos[c]
                cy, cx = (h0 + dh // 2) // 4, (w0 + dw // 2) // 4
                y[i, cy, cx, :C] = 0.0
                y[i, cy, cx, c] = 1.0
                y[i, cy, cx, C:C + 2] = (dh / H, dw / W)
                y[i, cy, cx, -1] = 1.0
        return x, y

    tx, ty = make(n_train, rng)
    ex, ey = make(n_test, rng)
    return tx, ty, ex, ey


def load_raw(spec: DatasetSpec, cache_dir: str, n_train: int, n_test: int, seed: int):
    """(tx, ty, ex, ey, real) — real data if cached on disk, else synthetic
    with identical shapes; ``real`` says which one the caller got (the
    baseline-reproduction harness refuses to claim published numbers on
    synthetic data)."""
    if spec.name == "mnist":
        real = try_load_mnist(cache_dir)
        if real is not None:
            logger.info("mnist: using real IDX files from %s", cache_dir)
            # 5-tuple = the t10k-split fallback; its 5th element is the
            # protocol tag that rides meta["real_files"] to the repro harness
            return real if len(real) == 5 else real + (True,)
    if spec.name in ("cifar10", "cifar100"):
        real = try_load_cifar(cache_dir, spec.name)
        if real is not None:
            logger.info("%s: using real pickle batches from %s", spec.name, cache_dir)
            return real + (True,)
    logger.info("%s: synthetic fallback (%d train / %d test)", spec.name, n_train, n_test)
    if spec.n_nodes > 0:  # FedGraphNN family: packed dense graph blocks
        from .graphs import synth_graph

        return synth_graph(spec, n_train, n_test, seed) + (False,)
    if spec.task == "seq_tagging":
        return synth_seq_tagging(spec, n_train, n_test, seed) + (False,)
    if spec.task == "span_extraction":
        return synth_span_extraction(spec, n_train, n_test, seed) + (False,)
    if spec.name == "fednlp_seq2seq":
        return synth_seq2seq(spec, n_train, n_test, seed) + (False,)
    if spec.task == "detection":
        return synth_detection(spec, n_train, n_test, seed) + (False,)
    if spec.task == "regression":
        return synth_regression(spec, n_train, n_test, seed) + (False,)
    if spec.task == "classification":
        return synth_classification(spec, n_train, n_test, seed) + (False,)
    if spec.task == "tagpred":
        return synth_tagpred(spec, n_train, n_test, seed) + (False,)
    if spec.task == "segmentation":
        return synth_segmentation(spec, n_train, n_test, seed) + (False,)
    return synth_nwp(spec, n_train, n_test, seed) + (False,)
