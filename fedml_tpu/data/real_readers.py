"""Real on-disk readers: stackoverflow lr/nwp, ImageNet folders, Landmarks.

reference dispatch keys (``python/fedml/data/data_loader.py:30-330``):
``stackoverflow_lr`` / ``stackoverflow_nwp`` (TFF h5 +
``stackoverflow.word_count`` / ``stackoverflow.tag_count`` vocab files,
``data/stackoverflow_nwp/dataset.py`` + ``utils.py``), ``ILSVRC2012``
(ImageFolder layout, clients = class ranges — ``data/ImageNet/datasets.py:
28-56`` ``make_dataset``), ``gld23k``/``gld160k`` (csv user→image→class
mapping + image dir — ``data/Landmarks/data_loader.py:121-133``).

Same contract as ``leaf.py``/``tff_h5.py``: each ``try_load_*`` returns
``(client_xs, client_ys, test_x, test_y)`` with a NATURAL per-client
partition when the files are staged under ``data_cache_dir``, else ``None``
(synthetic fallback takes over). No downloads ever happen here.
"""

from __future__ import annotations

import csv
import json
import logging
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_EXAMPLE = "examples"

SO_TRAIN = "stackoverflow_train.h5"
SO_TEST = "stackoverflow_test.h5"
SO_WORD_COUNT = "stackoverflow.word_count"
SO_TAG_COUNT = "stackoverflow.tag_count"


def _find(cache_dir: str, name: str, subs: Tuple[str, ...]) -> Optional[str]:
    for sub in ("",) + subs:
        p = os.path.join(cache_dir, sub, name)
        if os.path.exists(p):
            return p
    return None


# ---------------------------------------------------------------------------
# stackoverflow vocab (reference: stackoverflow_nwp/utils.py:19-50)
# ---------------------------------------------------------------------------


def _load_word_dict(path: str, vocab_size: int) -> Dict[str, int]:
    """pad(0) + most-frequent words + bos + eos — ids match the reference's
    ``get_word_dict`` ordering. Reads at most ``vocab_size`` words (the
    reference hard-crashes on shorter files; we take what's there)."""
    words = []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if parts:
                words.append(parts[0])
            if len(words) >= vocab_size:
                break
    d = {"<pad>": 0}
    for w in words:
        d[w] = len(d)
    d["<bos>"] = len(d)
    d["<eos>"] = len(d)
    return d


def try_load_stackoverflow_nwp(cache_dir: str, seq_len: int = 20,
                               vocab_size: int = 10000):
    """Next-word prediction: h5 ``examples/<client>/tokens`` sentences →
    [bos] + ids (+eos) padded rows; x = row[:-1], y = row[1:] (reference
    ``dataset.py.__getitem__``). OOV = one hash bucket past eos."""
    subs = ("stackoverflow", "stackoverflow_nwp")
    train = _find(cache_dir, SO_TRAIN, subs)
    test = _find(cache_dir, SO_TEST, subs)
    wc = _find(cache_dir, SO_WORD_COUNT, subs)
    if train is None or test is None or wc is None:
        return None
    import h5py

    word_dict = _load_word_dict(wc, vocab_size)
    bos, eos, oov = word_dict["<bos>"], word_dict["<eos>"], len(word_dict)

    def encode(sentence: str) -> np.ndarray:
        toks = sentence.split(" ")[:seq_len]
        ids = [word_dict.get(t, oov) for t in toks]
        if len(ids) < seq_len:
            ids = ids + [eos]
        ids = [bos] + ids
        ids += [0] * (seq_len + 1 - len(ids))
        return np.asarray(ids[: seq_len + 1], np.int32)

    def load_split(path):
        xs, ys = [], []
        with h5py.File(path, "r") as h5:
            for cid in sorted(h5[_EXAMPLE].keys()):
                rows = [
                    encode(s.decode("utf-8", errors="ignore")
                           if isinstance(s, bytes) else str(s))
                    for s in h5[_EXAMPLE][cid]["tokens"][()]
                ]
                if rows:
                    arr = np.stack(rows)
                    xs.append(arr[:, :-1])
                    ys.append(arr[:, 1:])
        return xs, ys

    client_xs, client_ys = load_split(train)
    if not client_xs:
        return None
    txs, tys = load_split(test)
    test_x = np.concatenate(txs) if txs else client_xs[0][:0]
    test_y = np.concatenate(tys) if tys else client_ys[0][:0]
    logger.info("stackoverflow_nwp: %d clients, %d test rows from %s",
                len(client_xs), len(test_x), train)
    return client_xs, client_ys, test_x, test_y


def try_load_stackoverflow_lr(cache_dir: str, vocab_size: int = 10000,
                              tag_size: int = 500):
    """Tag prediction: bag-of-words inputs (mean one-hot over the vocab,
    OOV dropped — reference ``preprocess_inputs`` slices ``[:vocab_size]``)
    and multi-hot tag targets over the ``tag_count`` JSON's top tags."""
    subs = ("stackoverflow", "stackoverflow_lr")
    train = _find(cache_dir, SO_TRAIN, subs)
    test = _find(cache_dir, SO_TEST, subs)
    wc = _find(cache_dir, SO_WORD_COUNT, subs)
    tc = _find(cache_dir, SO_TAG_COUNT, subs)
    if train is None or test is None or wc is None or tc is None:
        return None
    import h5py

    word_dict = _load_word_dict(wc, vocab_size)
    # BoW ids are the plain frequent-word ranks — the lr-side ``get_word_dict``
    # (stackoverflow_lr/utils.py) has no pad/bos/eos specials
    vocab = {w: i for i, w in enumerate(
        w for w in word_dict if w not in ("<pad>", "<bos>", "<eos>")
    )}
    with open(tc) as f:
        tags = list(json.load(f).keys())[:tag_size]
    tag_dict = {t: i for i, t in enumerate(tags)}
    V, T = len(vocab), len(tag_dict)

    def bow(sentence: str) -> np.ndarray:
        toks = sentence.split(" ")
        out = np.zeros((V,), np.float32)
        hits = 0
        for t in toks:
            i = vocab.get(t)
            if i is not None:
                out[i] += 1.0
            hits += 1
        return out / max(hits, 1)

    def multihot(tagline: str) -> np.ndarray:
        out = np.zeros((T,), np.float32)
        for t in tagline.split("|"):
            i = tag_dict.get(t)
            if i is not None:
                out[i] = 1.0
        return out

    def _s(v) -> str:
        return v.decode("utf-8", errors="ignore") if isinstance(v, bytes) else str(v)

    def load_split(path):
        xs, ys = [], []
        with h5py.File(path, "r") as h5:
            for cid in sorted(h5[_EXAMPLE].keys()):
                g = h5[_EXAMPLE][cid]
                toks = g["tokens"][()]
                if "title" in g:
                    # reference joins tokens + " " + title per sample
                    # (stackoverflow_lr/dataset.py:64-67) — the title's words
                    # count toward both the BoW mass and the token count
                    titles = g["title"][()]
                    if len(titles) != len(toks):
                        raise ValueError(
                            f"stackoverflow_lr client {cid}: "
                            f"{len(toks)} tokens vs {len(titles)} titles "
                            f"(corrupt h5 — features would misalign with tags)"
                        )
                    sents = [" ".join([_s(s), _s(t)])
                             for s, t in zip(toks, titles)]
                else:
                    sents = [_s(s) for s in toks]
                sx = [bow(s) for s in sents]
                sy = [multihot(_s(t)) for t in g["tags"][()]]
                if sx:
                    xs.append(np.stack(sx))
                    ys.append(np.stack(sy))
        return xs, ys

    client_xs, client_ys = load_split(train)
    if not client_xs:
        return None
    txs, tys = load_split(test)
    test_x = np.concatenate(txs) if txs else client_xs[0][:0]
    test_y = np.concatenate(tys) if tys else client_ys[0][:0]
    logger.info("stackoverflow_lr: %d clients (V=%d, T=%d) from %s",
                len(client_xs), V, T, train)
    return client_xs, client_ys, test_x, test_y


# ---------------------------------------------------------------------------
# image folders (ImageNet) and csv-mapped images (Landmarks)
# ---------------------------------------------------------------------------

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif")


def _read_image(path: str, hw: Tuple[int, int]) -> np.ndarray:
    from PIL import Image

    with Image.open(path) as im:
        im = im.convert("RGB").resize((hw[1], hw[0]))
        return np.asarray(im, np.float32) / 255.0


def try_load_imagenet(cache_dir: str, image_hw: Tuple[int, int] = (224, 224),
                      max_per_client: int = 256, max_test: int = 10_000):
    """ImageFolder layout ``<root>/train/<class>/*`` + ``<root>/val/...``;
    natural partition = one client per class directory (the reference's
    ``net_dataidx_map`` is exactly the per-class index ranges).

    Decoding is bounded (``max_per_client`` images per class,
    ``max_test`` total val images): the packed [clients, cap, H, W, 3]
    float32 layout cannot hold full ILSVRC2012 (~770 GB) — a full-scale run
    needs the host-streaming path, not this eager reader. Bounds hit are
    logged, never silent."""
    root = None
    for sub in ("ILSVRC2012", "imagenet", "ImageNet"):
        p = os.path.join(cache_dir, sub)
        if os.path.isdir(os.path.join(p, "train")):
            root = p
            break
    if root is None:
        return None

    def class_dirs(split):
        d = os.path.join(root, split)
        if not os.path.isdir(d):
            return []
        return sorted(
            c for c in os.listdir(d) if os.path.isdir(os.path.join(d, c))
        )

    classes = class_dirs("train")
    if not classes:
        return None
    class_to_idx = {c: i for i, c in enumerate(classes)}

    def load_split(split, per_dir) -> Tuple[List[np.ndarray], List[np.ndarray]]:
        xs, ys = [], []
        truncated = 0
        for c in class_dirs(split):
            d = os.path.join(root, split, c)
            files = sorted(
                f for f in os.listdir(d)
                if f.lower().endswith(IMG_EXTENSIONS)
            )
            if len(files) > per_dir:
                truncated += 1
                files = files[:per_dir]
            imgs = [_read_image(os.path.join(d, f), image_hw) for f in files]
            if imgs:
                xs.append(np.stack(imgs))
                ys.append(np.full((len(imgs),), class_to_idx[c], np.int32))
        if truncated:
            logger.warning(
                "ILSVRC2012 %s: truncated %d class dirs to %d images each "
                "(packed-layout bound; full-scale runs need host streaming)",
                split, truncated, per_dir,
            )
        return xs, ys

    client_xs, client_ys = load_split("train", max_per_client)
    if not client_xs:
        return None
    n_val_classes = max(len(class_dirs("val")), 1)
    txs, tys = load_split("val", max(max_test // n_val_classes, 1))
    test_x = np.concatenate(txs) if txs else client_xs[0][:0]
    test_y = np.concatenate(tys) if tys else client_ys[0][:0]
    logger.info("ILSVRC2012: %d class-clients, %d val images from %s",
                len(client_xs), len(test_x), root)
    return client_xs, client_ys, test_x, test_y


def try_load_landmarks(cache_dir: str, name: str = "gld23k",
                       image_hw: Tuple[int, int] = (224, 224),
                       max_per_client: int = 256, max_test: int = 10_000):
    """Google Landmarks federated split: ``data_user_dict/
    <name>_user_dict_train.csv`` rows ``user_id,image_id,class`` + an image
    dir; natural partition = one client per user_id (reference
    ``get_mapping_per_user``). Decoding bounded like
    :func:`try_load_imagenet` (logged, never silent)."""
    mapping_dir = None
    for sub in ("", "gld", "landmarks"):
        p = os.path.join(cache_dir, sub, "data_user_dict")
        if os.path.isdir(p):
            mapping_dir = p
            break
    if mapping_dir is None:
        return None
    train_csv = os.path.join(mapping_dir, f"{name}_user_dict_train.csv")
    test_csv = os.path.join(mapping_dir, f"{name}_user_dict_test.csv")
    if not os.path.exists(train_csv):
        return None
    base = os.path.dirname(mapping_dir)
    img_dir = None
    for cand in ("images", "image", "."):
        p = os.path.join(base, cand)
        if os.path.isdir(p):
            img_dir = p
            break
    if img_dir is None:
        return None

    def find_image(image_id: str) -> Optional[str]:
        for ext in ("",) + IMG_EXTENSIONS:
            p = os.path.join(img_dir, image_id + ext)
            if os.path.isfile(p):
                return p
        return None

    def read_rows(path):
        with open(path, newline="") as f:
            return list(csv.DictReader(f))

    per_user: Dict[str, List[Tuple[str, int]]] = {}
    for row in read_rows(train_csv):
        p = find_image(row["image_id"])
        if p is not None:
            per_user.setdefault(row["user_id"], []).append(
                (p, int(row["class"]))
            )
    if not per_user:
        return None
    client_xs, client_ys = [], []
    truncated = 0
    for uid in sorted(per_user):
        pairs = per_user[uid]
        if len(pairs) > max_per_client:
            truncated += 1
            pairs = pairs[:max_per_client]
        client_xs.append(np.stack([_read_image(p, image_hw) for p, _ in pairs]))
        client_ys.append(np.asarray([c for _, c in pairs], np.int32))
    if truncated:
        logger.warning(
            "%s: truncated %d users to %d images each (packed-layout bound)",
            name, truncated, max_per_client,
        )

    txs, tys = [], []
    if os.path.exists(test_csv):
        for row in read_rows(test_csv):
            if len(txs) >= max_test:
                logger.warning("%s: test set capped at %d images", name,
                               max_test)
                break
            p = find_image(row["image_id"])
            if p is not None:
                txs.append(_read_image(p, image_hw))
                tys.append(int(row["class"]))
    test_x = np.stack(txs) if txs else client_xs[0][:0]
    test_y = np.asarray(tys, np.int32) if tys else client_ys[0][:0]
    logger.info("%s: %d user-clients, %d test images from %s",
                name, len(client_xs), len(test_x), base)
    return client_xs, client_ys, test_x, test_y


# ---------------------------------------------------------------------------
# COCO-format detection (reference: python/app/fedcv/object_detection — the
# YOLOv5 task trains from COCO-layout datasets, data/coco128.yaml +
# coco128/{images,labels}; the canonical interchange format is the
# annotations-JSON + images-dir pair read here)
# ---------------------------------------------------------------------------


def _coco_dense_target(boxes, cats, src_hw, out_hw, num_classes, stride=4):
    """Encode COCO boxes ([x, y, w, h] in source pixels) as the dense
    CenterNet-style grid ``models/detection.py`` trains on: per-cell one-hot
    class heatmap ++ normalized (h, w) ++ center mask — the SAME layout
    ``datasets.synth_detection`` emits, so loss/eval/decode are shared."""
    H, W = out_hw
    Hs, Ws = H // stride, W // stride
    y = np.zeros((Hs, Ws, num_classes + 3), np.float32)
    sh, sw = H / max(src_hw[0], 1), W / max(src_hw[1], 1)
    for (bx, by, bw, bh), c in zip(boxes, cats):
        if not 0 <= c < num_classes:
            continue
        cy = int((by + bh / 2) * sh) // stride
        cx = int((bx + bw / 2) * sw) // stride
        cy = min(max(cy, 0), Hs - 1)
        cx = min(max(cx, 0), Ws - 1)
        y[cy, cx, :num_classes] = 0.0
        y[cy, cx, c] = 1.0
        y[cy, cx, num_classes:num_classes + 2] = (bh * sh / H, bw * sw / W)
        y[cy, cx, -1] = 1.0
    return y


def try_load_coco_detection(cache_dir: str,
                            image_hw: Tuple[int, int] = (224, 224),
                            num_classes: int = 6,
                            max_per_client: int = 128,
                            max_test: int = 512):
    """COCO-format detection: ``annotations/instances_*.json`` + image dirs.

    Layout searched under ``cache_dir/{coco,coco128,COCO}``: the standard
    ``annotations/instances_train*.json`` (+ ``instances_val*.json``), with
    each image's ``file_name`` resolved against the split dir, ``images/``,
    or the root. Category ids (sparse in COCO) map to contiguous classes in
    sorted order; boxes beyond ``num_classes`` categories are skipped
    (logged). Natural partition: one client per DOMINANT category of the
    image — detection's analog of the ImageNet reader's class-clients (the
    reference partitions COCO across clients by label distribution too).
    Targets are dense stride-4 grids (:func:`_coco_dense_target`)."""
    root = None
    for sub in ("coco", "coco128", "COCO"):
        p = os.path.join(cache_dir, sub)
        if os.path.isdir(os.path.join(p, "annotations")):
            root = p
            break
    if root is None:
        return None
    ann_dir = os.path.join(root, "annotations")

    def find_ann(kind):
        cands = sorted(
            f for f in os.listdir(ann_dir)
            if f.startswith(f"instances_{kind}") and f.endswith(".json")
        )
        return os.path.join(ann_dir, cands[0]) if cands else None

    train_json = find_ann("train")
    if train_json is None:
        return None

    def load_split(path, bound, what):
        with open(path) as f:
            blob = json.load(f)
        cat_ids = sorted(c["id"] for c in blob.get("categories", []))
        cat_map = {cid: i for i, cid in enumerate(cat_ids)}
        skipped = sum(1 for cid in cat_ids if cat_map[cid] >= num_classes)
        if skipped:
            logger.warning(
                "coco %s: %d categories beyond num_classes=%d skipped",
                what, skipped, num_classes,
            )
        per_img: Dict[int, Dict] = {
            im["id"]: {"meta": im, "boxes": [], "cats": []}
            for im in blob.get("images", [])
        }
        for a in blob.get("annotations", []):
            rec = per_img.get(a.get("image_id"))
            c = cat_map.get(a.get("category_id"), -1)
            if rec is not None and 0 <= c < num_classes:
                rec["boxes"].append([float(v) for v in a["bbox"]])
                rec["cats"].append(c)
        split_dir = os.path.splitext(os.path.basename(path))[0].replace(
            "instances_", ""
        )
        xs, ys, dom = [], [], []
        n_boxes = 0
        for rec in per_img.values():
            if len(xs) >= bound:
                logger.warning("coco %s: capped at %d images", what, bound)
                break
            if not rec["boxes"]:
                continue  # unannotated images train nothing here
            img_path = None
            for sub in (split_dir, "images", "."):
                p = os.path.join(root, sub, rec["meta"]["file_name"])
                if os.path.isfile(p):
                    img_path = p
                    break
            if img_path is None:
                continue
            src_hw = (int(rec["meta"].get("height", 0) or 0),
                      int(rec["meta"].get("width", 0) or 0))
            if src_hw[0] <= 0 or src_hw[1] <= 0:
                from PIL import Image

                with Image.open(img_path) as im:
                    src_hw = (im.height, im.width)
            xs.append(_read_image(img_path, image_hw))
            ys.append(_coco_dense_target(rec["boxes"], rec["cats"], src_hw,
                                         image_hw, num_classes))
            dom.append(int(np.bincount(rec["cats"]).argmax()))
            n_boxes += len(rec["boxes"])
        logger.info("coco %s: %d images, %d boxes", what, len(xs), n_boxes)
        return xs, ys, dom

    xs, ys, dom = load_split(train_json, max_per_client * num_classes,
                             "train")
    if not xs:
        return None
    client_xs, client_ys = [], []
    for c in sorted(set(dom)):
        idx = [i for i, d in enumerate(dom) if d == c][:max_per_client]
        client_xs.append(np.stack([xs[i] for i in idx]))
        client_ys.append(np.stack([ys[i] for i in idx]))

    val_json = find_ann("val")
    if val_json is not None:
        txs, tys, _ = load_split(val_json, max_test, "val")
    else:
        txs, tys = [], []
    test_x = np.stack(txs) if txs else client_xs[0][:0]
    test_y = np.stack(tys) if tys else client_ys[0][:0]
    logger.info("coco: %d dominant-category clients, %d val images from %s",
                len(client_xs), len(test_x), root)
    return client_xs, client_ys, test_x, test_y
