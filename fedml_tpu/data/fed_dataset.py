"""Federated dataset container with a TPU-native packed layout.

The reference's ``fedml.data.load`` (``python/fedml/data/data_loader.py:30-330``)
returns an 8-tuple of torch DataLoader dicts keyed by client index. A dict of
ragged per-client loaders cannot live in HBM or under ``jit``; here the whole
federation is three dense arrays —

    train_x      [clients, cap, ...]   per-client samples, zero-padded
    train_y      [clients, cap, ...]
    train_counts [clients]             true sample counts (mask = iota < count)

— so a round's cohort is a gather over the leading axis, local training is
``vmap`` over it, and the same arrays shard directly over a ``clients`` mesh
axis (SURVEY.md §7 "Heterogeneous per-client data residency").
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

import numpy as np


@dataclass
class FedDataset:
    """Packed federated dataset.

    ``task`` ∈ {"classification", "nwp", "tagpred"} selects loss/metric
    semantics downstream (reference analog: create_model_trainer dispatch,
    ``ml/trainer/trainer_creator.py:6-13``).
    """

    train_x: np.ndarray
    train_y: np.ndarray
    train_counts: np.ndarray
    test_x: np.ndarray
    test_y: np.ndarray
    class_num: int
    task: str = "classification"
    # Optional per-client test shards (reference keeps test_data_local_dict);
    # global test set above is what the headline metrics use.
    test_local_x: Optional[np.ndarray] = None
    test_local_y: Optional[np.ndarray] = None
    test_local_counts: Optional[np.ndarray] = None
    # vocab etc. for text tasks
    meta: Dict = field(default_factory=dict)

    @property
    def client_num(self) -> int:
        return int(self.train_x.shape[0])

    @property
    def cap(self) -> int:
        """Per-client sample capacity (padded length)."""
        return int(self.train_x.shape[1])

    @property
    def train_data_num(self) -> int:
        return int(self.train_counts.sum())

    @property
    def test_data_num(self) -> int:
        return int(self.test_x.shape[0])

    def client_shard(self, idx: int) -> Tuple[np.ndarray, np.ndarray, int]:
        return self.train_x[idx], self.train_y[idx], int(self.train_counts[idx])

    def as_reference_tuple(self):
        """The reference's 8-tuple shape (data_loader.py:318-330), with arrays
        in place of DataLoaders, for users migrating call sites."""
        train_data_local_dict = {
            i: (self.train_x[i], self.train_y[i]) for i in range(self.client_num)
        }
        train_data_local_num_dict = {
            i: int(self.train_counts[i]) for i in range(self.client_num)
        }
        test_data_local_dict = (
            {
                i: (self.test_local_x[i], self.test_local_y[i])
                for i in range(self.client_num)
            }
            if self.test_local_x is not None
            else {}
        )
        return (
            self.train_data_num,
            self.test_data_num,
            (self.train_x.reshape((-1,) + self.train_x.shape[2:]), None),
            (self.test_x, self.test_y),
            train_data_local_num_dict,
            train_data_local_dict,
            test_data_local_dict,
            self.class_num,
        )


def pad_cap_to_batch_multiple(ds: FedDataset, batch_size: int) -> FedDataset:
    """Grow the packed capacity to a multiple of ``batch_size`` so the training
    loop's batch grid is exact (static shapes; masked tails)."""
    cap = ds.cap
    new_cap = int(-(-cap // batch_size) * batch_size)
    if new_cap == cap:
        return ds
    pad = [(0, 0), (0, new_cap - cap)] + [(0, 0)] * (ds.train_x.ndim - 2)
    ds.train_x = np.pad(ds.train_x, pad)
    pad_y = [(0, 0), (0, new_cap - cap)] + [(0, 0)] * (ds.train_y.ndim - 2)
    ds.train_y = np.pad(ds.train_y, pad_y)
    return ds
