"""LEAF-format JSON readers: femnist and shakespeare.

reference: ``python/fedml/data/data_loader.py:30-330`` dispatches "femnist" /
"shakespeare" to per-dataset loaders that read the LEAF benchmark's JSON
shards (``data/fed_shakespeare/``, ``data/FederatedEMNIST/``): each file under
``<root>/train`` / ``<root>/test`` holds ``{"users": [...], "user_data":
{user: {"x": [...], "y": [...]}}, "num_samples": [...]}``. The reference's
char table is ``utils/language_utils.py`` ``ALL_LETTERS`` (80 printable
chars); chars encode to ``index + 1`` with 0 reserved for padding, matching
the registry's vocab of 90 (embedding headroom, reference
``model/nlp/rnn.py`` embeds 90).

Readers return NATURAL per-user partitions — LEAF's whole point is that the
federation's non-IID-ness comes from real authorship, not a synthetic
Dirichlet split.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

# LEAF's 80-char table (utils/language_utils.py ALL_LETTERS), order preserved
ALL_LETTERS = (
    "\n !\"&'(),-.0123456789:;>?ABCDEFGHIJKLMNOPQRSTUVWXYZ"
    "[]abcdefghijklmnopqrstuvwxyz}"
)
_CHAR_TO_ID = {c: i + 1 for i, c in enumerate(ALL_LETTERS)}  # 0 = pad/unknown


def encode_chars(s: str, length: int) -> np.ndarray:
    ids = [_CHAR_TO_ID.get(c, 0) for c in s[:length]]
    ids += [0] * (length - len(ids))
    return np.asarray(ids, np.int32)


def _iter_leaf_json(split_dir: str):
    if not os.path.isdir(split_dir):
        return
    for name in sorted(os.listdir(split_dir)):
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(split_dir, name)) as f:
                yield json.load(f)
        except (OSError, ValueError) as e:
            logger.warning("leaf: skipping unreadable %s (%s)", name, e)


def _leaf_root(cache_dir: str, names: Tuple[str, ...]) -> Optional[str]:
    for sub in names:
        root = os.path.join(cache_dir, sub)
        if os.path.isdir(os.path.join(root, "train")):
            return root
    return None


def try_load_leaf_femnist(cache_dir: str):
    """FEMNIST: x = flat 784 grayscale pixels, y = class 0..61.

    Returns ``(client_xs, client_ys, test_x, test_y)`` with natural per-user
    train partitions, or None when no LEAF files are staged.
    """
    root = _leaf_root(cache_dir, ("femnist", "FederatedEMNIST", "fed_emnist"))
    if root is None:
        return None
    client_xs: List[np.ndarray] = []
    client_ys: List[np.ndarray] = []
    for blob in _iter_leaf_json(os.path.join(root, "train")):
        for user in blob.get("users", []):
            ud = blob["user_data"][user]
            x = np.asarray(ud["x"], np.float32).reshape(-1, 28, 28, 1)
            y = np.asarray(ud["y"], np.int32)
            if len(x):
                client_xs.append(x)
                client_ys.append(y)
    if not client_xs:
        return None
    tx, ty = [], []
    for blob in _iter_leaf_json(os.path.join(root, "test")):
        for user in blob.get("users", []):
            ud = blob["user_data"][user]
            tx.append(np.asarray(ud["x"], np.float32).reshape(-1, 28, 28, 1))
            ty.append(np.asarray(ud["y"], np.int32))
    test_x = np.concatenate(tx) if tx else client_xs[0][:0]
    test_y = np.concatenate(ty) if ty else client_ys[0][:0]
    logger.info(
        "femnist: %d LEAF users, %d test samples from %s",
        len(client_xs), len(test_y), root,
    )
    return client_xs, client_ys, test_x, test_y


def try_load_leaf_shakespeare(cache_dir: str, seq_len: int = 80):
    """Shakespeare: x = 80-char window, y = next char.

    Per-position NWP targets are built by shifting the window and appending
    LEAF's next-char label — strictly more supervision than final-char-only,
    and the shape the nwp loss expects.
    """
    root = _leaf_root(cache_dir, ("shakespeare", "fed_shakespeare"))
    if root is None:
        return None

    def load_split(split: str):
        xs: List[np.ndarray] = []
        ys: List[np.ndarray] = []
        for blob in _iter_leaf_json(os.path.join(root, split)):
            for user in blob.get("users", []):
                ud = blob["user_data"][user]
                raw_x, raw_y = ud["x"], ud["y"]
                if not raw_x:
                    continue
                ux = np.stack([encode_chars(s, seq_len) for s in raw_x])
                nxt = np.asarray(
                    [_CHAR_TO_ID.get((s or "\0")[0], 0) for s in raw_y],
                    np.int32,
                )
                uy = np.zeros_like(ux)
                uy[:, :-1] = ux[:, 1:]
                uy[:, -1] = nxt
                xs.append(ux)
                ys.append(uy)
        return xs, ys

    client_xs, client_ys = load_split("train")
    if not client_xs:
        return None
    test_xs, test_ys = load_split("test")
    test_x = (
        np.concatenate(test_xs) if test_xs else client_xs[0][:0]
    )
    test_y = (
        np.concatenate(test_ys) if test_ys else client_ys[0][:0]
    )
    logger.info(
        "shakespeare: %d LEAF users, %d test samples from %s",
        len(client_xs), len(test_x), root,
    )
    return client_xs, client_ys, test_x, test_y
