"""Google-TFF-derived h5 readers: fed_cifar100 and fed_shakespeare.

reference: ``data/fed_cifar100/data_loader.py`` (h5 ``examples/<client>/image``
uint8 [n,32,32,3] + ``label``) and ``data/fed_shakespeare/data_loader.py`` +
``utils.py`` (h5 ``examples/<client>/snippets`` byte strings; TFF's 86-char
vocab with pad/bos/eos, 80-char windows, per-position NWP targets).

Readers return NATURAL per-client partitions (same contract as the LEAF
readers in ``leaf.py``) and activate only when the h5 files are staged under
``data_cache_dir`` — no downloads.
"""

from __future__ import annotations

import logging
import os
from typing import List, Optional

import numpy as np

logger = logging.getLogger(__name__)

_EXAMPLE = "examples"

# TFF shakespeare vocab (reference data/fed_shakespeare/utils.py CHAR_VOCAB):
# ids: 0 = pad, 1..86 chars, 87 = bos, 88 = eos — 89 total, matching the
# registry's embedding size of 90
CHAR_VOCAB = list(
    "dhlptx@DHLPTX $(,048cgkoswCGKOSW[_#'/37;?bfjnrvzBFJNRVZ\"&*.26:"
    "\naeimquyAEIMQUY]!%)-159\r"
)
_CHAR_TO_ID = {c: i + 1 for i, c in enumerate(CHAR_VOCAB)}
BOS_ID = len(CHAR_VOCAB) + 1
EOS_ID = len(CHAR_VOCAB) + 2
SEQ_LEN = 80


def _find(cache_dir: str, names: List[str]) -> Optional[str]:
    for name in names:
        for sub in ("", "fed_cifar100", "fed_shakespeare"):
            p = os.path.join(cache_dir, sub, name)
            if os.path.exists(p):
                return p
    return None


def try_load_fed_cifar100(cache_dir: str):
    """-> (client_xs, client_ys, test_x, test_y) or None."""
    train_path = _find(cache_dir, ["fed_cifar100_train.h5"])
    test_path = _find(cache_dir, ["fed_cifar100_test.h5"])
    if train_path is None or test_path is None:
        return None
    import h5py

    client_xs, client_ys = [], []
    with h5py.File(train_path, "r") as h5:
        for cid in sorted(h5[_EXAMPLE].keys()):
            g = h5[_EXAMPLE][cid]
            x = np.asarray(g["image"][()], np.float32) / 255.0
            y = np.asarray(g["label"][()], np.int32)
            if len(x):
                client_xs.append(x)
                client_ys.append(y)
    if not client_xs:
        return None
    txs, tys = [], []
    with h5py.File(test_path, "r") as h5:
        for cid in sorted(h5[_EXAMPLE].keys()):
            g = h5[_EXAMPLE][cid]
            txs.append(np.asarray(g["image"][()], np.float32) / 255.0)
            tys.append(np.asarray(g["label"][()], np.int32))
    test_x = np.concatenate(txs) if txs else client_xs[0][:0]
    test_y = np.concatenate(tys) if tys else client_ys[0][:0]
    logger.info(
        "fed_cifar100: %d TFF clients, %d test samples from %s",
        len(client_xs), len(test_y), train_path,
    )
    return client_xs, client_ys, test_x, test_y


def encode_snippet(text) -> np.ndarray:
    """bos + chars + eos, split into SEQ_LEN windows with per-position
    next-char targets (TFF preprocessing: to_ids → split → batch)."""
    if isinstance(text, bytes):
        text = text.decode("utf-8", errors="ignore")
    ids = [BOS_ID] + [_CHAR_TO_ID.get(c, 0) for c in text] + [EOS_ID]
    return np.asarray(ids, np.int32)


def try_load_fed_shakespeare(cache_dir: str):
    """-> (client_xs, client_ys, test_x, test_y) or None."""
    train_path = _find(cache_dir, ["shakespeare_train.h5"])
    test_path = _find(cache_dir, ["shakespeare_test.h5"])
    if train_path is None or test_path is None:
        return None
    import h5py

    def load_split(path):
        xs, ys = [], []
        with h5py.File(path, "r") as h5:
            for cid in sorted(h5[_EXAMPLE].keys()):
                stream: List[int] = []
                for snip in h5[_EXAMPLE][cid]["snippets"][()]:
                    stream.extend(encode_snippet(snip).tolist())
                if len(stream) < 2:
                    continue
                arr = np.asarray(stream, np.int32)
                n_win = max((len(arr) - 1) // SEQ_LEN, 1)
                need = n_win * SEQ_LEN + 1
                if len(arr) < need:
                    arr = np.pad(arr, (0, need - len(arr)))
                x = arr[: n_win * SEQ_LEN].reshape(n_win, SEQ_LEN)
                y = arr[1: n_win * SEQ_LEN + 1].reshape(n_win, SEQ_LEN)
                xs.append(x)
                ys.append(y)
        return xs, ys

    client_xs, client_ys = load_split(train_path)
    if not client_xs:
        return None
    txs, tys = load_split(test_path)
    test_x = np.concatenate(txs) if txs else client_xs[0][:0]
    test_y = np.concatenate(tys) if tys else client_ys[0][:0]
    logger.info(
        "fed_shakespeare: %d TFF clients, %d test windows from %s",
        len(client_xs), len(test_x), train_path,
    )
    return client_xs, client_ys, test_x, test_y
