"""Synthetic graph datasets for the FedGraphNN application family.

reference: ``python/app/fedgraphnn/`` stages MoleculeNet (graph clf/reg),
ego-network (node clf / link pred), social-network (graph clf) and recsys
(subgraph link pred) datasets through torch-geometric sparse loaders with
per-client natural splits.

TPU re-grounding: graphs are generated directly in the packed dense-block
layout the models consume (``models/gnn.py``: ``[N, F+N+1]`` = features,
dense adjacency, node mask), deterministic and *learnable* — labels are
planted in feature prototypes, homophilous edges, and structure — so the
"tiny-config real training" smoke pattern (SURVEY.md §4) holds for every
graph task without torch-geometric or downloads.
"""

from __future__ import annotations

import numpy as np


def _pack_np(feats: np.ndarray, adj: np.ndarray, mask: np.ndarray) -> np.ndarray:
    return np.concatenate([feats, adj, mask[..., None]], axis=-1)


def _random_masks(rng, n_graphs: int, n_nodes: int) -> np.ndarray:
    """Real node counts vary (padding realism): n_i ∈ [N/2, N]."""
    counts = rng.randint(n_nodes // 2, n_nodes + 1, size=n_graphs)
    mask = np.zeros((n_graphs, n_nodes), np.float32)
    for i, c in enumerate(counts):
        mask[i, :c] = 1.0
    return mask


def _er_adj(rng, mask: np.ndarray, p: np.ndarray) -> np.ndarray:
    """Symmetric Erdős–Rényi adjacency per graph; ``p`` broadcastable to
    [G, N, N]. Padding rows/cols zeroed."""
    g, n = mask.shape
    up = (rng.rand(g, n, n) < p).astype(np.float32)
    up = np.triu(up, 1)
    adj = up + np.swapaxes(up, -1, -2)
    pair = mask[:, :, None] * mask[:, None, :]
    return adj * pair


def synth_graph_clf(spec, n_train: int, n_test: int, seed: int):
    """Graph classification (MoleculeNet clf / social-network clf analog):
    class plants a feature prototype on every node AND an edge density."""
    rng = np.random.RandomState(seed)
    N, F, C = spec.n_nodes, spec.n_feats, spec.class_num
    protos = rng.randn(C, F).astype(np.float32)
    densities = np.linspace(0.1, 0.5, C)

    def make(n, rng):
        y = rng.randint(0, C, size=n).astype(np.int32)
        mask = _random_masks(rng, n, N)
        feats = (protos[y][:, None, :] * 0.6 +
                 rng.randn(n, N, F).astype(np.float32) * 0.8)
        feats *= mask[..., None]
        adj = _er_adj(rng, mask, densities[y][:, None, None])
        return _pack_np(feats, adj, mask), y

    tx, ty = make(n_train, rng)
    ex, ey = make(n_test, rng)
    return tx, ty, ex, ey


def synth_graph_reg(spec, n_train: int, n_test: int, seed: int):
    """Graph regression (MoleculeNet reg analog): target is a fixed linear
    functional of mean node features and mean degree."""
    rng = np.random.RandomState(seed)
    N, F = spec.n_nodes, spec.n_feats
    w = rng.randn(F).astype(np.float32)

    def make(n, rng):
        mask = _random_masks(rng, n, N)
        feats = rng.randn(n, N, F).astype(np.float32) * mask[..., None]
        dens = rng.rand(n).astype(np.float32) * 0.4 + 0.1
        adj = _er_adj(rng, mask, dens[:, None, None])
        nodes = np.maximum(mask.sum(-1), 1.0)
        mean_feat = feats.sum(1) / nodes[:, None]
        mean_deg = adj.sum((1, 2)) / nodes
        y = (mean_feat @ w + 0.5 * mean_deg).astype(np.float32)
        y += rng.randn(n).astype(np.float32) * 0.05
        return _pack_np(feats, adj, mask), y

    tx, ty = make(n_train, rng)
    ex, ey = make(n_test, rng)
    return tx, ty, ex, ey


def synth_node_clf(spec, n_train: int, n_test: int, seed: int):
    """Node classification (ego-network analog): homophilous communities —
    same-class nodes connect densely, features carry a noisy prototype.
    Labels are per-node ints, padding marked -1."""
    rng = np.random.RandomState(seed)
    N, F, C = spec.n_nodes, spec.n_feats, spec.class_num
    protos = rng.randn(C, F).astype(np.float32)

    def make(n, rng):
        mask = _random_masks(rng, n, N)
        node_y = rng.randint(0, C, size=(n, N)).astype(np.int32)
        feats = (protos[node_y] * 0.5 +
                 rng.randn(n, N, F).astype(np.float32) * 1.0)
        feats *= mask[..., None]
        same = (node_y[:, :, None] == node_y[:, None, :])
        p = np.where(same, 0.5, 0.04)
        adj = _er_adj(rng, mask, p)
        y = np.where(mask > 0, node_y, -1).astype(np.int32)
        return _pack_np(feats, adj, mask), y

    tx, ty = make(n_train, rng)
    ex, ey = make(n_test, rng)
    return tx, ty, ex, ey


def synth_link_pred(spec, n_train: int, n_test: int, seed: int):
    """Link prediction (ego / recsys-subgraph analog): community graphs;
    the model sees an adjacency with 30% of edges held out and must score
    the full one. Target y = ``[N, N+1]`` (full adjacency ++ node mask)."""
    rng = np.random.RandomState(seed)
    N, F = spec.n_nodes, spec.n_feats
    K = max(2, spec.class_num)
    protos = rng.randn(K, F).astype(np.float32)

    def make(n, rng):
        mask = _random_masks(rng, n, N)
        comm = rng.randint(0, K, size=(n, N))
        feats = (protos[comm] * 0.7 +
                 rng.randn(n, N, F).astype(np.float32) * 0.6)
        feats *= mask[..., None]
        same = comm[:, :, None] == comm[:, None, :]
        full = _er_adj(rng, mask, np.where(same, 0.6, 0.03))
        keep = np.triu((rng.rand(n, N, N) >= 0.3), 1)
        keep = keep + np.swapaxes(keep, -1, -2)
        visible = full * keep
        y = np.concatenate([full, mask[..., None]], axis=-1).astype(np.float32)
        return _pack_np(feats, visible, mask), y

    tx, ty = make(n_train, rng)
    ex, ey = make(n_test, rng)
    return tx, ty, ex, ey


SYNTH_BY_TASK = {
    "classification": synth_graph_clf,
    "regression": synth_graph_reg,
    "node_clf": synth_node_clf,
    "link_pred": synth_link_pred,
}


def synth_graph(spec, n_train: int, n_test: int, seed: int):
    return SYNTH_BY_TASK[spec.task](spec, n_train, n_test, seed)
