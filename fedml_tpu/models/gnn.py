"""Graph neural networks over padded dense blocks (FedGraphNN model zoo).

reference: ``python/app/fedgraphnn/`` — GCN/GAT/GraphSAGE with readout for
MoleculeNet graph classification/regression (``moleculenet_graph_clf/model/
gcn_readout.py``, ``gat_readout.py``), node classification on ego networks
(``ego_networks_node_clf/model/{gcn,gat,sage}.py``), and link prediction on
ego/recsys subgraphs. Those models run on torch-geometric-style sparse
edge lists with dynamic node counts.

TPU re-grounding: sparse gather/scatter over ragged edge lists is the worst
possible XLA program — dynamic shapes, serialized scatters, nothing on the
MXU. Molecule/ego graphs are SMALL (tens of nodes), so every graph is packed
into one fixed-shape dense block and message passing becomes batched
matmuls:

- sample = ``[N, F + N + 1]``: node features ``[:, :F]``, dense adjacency
  row ``[:, F:F+N]``, node-validity mask ``[:, -1]`` (padding rows are 0);
- one GCN layer for a whole batch is ``adj_hat @ h @ W`` — two MXU matmuls
  under ``vmap``, no scatter anywhere;
- attention (GAT) is a masked dense ``[N, N]`` softmax — cheap at these N.

The same packing rides every federated engine unchanged (vmap cohorts,
mesh sharding, DP, compression), because a graph client's shard is just
another ``[clients, cap, N, F+N+1]`` array.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn


# ---------------------------------------------------------------------------
# packing
# ---------------------------------------------------------------------------


def pack_graph(feats: jnp.ndarray, adj: jnp.ndarray,
               mask: jnp.ndarray) -> jnp.ndarray:
    """[..., N, F], [..., N, N], [..., N] → one [..., N, F+N+1] block."""
    return jnp.concatenate([feats, adj, mask[..., None]], axis=-1)


def unpack_graph(x: jnp.ndarray, n_feats: int
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Inverse of :func:`pack_graph`; N is read off the block shape."""
    n = x.shape[-2]
    feats = x[..., :n_feats]
    adj = x[..., n_feats:n_feats + n]
    mask = x[..., -1]
    return feats, adj, mask


def normalize_adj(adj: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Symmetric GCN normalization D^-1/2 (A+I) D^-1/2, padding-aware."""
    n = adj.shape[-1]
    eye = jnp.eye(n, dtype=adj.dtype)
    m = mask[..., :, None] * mask[..., None, :]
    a = (adj + eye) * m  # self-loops only on real nodes (mask zeroes pads)
    deg = a.sum(-1)
    inv_sqrt = jnp.where(deg > 0, jax.lax.rsqrt(jnp.maximum(deg, 1e-12)), 0.0)
    return a * inv_sqrt[..., :, None] * inv_sqrt[..., None, :]


def masked_mean_pool(h: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """[..., N, D], [..., N] → [..., D] over real nodes only."""
    s = (h * mask[..., None]).sum(-2)
    return s / jnp.maximum(mask.sum(-1)[..., None], 1.0)


# ---------------------------------------------------------------------------
# layers
# ---------------------------------------------------------------------------


class GCNLayer(nn.Module):
    """Kipf-Welling convolution: act(Â h W) (reference: gcn_readout.py)."""

    features: int

    @nn.compact
    def __call__(self, h, adj_hat, mask):
        h = nn.Dense(self.features, use_bias=True)(h)
        h = adj_hat @ h
        return nn.relu(h) * mask[..., None]


class SAGELayer(nn.Module):
    """GraphSAGE-mean: act(W_self h ++ W_neigh mean_nbr(h))
    (reference: ego_networks_node_clf/model/sage.py)."""

    features: int

    @nn.compact
    def __call__(self, h, adj_hat, mask):
        deg = jnp.maximum(adj_hat.sum(-1, keepdims=True), 1e-12)
        nbr = (adj_hat @ h) / deg
        out = nn.Dense(self.features)(h) + nn.Dense(self.features)(nbr)
        return nn.relu(out) * mask[..., None]


class GATLayer(nn.Module):
    """Single-head graph attention as a masked dense softmax
    (reference: gat_readout.py; dense is the TPU-shaped formulation)."""

    features: int

    @nn.compact
    def __call__(self, h, adj_hat, mask):
        n = h.shape[-2]
        hw = nn.Dense(self.features, use_bias=False)(h)
        a_src = nn.Dense(1, use_bias=False)(hw)[..., 0]   # [..., N]
        a_dst = nn.Dense(1, use_bias=False)(hw)[..., 0]
        logits = nn.leaky_relu(
            a_src[..., :, None] + a_dst[..., None, :], negative_slope=0.2
        )
        # attend only along real edges (adj_hat > 0 includes self-loops)
        connected = (adj_hat > 0).astype(h.dtype)
        logits = jnp.where(connected > 0, logits, -1e9)
        att = jax.nn.softmax(logits, axis=-1) * connected
        out = att @ hw
        return nn.elu(out) * mask[..., None]


_LAYERS = {"gcn": GCNLayer, "sage": SAGELayer, "gat": GATLayer}


class GraphEncoder(nn.Module):
    """Stacked message passing over a packed graph block."""

    n_feats: int
    hidden: Sequence[int] = (64, 64)
    conv: str = "gcn"

    @nn.compact
    def __call__(self, x):
        feats, adj, mask = unpack_graph(x, self.n_feats)
        adj_hat = normalize_adj(adj, mask)
        layer = _LAYERS[self.conv]
        h = feats
        for width in self.hidden:
            h = layer(width)(h, adj_hat, mask)
        return h, mask


# ---------------------------------------------------------------------------
# task heads (one per FedGraphNN application family)
# ---------------------------------------------------------------------------


class GraphClassifier(nn.Module):
    """Graph-level prediction: encode → masked-mean readout → MLP.

    ``num_outputs`` classes (moleculenet_graph_clf) or 1 regression target
    (moleculenet_graph_reg / social_networks_graph_clf analogs).
    """

    n_feats: int
    num_outputs: int
    hidden: Sequence[int] = (64, 64)
    conv: str = "gcn"

    @nn.compact
    def __call__(self, x, train: bool = False):
        h, mask = GraphEncoder(self.n_feats, self.hidden, self.conv)(x)
        pooled = masked_mean_pool(h, mask)
        pooled = nn.relu(nn.Dense(self.hidden[-1])(pooled))
        return nn.Dense(self.num_outputs)(pooled)


class NodeClassifier(nn.Module):
    """Per-node prediction (ego_networks_node_clf): logits [..., N, C]."""

    n_feats: int
    num_classes: int
    hidden: Sequence[int] = (64, 64)
    conv: str = "gcn"

    @nn.compact
    def __call__(self, x, train: bool = False):
        h, mask = GraphEncoder(self.n_feats, self.hidden, self.conv)(x)
        return nn.Dense(self.num_classes)(h) * mask[..., None]


class LinkPredictor(nn.Module):
    """Dot-product edge decoder (ego_networks_link_pred /
    recsys_subgraph_link_pred): score[i,j] = z_i · z_j, logits [..., N, N].

    Trained to reconstruct the adjacency (padding pairs masked by the loss);
    at serving time the scores rank held-out candidate edges.
    """

    n_feats: int
    embed_dim: int = 32
    hidden: Sequence[int] = (64,)
    conv: str = "gcn"

    @nn.compact
    def __call__(self, x, train: bool = False):
        h, mask = GraphEncoder(self.n_feats, self.hidden, self.conv)(x)
        z = nn.Dense(self.embed_dim)(h) * mask[..., None]
        return z @ jnp.swapaxes(z, -1, -2)
