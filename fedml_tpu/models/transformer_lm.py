"""The Cheetah transformer as a federated model-zoo citizen.

This is where the two product pillars meet: the flagship LLM
(``parallel/transformer.py``) packaged behind the same :class:`ModelBundle`
surface the FL planes consume, so the cross-silo FSM, aggregators, and eval
paths federate it like any zoo model — while its *local training* runs
sharded over each silo's mesh (``cross_silo/fedllm.py``).

reference: the Cheetah pillar is an empty stub (``python/fedml/distributed/``
holds one empty ``__init__.py``) and ``model/model_hub.py:20-83`` has no
transformer — federated LLM fine-tuning is exactly the capability gap this
module closes. The reference's closest seam is ``create`` dispatch keyed on
``args.model``; registering the flagship under ``model: "cheetah"`` keeps
that UX.
"""

from __future__ import annotations

import dataclasses
import logging

import jax
import jax.numpy as jnp

from ..parallel.sharding import unbox
from ..parallel.transformer import Transformer, TransformerConfig

logger = logging.getLogger(__name__)


class TransformerBundle:
    """ModelBundle-shaped adapter over :class:`parallel.Transformer`.

    Same duck-typed surface as :class:`models.ModelBundle` (``init`` /
    ``apply`` / ``task`` / ``input_shape``): ``init`` returns UNBOXED params
    (plain pytree — the FL planes flatten leaves onto the wire; partition
    metadata is re-derived from the module by whichever mesh trains it), and
    ``apply`` maps tokens [B, L] → logits [B, L, V] fp32, which is the
    ``nwp`` task contract (logits at position t predict the target y[t], the
    next token) — so ``ml/evaluate.make_eval_fn`` and ``ml/losses.nwp_loss``
    work unchanged.
    """

    def __init__(self, cfg: TransformerConfig, name: str = "cheetah"):
        self.cfg = cfg
        self.module = Transformer(cfg)
        self.name = name
        self.task = "nwp"
        self.input_shape = (cfg.max_seq_len,)
        self.input_dtype = jnp.int32
        self.meta = {"cfg": cfg}

    def dummy_input(self, batch_size: int = 2):
        return jnp.zeros((batch_size, 8), jnp.int32)

    def init(self, rng: jax.Array, batch_size: int = 2):
        variables = self.module.init(rng, self.dummy_input(batch_size))
        return {"params": unbox(variables["params"])}

    def apply(self, params, x, train: bool = False, rngs=None):
        return self.module.apply(
            {"params": params["params"]}, jnp.asarray(x, jnp.int32)
        )

    def param_count(self, params) -> int:
        return sum(int(p.size) for p in jax.tree.leaves(params))


def create_transformer_bundle(args, output_dim: int, spec=None) -> TransformerBundle:
    """Build the federated transformer for ``(args, dataset)``.

    Shape knobs ride the same YAML surface as the Cheetah runner
    (``cheetah/runner.py:config_from_args`` — model_size / d_model / ... /
    moe_* / attn_*); the DATASET owns the token space, so its vocab and
    window length override the config's (an nwp dataset's ``output_dim`` is
    its vocab).
    """
    from ..cheetah.runner import config_from_args

    cfg = config_from_args(args)
    vocab = int(getattr(spec, "vocab_size", 0) or 0) or int(output_dim)
    seq_len = int(getattr(spec, "seq_len", 0) or 0) or cfg.max_seq_len
    cfg = dataclasses.replace(cfg, vocab_size=vocab, max_seq_len=seq_len)
    logger.info(
        "transformer_lm: vocab=%d seq_len=%d d_model=%d layers=%d",
        cfg.vocab_size, cfg.max_seq_len, cfg.d_model, cfg.n_layers,
    )
    return TransformerBundle(cfg)
