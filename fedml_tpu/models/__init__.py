"""``fedml_tpu.models`` — model zoo factory.

Public surface mirrors the reference (``fedml.model.create``,
``python/fedml/model/model_hub.py:20-83``): keyed on ``(args.model,
args.dataset)``. Returns a :class:`ModelBundle` — the Flax module plus enough
input metadata to initialise parameters without a dataset in hand.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..data.datasets import REGISTRY as DATA_REGISTRY
from .layers import MLP
from .nlp import RNNOriginalFedAvg, RNNStackOverflow
from .vision import (
    VGG,
    VGG11_CFG,
    VGG16_CFG,
    CNNDropOut,
    EfficientNetB0,
    LogisticRegression,
    MobileNetV1,
    MobileNetV2,
    MobileNetV3Small,
    resnet18_gn,
    resnet20,
    resnet56,
)

logger = logging.getLogger(__name__)

# model names whose forward is convolution-dominated (cohort-impl heuristic)
CONV_MODEL_FAMILIES = frozenset((
    "cnn", "cnn_dropout", "cnn_web", "resnet18_gn", "resnet18", "resnet20",
    "resnet56", "mobilenet", "mobilenet_v1", "mobilenet_v2", "mobilenet_v3",
    "mobilenet_v3_small", "vgg11", "vgg16", "vgg", "efficientnet",
    "efficientnet_b0", "efficientnet-b0", "fcn", "deeplab", "deeplabv3_plus",
    "unet", "darts", "darts_search", "centernet", "centernet_lite", "yolo",
    "detector", "dcgan", "gan",
))

__all__ = ["create", "ModelBundle"]


@dataclass
class ModelBundle:
    """A Flax module + input spec, the unit the trainers consume."""

    module: nn.Module
    name: str
    input_shape: Tuple[int, ...]  # per-sample shape (no batch dim)
    input_dtype: Any = jnp.float32
    task: str = "classification"
    meta: dict = field(default_factory=dict)

    def dummy_input(self, batch_size: int = 2) -> jax.Array:
        if jnp.issubdtype(self.input_dtype, jnp.integer):
            return jnp.zeros((batch_size,) + self.input_shape, self.input_dtype)
        return jnp.zeros((batch_size,) + self.input_shape, self.input_dtype)

    def init(self, rng: jax.Array, batch_size: int = 2):
        return self.module.init(
            {"params": rng, "dropout": rng}, self.dummy_input(batch_size), train=False
        )

    def apply(self, params, x, train: bool = False, rngs=None):
        return self.module.apply(params, x, train=train, rngs=rngs)

    def param_count(self, params) -> int:
        return sum(int(p.size) for p in jax.tree.leaves(params))


def create(args, output_dim: int) -> ModelBundle:
    """Build a model for ``(args.model, args.dataset)``.

    Name registry follows the reference's dispatch (model_hub.py:20-83):
    lr, cnn (CNN_DropOut), resnet18_gn, resnet20, resnet56, mobilenet,
    mobilenet_v2, mobilenet_v3, efficientnet, vgg11/vgg16, rnn
    (dataset-routed), mlp, fcn/deeplab (segmentation), darts (NAS search).
    """
    name = str(args.model).lower()
    dataset = getattr(args, "dataset", "synthetic")
    spec = DATA_REGISTRY.get(dataset)
    sample_shape = spec.sample_shape if spec else (60,)
    task = spec.task if spec else "classification"
    int_input = task in ("nwp", "seq_tagging", "span_extraction")

    if name in ("cheetah_tagger", "cheetah_span"):
        # FedNLP heads on the REAL Cheetah backbone (row 75 scale path):
        # same transformer as the flagship, task head on hidden states
        from .transformer_heads import create_head_bundle

        return create_head_bundle(
            args, output_dim, spec,
            "tagger" if name == "cheetah_tagger" else "span",
        )

    if name in ("cheetah", "llama", "cheetah_lm"):
        # the flagship Cheetah transformer as a federated model (FedLLM):
        # its own bundle type — local training runs mesh-sharded
        # (cross_silo/fedllm.py), the FL planes see the ModelBundle surface
        from .transformer_lm import create_transformer_bundle

        return create_transformer_bundle(args, output_dim, spec)

    if name in ("lr", "logistic_regression"):
        module: nn.Module = LogisticRegression(output_dim)
    elif name in ("cnn", "cnn_dropout", "cnn_web"):
        module = CNNDropOut(output_dim)
    elif name in ("resnet18_gn", "resnet18"):
        module = resnet18_gn(output_dim)
    elif name == "resnet20":
        module = resnet20(output_dim)
    elif name == "resnet56":
        module = resnet56(output_dim)
    elif name in ("mobilenet", "mobilenet_v1"):
        module = MobileNetV1(output_dim)
    elif name in ("mobilenet_v2",):
        module = MobileNetV2(output_dim)
    elif name == "vgg11":
        module = VGG(VGG11_CFG, output_dim)
    elif name in ("vgg16", "vgg"):
        module = VGG(VGG16_CFG, output_dim)
    elif name == "rnn":
        # dataset-routed like the reference (model_hub.py rnn branches)
        if dataset in ("stackoverflow_nwp",):
            module = RNNStackOverflow(vocab_size=output_dim)
        else:
            module = RNNOriginalFedAvg(vocab_size=output_dim)
    elif name == "mlp":
        module = MLP((128, 64, output_dim))
    elif name in ("efficientnet", "efficientnet_b0", "efficientnet-b0"):
        module = EfficientNetB0(output_dim)
    elif name in ("mobilenet_v3", "mobilenet_v3_small"):
        module = MobileNetV3Small(output_dim)
    elif name in ("fcn", "deeplab", "deeplabv3_plus", "unet"):
        from .segmentation import FCNSeg

        module = FCNSeg(output_dim,
                        width=int(getattr(args, "seg_model_width", 32) or 32))
    elif name in ("darts", "darts_search"):
        from .darts import DartsNetwork

        module = DartsNetwork(output_dim)
    elif name in ("centernet", "centernet_lite", "yolo", "detector"):
        # FedCV detection (reference: app/fedcv/object_detection) —
        # dense anchor-free head, see models/detection.py; real-resolution
        # inputs (>=128px) get a deeper feature stack
        from .detection import CenterNetLite

        widths = (
            (32, 64, 128, 128) if sample_shape[0] >= 128 else (32, 64, 64)
        )
        module = CenterNetLite(num_classes=output_dim, widths=widths)
    elif name in ("transformer", "tiny_transformer", "transformer_lm",
                  "bilstm_tagger", "tagger", "span_extractor", "bilstm_span"):
        # FedNLP zoo (reference: app/fednlp/{seq_tagging,span_extraction,
        # seq2seq}) — all need a token-vocab dataset
        if spec is None or spec.vocab_size <= 0:
            raise ValueError(
                f"model {name!r} needs a text dataset with a vocab "
                f"(got {dataset!r})"
            )
        if name in ("bilstm_tagger", "tagger"):
            from .nlp import TokenTagger

            module = TokenTagger(vocab_size=spec.vocab_size,
                                 num_tags=output_dim)
        elif name in ("span_extractor", "bilstm_span"):
            from .nlp import SpanExtractor

            module = SpanExtractor(vocab_size=spec.vocab_size)
        else:
            from .nlp import TinyTransformerLM

            module = TinyTransformerLM(
                vocab_size=max(spec.vocab_size, output_dim),
                max_len=spec.seq_len if spec.seq_len > 0 else 128,
            )
    elif name in ("gcn", "gat", "sage", "graphsage"):
        # FedGraphNN zoo (reference: app/fedgraphnn/*/model/) — head routed
        # by the dataset's task, conv by the model name
        from .gnn import GraphClassifier, LinkPredictor, NodeClassifier

        conv = {"graphsage": "sage"}.get(name, name)
        if spec is None or spec.n_nodes == 0:
            raise ValueError(
                f"model {name!r} needs a graph dataset (got {dataset!r})"
            )
        if task == "node_clf":
            module = NodeClassifier(spec.n_feats, output_dim, conv=conv)
        elif task == "link_pred":
            module = LinkPredictor(spec.n_feats, conv=conv)
        elif task == "regression":
            module = GraphClassifier(spec.n_feats, 1, conv=conv)
        else:
            module = GraphClassifier(spec.n_feats, output_dim, conv=conv)
    else:
        raise ValueError(f"unknown model {name!r}")

    bundle = ModelBundle(
        module=module,
        name=name,
        input_shape=tuple(sample_shape),
        input_dtype=jnp.int32 if int_input else jnp.float32,
        task=task,
        meta={"dataset": dataset, "output_dim": output_dim},
    )
    # convolutional families: consumed by the sp engine's cohort-impl
    # heuristic (XLA:CPU lowers VMAPPED convs pathologically; lr/mlp on
    # image datasets must NOT be demoted to lax.map by shape alone)
    bundle.conv_model = name in CONV_MODEL_FAMILIES
    logger.info("model: %s for %s (output_dim=%d)", name, dataset, output_dim)
    return bundle
