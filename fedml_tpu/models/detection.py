"""Object detection — anchor-free dense head (FedCV detection family).

reference: ``python/app/fedcv/object_detection`` — YOLOv5 fine-tuning through
the federated API (torch hub model, ragged NMS pipelines).

TPU re-grounding: ragged per-image box lists and NMS loops are hostile to
XLA; a CenterNet-style *dense* formulation is the TPU-shaped equivalent and
keeps every tensor static: the network predicts, at stride 4, a per-cell
class heatmap plus a box-size regression, and the target is the same dense
grid (``data/datasets.py synth_detection``). Decoding to boxes (top-k over
the heatmap) happens host-side after eval and never enters jit.

Output layout: ``[H/4, W/4, C + 2]`` = class logits ++ (h, w) size
regression. Target layout: ``[H/4, W/4, C + 3]`` = one-hot center heatmap
++ (h, w) ++ center mask.
"""

from __future__ import annotations

from typing import Sequence

from flax import linen as nn

from .segmentation import ConvGN


class CenterNetLite(nn.Module):
    """Stride-4 backbone + dense detection heads.

    ``num_classes`` object categories; returns ``[B, H/4, W/4, C + 2]``.
    """

    num_classes: int
    widths: Sequence[int] = (32, 64, 64)

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = ConvGN(self.widths[0], stride=2)(x)
        h = ConvGN(self.widths[1], stride=2)(h)
        for w in self.widths[2:]:
            h = ConvGN(w)(h)
        cls = nn.Conv(self.num_classes, (1, 1))(h)
        size = nn.Conv(2, (1, 1))(h)
        import jax.numpy as jnp

        return jnp.concatenate([cls, size], axis=-1)
