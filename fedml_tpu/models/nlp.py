"""NLP models: char/word LSTMs for shakespeare & stackoverflow.

reference: ``python/fedml/model/nlp/rnn.py:1-115`` — RNN_OriginalFedAvg
(embed 8 → 2×LSTM 256 → dense vocab, char LM) and RNN_StackOverFlow
(embed 96 → LSTM 670 → dense 96 → dense vocab). Flax ``nn.RNN`` over
``nn.OptimizedLSTMCell`` — unrolled by XLA as a fused scan on TPU.
"""

from __future__ import annotations

from flax import linen as nn


class RNNOriginalFedAvg(nn.Module):
    """Char-level LM (shakespeare). Logits for every position: [B, L, vocab]."""

    vocab_size: int = 90
    embedding_dim: int = 8
    hidden: int = 256

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Embed(self.vocab_size, self.embedding_dim)(x)
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden))(h)
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden))(h)
        return nn.Dense(self.vocab_size)(h)


class RNNStackOverflow(nn.Module):
    """Next-word prediction LM (stackoverflow_nwp)."""

    vocab_size: int = 10004
    embedding_dim: int = 96
    hidden: int = 670

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Embed(self.vocab_size, self.embedding_dim)(x)
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden))(h)
        h = nn.Dense(self.embedding_dim)(h)
        return nn.Dense(self.vocab_size)(h)


class BiLSTMEncoder(nn.Module):
    """Shared FedNLP encoder: embed → bidirectional LSTM → per-token states.

    reference: ``python/app/fednlp`` model stacks (BiLSTM baselines for
    seq_tagging / span_extraction). Both directions are XLA scans; the
    reverse pass is a flip, not a dynamic loop.
    """

    vocab_size: int
    embedding_dim: int = 32
    hidden: int = 64

    @nn.compact
    def __call__(self, x):
        h = nn.Embed(self.vocab_size, self.embedding_dim)(x)
        fwd = nn.RNN(nn.OptimizedLSTMCell(self.hidden))(h)
        bwd = nn.RNN(nn.OptimizedLSTMCell(self.hidden))(h[:, ::-1, :])[:, ::-1, :]
        import jax.numpy as jnp

        return jnp.concatenate([fwd, bwd], axis=-1)


class TokenTagger(nn.Module):
    """Sequence tagging (reference: app/fednlp/seq_tagging — NER-style
    per-token labels): logits [B, L, num_tags]."""

    vocab_size: int
    num_tags: int
    embedding_dim: int = 32
    hidden: int = 64

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = BiLSTMEncoder(self.vocab_size, self.embedding_dim, self.hidden)(x)
        return nn.Dense(self.num_tags)(h)


class TinyTransformerLM(nn.Module):
    """Small causal-attention LM for federated NLP tasks.

    reference: app/fednlp transformer baselines (distilbert/bart heads). The
    Cheetah transformer (``parallel/transformer.py``) is the scale path; this
    zoo model is its federated-client-sized sibling — self-contained (no mesh
    partitioning metadata), so it drops into the vmapped cohort engines.
    Attention makes copy/reorder seq2seq tasks learnable where a small LSTM's
    fixed-width state cannot (prefix-LM framing, fednlp_seq2seq).
    """

    vocab_size: int
    d_model: int = 64
    n_layers: int = 2
    n_heads: int = 4
    max_len: int = 128

    @nn.compact
    def __call__(self, x, train: bool = False):
        import jax.numpy as jnp

        B, L = x.shape
        pos_emb = self.param(
            "pos_emb", nn.initializers.normal(0.02),
            (self.max_len, self.d_model),
        )
        h = nn.Embed(self.vocab_size, self.d_model)(x) + pos_emb[None, :L]
        causal = jnp.tril(jnp.ones((L, L), bool))[None, None]
        for _ in range(self.n_layers):
            a = nn.LayerNorm()(h)
            a = nn.SelfAttention(num_heads=self.n_heads,
                                 qkv_features=self.d_model)(a, mask=causal)
            h = h + a
            m = nn.LayerNorm()(h)
            m = nn.Dense(4 * self.d_model)(m)
            m = nn.gelu(m)
            h = h + nn.Dense(self.d_model)(m)
        h = nn.LayerNorm()(h)
        return nn.Dense(self.vocab_size)(h)


class SpanExtractor(nn.Module):
    """Span extraction (reference: app/fednlp/span_extraction — QA-style
    start/end pointers): logits [B, L, 2] (start scores, end scores)."""

    vocab_size: int
    embedding_dim: int = 32
    hidden: int = 64

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = BiLSTMEncoder(self.vocab_size, self.embedding_dim, self.hidden)(x)
        return nn.Dense(2)(h)
