"""NLP models: char/word LSTMs for shakespeare & stackoverflow.

reference: ``python/fedml/model/nlp/rnn.py:1-115`` — RNN_OriginalFedAvg
(embed 8 → 2×LSTM 256 → dense vocab, char LM) and RNN_StackOverFlow
(embed 96 → LSTM 670 → dense 96 → dense vocab). Flax ``nn.RNN`` over
``nn.OptimizedLSTMCell`` — unrolled by XLA as a fused scan on TPU.
"""

from __future__ import annotations

from flax import linen as nn


class RNNOriginalFedAvg(nn.Module):
    """Char-level LM (shakespeare). Logits for every position: [B, L, vocab]."""

    vocab_size: int = 90
    embedding_dim: int = 8
    hidden: int = 256

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Embed(self.vocab_size, self.embedding_dim)(x)
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden))(h)
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden))(h)
        return nn.Dense(self.vocab_size)(h)


class RNNStackOverflow(nn.Module):
    """Next-word prediction LM (stackoverflow_nwp)."""

    vocab_size: int = 10004
    embedding_dim: int = 96
    hidden: int = 670

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Embed(self.vocab_size, self.embedding_dim)(x)
        h = nn.RNN(nn.OptimizedLSTMCell(self.hidden))(h)
        h = nn.Dense(self.embedding_dim)(h)
        return nn.Dense(self.vocab_size)(h)
