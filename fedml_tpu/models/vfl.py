"""Vertical-FL party models.

reference: ``model/finance/vfl_models_standalone.py`` (DenseModel guest/host
pairs used by ``simulation/sp/classical_vertical_fl``). Each party owns a
feature encoder; the guest additionally owns the interactive head that
combines both parties' intermediate representations.
"""

from __future__ import annotations

from typing import Sequence

from flax import linen as nn


class PartyEncoder(nn.Module):
    """Per-party feature encoder → k-dim intermediate representation."""

    features: Sequence[int] = (32,)

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = x.reshape((x.shape[0], -1))
        for f in self.features[:-1]:
            h = nn.relu(nn.Dense(f)(h))
        return nn.Dense(self.features[-1])(h)


class InteractiveHead(nn.Module):
    """Guest-side head over summed party representations → logits."""

    num_classes: int

    @nn.compact
    def __call__(self, combined, train: bool = False):
        return nn.Dense(self.num_classes)(nn.relu(combined))
