"""FedNLP task heads on the Cheetah transformer backbone.

Closes SURVEY.md row 75's scale gap: the reference's ``python/app/fednlp``
trains real-resolution transformer baselines (distilbert/bart heads) while
the r3 zoo offered BiLSTM-sized stand-ins whose own docstrings pointed at
the Cheetah transformer as "the scale path". These heads TAKE that path —
the identical ``parallel/transformer.py`` backbone the flagship pretrains
(rotary GQA attention, RMSNorm, fused matmuls, splash on TPU), with a task
head on the hidden states:

- ``TransformerTagger`` — per-token tag logits (seq_tagging / NER)
- ``TransformerSpanExtractor`` — start/end pointer logits (QA spans)
- seq2seq needs no head at all: ``model: "cheetah"`` on a prefix-LM dataset
  IS the task (``models/transformer_lm.py``)

All three scale with the same YAML knobs as the flagship (d_model /
n_layers / model_size — up to 7B), and as bundles they drop into every FL
plane: the vmapped sp cohorts, cross-silo (where ``ml/trainer`` routes
TransformerBundle-family models through the mesh-sharded FedLLM trainer for
LM tasks), and the federated eval paths.
"""

from __future__ import annotations

import dataclasses
import logging

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..parallel.sharding import unbox
from ..parallel.transformer import Transformer, TransformerConfig

logger = logging.getLogger(__name__)


class TransformerTagger(nn.Module):
    """Cheetah backbone → per-token tag logits [B, L, num_tags]."""

    cfg: TransformerConfig
    num_tags: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = Transformer(self.cfg)(x, return_hidden=True)
        return nn.Dense(self.num_tags, dtype=jnp.float32)(
            h.astype(jnp.float32)
        )


class TransformerSpanExtractor(nn.Module):
    """Cheetah backbone → start/end pointer logits [B, L, 2]."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = Transformer(self.cfg)(x, return_hidden=True)
        return nn.Dense(2, dtype=jnp.float32)(h.astype(jnp.float32))


class CheetahHeadBundle:
    """ModelBundle-shaped wrapper (duck-typed like TransformerBundle):
    ``init`` returns UNBOXED params so the FL planes' tree ops see plain
    arrays; partition metadata is re-derived by whichever mesh trains it."""

    def __init__(self, module: nn.Module, cfg: TransformerConfig,
                 name: str, task: str):
        self.module = module
        self.cfg = cfg
        self.name = name
        self.task = task
        self.input_shape = (cfg.max_seq_len,)
        self.input_dtype = jnp.int32
        self.meta = {"cfg": cfg}

    def dummy_input(self, batch_size: int = 2):
        return jnp.zeros((batch_size,) + self.input_shape, jnp.int32)

    def init(self, rng: jax.Array, batch_size: int = 2):
        variables = self.module.init(rng, self.dummy_input(batch_size))
        return {"params": unbox(variables["params"])}

    def apply(self, params, x, train: bool = False, rngs=None):
        return self.module.apply(params, jnp.asarray(x, jnp.int32),
                                 train=train)

    def param_count(self, params) -> int:
        return sum(int(p.size) for p in jax.tree.leaves(params))


def create_head_bundle(args, output_dim: int, spec, kind: str) -> CheetahHeadBundle:
    """Build a Cheetah-backed FedNLP head for ``(args, dataset)``."""
    from ..cheetah.runner import config_from_args

    cfg = config_from_args(args)
    vocab = int(getattr(spec, "vocab_size", 0) or 0) or 256
    seq_len = int(getattr(spec, "seq_len", 0) or 0) or cfg.max_seq_len
    # encoder attention: tagging/span heads classify tokens in context,
    # and span END pointers need lookahead a causal mask cannot give
    cfg = dataclasses.replace(cfg, vocab_size=vocab, max_seq_len=seq_len,
                              causal=False)
    if kind == "tagger":
        module: nn.Module = TransformerTagger(cfg, num_tags=int(output_dim))
        task = "seq_tagging"
    elif kind == "span":
        module = TransformerSpanExtractor(cfg)
        task = "span_extraction"
    else:
        raise ValueError(f"unknown head kind {kind!r}")
    logger.info(
        "transformer_heads: %s on d%d x %dL backbone (vocab=%d, seq=%d)",
        kind, cfg.d_model, cfg.n_layers, vocab, seq_len,
    )
    return CheetahHeadBundle(module, cfg, f"cheetah_{kind}", task)
