"""Shared building blocks for the model zoo.

Normalisation stance: GroupNorm everywhere. The reference mixes BatchNorm
(resnet56, mobilenet — ``model/cv/resnet.py``, ``model/cv/mobilenet.py``) and
GroupNorm (resnet18_gn per FedOpt/Adaptive-Federated-Optimization practice,
``model/cv/resnet_gn.py``). On TPU, BatchNorm's mutable running stats break
the pure-functional client training transform (``vmap`` over a client cohort)
and are known-bad under non-IID FL anyway; GroupNorm keeps every model a pure
``params -> logits`` function. Parity note recorded per-model.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax.numpy as jnp
from flax import linen as nn


def group_norm(channels: int) -> nn.GroupNorm:
    # 32 groups unless the channel count is small / not divisible
    groups = 32
    while channels % groups != 0:
        groups //= 2
    return nn.GroupNorm(num_groups=max(groups, 1))


class MLP(nn.Module):
    features: Sequence[int]
    activation: Callable = nn.relu

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        for i, f in enumerate(self.features):
            x = nn.Dense(f)(x)
            if i < len(self.features) - 1:
                x = self.activation(x)
        return x


def flatten_images(x: jnp.ndarray) -> jnp.ndarray:
    return x.reshape((x.shape[0], -1))
