"""Semantic segmentation model: encoder-decoder FCN with atrous context.

reference: ``model/cv/deeplabV3_plus.py`` + ``unet.py`` (the FedSeg models,
dispatched at ``model/model_hub.py``). TPU-native re-design instead of a
port: NHWC layout end to end, GroupNorm (batch-stat-free — right for FL
where client batches are tiny and non-IID), dilated 3x3 convs standing in
for the ASPP context module, bilinear upsample + skip fusion like
DeepLabV3+'s decoder. Everything is shapes XLA tiles cleanly onto the MXU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn


class ConvGN(nn.Module):
    features: int
    kernel: int = 3
    stride: int = 1
    dilation: int = 1

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(
            self.features, (self.kernel, self.kernel),
            strides=(self.stride, self.stride),
            kernel_dilation=(self.dilation, self.dilation),
            use_bias=False,
        )(x)
        x = nn.GroupNorm(num_groups=min(8, self.features))(x)
        return nn.relu(x)


class FCNSeg(nn.Module):
    """Encoder (stride 4) + dilated context + decoder with skip fusion.

    x [B, H, W, 3] float -> logits [B, H, W, num_classes] fp32.
    """

    num_classes: int
    width: int = 32

    @nn.compact
    def __call__(self, x, train: bool = False):
        w = self.width
        # encoder
        e1 = ConvGN(w)(x)                    # H
        e1 = ConvGN(w)(e1)
        e2 = ConvGN(2 * w, stride=2)(e1)     # H/2
        e2 = ConvGN(2 * w)(e2)
        e3 = ConvGN(4 * w, stride=2)(e2)     # H/4
        # context: parallel dilated branches (ASPP-lite), summed
        c = (
            ConvGN(4 * w, dilation=1)(e3)
            + ConvGN(4 * w, dilation=2)(e3)
            + ConvGN(4 * w, dilation=4)(e3)
        )
        # decoder: upsample + skip-fuse
        B, H4, W4, _ = c.shape
        up2 = jax.image.resize(c, (B, H4 * 2, W4 * 2, c.shape[-1]), "bilinear")
        d2 = ConvGN(2 * w)(jnp.concatenate([up2, e2], axis=-1))
        B, H2, W2, _ = d2.shape
        up1 = jax.image.resize(d2, (B, H2 * 2, W2 * 2, d2.shape[-1]), "bilinear")
        d1 = ConvGN(w)(jnp.concatenate([up1, e1], axis=-1))
        logits = nn.Conv(self.num_classes, (1, 1))(d1)
        return logits.astype(jnp.float32)
