"""DARTS search network: mixed ops weighted by architecture parameters.

reference: ``model/cv/darts/`` (model_search.py — MixedOp over PRIMITIVES,
softmax over alphas; architect.py — the bilevel arch step). TPU-native
re-design: the cell is a fixed DAG of mixed ops whose branch outputs are a
single stacked tensor contracted with softmax(alpha) — one einsum instead of
a Python sum over op modules, so the whole search net stays one fused XLA
program under vmap over clients.

Architecture parameters live in the regular param tree under ``alpha_*`` —
``split_arch_params`` partitions them out for FedNAS's separate averaging
(reference FedNASAggregator averages weights AND alphas).
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

PyTree = Any

# op primitives (vector-data analog of the reference's conv PRIMITIVES)
N_OPS = 4  # [zero, identity, relu-dense, tanh-dense]


class MixedLayer(nn.Module):
    """All primitives computed, stacked, contracted with softmax(alpha)."""

    features: int

    @nn.compact
    def __call__(self, x, alpha):
        d_in = x.shape[-1]
        proj = (
            x if d_in == self.features
            else nn.Dense(self.features, use_bias=False, name="proj")(x)
        )
        branches = jnp.stack(
            [
                jnp.zeros_like(proj),                       # zero
                proj,                                        # identity
                nn.relu(nn.Dense(self.features)(x)),         # relu-dense
                jnp.tanh(nn.Dense(self.features)(x)),        # tanh-dense
            ],
            axis=0,
        )  # [N_OPS, B, F]
        w = jax.nn.softmax(alpha)
        return jnp.einsum("o,obf->bf", w, branches)


class DartsNetwork(nn.Module):
    """A stack of mixed layers + classifier head.

    Flattens any input shape; alphas are params ``alpha_0..alpha_{L-1}``.
    """

    num_classes: int
    n_layers: int = 3
    features: int = 64

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = x.reshape((x.shape[0], -1))
        for i in range(self.n_layers):
            alpha = self.param(
                f"alpha_{i}", nn.initializers.zeros, (N_OPS,), jnp.float32
            )
            h = MixedLayer(self.features, name=f"mixed_{i}")(h, alpha)
        return nn.Dense(self.num_classes, name="head")(h)


def is_arch_param(path: Tuple) -> bool:
    return any(
        str(getattr(k, "key", k)).startswith("alpha_") for k in path
    )


def split_arch_params(params: PyTree) -> Tuple[PyTree, PyTree]:
    """-> (weights-with-zeroed-alphas mask, alphas mask) as boolean trees."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    return (
        jax.tree_util.tree_map_with_path(
            lambda p, x: not is_arch_param(p), params
        ),
        jax.tree_util.tree_map_with_path(is_arch_param, params),
    )


def genotype(params: PyTree) -> dict:
    """Discretize: argmax op per layer (reference model_search.genotype)."""
    out = {}

    def visit(path, leaf):
        if is_arch_param(path):
            name = "/".join(str(getattr(k, "key", k)) for k in path)
            out[name] = int(jnp.argmax(leaf))
        return leaf

    jax.tree_util.tree_map_with_path(visit, params)
    return out
