"""Federated GAN model pair: generator + discriminator.

reference: ``simulation/mpi/fedgan/`` trains a vanilla GAN per client
(FedGANTrainer.py: BCE adversarial losses, alternating D/G steps) and
averages both nets. The modules here are dataset-shape-agnostic: they
generate/score flattened samples, so one pair serves every registered
dataset (images flatten; the API reshapes on the way out).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from flax import linen as nn


class Generator(nn.Module):
    """z [B, z_dim] -> samples [B, *sample_shape] in tanh range."""

    sample_shape: tuple
    hidden: int = 256

    @nn.compact
    def __call__(self, z, train: bool = False):
        d = int(np.prod(self.sample_shape))
        h = nn.relu(nn.Dense(self.hidden)(z))
        h = nn.relu(nn.Dense(self.hidden)(h))
        out = jnp.tanh(nn.Dense(d)(h)) * 3.0  # cover the data range
        return out.reshape((z.shape[0],) + tuple(self.sample_shape))


class Discriminator(nn.Module):
    """samples [B, *shape] -> real/fake logit [B]."""

    hidden: int = 256

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = x.reshape((x.shape[0], -1))
        h = nn.leaky_relu(nn.Dense(self.hidden)(h), 0.2)
        h = nn.leaky_relu(nn.Dense(self.hidden)(h), 0.2)
        return nn.Dense(1)(h)[:, 0]
