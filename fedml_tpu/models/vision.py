"""Vision models: LR, CNNs, ResNets, MobileNet, VGG, EfficientNet-lite.

Re-foundings of the reference zoo (``python/fedml/model/model_hub.py:20-83``
and ``model/cv/*.py``) as Flax modules. Every module is a pure function of
params with the uniform signature ``__call__(x, train: bool = False)`` so the
trainer transforms (``vmap`` over cohorts, ``lax.scan`` over batches) apply to
all of them. NHWC layout (TPU conv-native); GroupNorm (see layers.py).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn

from .layers import group_norm


class LogisticRegression(nn.Module):
    """reference: ``model/linear/lr.py`` (one Linear over flattened input)."""

    num_classes: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(self.num_classes)(x)


class CNNDropOut(nn.Module):
    """FedAvg-paper FEMNIST CNN (reference: ``model/cv/cnn.py`` CNN_DropOut:
    two 3x3 convs 32/64 + maxpool + dropout + dense 128 + dense classes)."""

    num_classes: int
    only_digits: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(32, (3, 3), padding="VALID")(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3), padding="VALID")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(0.25, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        y = nn.Conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                    padding="SAME", use_bias=False)(x)
        y = group_norm(self.filters)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False)(y)
        y = group_norm(self.filters)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1),
                               strides=(self.strides, self.strides),
                               use_bias=False)(x)
            residual = group_norm(self.filters)(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """ResNet-18-GN (ImageNet-style stem) or CIFAR-style ResNet-20/56.

    reference: ``model/cv/resnet_gn.py`` (resnet18, GroupNorm, used for
    fed_cifar100 per Adaptive Federated Optimization) and ``model/cv/resnet.py``
    (resnet20/56 for CIFAR, BatchNorm in the reference — GN here, see layers.py).
    """

    stage_sizes: Sequence[int]
    stage_filters: Sequence[int]
    num_classes: int
    cifar_stem: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.cifar_stem:
            x = nn.Conv(self.stage_filters[0], (3, 3), padding="SAME", use_bias=False)(x)
        else:
            x = nn.Conv(self.stage_filters[0], (7, 7), strides=(2, 2),
                        padding="SAME", use_bias=False)(x)
            x = group_norm(self.stage_filters[0])(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, (size, filters) in enumerate(zip(self.stage_sizes, self.stage_filters)):
            for j in range(size):
                strides = 2 if (i > 0 and j == 0) else 1
                x = BasicBlock(filters, strides)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


def resnet18_gn(num_classes: int) -> ResNet:
    return ResNet([2, 2, 2, 2], [64, 128, 256, 512], num_classes, cifar_stem=False)


def resnet20(num_classes: int) -> ResNet:
    return ResNet([3, 3, 3], [16, 32, 64], num_classes, cifar_stem=True)


def resnet56(num_classes: int) -> ResNet:
    return ResNet([9, 9, 9], [16, 32, 64], num_classes, cifar_stem=True)


class DepthwiseSeparable(nn.Module):
    filters: int
    strides: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        x = nn.Conv(in_ch, (3, 3), strides=(self.strides, self.strides),
                    padding="SAME", feature_group_count=in_ch, use_bias=False)(x)
        x = group_norm(in_ch)(x)
        x = nn.relu(x)
        x = nn.Conv(self.filters, (1, 1), use_bias=False)(x)
        x = group_norm(self.filters)(x)
        return nn.relu(x)


class MobileNetV1(nn.Module):
    """reference: ``model/cv/mobilenet.py`` (width-1.0 MobileNet)."""

    num_classes: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(32, (3, 3), strides=(2, 2), padding="SAME", use_bias=False)(x)
        x = group_norm(32)(x)
        x = nn.relu(x)
        cfg = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
               (512, 1), (512, 1), (512, 1), (512, 1), (512, 1),
               (1024, 2), (1024, 1)]
        for filters, strides in cfg:
            x = DepthwiseSeparable(filters, strides)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.2, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


class InvertedResidual(nn.Module):
    filters: int
    strides: int
    expand: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        hidden = in_ch * self.expand
        y = x
        if self.expand != 1:
            y = nn.Conv(hidden, (1, 1), use_bias=False)(y)
            y = group_norm(hidden)(y)
            y = nn.relu6(y)
        y = nn.Conv(hidden, (3, 3), strides=(self.strides, self.strides),
                    padding="SAME", feature_group_count=hidden, use_bias=False)(y)
        y = group_norm(hidden)(y)
        y = nn.relu6(y)
        y = nn.Conv(self.filters, (1, 1), use_bias=False)(y)
        y = group_norm(self.filters)(y)
        if self.strides == 1 and in_ch == self.filters:
            y = y + x
        return y


class MobileNetV2(nn.Module):
    """reference: ``model/cv/mobilenet_v2.py``."""

    num_classes: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(32, (3, 3), strides=(2, 2), padding="SAME", use_bias=False)(x)
        x = group_norm(32)(x)
        x = nn.relu6(x)
        cfg = [  # (expand, filters, repeats, stride)
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        for expand, filters, repeats, stride in cfg:
            for r in range(repeats):
                x = InvertedResidual(filters, stride if r == 0 else 1, expand)(
                    x, train=train
                )
        x = nn.Conv(1280, (1, 1), use_bias=False)(x)
        x = group_norm(1280)(x)
        x = nn.relu6(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


class VGG(nn.Module):
    """reference: ``model/cv/vgg.py`` (vgg11/16/19 without BN)."""

    cfg: Tuple
    num_classes: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(int(v), (3, 3), padding="SAME")(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        for f in (512, 512):
            x = nn.Dense(f)(x)
            x = nn.relu(x)
            x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


VGG11_CFG = (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M")
VGG16_CFG = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
             512, 512, 512, "M", 512, 512, 512, "M")


class SqueezeExcite(nn.Module):
    """SE attention over channels (ratio wrt the block's input width)."""

    reduced: int

    @nn.compact
    def __call__(self, x):
        s = jnp.mean(x, axis=(1, 2))
        s = nn.relu(nn.Dense(self.reduced)(s))
        s = nn.sigmoid(nn.Dense(x.shape[-1])(s))
        return x * s[:, None, None, :]


def hard_swish(x):
    return x * nn.relu6(x + 3.0) / 6.0


class MBConv(nn.Module):
    """EfficientNet MBConv: expand → depthwise → SE → project (+residual)."""

    filters: int
    strides: int
    expand: int
    kernel: int = 3
    se_ratio: float = 0.25

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        hidden = in_ch * self.expand
        y = x
        if self.expand != 1:
            y = nn.Conv(hidden, (1, 1), use_bias=False)(y)
            y = group_norm(hidden)(y)
            y = nn.swish(y)
        y = nn.Conv(hidden, (self.kernel, self.kernel),
                    strides=(self.strides, self.strides), padding="SAME",
                    feature_group_count=hidden, use_bias=False)(y)
        y = group_norm(hidden)(y)
        y = nn.swish(y)
        y = SqueezeExcite(max(1, int(in_ch * self.se_ratio)))(y)
        y = nn.Conv(self.filters, (1, 1), use_bias=False)(y)
        y = group_norm(self.filters)(y)
        if self.strides == 1 and in_ch == self.filters:
            y = y + x
        return y


class EfficientNetB0(nn.Module):
    """reference: ``model/cv/efficientnet/`` (B0 scaling). GroupNorm instead
    of BN — batch-stat-free for tiny non-IID client batches."""

    num_classes: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(32, (3, 3), strides=(2, 2), padding="SAME", use_bias=False)(x)
        x = group_norm(32)(x)
        x = nn.swish(x)
        cfg = [  # (expand, filters, repeats, stride, kernel)
            (1, 16, 1, 1, 3), (6, 24, 2, 2, 3), (6, 40, 2, 2, 5),
            (6, 80, 3, 2, 3), (6, 112, 3, 1, 5), (6, 192, 4, 2, 5),
            (6, 320, 1, 1, 3),
        ]
        for expand, filters, repeats, stride, kernel in cfg:
            for r in range(repeats):
                x = MBConv(filters, stride if r == 0 else 1, expand, kernel)(
                    x, train=train
                )
        x = nn.Conv(1280, (1, 1), use_bias=False)(x)
        x = group_norm(1280)(x)
        x = nn.swish(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.2, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


class MobileNetV3Block(nn.Module):
    filters: int
    hidden: int
    strides: int
    kernel: int
    use_se: bool
    use_hs: bool

    @nn.compact
    def __call__(self, x, train: bool = False):
        act = hard_swish if self.use_hs else nn.relu
        in_ch = x.shape[-1]
        y = x
        if self.hidden != in_ch:
            y = nn.Conv(self.hidden, (1, 1), use_bias=False)(y)
            y = group_norm(self.hidden)(y)
            y = act(y)
        y = nn.Conv(self.hidden, (self.kernel, self.kernel),
                    strides=(self.strides, self.strides), padding="SAME",
                    feature_group_count=self.hidden, use_bias=False)(y)
        y = group_norm(self.hidden)(y)
        if self.use_se:
            y = SqueezeExcite(max(1, self.hidden // 4))(y)
        y = act(y)
        y = nn.Conv(self.filters, (1, 1), use_bias=False)(y)
        y = group_norm(self.filters)(y)
        if self.strides == 1 and in_ch == self.filters:
            y = y + x
        return y


class MobileNetV3Small(nn.Module):
    """reference: ``model/cv/mobilenet_v3.py`` (small profile)."""

    num_classes: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(16, (3, 3), strides=(2, 2), padding="SAME", use_bias=False)(x)
        x = group_norm(16)(x)
        x = hard_swish(x)
        cfg = [  # (kernel, hidden, filters, se, hs, stride)
            (3, 16, 16, True, False, 2), (3, 72, 24, False, False, 2),
            (3, 88, 24, False, False, 1), (5, 96, 40, True, True, 2),
            (5, 240, 40, True, True, 1), (5, 240, 40, True, True, 1),
            (5, 120, 48, True, True, 1), (5, 144, 48, True, True, 1),
            (5, 288, 96, True, True, 2), (5, 576, 96, True, True, 1),
            (5, 576, 96, True, True, 1),
        ]
        for kernel, hidden, filters, se, hs, stride in cfg:
            x = MobileNetV3Block(filters, hidden, stride, kernel, se, hs)(
                x, train=train
            )
        x = nn.Conv(576, (1, 1), use_bias=False)(x)
        x = group_norm(576)(x)
        x = hard_swish(x)
        x = jnp.mean(x, axis=(1, 2))
        x = hard_swish(nn.Dense(1024)(x))
        x = nn.Dropout(0.2, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)
