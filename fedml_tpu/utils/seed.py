"""Deterministic seeding.

The reference seeds python/numpy/torch RNGs globally at init
(``python/fedml/__init__.py:45-50``). JAX is functional: we seed the host RNGs
for data partitioning / client sampling and hand out explicit ``PRNGKey``s for
everything on-device — determinism by construction rather than global state.
"""

from __future__ import annotations

import random

import jax
import numpy as np


def seed_everything(seed: int) -> jax.Array:
    """Seed host RNGs and return the root PRNGKey for device-side randomness."""
    random.seed(seed)
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)


def new_rng(seed: int = 0) -> jax.Array:
    return jax.random.PRNGKey(seed)
