from .seed import seed_everything, new_rng  # noqa: F401
from .tree import (  # noqa: F401
    tree_flatten_to_vector,
    tree_unflatten_from_vector,
    tree_zeros_like,
    tree_add,
    tree_sub,
    tree_scale,
    tree_dot,
    tree_l2_norm,
    tree_cast,
    global_norm,
)
