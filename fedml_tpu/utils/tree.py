"""Pytree utilities — the framework's equivalent of the reference's
``python/fedml/utils/model_utils.py`` (named-param flatten/unflatten,
tensor↔list transforms), re-expressed over JAX pytrees.

Everything here is pure and jit-compatible; these are the primitives the
aggregation/defense/DP kernels are built from.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

PyTree = Any


def tree_flatten_to_vector(tree: PyTree) -> Tuple[jax.Array, Any, list]:
    """Flatten a pytree of arrays into one 1-D vector.

    Returns (vector, treedef, shapes) so the tree can be reconstructed.
    Replaces model_utils.py's named-param flatten (dict-of-tensors → list).
    """
    leaves, treedef = jax.tree.flatten(tree)
    shapes = [l.shape for l in leaves]
    vec = jnp.concatenate([jnp.ravel(l) for l in leaves]) if leaves else jnp.zeros((0,))
    return vec, treedef, shapes


def tree_unflatten_from_vector(vec: jax.Array, treedef, shapes) -> PyTree:
    leaves = []
    offset = 0
    for shape in shapes:
        size = 1
        for s in shape:
            size *= s
        leaves.append(jnp.reshape(vec[offset : offset + size], shape))
        offset += size
    if offset != vec.size:
        raise ValueError(
            f"vector length {vec.size} does not match total leaf size {offset}"
        )
    return jax.tree.unflatten(treedef, leaves)


def tree_zeros_like(tree: PyTree) -> PyTree:
    return jax.tree.map(jnp.zeros_like, tree)


def tree_add(a: PyTree, b: PyTree) -> PyTree:
    return jax.tree.map(jnp.add, a, b)


def tree_sub(a: PyTree, b: PyTree) -> PyTree:
    return jax.tree.map(jnp.subtract, a, b)


def tree_scale(tree: PyTree, scalar) -> PyTree:
    return jax.tree.map(lambda x: x * scalar, tree)


def tree_dot(a: PyTree, b: PyTree) -> jax.Array:
    parts = jax.tree.leaves(jax.tree.map(lambda x, y: jnp.vdot(x, y), a, b))
    return jnp.sum(jnp.stack(parts)) if parts else jnp.zeros(())


def tree_l2_norm(tree: PyTree) -> jax.Array:
    return jnp.sqrt(tree_dot(tree, tree))


global_norm = tree_l2_norm


def tree_cast(tree: PyTree, dtype) -> PyTree:
    return jax.tree.map(lambda x: x.astype(dtype), tree)
