"""Rank topology of the hierarchical edge tier (docs/traffic.md).

Flat worlds are rank 0 = server, ranks 1..N = clients. A tiered world
keeps BOTH of those assignments untouched — clients keep the exact ranks,
data shards (``client_index = rank - 1``) and sender ids they have in a
flat world, which is what lets the chaos harness compare a tiered run
bitwise against a flat reference — and appends E edge-aggregator ranks
after the clients:

    rank 0                      root server
    ranks 1..N                  clients (unchanged from flat)
    ranks base..base+E-1        edge aggregators (base = N+1 by default)

``edge_rank_base`` may be pushed past N+1 to align edges onto their own
gRPC port group when N is not a multiple of ``grpc_ranks_per_port``
(port_for_rank maps contiguous rank blocks onto ports; an unaligned edge
rank would land in the last device-host process's port). The padding
ranks are simply never used.

Clients are leased to edges in contiguous blocks (``home_edge``), and an
orphaned client re-homes around the sibling ring — then to the root in
degraded mode (``rehome_targets``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional


@dataclass(frozen=True)
class Topology:
    clients: int
    edges: int
    edge_rank_base: int = 0  # 0 → clients + 1

    def __post_init__(self):
        if self.clients <= 0:
            raise ValueError(f"clients must be positive, got {self.clients}")
        if self.edges <= 0:
            raise ValueError(f"edges must be positive, got {self.edges}")
        if self.edges > self.clients:
            raise ValueError(
                f"more edges ({self.edges}) than clients ({self.clients})")
        base = self.edge_rank_base or self.clients + 1
        if base < self.clients + 1:
            raise ValueError(
                f"edge_rank_base {base} overlaps client ranks 1..{self.clients}")
        object.__setattr__(self, "edge_rank_base", base)

    # -- construction --------------------------------------------------------

    @classmethod
    def from_args(cls, args) -> Optional["Topology"]:
        """The tiered topology this world runs under, or None when flat.

        ``hierarchy_edges`` is the single on/off knob (``--tiers 2`` at the
        CLI resolves to a concrete edge count before args reach here).
        """
        edges = int(getattr(args, "hierarchy_edges", 0) or 0)
        if edges <= 0:
            return None
        clients = int(getattr(args, "client_num_in_total", 0) or 0)
        base = int(getattr(args, "hierarchy_edge_rank_base", 0) or 0)
        return cls(clients=clients, edges=edges, edge_rank_base=base)

    # -- rank classification -------------------------------------------------

    @property
    def world_size(self) -> int:
        return self.edge_rank_base + self.edges

    @property
    def edge_ranks(self) -> List[int]:
        return list(range(self.edge_rank_base, self.edge_rank_base + self.edges))

    def is_client(self, rank: int) -> bool:
        return 1 <= rank <= self.clients

    def is_edge(self, rank: int) -> bool:
        return self.edge_rank_base <= rank < self.edge_rank_base + self.edges

    # -- leasing -------------------------------------------------------------

    def home_edge(self, client_rank: int) -> int:
        """The edge a client initially leases against (contiguous blocks)."""
        if not self.is_client(client_rank):
            raise ValueError(f"rank {client_rank} is not a client")
        return self.edge_rank_base + ((client_rank - 1) * self.edges) // self.clients

    def edge_clients(self, edge_rank: int) -> List[int]:
        """The initial lease block of an edge (inverse of home_edge)."""
        if not self.is_edge(edge_rank):
            raise ValueError(f"rank {edge_rank} is not an edge")
        return [c for c in range(1, self.clients + 1)
                if self.home_edge(c) == edge_rank]

    def rehome_targets(self, client_rank: int) -> List[int]:
        """Failover order for an orphaned client: the sibling ring starting
        just past its home edge, then rank 0 (root, degraded mode)."""
        home = self.home_edge(client_rank)
        ring = self.edge_ranks
        i = ring.index(home)
        siblings = ring[i + 1:] + ring[:i]
        return siblings + [0]
