"""Edge aggregator: the mid-tree tier of the hierarchical federation
(docs/traffic.md "Hierarchical edge tier", docs/robustness.md "Edge tier
failure domains").

An edge leases a contiguous block of clients (``Topology.edge_clients``)
and runs the serving plane's CONTROL half locally: admission, dedup (the
comm layer's window), staleness annotation, heartbeat leases, resync acks.
The DATA half — decode, staleness-weighted fold, aggregate — stays at the
root: the edge buffers its clients' updates as opaque *entries* and ships
them up as one batched, delta-encoded summary per fill/flush
(:mod:`fedml_tpu.hierarchy.summary`). Down the tree the edge is a caching
replica: every root dispatch is installed into a local
:class:`~fedml_tpu.delivery.VersionedModelStore` and fanned out per client
(delta frames against each client's last ACKed base, exactly like the
root's own dispatch path).

Failure-domain contract (the robustness core):

- the edge leases against the ROOT with the same heartbeat/resync FSM its
  clients run against it; a root partition is absorbed by bounded-backoff
  ``e2s_edge_resync`` + verbatim replay of the last summary (the root's
  dedup window and committed-round guard absorb duplicates);
- a killed edge (``FaultPlan.kill_edge``) takes its buffer with it — the
  orphaned clients heartbeat-miss, exhaust their resync budget against the
  corpse, then RE-HOME (``c2e_rehome``) to a sibling edge and replay their
  cached update under a bumped delivery epoch, so the contribution folds
  exactly once whether or not the dead edge had already shipped it;
- a restarted edge re-seeds its replica from the root and RE-SOLICITS its
  lease block (``e2c_resolicit`` — ``_recover_serving_state`` generalized:
  the fold buffer is recovered from the clients who still hold the
  updates, not from disk).

Worker threads and timers are registered with the world scope (graftiso
I005); every mutable field is guarded by ``_lock`` — handlers run on the
comm thread, shipping also runs on the flush/backoff timer threads.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .. import constants
from ..core.distributed import FedMLCommManager, Message
from ..core.containers import BoundedDict
from ..delivery import VersionedModelStore, WireCodec, flatten_leaves
from ..delivery.delta_codec import DELTA_KEY, payload_nbytes
from ..cross_silo.message_define import MyMessage
from ..traffic.admission import AdmissionController
from ..traffic.async_aggregator import AsyncConfig
from .summary import pack_summary
from .topology import Topology

logger = logging.getLogger(__name__)


class EdgeAggregatorManager(FedMLCommManager):
    def __init__(self, args, comm=None, rank: int = 0, size: int = 0,
                 backend: str = constants.COMM_BACKEND_LOOPBACK):
        super().__init__(args, comm, rank, size, backend)
        topo = Topology.from_args(args)
        if topo is None or not topo.is_edge(rank):
            raise ValueError(
                f"rank {rank} is not an edge of the configured topology")
        self.topology = topo
        self.done = threading.Event()
        # ONE lock for lease/replica/buffer/FSM state: handlers (comm
        # thread) and the flush/heartbeat/backoff timers all mutate it
        self._lock = threading.Lock()
        # -- lease state ------------------------------------------------------
        self._leased = set(topo.edge_clients(rank))
        self._online: set = set()
        self._dispatched: set = set()   # clients that got their first model
        # highest client_version this edge already SHIPPED per client — the
        # committed record its resync acks answer with (a contribution in a
        # shipped summary is the edge's to re-deliver, not the client's).
        # LRU-bounded (graftmem M001): an evicted client's resync replays
        # at most one already-shipped update, which the root's dedup and
        # round-index guards drop.
        self._forwarded: Dict[int, int] = BoundedDict(
            65536, lru=True, name="edge.forwarded")
        self._acked: Dict[int, int] = {}  # client -> last ACKed version
        # -- model replica ----------------------------------------------------
        self.version = -1
        self._leaves: Optional[List[np.ndarray]] = None
        self._vec: Optional[np.ndarray] = None
        self._shapes: Optional[List[tuple]] = None
        self.store = VersionedModelStore(
            int(getattr(args, "delta_store_versions", 8) or 8),
            metric_prefix="comm.edge.store",
        )
        self.wire = WireCodec(getattr(args, "wire_path", "auto"),
                              scoped=self.world.telemetry)
        # -- fold buffer (entry-preserving — see hierarchy/summary.py) --------
        self._entries: List[Dict] = []
        self._sync_mode = (
            str(getattr(args, "aggregation_mode", "sync") or "sync").lower()
            != "async")
        cfg = AsyncConfig.from_args(args, max(len(self._leased), 1))
        # sync worlds ship once the whole live lease answered; async worlds
        # ship at the edge's own FedBuff fill mark. Either way the flush
        # timer bounds summary latency — batching is transport-only, the
        # root re-buffers entries, so ship size never affects the math.
        self._ship_target = (0 if self._sync_mode
                             else int(getattr(args, "edge_buffer_size", 0)
                                      or cfg.buffer_size))
        self._flush_s = float(getattr(args, "edge_flush_s", 0.25) or 0.25)
        self.admission = AdmissionController.from_args(
            args, cfg.buffer_size)
        self._summary_seq = 0
        self._last_summary_msg: Optional[Message] = None
        # -- health stats (piggybacked on summaries so they survive gRPC
        # process boundaries; docs/telemetry.md `edge.*`) --------------------
        self._stats = {"folds": 0, "rehomed": 0, "resolicited": 0,
                       "summaries": 0, "staleness": {}}
        # -- root-facing liveness FSM (same shape as the client's) ------------
        self._hb_s = float(getattr(args, "heartbeat_s", 0.0) or 0.0)
        self._hb_miss_limit = max(
            int(getattr(args, "heartbeat_miss_limit", 3) or 3), 1)
        self._resync_base_s = float(
            getattr(args, "resync_backoff_s", 0.5) or 0.5)
        self._resync_max_s = float(
            getattr(args, "resync_backoff_max_s", 10.0) or 10.0)
        self._resync_max_attempts = int(
            getattr(args, "resync_max_attempts", 30) or 30)
        self._fsm_state = "running"   # running | resync | lost
        self._resync_attempt = 0
        self._last_root_traffic = time.monotonic()
        # seeded jitter, deterministic per (world seed, rank) — same scheme
        # as the client backoff (docs/robustness.md "thundering herd")
        seed = int(getattr(args, "random_seed", 0) or 0)
        self._jitter_rng = np.random.RandomState(
            (seed * 1_000_003 + rank * 7919) % (2 ** 31 - 1))
        self._killed = False

    @property
    def killed(self) -> bool:
        """True once the fault plan fail-stopped this edge (chaos harness
        verdicts read this to prove the armed phase actually fired)."""
        with self._lock:
            return self._killed

    # -- handler registry -----------------------------------------------------

    def register_message_receive_handlers(self) -> None:
        # root-facing
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_CONNECTION_IS_READY, self._on_connection_ready)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self._on_root_model)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self._on_root_model)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_FINISH, self._on_root_finish)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_RESYNC_ACK, self._on_root_resync_ack)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_HEARTBEAT_ACK, self._on_root_heartbeat_ack)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_SHED_NOTICE, self._on_root_shed)
        # client-facing
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, self._on_client_status)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
            self._on_client_model)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_HEARTBEAT, self._on_client_heartbeat)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_RESYNC, self._on_client_resync)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_PULL_REQUEST, self._on_client_pull)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2E_REHOME, self._on_rehome)

    # -- fault hook (FaultPlan.kill_edge) -------------------------------------

    def _maybe_kill_edge(self, phase: str) -> bool:
        """Fail-stop this edge if the fault plan targets (phase, round).
        In-process analog of the server's SIGKILL: the transport wrapper
        goes dark (sends dropped, receive loop stopped) and every
        in-flight buffer dies with it — nothing is drained or flushed."""
        plan = getattr(self.args, "fault_plan", None)
        if plan is None or self._killed:
            return self._killed
        if not plan.maybe_kill_edge(phase, int(self.version)):
            return False
        with self._lock:
            self._killed = True
        self.world.trace.event("edge_killed", phase=phase,
                               round_idx=int(self.version), edge=self.rank)
        logger.warning("edge %d: fault plan kill at %s (round %d)",
                       self.rank, phase, int(self.version))
        kill = getattr(self.com_manager, "kill", None)
        if kill is not None:
            kill()
        else:
            self.com_manager.stop_receive_message()
        return True

    # -- root-facing FSM ------------------------------------------------------

    def _on_connection_ready(self, msg: Message) -> None:
        self._announce_to_root()
        self._arm_heartbeat()
        self._arm_flush()

    def _announce_to_root(self) -> None:
        """The idempotent edge handshake: doubles as ONLINE on a fresh root
        and as re-seed request on a restarted edge (the ack answers with
        the root's head; a mid-world joiner also gets a full S2C_SYNC)."""
        msg = Message(MyMessage.MSG_TYPE_E2S_EDGE_RESYNC, self.rank, 0)
        msg.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, int(self.version))
        if self.version >= 0:
            # delta ACK: we still hold this version — root S2C deltas may
            # resume against it
            msg.add(MyMessage.MSG_ARG_KEY_DELTA_CAPABLE, 1)
        try:
            self.send_message(msg)
        except Exception as e:  # noqa: BLE001 — root down: FSM takes over
            if self._hb_s <= 0:
                raise
            self._suspect_root(f"edge announce failed: {e}")

    def _note_root_traffic(self) -> None:
        with self._lock:
            self._last_root_traffic = time.monotonic()

    def _arm_heartbeat(self) -> None:
        if self._hb_s <= 0 or self.done.is_set() or self._killed:
            return
        t = threading.Timer(self._hb_s, self._on_heartbeat_tick)
        t.daemon = True
        self.world.register_timer(t)
        t.start()

    def _on_heartbeat_tick(self) -> None:
        if self.done.is_set() or self._killed:
            return
        enter_resync = False
        with self._lock:
            silence = time.monotonic() - self._last_root_traffic
            running = self._fsm_state == "running"
            if running and silence > self._hb_miss_limit * self._hb_s:
                self._fsm_state = "resync"
                self._resync_attempt = 0
                enter_resync = True
        if enter_resync:
            self.world.telemetry.counter_inc("comm.heartbeat_misses")
            logger.warning(
                "edge %d: no root traffic for %.2fs — entering resync",
                self.rank, silence)
            self._attempt_root_resync()
        elif running:
            hb = Message(MyMessage.MSG_TYPE_C2S_HEARTBEAT, self.rank, 0)
            hb.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, int(self.version))
            hb.add(MyMessage.MSG_ARG_KEY_HB_T_SEND, time.monotonic())
            try:
                self.send_message(hb)
            except Exception as e:  # noqa: BLE001
                self._suspect_root(f"heartbeat send failed: {e}")
        self._arm_heartbeat()

    def _suspect_root(self, reason: str) -> None:
        if self._hb_s <= 0 or self.done.is_set() or self._killed:
            return
        with self._lock:
            if self._fsm_state != "running":
                return
            self._fsm_state = "resync"
            self._resync_attempt = 0
        self.world.telemetry.counter_inc("comm.heartbeat_misses")
        logger.warning("edge %d: root connection suspect (%s) — resync",
                       self.rank, reason)
        self._attempt_root_resync()

    def _attempt_root_resync(self) -> None:
        if self.done.is_set() or self._killed:
            return
        with self._lock:
            if self._fsm_state != "resync":
                return
            self._resync_attempt += 1
            attempt = self._resync_attempt
        if attempt > self._resync_max_attempts:
            with self._lock:
                self._fsm_state = "lost"
            logger.error("edge %d: root resync gave up after %d attempts",
                         self.rank, self._resync_max_attempts)
            return
        self.world.telemetry.counter_inc("comm.reconnects")
        try:
            self._announce_to_root()
        except Exception as e:  # noqa: BLE001
            logger.info("edge %d: resync attempt %d failed (%s)",
                        self.rank, attempt, e)
        delay = min(self._resync_base_s * (2.0 ** (attempt - 1)),
                    self._resync_max_s)
        # seeded jitter — see client_manager._attempt_resync
        delay *= 0.5 + self._jitter_rng.rand()
        t = threading.Timer(delay, self._attempt_root_resync)
        t.daemon = True
        self.world.register_timer(t)
        t.start()

    def _on_root_resync_ack(self, msg: Message) -> None:
        """Root answered the handshake: back to RUNNING. A mid-world
        (re)started edge re-solicits its lease block — the fold buffer the
        crash took is recovered from the clients who still hold the
        updates; a live edge that merely rode out a partition re-ships its
        last summary verbatim instead (dedup + the root's committed-round
        guard absorb whatever did arrive)."""
        self._note_root_traffic()
        with self._lock:
            was = self._fsm_state
            self._fsm_state = "running"
            self._resync_attempt = 0
            last_summary = self._last_summary_msg
        head = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, 0))
        try:
            if head > 0 and self.version < 0:
                # fresh replica in an already-running world: this is a
                # restart — re-solicit the lease block's cached updates
                self._resolicit_leased()
            elif was != "running" and last_summary is not None:
                self.world.telemetry.counter_inc("comm.resync_replays")
                logger.info(
                    "edge %d: replaying last summary after resync",
                    self.rank)
                self.send_message(last_summary)
        except Exception as e:  # noqa: BLE001
            self._suspect_root(f"resync replay failed: {e}")

    def _on_root_heartbeat_ack(self, msg: Message) -> None:
        self._note_root_traffic()
        t_echo = msg.get(MyMessage.MSG_ARG_KEY_HB_T_ECHO)
        t_recv = msg.get(MyMessage.MSG_ARG_KEY_HB_T_RECV)
        t_reply = msg.get(MyMessage.MSG_ARG_KEY_HB_T_REPLY)
        if t_echo is not None and t_recv is not None and t_reply is not None:
            self.world.trace.clock_probe(
                peer=0, t_send=float(t_echo), t_peer_recv=float(t_recv),
                t_peer_send=float(t_reply), t_recv=time.monotonic())

    def _on_root_shed(self, msg: Message) -> None:
        """Root admission shed a whole summary: back off, re-offer it
        freshly stamped (the original seq is burned in the root's window)."""
        self._note_root_traffic()
        delay = max(float(
            msg.get(MyMessage.MSG_ARG_KEY_RETRY_AFTER_S, 0.1)), 0.01)
        with self._lock:
            cached = self._last_summary_msg
        if cached is None:
            return
        self.world.telemetry.counter_inc("traffic.client_retries")
        t = threading.Timer(delay, self._reoffer_summary)
        t.daemon = True
        self.world.register_timer(t)
        t.start()

    def _reoffer_summary(self) -> None:
        if self.done.is_set() or self._killed:
            return
        with self._lock:
            cached = self._last_summary_msg
        if cached is None:
            return
        fresh = Message()
        fresh.init({
            k: v for k, v in cached.get_params().items()
            if k not in (Message.MSG_ARG_KEY_SEQ, Message.MSG_ARG_KEY_EPOCH)
        })
        fresh.set_arrays(cached.get_arrays())
        try:
            self.send_message(fresh)
        except Exception as e:  # noqa: BLE001
            self._suspect_root(f"summary re-offer failed: {e}")

    def _resolicit_leased(self) -> None:
        """``e2c_resolicit`` to every leased client: re-offer your cached
        still-stamped update. A fresh dedup window (we just restarted)
        accepts the verbatim replays; the root's committed guard drops the
        ones our predecessor already shipped."""
        with self._lock:
            targets = sorted(self._leased)
        for c in targets:
            m = Message(MyMessage.MSG_TYPE_E2C_RESOLICIT, self.rank, c)
            m.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, int(self.version))
            try:
                self.send_message(m)
            except Exception:  # noqa: BLE001 — dead client: its lease expires
                continue
            with self._lock:
                self._stats["resolicited"] += 1
            self.world.telemetry.counter_inc("edge.resolicited_updates")

    # -- downlink: root model -> replica -> per-client fan-out ----------------

    def _on_root_model(self, msg: Message) -> None:
        self._note_root_traffic()
        if self._killed:
            return
        version = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, 0))
        if not self._install_replica(msg, version):
            return
        with self._lock:
            targets = sorted(self._online & self._leased)
            dispatched = set(self._dispatched)
        # one encode per distinct ACKed base across the whole fan-out
        cache: Dict = {}
        for c in targets:
            self._dispatch_to_client(c, first=c not in dispatched,
                                     cache=cache)

    def _install_replica(self, msg: Message, version: int) -> bool:
        """Install a root dispatch into the replica store — full leaves or
        an S2C delta frame against a version we ACKed (same decode the
        clients run; docs/delivery.md)."""
        dmeta = msg.get(DELTA_KEY)
        if dmeta is None:
            leaves = [np.asarray(a) for a in msg.get_arrays()]
            vec = flatten_leaves(leaves)
            shapes = [a.shape for a in leaves]
        else:
            base = self.store.get(int(dmeta["base_version"]))
            if base is None:
                self.world.telemetry.counter_inc(
                    "comm.delta.client_base_missing")
                logger.error(
                    "edge %d: S2C delta references version %s this replica "
                    "no longer holds — re-announcing for a full frame",
                    self.rank, dmeta.get("base_version"))
                with self._lock:
                    self.version = -1  # clear our ACK: next frame is full
                self._announce_to_root()
                return False
            vec = np.asarray(self.wire.decode(base, msg.get_arrays(), dmeta))
            with self._lock:
                shapes = self._shapes
            if shapes is None:
                return False  # can't have ACKed without a prior full frame
            sizes = [int(np.prod(s)) if s else 1 for s in shapes]
            leaves = [seg.reshape(s) for seg, s in zip(
                np.split(vec, np.cumsum(sizes)[:-1]), shapes)]
        self.store.put(version, vec)
        with self._lock:
            self.version = version
            self._leaves = leaves
            self._vec = vec
            self._shapes = shapes
        return True

    def _dispatch_to_client(self, c: int, first: bool = False,
                            cache: Optional[Dict] = None) -> None:
        """One personalized dispatch from the replica head: INIT for a
        client's first model (carries its data-shard index), SYNC after;
        delta-encoded against the client's last ACKed base when possible."""
        with self._lock:
            version, leaves, vec = self.version, self._leaves, self._vec
            acked = self._acked.get(c)
        if version < 0 or leaves is None:
            return
        mtype = (MyMessage.MSG_TYPE_S2C_INIT_CONFIG if first
                 else MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT)
        msg = Message(mtype, self.rank, c)
        msg.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, version)
        msg.add(MyMessage.MSG_ARG_KEY_CLIENT_INDEX, c - 1)
        base = self.store.get(acked) if (
            acked is not None and acked != version) else None
        if base is not None:
            if cache is not None and int(acked) in cache:
                arrays, meta = cache[int(acked)]
            else:
                arrays, meta = self.wire.encode(base, vec)
                if cache is not None:
                    cache[int(acked)] = (arrays, meta)
            msg.add(DELTA_KEY, {**meta, "base_version": int(acked)})
            msg.set_arrays(arrays)
            self.world.telemetry.counter_inc(
                "comm.edge.s2c_bytes_saved",
                max(payload_nbytes(leaves) - payload_nbytes(arrays), 0))
        else:
            msg.set_arrays(leaves)
        try:
            self.send_message(msg)
            with self._lock:
                self._dispatched.add(c)
        except Exception as e:  # noqa: BLE001 — client gone: lease expires
            logger.info("edge %d: dispatch to client %d failed (%s)",
                        self.rank, c, e)

    def _on_root_finish(self, msg: Message) -> None:
        self._note_root_traffic()
        with self._lock:
            targets = sorted(self._leased)
        for c in targets:
            fin = Message(MyMessage.MSG_TYPE_S2C_FINISH, self.rank, c)
            fin.add(MyMessage.MSG_ARG_KEY_ROUND_IDX,
                    int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, 0)))
            fin.set_arrays(msg.get_arrays())
            try:
                self.send_message(fin)
            except Exception:  # noqa: BLE001
                continue
        logger.info("edge %d: finished (relayed FINISH to %d clients)",
                    self.rank, len(targets))
        with self._lock:
            # release terminal state (graftmem M001/M005): the lease roster
            # and the retained last-summary payload die with the federation
            self._leased.clear()
            self._last_summary_msg = None
        self.done.set()
        self.finish()

    # -- client-facing serving plane ------------------------------------------

    def _record_client_ack(self, msg: Message) -> None:
        """C2S traffic tagged delta-capable ACKs the version the client
        holds — the base the next fan-out delta encodes against (mirror of
        the root's ``_record_ack``)."""
        if not msg.get(MyMessage.MSG_ARG_KEY_DELTA_CAPABLE):
            return
        version = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, -1))
        if version < 0:
            return
        with self._lock:
            prev = self._acked.get(msg.get_sender_id(), -1)
            if version > prev:
                self._acked[msg.get_sender_id()] = version

    def _on_client_status(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        status = msg.get(MyMessage.MSG_ARG_KEY_CLIENT_STATUS)
        if status == MyMessage.CLIENT_STATUS_ONLINE:
            with self._lock:
                adopted = sender not in self._leased
                self._leased.add(sender)
                self._online.add(sender)
                self._acked.pop(sender, None)  # fresh process: ACKs are gone
                self._dispatched.discard(sender)
                have_model = self.version >= 0
            if adopted:
                logger.info("edge %d: adopted client %d via ONLINE",
                            self.rank, sender)
            if have_model:
                # late joiner (or re-announcer): release its first dispatch
                self._dispatch_to_client(sender, first=True)
        else:
            with self._lock:
                self._online.discard(sender)
            logger.info("edge %d: client %d offline", self.rank, sender)
        self._maybe_ship()

    def _on_client_heartbeat(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        with self._lock:
            known = sender in self._leased
        if not known:
            # a client we never leased (re-homed away, or our state died
            # with a restart): silence forces its resync handshake, which
            # is the adoption path — mirror of the root's unknown-client
            # heartbeat policy
            self.world.telemetry.counter_inc("comm.heartbeat_unknown")
            return
        ack = Message(MyMessage.MSG_TYPE_S2C_HEARTBEAT_ACK, self.rank, sender)
        ack.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, int(self.version))
        t_send = msg.get(MyMessage.MSG_ARG_KEY_HB_T_SEND)
        if t_send is not None:
            ack.add(MyMessage.MSG_ARG_KEY_HB_T_ECHO, float(t_send))
            now = time.monotonic()
            ack.add(MyMessage.MSG_ARG_KEY_HB_T_RECV, now)
            ack.add(MyMessage.MSG_ARG_KEY_HB_T_REPLY, now)
        try:
            self.send_message(ack)
        except Exception:  # noqa: BLE001 — client gone: its lease expires
            pass

    def _adopt_and_ack(self, msg: Message, rehomed: bool) -> None:
        """Shared tail of ``c2s_resync`` and ``c2e_rehome``: (re)lease the
        sender, answer with our head + the committed record (the highest
        client round already SHIPPED in a summary — shipped contributions
        are ours to re-deliver, unshipped ones the client must replay),
        then re-dispatch the head so the client re-enters the round loop."""
        sender = msg.get_sender_id()
        self._record_client_ack(msg)
        with self._lock:
            adopted = sender not in self._leased
            self._leased.add(sender)
            self._online.add(sender)
            committed = self._forwarded.get(sender, -1)
            # an unshipped buffered entry also counts as covered — it will
            # ship with the next summary, so a replay would double-buffer
            for e in self._entries:
                if e["sender"] == sender:
                    committed = max(committed, int(e["client_version"]))
            if rehomed and adopted:
                self._stats["rehomed"] += 1
        if rehomed and adopted:
            self.world.telemetry.counter_inc("edge.rehomed_clients")
            logger.info(
                "edge %d: client %d re-homed here (old edge %s)", self.rank,
                sender, msg.get(MyMessage.MSG_ARG_KEY_OLD_EDGE))
        self.world.telemetry.counter_inc("comm.resyncs")
        ack = Message(MyMessage.MSG_TYPE_S2C_RESYNC_ACK, self.rank, sender)
        ack.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, int(self.version))
        ack.add(MyMessage.MSG_ARG_KEY_COMMITTED_ROUND, committed)
        try:
            self.send_message(ack)
        except Exception:  # noqa: BLE001
            return
        # re-engage: the client's replay guard absorbs a version it already
        # trained; a version it missed restarts its round loop
        client_round = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, -1))
        if self.version >= 0 and client_round < self.version:
            with self._lock:
                first = sender not in self._dispatched
            self._dispatch_to_client(sender, first=first)

    def _on_client_resync(self, msg: Message) -> None:
        self._adopt_and_ack(msg, rehomed=False)

    def _on_rehome(self, msg: Message) -> None:
        self._adopt_and_ack(msg, rehomed=True)

    def _on_client_pull(self, msg: Message) -> None:
        """client_pull dispatch: answer now if our replica head is already
        newer than what the sender holds (the next root bump re-dispatches
        to everyone leased, so parking is unnecessary at this tier)."""
        self._record_client_ack(msg)
        sender = msg.get_sender_id()
        held = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, -1))
        if self.version > held >= 0 or (held < 0 <= self.version):
            with self._lock:
                first = sender not in self._dispatched
            self._dispatch_to_client(sender, first=first)

    # -- uplink: client updates -> entry buffer -> summaries ------------------

    def _on_client_model(self, msg: Message) -> None:
        """Buffer one client update as an opaque entry (the control-plane
        pre-fold: admission here, dedup already done by the comm layer,
        staleness annotated against our replica head — the ROOT computes
        the authoritative staleness weight from the same client_version)."""
        if self._maybe_kill_edge("pre_fold"):
            return
        sender = msg.get_sender_id()
        self._record_client_ack(msg)
        client_version = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND_IDX, 0))
        verdict = self.admission.offer()
        if not verdict.admitted:
            shed = Message(MyMessage.MSG_TYPE_S2C_SHED_NOTICE,
                           self.rank, sender)
            shed.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, client_version)
            shed.add(MyMessage.MSG_ARG_KEY_RETRY_AFTER_S,
                     verdict.retry_after_s)
            shed.add(MyMessage.MSG_ARG_KEY_SHED_REASON, verdict.reason)
            try:
                self.send_message(shed)
            except Exception:  # noqa: BLE001
                pass
            return
        from ..core.compression import UpdateCodec
        from ..delivery.payload_filter import FILTER_KEY

        entry = {
            "sender": sender,
            "client_version": client_version,
            "num_samples": float(
                msg.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, 0.0)),
            "codec_meta": msg.get(UpdateCodec.META_KEY),
            "filter_meta": msg.get(FILTER_KEY),
            "arrays": msg.get_arrays(),
            "staleness": max(int(self.version) - client_version, 0),
        }
        self._maybe_delta_encode(entry)
        with self._lock:
            dup = any(e["sender"] == sender
                      and e["client_version"] == client_version
                      for e in self._entries)
            if not dup:
                self._entries.append(entry)
                self._stats["folds"] += 1
                # clamped histogram key (graftmem M001): staleness is
                # unbounded under long partitions; 64+ is one bucket
                s = str(min(int(entry["staleness"]), 64))
                self._stats["staleness"][s] = \
                    self._stats["staleness"].get(s, 0) + 1
        if dup:
            # a replayed round result the comm dedup couldn't see (fresh
            # stamp after shed/re-home) — one buffered copy is enough
            self.world.telemetry.counter_inc("edge.buffer_dedup_drops")
            return
        self.world.telemetry.counter_inc("edge.folds")
        self._maybe_ship()

    def _maybe_delta_encode(self, entry: Dict) -> None:
        """Re-encode a PLAIN full-leaves entry as a lossless delta against
        the version the client trained from — the edge→root summary rides
        delta frames (tentpole requirement) without touching entries the
        client already encoded (compression codec / payload filter).
        Lossless: the root's decode reproduces the leaves bitwise, so the
        fold is unchanged."""
        if entry["codec_meta"] is not None or entry["filter_meta"] is not None:
            return
        base = self.store.get(entry["client_version"])
        if base is None:
            return
        vec = flatten_leaves(entry["arrays"])
        if vec.shape != base.shape or vec.dtype != base.dtype:
            return
        raw = payload_nbytes(entry["arrays"])
        arrays, meta = self.wire.encode(base, vec)
        entry["dmeta"] = {**meta, "base_version": int(entry["client_version"])}
        entry["arrays"] = arrays
        self.world.telemetry.counter_inc(
            "comm.edge.c2s_bytes_saved", max(raw - payload_nbytes(arrays), 0))

    def _arm_flush(self) -> None:
        if self.done.is_set() or self._killed:
            return
        t = threading.Timer(self._flush_s, self._on_flush_tick)
        t.daemon = True
        self.world.register_timer(t)
        t.start()

    def _on_flush_tick(self) -> None:
        if self.done.is_set() or self._killed:
            return
        self._ship_summary()
        self._arm_flush()

    def _maybe_ship(self) -> None:
        """Ship when the buffer hit its fill mark: the whole live lease in
        sync worlds, the edge FedBuff K in async ones."""
        with self._lock:
            target = (len(self._online & self._leased) if self._sync_mode
                      else self._ship_target)
            full = len(self._entries) >= max(int(target), 1) \
                and len(self._entries) > 0
        if full:
            self._ship_summary()

    def _ship_summary(self) -> None:
        """Drain the entry buffer into ONE e2s_edge_summary message (sorted
        by (sender, client_version) — same canonical order the root's own
        buffer drains in) and send it up, kill hooks on either side."""
        with self._lock:
            if not self._entries or self._killed:
                return
            entries = sorted(self._entries,
                             key=lambda e: (e["sender"], e["client_version"]))
            self._entries = []
            self._summary_seq += 1
            seq = self._summary_seq
            self._stats["summaries"] = seq
            stats = {k: (dict(v) if isinstance(v, dict) else v)
                     for k, v in self._stats.items()}
            for e in entries:
                prev = self._forwarded.get(e["sender"], -1)
                self._forwarded[e["sender"]] = max(prev,
                                                   int(e["client_version"]))
        meta, arrays = pack_summary(entries, stats=stats, seq=seq)
        msg = Message(MyMessage.MSG_TYPE_E2S_EDGE_SUMMARY, self.rank, 0)
        msg.add(MyMessage.MSG_ARG_KEY_SUMMARY_META, meta)
        msg.add(MyMessage.MSG_ARG_KEY_ROUND_IDX, int(self.version))
        if self.version >= 0:
            msg.add(MyMessage.MSG_ARG_KEY_DELTA_CAPABLE, 1)
        msg.set_arrays(arrays)
        with self._lock:
            self._last_summary_msg = msg
        if self._maybe_kill_edge("mid_fold"):
            return  # the built summary dies with us — clients re-home
        self.world.telemetry.counter_inc("edge.summaries_sent")
        self.world.telemetry.counter_inc(
            "comm.edge.summary_bytes", payload_nbytes(arrays))
        try:
            self.send_message(msg)
        except Exception as e:  # noqa: BLE001 — root gone: FSM replays it
            self._suspect_root(f"summary send failed: {e}")
            return
        self._maybe_kill_edge("post_commit")
