"""Hierarchical edge-aggregation tier (ISSUE 19, docs/traffic.md
"Hierarchical edge tier" / docs/robustness.md "Edge tier failure domains").

A two-tier federation: E edge aggregators lease blocks of clients, run the
FedBuff admission/dedup/staleness control plane locally against their own
:class:`~fedml_tpu.delivery.VersionedModelStore` replica, and ship
*entry-preserving* buffer summaries up to the root — one batched frame per
summary instead of one message per client. The root expands the entries
through the exact same decode + fold + aggregate code the flat path uses,
which is what makes a 2-tier run bitwise-equal to flat FedBuff (float
addition is non-associative, so any numerically pre-folded two-tier
reduction could not be).

reference: the shape named by ``cross_silo/client/process_group_manager.py``
and the Beehive cross-device pillar — re-founded here as a failure-domain
tier: edges crash, partition and straggle as first-class chaos subjects
(clients re-home, edges resync, contributions fold exactly once).
"""

from .topology import Topology
from .summary import pack_summary, unpack_summary

__all__ = ["Topology", "pack_summary", "unpack_summary",
           "EdgeAggregatorManager"]


def __getattr__(name):
    # EdgeAggregatorManager pulls in the comm stack (jax, transports);
    # keep `from fedml_tpu.hierarchy import Topology` import-light
    if name == "EdgeAggregatorManager":
        from .edge_manager import EdgeAggregatorManager

        return EdgeAggregatorManager
    raise AttributeError(name)
