"""Entry-preserving edge buffer summaries (docs/traffic.md).

An edge summary is ONE message carrying every update the edge's FedBuff
buffer drained, as a list of *entries*. Each entry keeps the client's
original control-plane identity — sender rank, the model version it
trained against (``client_version``), its sample weight — next to its
payload frame, verbatim or re-encoded as a lossless delta against the
edge's model-store replica. The root expands the entries and runs the
exact same decode + fold + aggregate code a flat world runs per client
message; the summary only batches the *transport*, never the math. That
is the entire bitwise-parity argument: float addition is non-associative,
so a numerically pre-folded summary could not reproduce the flat
trajectory — an entry-preserving one cannot fail to.

Wire layout: the message's array list is the concatenation of the
entries' frames; ``MSG_ARG_KEY_SUMMARY_META`` carries the JSON-safe
per-entry metadata (including each entry's frame count, so unpacking is
pure slicing) plus the edge's piggybacked health stats.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple


def pack_summary(entries: Sequence[Dict], stats: Optional[Dict] = None,
                 seq: int = 0) -> Tuple[Dict, List]:
    """``entries`` → ``(meta, arrays)`` for one summary message.

    Each entry is a dict with ``sender`` / ``client_version`` /
    ``num_samples`` / ``arrays`` and optionally ``codec_meta`` /
    ``filter_meta`` (the client's own C2S encodings, forwarded untouched),
    ``dmeta`` (an edge-side lossless delta re-encode of a plain frame
    against the replica store) and ``staleness`` (edge-view annotation).
    """
    meta_entries = []
    arrays: List = []
    for e in entries:
        frames = list(e["arrays"])
        meta_entries.append({
            "sender": int(e["sender"]),
            "client_version": int(e["client_version"]),
            "num_samples": float(e["num_samples"]),
            "codec_meta": e.get("codec_meta"),
            "filter_meta": e.get("filter_meta"),
            "dmeta": e.get("dmeta"),
            "staleness": int(e.get("staleness", 0)),
            "k": len(frames),
        })
        arrays.extend(frames)
    meta = {"seq": int(seq), "entries": meta_entries}
    if stats is not None:
        meta["stats"] = stats
    return meta, arrays


def unpack_summary(meta: Dict, arrays: Sequence) -> List[Dict]:
    """Inverse of :func:`pack_summary`: slice the concatenated frame list
    back into per-entry dicts (``arrays`` per entry, metadata inlined)."""
    out: List[Dict] = []
    i = 0
    for m in meta.get("entries", ()):
        k = int(m["k"])
        e = dict(m)
        e["arrays"] = list(arrays[i:i + k])
        i += k
        out.append(e)
    if i != len(arrays):
        raise ValueError(
            f"edge summary: {len(arrays)} frames but entries consume {i}")
    return out
