"""Chaos soak harness: prove the crash-recovery plane end-to-end.

reference: none — the reference has no recovery soak of any kind (SURVEY.md
§5). This harness runs the SAME loopback cross-silo federation twice:

1. **reference leg** — fault-free, in-process, to completion;
2. **chaos leg** — a subprocess under a seeded fault matrix (visible loss +
   wire duplication + payload corruption on every client link) that
   SIGTERMs ITSELF after the run ledger commits round ``kill_round``, then
   a second subprocess restarts it with ``--resume auto``;

and asserts the recovered run's final global params are **bitwise equal**
to the fault-free run's, that no client contribution was ever counted
twice (per-round contribution counters from the durable ledger), and that
the combined ledger stream covers every round exactly like the reference
run's. That is the "kill -9 anywhere, restart, converge to the same
params" invariant as an executable check — ``fedml_tpu chaos`` from the
CLI, ``tools/chaos_smoke.sh`` in CI.

Why this catches real bugs: visible loss exercises the at-least-once retry
budget, duplication exercises the receiver dedup window, corruption
exercises the payload checksum + NACK re-send, and the mid-run SIGTERM +
restart exercises the preemption drain, the Orbax round checkpoint, and
ledger-driven resume — all composed, all seeded, all reproducible.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

import numpy as np

logger = logging.getLogger(__name__)

FINAL_PARAMS_FILE = "final_params.npz"
REPORT_FILE = "chaos_report.json"


def _world_overrides(a) -> Dict:
    over = dict(
        training_type="cross_silo", dataset="synthetic", model="lr",
        client_num_in_total=int(a.clients), client_num_per_round=int(a.clients),
        comm_round=int(a.rounds), epochs=int(a.epochs), batch_size=8,
        learning_rate=0.2, backend="LOOPBACK", frequency_of_the_test=1000,
        random_seed=int(a.seed),
    )
    if _kill_phase(a) or _edge_fault(a) \
            or float(getattr(a, "heartbeat_s", 0.0) or 0.0) > 0:
        # server-kill legs need the client liveness/resync FSM: a fast
        # lease so a dead server is detected within ~a second, and a
        # patient reconnect budget that rides out the restart leg's
        # process spawn + jax import (tens of seconds on a cold host).
        # Edge-fault legs run the same FSM one tier down (client↔edge,
        # edge↔root).
        over.update(
            heartbeat_s=float(getattr(a, "heartbeat_s", 0.0) or 0.3),
            heartbeat_miss_limit=2,
            resync_backoff_s=0.3,
            resync_backoff_max_s=2.0,
            resync_max_attempts=90,
        )
    if _edge_fault(a):
        # a killed edge's orphans must give up on the corpse quickly and
        # re-home to a sibling (docs/robustness.md "Edge tier failure
        # domains") instead of burning the whole resync budget on it
        over.update(rehome_after_attempts=2)
    if _partition_window(a) is not None \
            or _edge_partition_window(a) is not None:
        # a healed partition must cost backoff, not contributions: give
        # the at-least-once layer enough retry budget to outlast the cut
        over.update(comm_retry_max_attempts=10)
    scheme = str(getattr(a, "compression", "") or "")
    if scheme:
        # BOTH legs (reference and chaos) run compressed + delta-shipped:
        # the bitwise verdict then proves dedup, payload digests and the
        # version store survive delta frames under faults. Stateless
        # schemes only — eftopk's client-side residual dies with the
        # killed process and would legitimately diverge the resumed leg.
        if scheme == "eftopk":
            raise ValueError(
                "chaos --compression eftopk cannot hold bitwise parity "
                "across a kill/restart (client residual state is lost); "
                "use topk/quantize/qsgd"
            )
        over.update(compression=scheme,
                    compression_ratio=float(
                        getattr(a, "compression_ratio", 0.1)))
    tdir = str(getattr(a, "trace_dir", "") or "")
    if tdir:
        # traced leg (server-kill chaos runs set this): spans persist
        # through the JSONL sink into the shared trace dir, and the flight
        # recorder's pre-SIGKILL flush lands its post-mortem there too —
        # the orchestrator's verdict reads both
        over.update(enable_tracing=True, trace_sample=1.0, trace_dir=tdir,
                    enable_tracking=True, tracking_dir=tdir)
    return over


def _kill_phase(a) -> str:
    return str(getattr(a, "kill_phase", "") or "")


def _edge_count(a) -> int:
    return int(getattr(a, "edges", 0) or 0)


def _edge_kill_phase(a) -> str:
    return str(getattr(a, "kill_edge", "") or "")


def _edge_partition_window(a):
    """Parse ``--edge-partition START:DURATION`` — the root–edge cut — or
    None when unset."""
    raw = str(getattr(a, "edge_partition", "") or "")
    if not raw:
        return None
    try:
        start_s, dur_s = raw.split(":", 1)
        return float(start_s), float(dur_s)
    except ValueError as e:
        raise ValueError(
            f"--edge-partition wants START:DURATION seconds, got {raw!r}"
        ) from e


def _edge_fault(a) -> bool:
    return bool(_edge_kill_phase(a) or _edge_partition_window(a) is not None)


def _partition_window(a):
    """Parse ``--partition START:DURATION`` (seconds) into a (start,
    duration) tuple, or None when the flag is unset."""
    raw = str(getattr(a, "partition", "") or "")
    if not raw:
        return None
    try:
        start_s, dur_s = raw.split(":", 1)
        return float(start_s), float(dur_s)
    except ValueError as e:
        raise ValueError(
            f"--partition wants START:DURATION seconds, got {raw!r}"
        ) from e


def build_fault_plan(rank: int, seed: int, loss: float, duplicate: float,
                     corrupt: float, partition=None):
    """Seeded per-client fault matrix. Loss is VISIBLE (the sender sees the
    failure and retries — the at-least-once contract under test); rank
    decorrelates the client streams while keeping each reproducible.
    ``partition`` = (start_s, duration_s) cuts this client off from the
    server for the window — bidirectionally, since the server's own plan
    carries the same rule."""
    from .core.distributed.faults import FaultPlan

    plan = FaultPlan()
    if loss > 0:
        plan.loss(loss, seed=seed * 1000 + rank, visible=True)
    if duplicate > 0:
        plan.duplicate(p=duplicate, seed=seed * 2000 + rank)
    if corrupt > 0:
        plan.corrupt(p=corrupt, seed=seed * 3000 + rank)
    if partition is not None:
        plan.partition({0}, start_s=partition[0], duration_s=partition[1])
    return plan


def _resolved_heartbeat_s(a, kill_context: bool) -> float:
    """The heartbeat interval a leg actually runs with: the user's value,
    or the fast-lease default on kill legs (where the FSM is the thing
    under test). Resolving it HERE — once, for every leg — keeps the
    reference, killed, restart and client-process legs on one config."""
    hb = float(getattr(a, "heartbeat_s", 0.0) or 0.0)
    if hb <= 0 and kill_context:
        hb = 0.3
    return hb


def client_proc_cmd(a, rank: int, port: int,
                    kill_phase: str = "") -> List[str]:
    """The ONE spawn command for a real gRPC chaos client process — used
    by both the worker-owned leg (run_world) and the orchestrator-owned
    crash-failover leg, so their fault matrices can never decorrelate."""
    from fedml_tpu.traffic.swarm import python_module_cmd

    hb = _resolved_heartbeat_s(a, bool(kill_phase or _kill_phase(a)))
    cmd = python_module_cmd(
        "fedml_tpu.cli", "chaos", "--client",
        "--client_rank", str(rank), "--port", str(port),
        "--clients", str(a.clients), "--rounds", str(a.rounds),
        "--epochs", str(a.epochs), "--seed", str(a.seed),
        "--loss", str(a.loss), "--duplicate", str(a.duplicate),
        "--corrupt", str(a.corrupt),
        "--partition", str(getattr(a, "partition", "") or ""),
        "--heartbeat_s", str(hb),
        "--compression", str(getattr(a, "compression", "") or ""),
        "--compression_ratio", str(getattr(a, "compression_ratio", 0.1)),
        "--trace_dir", str(getattr(a, "trace_dir", "") or ""),
    )
    if kill_phase:
        # turns the client liveness/resync FSM on (matching the
        # _world_overrides the server legs run with)
        cmd += ["--kill-phase", kill_phase]
    return cmd


def build_server_fault_plan(a):
    """The SERVER side of the fault matrix: the kill switch (SIGKILL at a
    protocol phase) and/or its half of a partition cut. None when the
    server runs fault-free."""
    from .core.distributed.faults import FaultPlan

    plan = None
    kp = _kill_phase(a)
    if kp:
        plan = FaultPlan().kill_server(kp, int(a.kill_round))
    window = _partition_window(a)
    if window is not None:
        plan = plan or FaultPlan()
        plan.partition({0}, start_s=window[0], duration_s=window[1])
    return plan


def run_world(a, run_id: str, checkpoint_dir: str, faulty: bool,
              kill_round: int = -1, server_only: bool = False) -> Dict:
    """One cross-silo federation: server in THIS process; clients either as
    loopback threads (default) or — with ``--transport grpc`` on a faulty
    leg — as REAL client OS processes over multiprocess gRPC, spawned
    through the swarm harness's :class:`ProcSpawner` (ISSUE 7 satellite:
    chaos matrices beyond loopback). ``server_only`` runs JUST the server
    against ``a.port`` — the crash-failover flow, where the orchestrator
    owns long-lived client processes that must survive (and resync across)
    this server process's SIGKILL + restart.

    Returns {"params": leaves, "server": manager, "preempted": bool}. With
    ``kill_round >= 0`` a watcher thread SIGTERMs THIS process as soon as
    the run ledger commits that round — the real preemption path, timed
    deterministically off the durable commit rather than a sleep. With
    ``--kill-phase`` the server's fault plan SIGKILLs instead, at the
    armed protocol phase (faults.FaultPlan.kill_server).
    """
    import fedml_tpu as fedml
    from fedml_tpu import data as data_mod
    from fedml_tpu import models as model_mod
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.core import runstate
    from fedml_tpu.cross_silo import FedMLCrossSiloClient, FedMLCrossSiloServer

    from fedml_tpu.parallel.multihost import free_port

    grpc_leg = (faulty and not server_only and str(
        getattr(a, "transport", "loopback")).lower() == "grpc")
    port = free_port() if grpc_leg else int(getattr(a, "port", 0) or 0)
    # the edge tier rides the FAULTY leg only: the reference leg stays a
    # flat fault-free federation, so the bitwise verdict proves a 2-tier
    # chaos run converges to EXACTLY the flat FedBuff params
    tiered = faulty and _edge_count(a) > 0
    if tiered and (grpc_leg or server_only):
        raise ValueError(
            "chaos --edges composes with the loopback transport only")

    def mk(role, rank=0):
        overrides = dict(
            _world_overrides(a), role=role, rank=rank, run_id=run_id,
            checkpoint_dir=checkpoint_dir,
            checkpoint_rounds=int(a.checkpoint_rounds),
        )
        if tiered:
            overrides.update(
                hierarchy_edges=_edge_count(a),
                hierarchy_edge_rank_base=int(a.clients) + 1,
            )
        if grpc_leg or server_only:
            overrides.update(backend="GRPC", comm_port=port,
                             comm_host="127.0.0.1")
        return fedml.init(Arguments(overrides=overrides),
                          should_init_logs=False)

    args_s = mk("server")
    if faulty:
        server_plan = build_server_fault_plan(a)
        if server_plan is not None:
            args_s.fault_plan = server_plan
    ds, od = data_mod.load(args_s)
    bundle = model_mod.create(args_s, od)
    server = FedMLCrossSiloServer(args_s, None, ds, bundle)

    edge_managers: List = []
    if tiered:
        from fedml_tpu.core.distributed.faults import FaultPlan
        from fedml_tpu.hierarchy import EdgeAggregatorManager, Topology

        topo = Topology.from_args(args_s)
        ekill = _edge_kill_phase(a)
        ewin = _edge_partition_window(a)
        for i, er in enumerate(topo.edge_ranks):
            args_e = mk("client", er)
            if i == 0 and (ekill or ewin is not None):
                # the FIRST edge is the designated failure domain: it takes
                # the kill switch (in-process fail-stop at the armed phase,
                # first hit) and/or the root-link cut; its siblings stay
                # healthy so orphaned clients have somewhere to re-home
                plan = FaultPlan()
                if ekill:
                    plan.kill_edge(ekill, -1)
                if ewin is not None:
                    plan.partition({0}, start_s=ewin[0], duration_s=ewin[1])
                args_e.fault_plan = plan
            edge = EdgeAggregatorManager(args_e, rank=er,
                                         size=topo.world_size)
            edge.run_async()
            edge_managers.append(edge)

    partition = _partition_window(a) if faulty else None
    clients = []
    spawner = None
    if server_only:
        pass  # the orchestrator owns the client processes
    elif grpc_leg:
        from fedml_tpu.traffic.swarm import ProcSpawner

        spawner = ProcSpawner()
        for rank in range(1, int(a.clients) + 1):
            spawner.spawn(client_proc_cmd(a, rank, port))
    else:
        for rank in range(1, int(a.clients) + 1):
            args_c = mk("client", rank)
            if faulty:
                args_c.fault_plan = build_fault_plan(
                    rank, int(a.seed), float(a.loss), float(a.duplicate),
                    float(a.corrupt), partition=partition,
                )
            clients.append(FedMLCrossSiloClient(args_c, None, ds, bundle))

    if kill_round >= 0 and _kill_phase(a):
        kill_round = -1  # the phase switch owns the kill; no SIGTERM watcher
    if kill_round >= 0:
        ledger = runstate.RunLedger.for_checkpoint_dir(checkpoint_dir)
        stop_watch = threading.Event()

        def watch():
            while not stop_watch.is_set():
                last = ledger.last_round()
                if last is not None and last >= kill_round:
                    logger.warning(
                        "chaos: round %d committed — SIGTERM self", last
                    )
                    os.kill(os.getpid(), signal.SIGTERM)
                    return
                time.sleep(0.02)

        watcher = threading.Thread(target=watch, daemon=True,
                                   name="chaos-kill-watcher")
        watcher.start()

    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    time.sleep(0.05)
    try:
        server.run()
    except runstate.PreemptionError:
        pass  # expected under kill_round; reported via the preempted flag
    finally:
        if spawner is not None:
            # a preempted server leaves its client processes blocked on a
            # dead endpoint: reap them (the resumed leg spawns fresh ones,
            # which re-train the resumed round from its re-broadcast INIT)
            if not server.manager.preempted:
                spawner.wait_all(timeout_s=30.0)
            spawner.kill_all()
        # reap the in-process client threads: on a clean FINISH they exit
        # promptly; a preempted leg leaves them parked on a dead endpoint,
        # so the join is deadline-bounded (they are daemons — the process
        # exit that follows a preemption reclaims them)
        deadline = time.monotonic() + 5.0
        for t in threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.05))
        for em in edge_managers:
            # clean FINISH already tore these down via _on_root_finish;
            # a killed edge's world is drained here instead
            em.done.set()
            em.finish()
    if kill_round >= 0:
        stop_watch.set()
        watcher.join(timeout=5.0)
    import jax

    leaves = [np.asarray(l)
              for l in jax.tree.leaves(server.manager.global_params)]
    return {
        "params": leaves,
        "server": server.manager,
        "preempted": bool(server.manager.preempted),
        "edges": edge_managers,
    }


# ---------------------------------------------------------------------------
# worker entry (the subprocess the orchestrator spawns)
# ---------------------------------------------------------------------------


def run_worker(a) -> int:
    """One chaos leg in THIS process: run the faulty world, write the final
    params + report into --out, exit EXIT_PREEMPTED if preempted. A
    ``--kill-phase`` leg never reaches the report: the armed fault plan
    SIGKILLs this process at the protocol phase — the restart leg (same
    checkpoint_dir, no kill) writes them instead."""
    from fedml_tpu.core.runstate import EXIT_PREEMPTED

    os.makedirs(a.out, exist_ok=True)
    result = run_world(
        a, run_id=f"chaos-{os.getpid()}", checkpoint_dir=a.checkpoint_dir,
        faulty=True, kill_round=int(a.kill_round),
        server_only=bool(getattr(a, "server_only", False)),
    )
    report = {
        "preempted": result["preempted"],
        "round_idx": int(result["server"].round_idx),
        "contrib_counts": {
            str(r): {str(k): v for k, v in per.items()}
            for r, per in result["server"].contrib_counts.items()
        },
    }
    if result.get("edges"):
        # the tiered leg's edge verdict half: which edges the fault plan
        # actually fail-stopped, plus the re-homing/dedup counters the
        # orchestrator gates on (everything runs in THIS process under
        # loopback, so the registry sees all tiers)
        from fedml_tpu.core.mlops import telemetry

        counters = telemetry.registry().snapshot()["counters"]
        report["edge_tier"] = {
            "edges": len(result["edges"]),
            "killed_edges": sorted(
                e.rank for e in result["edges"] if e.killed),
            "edge_kill_exercised": any(e.killed for e in result["edges"]),
            "rehomed_clients": counters.get("comm.rehomes", 0.0),
            "root_adoptions": counters.get("edge.root_adoptions", 0.0),
            "edge_rehome_adoptions": counters.get(
                "edge.rehomed_clients", 0.0),
            "resolicited_updates": counters.get(
                "edge.resolicited_updates", 0.0),
            "edge_resyncs": counters.get("comm.edge_resyncs", 0.0),
            "heartbeat_misses": counters.get("comm.heartbeat_misses", 0.0),
            "resync_replays": counters.get("comm.resync_replays", 0.0),
            "replay_dedup_drops": counters.get(
                "traffic.replay_dedup_drops", 0.0),
            "summaries_folded": counters.get("edge.summaries_folded", 0.0),
            "direct_client_updates": counters.get(
                "edge.direct_client_updates", 0.0),
        }
    with open(os.path.join(a.out, REPORT_FILE), "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
    if not result["preempted"]:
        np.savez(os.path.join(a.out, FINAL_PARAMS_FILE), *result["params"])
    return EXIT_PREEMPTED if result["preempted"] else 0


# ---------------------------------------------------------------------------
# orchestrator
# ---------------------------------------------------------------------------


def _worker_cmd(a, out: str, ckpt_dir: str, kill_round: int,
                kill_phase: str = "", server_only: bool = False,
                port: int = 0) -> List[str]:
    cmd = [
        sys.executable, "-m", "fedml_tpu.cli", "chaos", "--worker",
        "--out", out, "--checkpoint_dir", ckpt_dir,
        "--clients", str(a.clients), "--rounds", str(a.rounds),
        "--epochs", str(a.epochs), "--seed", str(a.seed),
        "--loss", str(a.loss), "--duplicate", str(a.duplicate),
        "--corrupt", str(a.corrupt),
        "--checkpoint_rounds", str(a.checkpoint_rounds),
        "--kill-round", str(kill_round),
        "--kill-phase", kill_phase,
        "--partition", str(getattr(a, "partition", "") or ""),
        "--edges", str(_edge_count(a)),
        "--kill-edge", _edge_kill_phase(a),
        "--edge-partition", str(getattr(a, "edge_partition", "") or ""),
        "--transport", str(getattr(a, "transport", "loopback")),
        "--compression", str(getattr(a, "compression", "") or ""),
        "--compression_ratio", str(getattr(a, "compression_ratio", 0.1)),
        "--trace_dir", str(getattr(a, "trace_dir", "") or ""),
    ]
    if server_only:
        cmd += ["--server-only", "--port", str(port)]
    # the RESOLVED heartbeat interval reaches every leg — killed AND
    # restart (whose own kill_phase is "") — so parity never compares
    # two different FSM configs
    cmd += ["--heartbeat_s",
            str(_resolved_heartbeat_s(
                a, bool(kill_phase or server_only or _kill_phase(a))))]
    return cmd


SIGKILL_RCS = (-9, 137)  # subprocess returncode forms of a SIGKILL death


def _run_leg(cmd: List[str], timeout_s: float) -> int:
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
        "JAX_PLATFORMS", "cpu"))
    proc = subprocess.run(
        cmd, timeout=timeout_s, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    if proc.stdout:
        sys.stderr.write(proc.stdout.decode(errors="replace")[-4000:])
    return proc.returncode


def orchestrate(a) -> int:
    """Reference leg (in-process, fault-free) vs chaos leg (subprocess,
    faults + self-SIGTERM + resumed subprocess); verify bitwise parity and
    exactly-once contribution counting. Returns a process exit code."""
    from fedml_tpu.core.runstate import EXIT_PREEMPTED, RunLedger

    workdir = a.workdir or tempfile.mkdtemp(prefix="fedml_chaos_")
    os.makedirs(workdir, exist_ok=True)
    ref_ckpt = os.path.join(workdir, "ref_ckpt")
    chaos_ckpt = os.path.join(workdir, "chaos_ckpt")
    chaos_out = os.path.join(workdir, "chaos_out")

    logger.info("chaos: reference (fault-free) leg …")
    from fedml_tpu.core import world as world_mod

    threads_before = world_mod.thread_snapshot()
    ref = run_world(a, run_id=f"chaos-ref-{os.getpid()}-{time.time_ns()}",
                    checkpoint_dir=ref_ckpt, faulty=False)
    ref_params = ref["params"]
    # thread-leak witness (graftiso I005's runtime half): the in-process
    # world must not leak a non-daemon thread past its shutdown
    leaked = world_mod.leaked_threads(threads_before)
    if leaked:
        print(json.dumps({"ok": False,
                          "error": f"reference leg leaked threads: "
                                   f"{leaked}"}))
        return 1

    kill_round = int(a.kill_round)
    kill_phase = _kill_phase(a)
    if _edge_fault(a) and not kill_phase:
        # edge-fault legs complete in ONE worker process: the edge dies (or
        # rides out its partition) in-process and the federation must
        # survive it — the default self-SIGTERM would add an unrelated
        # server preemption on top
        kill_round = -1
    if kill_phase:
        # server-kill legs run traced: the pre-SIGKILL flight-recorder
        # flush must leave a post-mortem naming the kill phase, and the
        # killed + restarted legs' spans must merge orphan-free. Resolved
        # onto the namespace so _worker_cmd and client_proc_cmd (both
        # read ``a.trace_dir``) ship the SAME dir to every process. The
        # reference leg ran above, untraced — tracing must never be a
        # parity variable.
        a.trace_dir = (str(getattr(a, "trace_dir", "") or "")
                       or os.path.join(workdir, "trace"))
    grpc_failover = (kill_phase and str(
        getattr(a, "transport", "loopback")).lower() == "grpc")
    client_spawner = None
    port = 0
    if grpc_failover:
        # the crash-failover flow: the ORCHESTRATOR owns the client
        # processes, so they survive the server's SIGKILL and must resync
        # (heartbeat miss -> bounded reconnect -> c2s_resync -> replay)
        # onto the restarted server process at the same port
        from fedml_tpu.parallel.multihost import free_port
        from fedml_tpu.traffic.swarm import ProcSpawner

        port = free_port()
        client_spawner = ProcSpawner()
        for rank in range(1, int(a.clients) + 1):
            client_spawner.spawn(
                client_proc_cmd(a, rank, port, kill_phase=kill_phase))
    if kill_phase:
        logger.info("chaos: faulty leg (loss=%.2f dup=%.2f corrupt=%.2f, "
                    "SIGKILL at %s of round %d) …", a.loss, a.duplicate,
                    a.corrupt, kill_phase, kill_round)
    else:
        logger.info("chaos: faulty leg (loss=%.2f dup=%.2f corrupt=%.2f, "
                    "self-SIGTERM after round %d) …", a.loss, a.duplicate,
                    a.corrupt, kill_round)
    try:
        rc1 = _run_leg(
            _worker_cmd(a, chaos_out, chaos_ckpt, kill_round,
                        kill_phase=kill_phase, server_only=grpc_failover,
                        port=port),
            float(a.timeout))
        killed = rc1 == EXIT_PREEMPTED or (kill_phase
                                           and rc1 in SIGKILL_RCS)
        if not killed and rc1 != 0:
            print(json.dumps({"ok": False,
                              "error": f"chaos leg failed rc={rc1}"}))
            return 1
        if kill_phase and not killed:
            print(json.dumps({
                "ok": False,
                "error": f"kill-phase {kill_phase!r} of round {kill_round} "
                         "never fired (rc=0) — the armed phase was not "
                         "reached"}))
            return 1
        if kill_round >= 0 and not kill_phase and not killed:
            # the federation outran the watcher — still verify parity, but
            # report that preemption wasn't exercised so CI can tighten
            # knobs
            logger.warning("chaos: run completed before the SIGTERM landed")

        if killed:
            logger.info("chaos: killed as planned (rc=%d) — restarting "
                        "with --resume auto …", rc1)
            rc2 = _run_leg(
                _worker_cmd(a, chaos_out, chaos_ckpt, -1,
                            server_only=grpc_failover, port=port),
                float(a.timeout))
            if rc2 != 0:
                print(json.dumps({"ok": False,
                                  "error": f"resume leg failed rc={rc2}"}))
                return 1
        if client_spawner is not None:
            # every surviving client process must have resynced its way to
            # FINISH — a wedged resync FSM shows up here as a nonzero exit
            client_rcs = client_spawner.wait_all(
                timeout_s=float(a.timeout))
            if any(rc != 0 for rc in client_rcs):
                print(json.dumps({
                    "ok": False,
                    "error": f"client processes did not all reach FINISH "
                             f"across the server kill: {client_rcs}"}))
                return 1
    finally:
        if client_spawner is not None:
            client_spawner.kill_all()

    with np.load(os.path.join(chaos_out, FINAL_PARAMS_FILE)) as z:
        chaos_params = [z[k] for k in z.files]

    # -- verdicts -----------------------------------------------------------
    problems: List[str] = []
    if len(chaos_params) != len(ref_params):
        problems.append("param tree arity mismatch")
    else:
        for i, (x, y) in enumerate(zip(ref_params, chaos_params)):
            if x.dtype != y.dtype or x.shape != y.shape \
                    or not np.array_equal(x, y):
                problems.append(f"params leaf {i} not bitwise equal")

    ledger = RunLedger.for_checkpoint_dir(chaos_ckpt)
    rounds_seen: Dict[int, Dict] = {}
    round_counts: Dict[int, int] = {}
    double_counted: List[str] = []
    for e in ledger.rounds():
        rounds_seen[int(e["round"])] = e
        round_counts[int(e["round"])] = round_counts.get(
            int(e["round"]), 0) + 1
        for client, count in (e.get("contrib") or {}).items():
            if int(count) > 1:
                double_counted.append(
                    f"round {e['round']} client {client} counted {count}x"
                )
    if double_counted:
        problems.append("double-counted contributions: "
                        + "; ".join(double_counted))
    expect_rounds = set(range(int(a.rounds)))
    missing = expect_rounds - set(rounds_seen)
    if missing:
        problems.append(f"ledger missing committed rounds: {sorted(missing)}")
    if kill_phase:
        # a SIGKILL never drains, so no crash round is ever committed
        # twice: the combined ledger must hold EXACTLY one entry per round
        dups = sorted(r for r, n in round_counts.items() if n > 1)
        if dups:
            problems.append(
                f"ledger committed rounds more than once: {dups}")
    full_cohort = list(range(1, int(a.clients) + 1))
    bad_cohorts = [r for r, e in sorted(rounds_seen.items())
                   if sorted(e.get("cohort") or []) != full_cohort]
    if bad_cohorts:
        problems.append(f"rounds aggregated a partial cohort: {bad_cohorts}")

    edge_block = None
    if _edge_count(a) > 0:
        # tiered-leg verdict half: the worker's report must show the armed
        # edge fault actually fired AND the orphans found a new home —
        # a leg that never exercised the failure domain proves nothing
        try:
            with open(os.path.join(chaos_out, REPORT_FILE),
                      encoding="utf-8") as f:
                edge_block = (json.load(f) or {}).get("edge_tier")
        except (OSError, ValueError):
            edge_block = None
        if not edge_block:
            problems.append("tiered leg wrote no edge_tier report block")
        else:
            if float(edge_block.get("direct_client_updates", 0) or 0) > 0 \
                    and not _edge_kill_phase(a):
                # direct updates are LEGAL only as the degraded mode an
                # edge death forces; any other leg must stay two-tier
                problems.append("root folded direct client updates in a "
                                "fault-free edge tier")
            if _edge_kill_phase(a):
                if not edge_block.get("edge_kill_exercised"):
                    problems.append(
                        f"edge kill phase {_edge_kill_phase(a)!r} never "
                        "fired — the armed phase was not reached")
                rehomed = (float(edge_block.get("rehomed_clients", 0) or 0)
                           + float(edge_block.get("root_adoptions", 0)
                                   or 0))
                if rehomed <= 0:
                    problems.append(
                        "edge kill leg saw no client re-homing")
            if _edge_partition_window(a) is not None:
                cut_seen = (
                    float(edge_block.get("heartbeat_misses", 0) or 0)
                    + float(edge_block.get("resync_replays", 0) or 0))
                if cut_seen <= 0:
                    problems.append(
                        "root–edge partition leg never exercised the "
                        "edge resync FSM (no heartbeat miss, no replay)")

    flight_verdict = None
    trace_spans = None
    trace_orphans = None
    if kill_phase:
        flight_verdict, trace_spans, trace_orphans = _trace_verdict(
            str(a.trace_dir), kill_phase, kill_round, problems)

    verdict = {
        "ok": not problems,
        "parity": not any("leaf" in p or "arity" in p for p in problems),
        "preemption_exercised": bool(killed),
        "rounds": int(a.rounds),
        "clients": int(a.clients),
        "fault_matrix": {"loss": float(a.loss),
                         "duplicate": float(a.duplicate),
                         "corrupt": float(a.corrupt),
                         "seed": int(a.seed),
                         "kill_phase": kill_phase or None,
                         "partition": str(getattr(a, "partition", "")
                                          or "") or None,
                         "edges": _edge_count(a) or None,
                         "kill_edge": _edge_kill_phase(a) or None,
                         "edge_partition": str(getattr(a, "edge_partition",
                                                       "") or "") or None},
        "edge_tier": edge_block,
        "problems": problems,
        "workdir": workdir,
        "flight_recorder": flight_verdict,
        "trace_spans": trace_spans,
        "trace_orphans": trace_orphans,
    }
    print(json.dumps(verdict, indent=2, sort_keys=True))
    return 0 if verdict["ok"] else 1


def _trace_verdict(trace_dir: str, kill_phase: str, kill_round: int,
                   problems: List[str]):
    """Traced kill-leg verdict half: (a) a pre-SIGKILL flight-recorder
    post-mortem exists and its last phase mark names EXACTLY the armed
    kill phase+round; (b) the killed and restarted legs' spans merge into
    one orphan-free trace (flight rings recover the dead process's tail).
    Appends failures to ``problems``; returns the verdict fields."""
    import glob as glob_mod

    from fedml_tpu.core.mlops import tracing

    flight = None
    for path in sorted(glob_mod.glob(
            os.path.join(trace_dir, "flight_*_rank_0.json"))):
        try:
            with open(path, encoding="utf-8") as f:
                post = json.load(f)
        except (OSError, ValueError):
            continue
        if str(post.get("reason", "")).startswith("kill_server:"):
            flight = post
            break
    flight_verdict: Optional[Dict] = None
    if flight is None:
        problems.append(
            "no pre-SIGKILL flight-recorder post-mortem in trace dir")
    else:
        last = flight.get("last_phase") or {}
        flight_verdict = {"reason": flight.get("reason"),
                          "phase": last.get("phase"),
                          "round": last.get("round"),
                          "open_spans": len(flight.get("open_spans") or [])}
        if last.get("phase") != kill_phase:
            problems.append(
                f"post-mortem names phase {last.get('phase')!r}, "
                f"expected {kill_phase!r}")
        elif int(last.get("round", -1)) != int(kill_round):
            problems.append(
                f"post-mortem names round {last.get('round')}, "
                f"expected {kill_round}")
    spans, clocks = tracing.read_trace(
        tracing.collect_trace_files(trace_dir))
    merged = tracing.merge_trace(spans, clocks)
    if not merged["spans"]:
        problems.append("traced kill leg produced no spans")
    if merged["orphans"]:
        problems.append(
            f"merged trace has orphan spans: {merged['orphans'][:5]}")
    return flight_verdict, len(merged["spans"]), len(merged["orphans"])


def run_client_worker(a) -> int:
    """One REAL cross-silo client as its own OS process — the multiprocess
    gRPC chaos leg's client side, spawned by the chaos worker's
    ProcSpawner. It builds its own fault plan from the matrix flags (the
    same seeding as the loopback leg, so the fault stream per rank is
    transport-independent) and runs the production client FSM to FINISH."""
    import fedml_tpu as fedml
    from fedml_tpu import data as data_mod
    from fedml_tpu import models as model_mod
    from fedml_tpu.arguments import Arguments
    from fedml_tpu.cross_silo import FedMLCrossSiloClient

    rank = int(a.client_rank)
    overrides = dict(
        _world_overrides(a), role="client", rank=rank,
        run_id=f"chaos-grpc-{rank}", backend="GRPC",
        comm_port=int(a.port), comm_host="127.0.0.1",
    )
    args_c = fedml.init(Arguments(overrides=overrides),
                        should_init_logs=False)
    args_c.fault_plan = build_fault_plan(
        rank, int(a.seed), float(a.loss), float(a.duplicate),
        float(a.corrupt), partition=_partition_window(a),
    )
    ds, od = data_mod.load(args_c)
    bundle = model_mod.create(args_c, od)
    client = FedMLCrossSiloClient(args_c, None, ds, bundle)
    client.run()
    return 0 if client.manager.done.is_set() else 1


def main(a) -> int:
    if getattr(a, "client", False):
        return run_client_worker(a)
    if a.worker:
        return run_worker(a)
    return orchestrate(a)
