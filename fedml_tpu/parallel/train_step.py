"""Cheetah training step: sharded init, AdamW, grad accumulation, one jit.

Replaces what the reference delegates to torch DDP + NCCL (SURVEY.md §2.5
"Intra-silo data parallelism") and extends it with TP/SP/FSDP the reference
never had. Everything is one compiled program: forward, backward, gradient
accumulation (``lax.scan`` over microbatches), optimizer update. XLA inserts
the reduce-scatter/all-gather collectives implied by the shardings — no
hand-written NCCL calls to port.
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .sharding import batch_sharding, param_shardings, replicated, unbox
from .transformer import Transformer, TransformerConfig

logger = logging.getLogger(__name__)

PyTree = Any


@struct.dataclass
class TrainState:
    step: jax.Array
    params: PyTree
    opt_state: PyTree


def make_optimizer(
    learning_rate: float = 3e-4,
    weight_decay: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.95,
    grad_clip: float = 1.0,
    warmup_steps: int = 100,
    total_steps: int = 10000,
    mu_dtype=None,
) -> optax.GradientTransformation:
    """``mu_dtype=jnp.bfloat16`` halves the first-moment buffer — on a
    single 16 GB chip the difference between spilling and staying resident."""
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1)
    )
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(
            schedule, b1=b1, b2=b2, weight_decay=weight_decay,
            mu_dtype=mu_dtype,
        ),
    )


def lm_loss(logits: jax.Array, tokens: jax.Array, mask: jax.Array) -> jax.Array:
    """Next-token CE. logits [B, L, V] fp32, tokens [B, L], mask [B, L]."""
    targets = tokens[:, 1:]
    m = mask[:, 1:].astype(jnp.float32)
    per = optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1], targets
    )
    return (per * m).sum() / jnp.maximum(m.sum(), 1.0)


def lm_loss_chunked(
    hidden: jax.Array,
    w_head: jax.Array,
    tokens: jax.Array,
    mask: jax.Array,
    chunk: int = 256,
) -> jax.Array:
    """Fused head-matmul + next-token CE, chunked over the sequence.

    ``hidden``: [B, L, D] (bf16), ``w_head``: [D, V]. The full [B, L, V]
    fp32 logits tensor (≈1 GB at B=4, L=2k, V=32k) is never materialised:
    each lax.scan step computes one [B, chunk, V] slice, reduces it to CE
    sums, and discards it — HBM-bandwidth-bound CE becomes MXU-bound.
    """
    B, L, D = hidden.shape
    h = hidden[:, :-1]
    targets = tokens[:, 1:]
    m = mask[:, 1:].astype(jnp.float32)
    n = L - 1
    chunk = min(chunk, n)
    pad = (-n) % chunk
    if pad:
        h = jnp.pad(h, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
        m = jnp.pad(m, ((0, 0), (0, pad)))
    steps = (n + pad) // chunk
    h = h.reshape(B, steps, chunk, D).swapaxes(0, 1)
    targets = targets.reshape(B, steps, chunk).swapaxes(0, 1)
    m = m.reshape(B, steps, chunk).swapaxes(0, 1)
    w = w_head.astype(hidden.dtype)

    def body(acc, xs):
        hc, tc, mc = xs
        logits = (hc @ w).astype(jnp.float32)
        per = optax.softmax_cross_entropy_with_integer_labels(logits, tc)
        return acc + (per * mc).sum(), None

    total, _ = jax.lax.scan(body, jnp.zeros(()), (h, targets, m))
    return total / jnp.maximum(m.sum(), 1.0)


class CheetahTrainer:
    """Builds and owns the sharded init + train step for one config/mesh."""

    def __init__(
        self,
        cfg: TransformerConfig,
        mesh: Mesh,
        optimizer: Optional[optax.GradientTransformation] = None,
        accum_steps: int = 1,
        seq_sharded: bool = False,
        loss_chunk: int = 256,
    ):
        self.cfg = cfg
        self.mesh = mesh
        self.model = Transformer(cfg)
        self.opt = optimizer or make_optimizer()
        self.accum_steps = int(accum_steps)
        self.seq_sharded = seq_sharded
        # chunked CE needs whole-L hidden states per shard; under sequence
        # sharding L is split across devices, so fall back to full logits
        self.loss_chunk = 0 if seq_sharded else int(loss_chunk)
        self._batch_shard = batch_sharding(mesh, seq_sharded)
        self._repl = replicated(mesh)

        dummy = jnp.zeros((1, 8), jnp.int32)
        boxed_abstract = jax.eval_shape(
            lambda r: self.model.init(r, dummy), jax.random.PRNGKey(0)
        )
        self.param_shardings = jax.tree.map(
            lambda s: s,
            param_shardings(mesh, boxed_abstract["params"]),
            is_leaf=lambda x: isinstance(x, NamedSharding),
        )

        self._init_jit = jax.jit(
            self._init_raw,
            out_shardings={"params": self.param_shardings},
        )
        self._step_jit = jax.jit(self._train_step_raw, donate_argnums=(0,))

    # -- init ---------------------------------------------------------------
    def _init_raw(self, rng):
        dummy = jnp.zeros((1, 8), jnp.int32)
        variables = self.model.init(rng, dummy)
        return {"params": unbox(variables["params"])}

    def _commit_replicated(self, opt_state):
        """jit(opt.init) leaves scalar state (e.g. adam's count) on a single
        device; commit such leaves to the full mesh (replicated) so the
        train step sees one consistent device set (also post-restore)."""
        return jax.tree.map(
            lambda x: jax.device_put(x, self._repl)
            if isinstance(x, jax.Array)
            and len(x.sharding.device_set) < self.mesh.size
            else x,
            opt_state,
        )

    def init_state(self, rng: jax.Array) -> TrainState:
        with self.mesh:
            params = self._init_jit(rng)["params"]
            opt_state = jax.jit(self.opt.init)(params)
        opt_state = self._commit_replicated(opt_state)
        n_params = sum(int(p.size) for p in jax.tree.leaves(params))
        logger.info(
            "cheetah init: %.1fM params over mesh %s",
            n_params / 1e6, dict(self.mesh.shape),
        )
        # step must be committed to the mesh (replicated) — a default-device
        # scalar breaks jit after checkpoint restore (mixed device sets)
        step = jax.device_put(jnp.zeros((), jnp.int32), self._repl)
        return TrainState(step=step, params=params, opt_state=opt_state)

    def state_from_params(self, params: PyTree) -> TrainState:
        """Fresh TrainState around externally-provided params.

        The FedLLM seam (``cross_silo/fedllm.py``): each FL round re-inits
        the local optimizer around the broadcast global params — matching the
        reference's per-round torch optimizer construction in its trainers
        (``ml/trainer/my_model_trainer_classification.py:30-45``). Host
        (numpy) leaves are placed onto the mesh with this trainer's param
        shardings, so a silo's local steps run fsdp/tp/sp-sharded no matter
        where the global model came from.
        """
        def fresh(p, s):
            # train_step donates its state: device_put may ALIAS a
            # caller-owned jax array (same-sharding fast path, and even a
            # host->replicated put can reuse the source buffer as one
            # replica — observed: a replicated [128] norm weight deleted
            # under a silo's second round), and donation then deletes the
            # caller's array. Sharding-equivalence guards are not a reliable
            # aliasing oracle, so jax.Array inputs are always copied; numpy
            # inputs copy on transfer anyway.
            if isinstance(p, jax.Array):
                p = jnp.array(p, copy=True)
            return jax.device_put(jnp.asarray(p), s)

        with self.mesh:
            params = jax.tree.map(fresh, params, self.param_shardings)
            opt_state = jax.jit(self.opt.init)(params)
        opt_state = self._commit_replicated(opt_state)
        step = jax.device_put(jnp.zeros((), jnp.int32), self._repl)
        return TrainState(step=step, params=params, opt_state=opt_state)

    # -- train step ---------------------------------------------------------
    def _loss_fn(self, params, tokens, mask):
        moe = self.cfg.moe_experts > 1
        mutable = ["losses"] if moe else False
        if self.loss_chunk > 0:
            out = self.model.apply(
                {"params": params}, tokens, mask=None, return_hidden=True,
                mutable=mutable,
            )
            hidden, var_col = out if moe else (out, {})
            loss = lm_loss_chunked(
                hidden, params["w_lm_head"], tokens, mask, self.loss_chunk
            )
        else:
            out = self.model.apply(
                {"params": params}, tokens, mask=None, mutable=mutable
            )
            logits, var_col = out if moe else (out, {})
            loss = lm_loss(logits, tokens, mask)
        if moe:
            aux = sum(
                jnp.sum(jnp.asarray(v))
                for v in jax.tree.leaves(var_col.get("losses", {}))
            )
            loss = loss + self.cfg.moe_aux_weight * aux
        return loss

    def _train_step_raw(self, state: TrainState, tokens, mask):
        """tokens/mask: [accum, micro_batch, L] when accum_steps > 1,
        else [B, L]."""
        if self.accum_steps > 1:

            def micro(carry, xs):
                tok, msk = xs
                loss, grads = jax.value_and_grad(self._loss_fn)(
                    state.params, tok, msk
                )
                acc_loss, acc_grads = carry
                return (
                    acc_loss + loss,
                    jax.tree.map(jnp.add, acc_grads, grads),
                ), None

            zero = jax.tree.map(jnp.zeros_like, state.params)
            (loss_sum, grads), _ = jax.lax.scan(
                micro, (jnp.zeros(()), zero), (tokens, mask)
            )
            loss = loss_sum / self.accum_steps
            grads = jax.tree.map(lambda g: g / self.accum_steps, grads)
        else:
            loss, grads = jax.value_and_grad(self._loss_fn)(
                state.params, tokens, mask
            )
        updates, opt_state = self.opt.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            TrainState(step=state.step + 1, params=params, opt_state=opt_state),
            {"loss": loss, "grad_norm": optax.global_norm(grads)},
        )

    def shard_batch(self, tokens, mask):
        dp = 1
        for ax in self._batch_shard.spec[0] or ():
            dp *= int(self.mesh.shape[ax])
        b = tokens.shape[1] if self.accum_steps > 1 else tokens.shape[0]
        if b % dp:
            raise ValueError(
                f"batch size {b} must be divisible by the data-parallel "
                f"extent {dp} (mesh {dict(self.mesh.shape)}); raise batch_size "
                f"or shrink the data/fsdp axes"
            )
        if self.accum_steps > 1:
            spec = P(None, *self._batch_shard.spec)
            shard = NamedSharding(self.mesh, spec)
        else:
            shard = self._batch_shard
        return jax.device_put(tokens, shard), jax.device_put(mask, shard)

    def train_step(self, state: TrainState, tokens, mask) -> Tuple[TrainState, dict]:
        from .context import mesh_context

        tokens, mask = self.shard_batch(tokens, mask)
        if self.seq_sharded:
            from .context import sequence_parallelism

            with self.mesh, mesh_context(self.mesh), \
                    sequence_parallelism(self.mesh):
                return self._step_jit(state, tokens, mask)
        # mesh context lets the attention kernels shard_map themselves
        # (Mosaic kernels cannot be auto-partitioned by pjit)
        with self.mesh, mesh_context(self.mesh):
            return self._step_jit(state, tokens, mask)
