"""Sequence-parallel activation context.

The transformer module needs to know, at trace time, whether activations are
sharded over the ``sequence`` mesh axis (→ use ring attention via shard_map)
— but Flax modules can't take a Mesh as a call argument without threading it
through every layer. A context manager scopes it instead; CheetahTrainer
enters it around jit tracing when ``seq_sharded=True``.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass
from typing import Optional

from jax.sharding import Mesh

from .. import constants


@dataclass(frozen=True)
class SeqParallelCtx:
    mesh: Mesh
    axis_name: str
    size: int


# contextvars, not module globals: FL runtimes trace models from several
# FSM threads at once (one per silo client in-process), and one thread's
# parallelism context must never leak into another thread's trace
import contextvars

_ACTIVE: contextvars.ContextVar[Optional[SeqParallelCtx]] = (
    contextvars.ContextVar("fedml_tpu_seq_ctx", default=None)
)


@contextlib.contextmanager
def sequence_parallelism(mesh: Mesh, axis_name: str = constants.MESH_AXIS_SEQUENCE):
    """Activate sequence parallelism for model traces inside the block."""
    size = int(mesh.shape[axis_name]) if axis_name in mesh.axis_names else 1
    token = _ACTIVE.set(
        SeqParallelCtx(mesh, axis_name, size) if size > 1 else None
    )
    try:
        yield _ACTIVE.get()
    finally:
        _ACTIVE.reset(token)


def get_seq_context() -> Optional[SeqParallelCtx]:
    return _ACTIVE.get()


# -- ambient mesh (batch/tensor sharding) ------------------------------------
# Pallas kernels cannot be auto-partitioned by pjit ("Mosaic kernels cannot
# be automatically partitioned") — the attention kernels must be wrapped in
# shard_map over whatever mesh the step is jitted under. Same pattern as the
# sequence context: CheetahTrainer scopes its mesh here around tracing, and
# the Attention module reads it at trace time.

_MESH: "contextvars.ContextVar[Optional[Mesh]]" = (
    contextvars.ContextVar("fedml_tpu_mesh_ctx", default=None)
)


@contextlib.contextmanager
def mesh_context(mesh: Optional[Mesh]):
    """Scope the ambient mesh for model traces inside the block."""
    token = _MESH.set(mesh if mesh is not None and mesh.size > 1 else None)
    try:
        yield _MESH.get()
    finally:
        _MESH.reset(token)


def get_mesh_context() -> Optional[Mesh]:
    return _MESH.get()
