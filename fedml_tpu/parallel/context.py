"""Sequence-parallel activation context.

The transformer module needs to know, at trace time, whether activations are
sharded over the ``sequence`` mesh axis (→ use ring attention via shard_map)
— but Flax modules can't take a Mesh as a call argument without threading it
through every layer. A context manager scopes it instead; CheetahTrainer
enters it around jit tracing when ``seq_sharded=True``.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass
from typing import Optional

from jax.sharding import Mesh

from .. import constants


@dataclass(frozen=True)
class SeqParallelCtx:
    mesh: Mesh
    axis_name: str
    size: int


_ACTIVE: Optional[SeqParallelCtx] = None


@contextlib.contextmanager
def sequence_parallelism(mesh: Mesh, axis_name: str = constants.MESH_AXIS_SEQUENCE):
    """Activate sequence parallelism for model traces inside the block."""
    global _ACTIVE
    size = int(mesh.shape[axis_name]) if axis_name in mesh.axis_names else 1
    prev = _ACTIVE
    _ACTIVE = SeqParallelCtx(mesh, axis_name, size) if size > 1 else None
    try:
        yield _ACTIVE
    finally:
        _ACTIVE = prev


def get_seq_context() -> Optional[SeqParallelCtx]:
    return _ACTIVE
