"""Ring attention: exact causal attention with the sequence sharded over a
mesh axis.

New capability with NO reference analog (SURVEY.md §5 "Long-context /
sequence parallelism": absent in any form — the framework predates
long-context work). The design follows the public Ring Attention recipe
(blockwise attention with online softmax + K/V rotation over the ring):

- each of the S devices on the ``sequence`` axis holds one block of Q, K, V
- S steps: attend the local Q block against the currently-held K/V block,
  then ``lax.ppermute`` K/V one hop around the ring — compute and ICI
  transfer overlap, peak memory is O(L/S) per device, and the result is
  EXACT attention over the full length
- causal masking by global block offsets: past blocks attend fully, the
  diagonal block uses the in-block triangle, future blocks are skipped

Two inner engines, one contract:

- ``use_kernel=True`` (TPU): each block attend is the Pallas flash kernel
  (``_flash_attention(..., save_residuals=True)`` → per-block (o, l, m)),
  merged across ring steps with the standard online-softmax correction —
  the [Lq, Lk] score matrix never leaves VMEM (r3 ran fp32 einsum logits
  here while the single-device path had splash).
- ``use_kernel=False`` (CPU/tests): the fp32 einsum block attend.

Both run under ONE ``jax.custom_vjp``: the backward is the hand-scheduled
blockwise flash backward (recompute p against the saved global LSE per
K/V block; dk/dv ride the ring with their block). Before this, autodiff
through the fwd scan SAVED every block's [B,H,Lq,Lk] probabilities —
reassembling the full attention matrix in HBM and silently defeating ring
attention's O(L/S) training memory.

Call from inside ``shard_map`` with the sequence axis named; q/k/v carry the
per-device local blocks ``[B, L/S, H, D]``.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _masked_logits(q, k, q_offset, kv_offset, causal, scale):
    """[B,H,Lq,Lk] fp32 logits with the global-offset causal mask."""
    logits = jnp.einsum("blhd,bmhd->bhlm", q, k).astype(jnp.float32) * scale
    if causal:
        Lq, Lk = q.shape[1], k.shape[1]
        qpos = q_offset + jnp.arange(Lq)
        kpos = kv_offset + jnp.arange(Lk)
        mask = qpos[:, None] >= kpos[None, :]
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    return logits


def make_ring_attention(static_ring_size: int, axis_name: str,
                        causal: bool = True, use_kernel: bool = False,
                        block_q: int = 0, block_kv: int = 0,
                        interpret: bool = False):
    """Build a ring-attention fn for a statically-known ring size (the mesh
    axis size is always known at trace time). ``block_q``/``block_kv`` are
    the splash kernel tiles (0 = the measured (512, 512) default), same
    knobs the single-device path takes from the YAML surface.
    ``interpret=True`` runs the Pallas kernels in interpreter mode so the
    kernel path (fwd AND bwd) is testable on CPU meshes."""
    S = int(static_ring_size)
    rot_pairs = [(i, (i + 1) % S) for i in range(S)]

    def _rot(x):
        return jax.lax.ppermute(x, axis_name, rot_pairs)

    # -- forward: online-softmax merge over ring steps ----------------------
    def _fwd_einsum(q, k, v):
        B, Lb, H, Dh = q.shape
        scale = 1.0 / math.sqrt(Dh)
        my = jax.lax.axis_index(axis_name)
        q_offset = my * Lb

        def step(carry, s):
            o, m, l, k_cur, v_cur = carry
            kv_offset = ((my - s) % S) * Lb
            logits = _masked_logits(q, k_cur, q_offset, kv_offset, causal,
                                    scale)
            m_blk = jnp.max(logits, axis=-1)
            m_new = jnp.maximum(m, m_blk)
            corr = jnp.exp(m - m_new)
            p = jnp.exp(logits - m_new[..., None])
            l_new = l * corr + p.sum(-1)
            pv = jnp.einsum("bhlm,bmhd->bhld", p, v_cur.astype(jnp.float32))
            o_new = o * corr[..., None] + pv
            return (o_new, m_new, l_new, _rot(k_cur), _rot(v_cur)), None

        o0 = jnp.zeros((B, H, Lb, Dh), jnp.float32)
        m0 = jnp.full((B, H, Lb), NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, H, Lb), jnp.float32)
        (o, m, l, _, _), _ = jax.lax.scan(
            step, (o0, m0, l0, k, v), jnp.arange(S)
        )
        l_safe = jnp.maximum(l, 1e-30)
        out = (o / l_safe[..., None]).swapaxes(1, 2).astype(q.dtype)
        return out, m + jnp.log(l_safe)

    def _fwd_kernel(q, k, v):
        """Splash-kernel block attends merged across the ring.

        Step 0 is the diagonal block (every device: kv_idx == my — STATIC),
        so the in-block triangle uses a CausalMask kernel; later steps run a
        FullMask kernel and a per-device ``keep`` predicate zeroes future
        blocks (kv_idx > my) in the LSE merge. Each block's normalized
        output + logsumexp come from ``save_residuals=True`` — the [Lq, Lk]
        score matrix never leaves VMEM.
        """
        from jax.experimental.pallas.ops.tpu.splash_attention import (
            splash_attention_kernel as sk,
            splash_attention_mask as sm_lib,
        )

        B, Lb, H, Dh = q.shape
        scale = 1.0 / math.sqrt(Dh)
        my = jax.lax.axis_index(axis_name)

        # kernel tiles: config knobs when set, else the (512, 512) blocks
        # that took the single-device splash path from 42% to 76% MFU
        # (bench.py) — the kernel defaults underfeed the MXU
        from .transformer import _splash_blocks

        blocks = _splash_blocks(Lb, block_q or 512, block_kv or 512, Dh)

        def make(diag_causal: bool):
            mask = sm_lib.MultiHeadMask(
                [sm_lib.CausalMask((Lb, Lb)) if diag_causal
                 else sm_lib.FullMask((Lb, Lb))] * H
            )
            return sk.make_splash_mha(
                mask=mask, save_residuals=True,
                block_sizes=blocks, head_shards=1, q_seq_shards=1,
                interpret=interpret,
            )

        kern_diag = make(causal)
        kern_full = make(False)
        qt = (q * scale).swapaxes(1, 2)  # [B, H, Lb, D]

        def call(kern, kt, vt):
            o, (lse,) = jax.vmap(kern)(qt, kt, vt)
            return o.astype(jnp.float32), lse  # [B,H,Lb,D], [B,H,Lb]

        kt0 = k.swapaxes(1, 2)
        vt0 = v.swapaxes(1, 2)
        acc, lse = call(kern_diag, kt0, vt0)

        def step(carry, s):
            acc, lse, k_cur, v_cur = carry
            k_cur = _rot(k_cur)
            v_cur = _rot(v_cur)
            ob, lse_b = call(kern_full, k_cur, v_cur)
            if causal:
                lse_b = jnp.where(s <= my, lse_b, NEG_INF)
            lse_new = jnp.logaddexp(lse, lse_b)
            acc_new = (
                acc * jnp.exp(lse - lse_new)[..., None]
                + ob * jnp.exp(lse_b - lse_new)[..., None]
            )
            return (acc_new, lse_new, k_cur, v_cur), None

        if S > 1:
            (acc, lse, _, _), _ = jax.lax.scan(
                step, (acc, lse, kt0, vt0), jnp.arange(1, S)
            )
        return acc.swapaxes(1, 2).astype(q.dtype), lse

    _fwd_impl = _fwd_kernel if use_kernel else _fwd_einsum

    # -- kernel backward: splash dq/dkv Pallas kernels with the GLOBAL lse --
    def _bwd_kernel(res, do):
        """Blockwise flash backward where each block's dq/dk/dv come from the
        splash backward kernels (``_splash_attention_bwd_dq`` /
        ``_splash_attention_bwd_dkv``) instead of fp32 einsums.

        The flash/ring identity: the correct global gradient for K/V block b
        is the block-local flash backward evaluated with the BLOCK-local
        logsumexp replaced by the saved GLOBAL one — p = exp(s - lse) is then
        the exact softmax probability, so each block's contribution is exact
        and they sum over ring steps. This was the r4 gap (VERDICT weak #4:
        kernel fwd 1.45x but fwd+bwd 1.07x — the bwd was einsum-grade and,
        per ADVICE, materialized [B,H,Lb,Lb] fp32 per step; the kernels keep
        scores in VMEM)."""
        from jax.experimental.pallas.ops.tpu.splash_attention import (
            splash_attention_kernel as sk,
            splash_attention_mask as sm_lib,
            splash_attention_mask_info as mask_info_lib,
        )

        q, k, v, out, lse = res
        B, Lb, H, Dh = q.shape
        scale = 1.0 / math.sqrt(Dh)
        my = jax.lax.axis_index(axis_name)

        from .transformer import _splash_blocks

        blocks = _splash_blocks(Lb, block_q or 512, block_kv or 512, Dh)

        def make_bwd(diag_causal: bool):
            mask = sm_lib.MultiHeadMask(
                [sm_lib.CausalMask((Lb, Lb)) if diag_causal
                 else sm_lib.FullMask((Lb, Lb))] * H
            )
            dq_mi, mf_dq = mask_info_lib.process_mask(
                mask, (blocks.block_q_dq, blocks.block_kv_dq),
                head_shards=1, q_seq_shards=1,
            )
            dkv_mi, mf_dkv = mask_info_lib.process_mask_dkv(
                mask, (blocks.block_q_dkv, blocks.block_kv_dkv),
                head_shards=1, q_seq_shards=1,
            )
            dq_mi = jax.tree.map(jnp.array, dq_mi)
            dkv_mi = jax.tree.map(jnp.array, dkv_mi)

            def bwd_one(qs_t, k_t, v_t, lse_, do_t, di_):
                # per-example shapes: q/k/v/do [H, L, D]; lse/di [H, L]
                _, dk, dv = sk._splash_attention_bwd_dkv(
                    qs_t, k_t, v_t, None, None, lse_, do_t, di_,
                    bq=blocks.block_q_dkv, bkv=blocks.block_kv_dkv,
                    bkv_compute=blocks.block_kv_dkv_compute,
                    is_mqa=False, mask_info=dkv_mi,
                    mask_value=NEG_INF, attn_logits_soft_cap=None,
                    use_fused_bwd_kernel=False,
                    q_layout=blocks.q_layout, k_layout=blocks.k_layout,
                    v_layout=blocks.v_layout, mask_function=mf_dkv,
                    interpret=interpret,
                )
                dqs = sk._splash_attention_bwd_dq(
                    qs_t, k_t, v_t, None, None, lse_, do_t, di_,
                    bq=blocks.block_q_dq, bkv=blocks.block_kv_dq,
                    is_mqa=False, mask_info=dq_mi,
                    mask_value=NEG_INF, attn_logits_soft_cap=None,
                    q_layout=blocks.q_layout, k_layout=blocks.k_layout,
                    v_layout=blocks.v_layout, mask_function=mf_dq,
                    interpret=interpret,
                )
                return (dqs.astype(jnp.float32), dk.astype(jnp.float32),
                        dv.astype(jnp.float32))

            return jax.vmap(bwd_one)

        bwd_diag = make_bwd(causal)
        bwd_full = make_bwd(False)

        # head-major layouts for the kernels; q pre-scaled as in the forward
        qs_t = (q * scale).swapaxes(1, 2)          # [B, H, Lb, D]
        do_t = do.astype(q.dtype).swapaxes(1, 2)   # [B, H, Lb, D]
        di = jnp.einsum(
            "blhd,blhd->bhl",
            do.astype(jnp.float32), out.astype(jnp.float32),
        )  # [B, H, Lb]
        kt0 = k.swapaxes(1, 2)
        vt0 = v.swapaxes(1, 2)

        # step 0: the diagonal block on the home K/V
        dq, dk, dv = bwd_diag(qs_t, kt0, vt0, lse, do_t, di)

        def step(carry, s):
            dq, k_cur, v_cur, dk, dv = carry
            # dk/dv travel WITH their block, as in the forward
            k_cur, v_cur, dk, dv = _rot(k_cur), _rot(v_cur), _rot(dk), _rot(dv)
            dq_b, dk_b, dv_b = bwd_full(qs_t, k_cur, v_cur, lse, do_t, di)
            if causal:
                keep = (s <= my).astype(jnp.float32)
                dq_b, dk_b, dv_b = dq_b * keep, dk_b * keep, dv_b * keep
            return (dq + dq_b, k_cur, v_cur, dk + dk_b, dv + dv_b), None

        if S > 1:
            (dq, _, _, dk, dv), _ = jax.lax.scan(
                step, (dq, kt0, vt0, dk, dv), jnp.arange(1, S)
            )
            dk, dv = _rot(dk), _rot(dv)  # S-1 in-scan hops + 1 = home

        dq = (dq * scale).swapaxes(1, 2)  # grad w.r.t. unscaled q
        dk = dk.swapaxes(1, 2)
        dv = dv.swapaxes(1, 2)
        return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)

    # -- custom VJP: hand-scheduled blockwise backward ----------------------
    @jax.custom_vjp
    def ring(q, k, v):
        return _fwd_impl(q, k, v)[0]

    def ring_fwd(q, k, v):
        out, lse = _fwd_impl(q, k, v)
        return out, (q, k, v, out, lse)

    def ring_bwd(res, do):
        if use_kernel:
            return _bwd_kernel(res, do)
        return _bwd_einsum(res, do)

    def _bwd_einsum(res, do):
        """Blockwise flash backward: per ring step, recompute this block's
        probabilities against the saved GLOBAL log-sum-exp, accumulate
        dq locally while dk/dv ride the ring with their K/V block (after S
        rotations they are home). Memory stays O(block); nothing from the
        forward scan is retained but (q, k, v, out, lse)."""
        q, k, v, out, lse = res
        B, Lb, H, Dh = q.shape
        scale = 1.0 / math.sqrt(Dh)
        my = jax.lax.axis_index(axis_name)
        q_offset = my * Lb
        do32 = do.astype(jnp.float32)
        delta = jnp.einsum(
            "blhd,blhd->bhl", do32, out.astype(jnp.float32)
        )  # [B, H, Lq]

        def step(carry, s):
            dq, k_cur, v_cur, dk, dv = carry
            kv_offset = ((my - s) % S) * Lb
            logits = _masked_logits(q, k_cur, q_offset, kv_offset, causal,
                                    scale)
            p = jnp.exp(logits - lse[..., None])  # exact softmax probs
            dv_new = dv + jnp.einsum("bhlm,blhd->bmhd", p, do32)
            dp = jnp.einsum("blhd,bmhd->bhlm", do32,
                            v_cur.astype(jnp.float32))
            ds = p * (dp - delta[..., None]) * scale
            dq_new = dq + jnp.einsum(
                "bhlm,bmhd->blhd", ds, k_cur.astype(jnp.float32)
            )
            dk_new = dk + jnp.einsum(
                "bhlm,blhd->bmhd", ds, q.astype(jnp.float32)
            )
            # dk/dv travel WITH their block; after S rotations they're home
            return (dq_new, _rot(k_cur), _rot(v_cur), _rot(dk_new),
                    _rot(dv_new)), None

        zeros_kv = jnp.zeros((B, Lb, H, Dh), jnp.float32)
        (dq, _, _, dk, dv), _ = jax.lax.scan(
            step,
            (jnp.zeros((B, Lb, H, Dh), jnp.float32), k, v, zeros_kv,
             zeros_kv),
            jnp.arange(S),
        )
        return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)

    ring.defvjp(ring_fwd, ring_bwd)
    return ring
