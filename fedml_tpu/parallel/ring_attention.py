"""Ring attention: exact causal attention with the sequence sharded over a
mesh axis.

New capability with NO reference analog (SURVEY.md §5 "Long-context /
sequence parallelism": absent in any form — the framework predates
long-context work). The design follows the public Ring Attention recipe
(blockwise attention with online softmax + K/V rotation over the ring):

- each of the S devices on the ``sequence`` axis holds one block of Q, K, V
- S steps: attend the local Q block against the currently-held K/V block
  (flash-style running (m, l, o) accumulators), then ``lax.ppermute`` K/V one
  hop around the ring — compute and ICI transfer overlap, peak memory is
  O(L/S) per device, and the result is EXACT attention over the full length
- causal masking by global block offsets: past blocks attend fully, the
  diagonal block uses the in-block triangle, future blocks are skipped

Call from inside ``shard_map`` with the sequence axis named; q/k/v carry the
per-device local blocks ``[B, L/S, H, D]``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _block_attend(q, k, v, q_offset, kv_offset, causal, scale):
    """One Q-block × K/V-block partial attention.

    Returns (scores_max [B,H,Lq], exp_scores [B,H,Lq,Lk], pv [B,H,Lq,D]).
    """
    logits = jnp.einsum("blhd,bmhd->bhlm", q, k).astype(jnp.float32) * scale
    if causal:
        Lq, Lk = q.shape[1], k.shape[1]
        qpos = q_offset + jnp.arange(Lq)
        kpos = kv_offset + jnp.arange(Lk)
        mask = qpos[:, None] >= kpos[None, :]
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    return logits


def make_ring_attention(static_ring_size: int, axis_name: str, causal: bool = True):
    """Build a ring-attention fn for a statically-known ring size (the mesh
    axis size is always known at trace time)."""
    S = int(static_ring_size)
    rot_pairs = [(i, (i + 1) % S) for i in range(S)]

    def fn(q, k, v):
        B, Lb, H, Dh = q.shape
        scale = 1.0 / jnp.sqrt(jnp.asarray(Dh, jnp.float32))
        my = jax.lax.axis_index(axis_name)
        q_offset = my * Lb

        def step(carry, s):
            o, m, l, k_cur, v_cur = carry
            kv_idx = (my - s) % S
            kv_offset = kv_idx * Lb
            logits = _block_attend(q, k_cur, v_cur, q_offset, kv_offset,
                                   causal, scale)  # [B,H,Lq,Lk]
            m_blk = jnp.max(logits, axis=-1)  # [B,H,Lq]
            m_new = jnp.maximum(m, m_blk)
            corr = jnp.exp(m - m_new)
            p = jnp.exp(logits - m_new[..., None])  # [B,H,Lq,Lk]
            l_new = l * corr + p.sum(-1)
            pv = jnp.einsum("bhlm,bmhd->bhld", p, v_cur.astype(jnp.float32))
            o_new = o * corr[..., None] + pv
            k_next = jax.lax.ppermute(k_cur, axis_name, rot_pairs)
            v_next = jax.lax.ppermute(v_cur, axis_name, rot_pairs)
            return (o_new, m_new, l_new, k_next, v_next), None

        o0 = jnp.zeros((B, H, Lb, Dh), jnp.float32)
        m0 = jnp.full((B, H, Lb), NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, H, Lb), jnp.float32)
        (o, m, l, _, _), _ = jax.lax.scan(
            step, (o0, m0, l0, k, v), jnp.arange(S)
        )
        out = o / jnp.maximum(l[..., None], 1e-30)
        return jnp.einsum("bhld->blhd", out).astype(q.dtype)

    return fn
