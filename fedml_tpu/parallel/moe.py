"""Mixture-of-Experts feed-forward with expert parallelism.

New-capability work (SURVEY.md §2.5 "Expert parallelism / MoE" — the
reference has no MoE at all; the ``expert`` mesh axis existed here as a
constant only). Switch-Transformer-style design, TPU-native:

- router: one [D, E] matmul → top-1 (Switch) or top-2 (GShard/Mixtral,
  ``cfg.moe_top_k=2``) experts per token, with the Switch load-balancing
  auxiliary loss; top-2 gates renormalised over the chosen pair, second
  choices fill whatever capacity first choices left
- dense capacity-factor dispatch (GShard): tokens route into a
  [E, capacity, D] buffer via one einsum with a one-hot dispatch mask —
  static shapes, no ragged scatter, MXU end to end; over-capacity tokens
  drop (pass through the residual unchanged)
- expert FFNs are ONE stacked param tree [E, ...] vmapped over the expert
  axis; the logical ``expert`` axis maps to the ``expert`` mesh axis
  (sharding.LOGICAL_RULES), so under pjit the dispatch/combine einsums
  lower to the all-to-alls of expert parallelism — no hand-written
  collectives.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from .transformer import EMBED, MLP, TransformerConfig

EXPERT_AXIS = "expert_dim"  # logical name for the stacked-expert axis


class MoEFeedForward(nn.Module):
    """Drop-in replacement for the dense FeedForward when cfg.moe_experts>1.

    Returns ``(y, aux_loss)`` — the caller adds ``aux_loss`` (scaled by
    ``cfg.moe_aux_weight``) to the task loss; without it the router
    collapses onto one expert.
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x) -> Tuple[jax.Array, jax.Array]:
        cfg = self.cfg
        E = cfg.moe_experts
        D, F = cfg.d_model, cfg.d_ff
        B, L, _ = x.shape
        T = B * L
        top_k = int(getattr(cfg, "moe_top_k", 1))
        if top_k not in (1, 2):
            raise ValueError(f"moe_top_k must be 1 or 2, got {top_k}")
        # capacity scales with k (GShard/Mixtral): top-2 makes 2T route
        # assignments, so unscaled capacity would drop most second choices
        # even under a perfectly balanced router
        capacity = max(int(cfg.moe_capacity_factor * top_k * T / E), 1)
        init = nn.initializers.normal(0.02)

        w_router = self.param(
            "w_router", nn.with_partitioning(init, (EMBED, None)),
            (D, E), jnp.float32,
        )
        w_gate_up = self.param(
            "w_gate_up",
            nn.with_partitioning(init, (EXPERT_AXIS, EMBED, MLP)),
            (E, D, 2 * F), cfg.param_dtype,
        )
        w_down = self.param(
            "w_down",
            nn.with_partitioning(init, (EXPERT_AXIS, MLP, EMBED)),
            (E, F, D), cfg.param_dtype,
        )

        xt = x.reshape(T, D)
        # routing in fp32 (tiny, numerically sensitive)
        logits = xt.astype(jnp.float32) @ w_router  # [T, E]
        probs = jax.nn.softmax(logits, axis=-1)
        expert_idx = jnp.argmax(probs, axis=-1)  # [T] first choice
        expert_prob = jnp.take_along_axis(
            probs, expert_idx[:, None], axis=-1
        )[:, 0]

        one_hot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [T, E]
        if top_k == 2:
            # second choice: argmax with the first masked out
            probs2 = probs * (1.0 - one_hot)
            idx2 = jnp.argmax(probs2, axis=-1)
            prob2 = jnp.take_along_axis(probs2, idx2[:, None], axis=-1)[:, 0]
            one_hot2 = jax.nn.one_hot(idx2, E, dtype=jnp.float32)
            # GShard/Mixtral-style aux loss: load fraction over ALL k
            # assignments (second-choice hot-spotting is visible to the
            # regularizer), normalised by k so a balanced router still
            # scores 1.0
            frac = (one_hot + one_hot2).mean(0) / top_k
        else:
            one_hot2 = None
            # Switch aux loss: E * Σ_e frac_e * mean_prob_e over first choices
            frac = one_hot.mean(0)
        mean_prob = probs.mean(0)
        aux_loss = E * jnp.sum(frac * mean_prob)

        def positions(oh, offset_per_expert):
            """Per-token slot index within its expert's capacity buffer."""
            pos_in = (jnp.cumsum(oh, axis=0) - 1.0) * oh  # [T, E]
            off = jnp.sum(oh * offset_per_expert[None, :], axis=-1)
            pos = (jnp.sum(pos_in, axis=-1) + off).astype(jnp.int32)
            keep = (pos < capacity).astype(jnp.float32)
            return (
                oh[:, :, None]
                * jax.nn.one_hot(pos, capacity, dtype=jnp.float32)[:, None, :]
                * keep[:, None, None]
            )  # [T, E, C]

        dispatch1 = positions(one_hot, jnp.zeros((E,), jnp.float32))
        if top_k == 2:
            # second-choice slots start after ALL first-choice claims on that
            # expert (GShard ordering: first choices never lose capacity to
            # second choices)
            dispatch2 = positions(one_hot2, one_hot.sum(0))
            # renormalised pair gates (Mixtral: softmax over the chosen two)
            denom = jnp.maximum(expert_prob + prob2, 1e-9)
            gate1 = expert_prob / denom
            gate2 = prob2 / denom
            dispatch = dispatch1 + dispatch2
            combine = (
                dispatch1 * gate1[:, None, None]
                + dispatch2 * gate2[:, None, None]
            )
        else:
            dispatch = dispatch1
            combine = dispatch1 * expert_prob[:, None, None]

        expert_in = jnp.einsum(
            "tec,td->ecd", dispatch, xt.astype(jnp.float32)
        ).astype(cfg.dtype)

        def ffn(gu_w, down_w, h):
            gu = jnp.einsum("cd,df->cf", h, gu_w.astype(cfg.dtype))
            gate, up = jnp.split(gu, 2, axis=-1)
            return jnp.einsum(
                "cf,fd->cd", nn.silu(gate) * up, down_w.astype(cfg.dtype)
            )

        expert_out = jax.vmap(ffn)(w_gate_up, w_down, expert_in)  # [E, C, D]

        # combine, scaled by the (re)normalised router gates; dropped tokens
        # contribute nothing and pass through the residual unchanged
        y = jnp.einsum(
            "tec,ecd->td", combine, expert_out.astype(jnp.float32)
        ).astype(cfg.dtype)
        return y.reshape(B, L, D), aux_loss
