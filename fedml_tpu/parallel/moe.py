"""Mixture-of-Experts feed-forward with expert parallelism.

New-capability work (SURVEY.md §2.5 "Expert parallelism / MoE" — the
reference has no MoE at all; the ``expert`` mesh axis existed here as a
constant only). Switch-Transformer-style design, TPU-native:

- router: one [D, E] matmul → top-1 expert per token (+ optional top-2),
  with the Switch load-balancing auxiliary loss
- dense capacity-factor dispatch (GShard): tokens route into a
  [E, capacity, D] buffer via one einsum with a one-hot dispatch mask —
  static shapes, no ragged scatter, MXU end to end; over-capacity tokens
  drop (pass through the residual unchanged)
- expert FFNs are ONE stacked param tree [E, ...] vmapped over the expert
  axis; the logical ``expert`` axis maps to the ``expert`` mesh axis
  (sharding.LOGICAL_RULES), so under pjit the dispatch/combine einsums
  lower to the all-to-alls of expert parallelism — no hand-written
  collectives.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from .transformer import EMBED, MLP, TransformerConfig

EXPERT_AXIS = "expert_dim"  # logical name for the stacked-expert axis


class MoEFeedForward(nn.Module):
    """Drop-in replacement for the dense FeedForward when cfg.moe_experts>1.

    Returns ``(y, aux_loss)`` — the caller adds ``aux_loss`` (scaled by
    ``cfg.moe_aux_weight``) to the task loss; without it the router
    collapses onto one expert.
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x) -> Tuple[jax.Array, jax.Array]:
        cfg = self.cfg
        E = cfg.moe_experts
        D, F = cfg.d_model, cfg.d_ff
        B, L, _ = x.shape
        T = B * L
        capacity = max(int(cfg.moe_capacity_factor * T / E), 1)
        init = nn.initializers.normal(0.02)

        w_router = self.param(
            "w_router", nn.with_partitioning(init, (EMBED, None)),
            (D, E), jnp.float32,
        )
        w_gate_up = self.param(
            "w_gate_up",
            nn.with_partitioning(init, (EXPERT_AXIS, EMBED, MLP)),
            (E, D, 2 * F), cfg.param_dtype,
        )
        w_down = self.param(
            "w_down",
            nn.with_partitioning(init, (EXPERT_AXIS, MLP, EMBED)),
            (E, F, D), cfg.param_dtype,
        )

        xt = x.reshape(T, D)
        # routing in fp32 (tiny, numerically sensitive)
        logits = xt.astype(jnp.float32) @ w_router  # [T, E]
        probs = jax.nn.softmax(logits, axis=-1)
        expert_idx = jnp.argmax(probs, axis=-1)  # [T] top-1 (Switch)
        expert_prob = jnp.take_along_axis(
            probs, expert_idx[:, None], axis=-1
        )[:, 0]

        # Switch aux loss: E * Σ_e fraction_tokens_e * mean_prob_e
        one_hot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [T, E]
        frac = one_hot.mean(0)
        mean_prob = probs.mean(0)
        aux_loss = E * jnp.sum(frac * mean_prob)

        # position of each token within its expert's capacity buffer
        pos_in_expert = (jnp.cumsum(one_hot, axis=0) - 1.0) * one_hot  # [T, E]
        pos = jnp.sum(pos_in_expert, axis=-1).astype(jnp.int32)  # [T]
        keep = (pos < capacity).astype(jnp.float32)

        # dispatch: [T, E, C] one-hot → expert inputs [E, C, D]
        dispatch = (
            one_hot[:, :, None]
            * jax.nn.one_hot(pos, capacity, dtype=jnp.float32)[:, None, :]
            * keep[:, None, None]
        )
        expert_in = jnp.einsum(
            "tec,td->ecd", dispatch, xt.astype(jnp.float32)
        ).astype(cfg.dtype)

        def ffn(gu_w, down_w, h):
            gu = jnp.einsum("cd,df->cf", h, gu_w.astype(cfg.dtype))
            gate, up = jnp.split(gu, 2, axis=-1)
            return jnp.einsum(
                "cf,fd->cd", nn.silu(gate) * up, down_w.astype(cfg.dtype)
            )

        expert_out = jax.vmap(ffn)(w_gate_up, w_down, expert_in)  # [E, C, D]

        # combine, scaled by the router prob (straight-through for dropped)
        combine = dispatch * expert_prob[:, None, None]
        y = jnp.einsum(
            "tec,ecd->td", combine, expert_out.astype(jnp.float32)
        ).astype(cfg.dtype)
        return y.reshape(B, L, D), aux_loss
