"""Mixture-of-Experts feed-forward with expert parallelism.

New-capability work (SURVEY.md §2.5 "Expert parallelism / MoE" — the
reference has no MoE at all; the ``expert`` mesh axis existed here as a
constant only). Switch-Transformer-style design, TPU-native:

- router: one [D, E] matmul → top-1 (Switch) or top-2 (GShard/Mixtral,
  ``cfg.moe_top_k=2``) experts per token, with the Switch load-balancing
  auxiliary loss; top-2 gates renormalised over the chosen pair, second
  choices fill whatever capacity first choices left
- capacity-factor dispatch (GShard semantics) via static-shape
  scatter/gather: each token computes its expert slot with an O(T·E)
  cumsum and scatter-adds into the [E, capacity, D] buffer (unique
  destinations — no collisions), combine is a gather; over-capacity tokens
  drop (pass through the residual unchanged). The r3 one-hot dispatch
  einsum was O(T·E·C) memory and could not allocate at flagship scale.
- expert FFNs are ONE stacked param tree [E, ...] vmapped over the expert
  axis; the logical ``expert`` axis maps to the ``expert`` mesh axis
  (sharding.LOGICAL_RULES), so under pjit the dispatch/combine einsums
  lower to the all-to-alls of expert parallelism — no hand-written
  collectives.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from .transformer import EMBED, MLP, TransformerConfig

EXPERT_AXIS = "expert_dim"  # logical name for the stacked-expert axis


class MoEFeedForward(nn.Module):
    """Drop-in replacement for the dense FeedForward when cfg.moe_experts>1.

    Returns ``(y, aux_loss)`` — the caller adds ``aux_loss`` (scaled by
    ``cfg.moe_aux_weight``) to the task loss; without it the router
    collapses onto one expert.
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x) -> Tuple[jax.Array, jax.Array]:
        cfg = self.cfg
        E = cfg.moe_experts
        D, F = cfg.d_model, cfg.d_ff
        B, L, _ = x.shape
        T = B * L
        top_k = int(getattr(cfg, "moe_top_k", 1))
        if top_k not in (1, 2):
            raise ValueError(f"moe_top_k must be 1 or 2, got {top_k}")
        # capacity scales with k (GShard/Mixtral): top-2 makes 2T route
        # assignments, so unscaled capacity would drop most second choices
        # even under a perfectly balanced router
        capacity = max(int(cfg.moe_capacity_factor * top_k * T / E), 1)
        init = nn.initializers.normal(0.02)

        w_router = self.param(
            "w_router", nn.with_partitioning(init, (EMBED, None)),
            (D, E), jnp.float32,
        )
        w_gate_up = self.param(
            "w_gate_up",
            nn.with_partitioning(init, (EXPERT_AXIS, EMBED, MLP)),
            (E, D, 2 * F), cfg.param_dtype,
        )
        w_down = self.param(
            "w_down",
            nn.with_partitioning(init, (EXPERT_AXIS, MLP, EMBED)),
            (E, F, D), cfg.param_dtype,
        )

        xt = x.reshape(T, D)
        # routing in fp32 (tiny, numerically sensitive)
        logits = xt.astype(jnp.float32) @ w_router  # [T, E]
        probs = jax.nn.softmax(logits, axis=-1)
        expert_idx = jnp.argmax(probs, axis=-1)  # [T] first choice
        expert_prob = jnp.take_along_axis(
            probs, expert_idx[:, None], axis=-1
        )[:, 0]

        one_hot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [T, E]
        if top_k == 2:
            # second choice: argmax with the first masked out
            probs2 = probs * (1.0 - one_hot)
            idx2 = jnp.argmax(probs2, axis=-1)
            prob2 = jnp.take_along_axis(probs2, idx2[:, None], axis=-1)[:, 0]
            one_hot2 = jax.nn.one_hot(idx2, E, dtype=jnp.float32)
            # GShard/Mixtral-style aux loss: load fraction over ALL k
            # assignments (second-choice hot-spotting is visible to the
            # regularizer), normalised by k so a balanced router still
            # scores 1.0
            frac = (one_hot + one_hot2).mean(0) / top_k
        else:
            one_hot2 = None
            # Switch aux loss: E * Σ_e frac_e * mean_prob_e over first choices
            frac = one_hot.mean(0)
        mean_prob = probs.mean(0)
        aux_loss = E * jnp.sum(frac * mean_prob)

        # -- sort-based grouped dispatch (r5; VERDICT r4 #4) ----------------
        # The r4 path scatter-added token rows into the [E·C, D] buffer —
        # two row-scatters of [T, D] per layer, which TPUs serialize; MoE
        # measured 40.1% MFU vs the 75.8% dense bar. Sorting the (up to) k·T
        # assignments by expert makes every group contiguous, so dispatch,
        # combine, and un-sort are all row-GATHERS (MXU-friendly), with the
        # only scatters left the unavoidable ones autodiff inserts for the
        # gather transposes in backward. Priority semantics are unchanged
        # from GShard: the flat assignment order is (all first choices in
        # token order, then all second choices), and the stable sort
        # preserves it within each expert group, so over capacity second
        # choices drop before first and later tokens before earlier —
        # byte-identical keep sets to the r4 cumsum dispatch.
        kT = top_k * T
        if top_k == 2:
            flat_expert = jnp.concatenate([expert_idx, idx2]).astype(jnp.int32)
        else:
            flat_expert = expert_idx.astype(jnp.int32)
        order = jnp.argsort(flat_expert, stable=True)      # [kT]
        sorted_expert = flat_expert[order]
        sorted_token = (order % T).astype(jnp.int32)       # assignment → token
        counts = jnp.bincount(flat_expert, length=E)       # [E]
        group_start = (jnp.cumsum(counts) - counts).astype(jnp.int32)
        pos_sorted = jnp.arange(kT, dtype=jnp.int32) - group_start[sorted_expert]
        keep_sorted = pos_sorted < capacity

        xt_c = xt.astype(cfg.dtype)
        # dispatch: slot (e, c) is filled by sorted assignment
        # group_start[e] + c when c < counts[e]; one gather, no scatter
        slot_src = group_start[:, None] + jnp.arange(capacity,
                                                     dtype=jnp.int32)[None, :]
        slot_valid = jnp.arange(capacity)[None, :] < counts[:, None]  # [E, C]
        tok_for_slot = sorted_token[jnp.clip(slot_src, 0, kT - 1)]
        expert_in = jnp.where(
            slot_valid[..., None], xt_c[tok_for_slot], 0
        )  # [E, C, D]

        def ffn(gu_w, down_w, h):
            gu = jnp.einsum("cd,df->cf", h, gu_w.astype(cfg.dtype))
            gate, up = jnp.split(gu, 2, axis=-1)
            return jnp.einsum(
                "cf,fd->cd", nn.silu(gate) * up, down_w.astype(cfg.dtype)
            )

        expert_out = jax.vmap(ffn)(w_gate_up, w_down, expert_in)  # [E, C, D]

        # combine: gather each sorted assignment's slot output, un-sort via
        # the inverse permutation (another gather), and gate-weight per
        # choice; dropped assignments (keep=0) contribute nothing and pass
        # through the residual unchanged
        flat_out = expert_out.reshape(E * capacity, D)
        slot_of_sorted = jnp.clip(
            sorted_expert * capacity + pos_sorted, 0, E * capacity - 1
        )
        out_sorted = (
            flat_out[slot_of_sorted].astype(jnp.float32)
            * keep_sorted[:, None]
        )  # [kT, D]
        inv = jnp.argsort(order, stable=True)
        out_flat = out_sorted[inv]          # original assignment order
        keep_flat = keep_sorted[inv]
        if top_k == 2:
            keep1, keep2 = keep_flat[:T], keep_flat[T:]
            # renormalised pair gates (Mixtral: softmax over the chosen two)
            denom = jnp.maximum(expert_prob + prob2, 1e-9)
            gate1 = (expert_prob / denom) * keep1
            gate2 = (prob2 / denom) * keep2
            y32 = out_flat[:T] * gate1[:, None] + out_flat[T:] * gate2[:, None]
        else:
            gate1 = expert_prob * keep_flat
            y32 = out_flat * gate1[:, None]
        y = y32.astype(cfg.dtype)
        return y.reshape(B, L, D), aux_loss
