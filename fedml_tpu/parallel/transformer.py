"""Cheetah flagship model: a Llama-architecture decoder-only transformer.

The reference's "Cheetah" distributed-training pillar is an EMPTY STUB
(``python/fedml/distributed/`` holds one empty ``__init__.py``; SURVEY.md
intro) — this module is the new-capability work SURVEY.md §7 stage 6 calls
for: a data/tensor/sequence-parallel LLM pretraining path designed for the
MXU from the start.

TPU-first choices:
- bfloat16 activations/weights, fp32 RMSNorm accumulation and logits
- fused QKV and gate+up projections (fewer, larger matmuls for the MXU)
- rotary embeddings computed in fp32, GQA (n_kv_heads ≤ n_heads)
- every weight created through ``nn.with_partitioning`` with *logical* axis
  names; ``sharding.py`` maps logical → mesh axes (dp/fsdp/tensor/sequence),
  so the same module runs 1-chip or pod-scale unchanged
- no data-dependent Python control flow — the whole stack jits once
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

logger = logging.getLogger(__name__)

# Logical axis names (mapped to mesh axes by sharding.LOGICAL_RULES)
EMBED = "embed"
VOCAB = "vocab"
HEADS = "heads"
KV = "kv"
MLP = "mlp"
BATCH = "batch"
LENGTH = "length"


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    d_ff: int = 11008
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32
    remat: bool = True  # jax.checkpoint each block (HBM ⇄ FLOPs trade)
    # remat policy: "full" recomputes everything in the block;
    # "dots" saves matmul outputs and recomputes only elementwise/norm ops —
    # far cheaper backward for a modest activation-memory increase
    remat_policy: str = "full"
    # Mixture-of-Experts FFN (parallel/moe.py): 0/1 = dense; >1 = that many
    # experts, stacked expert weights shardable over the `expert` mesh axis
    moe_experts: int = 0
    # 1 = Switch top-1 routing; 2 = GShard/Mixtral top-2 (renormalised gates,
    # second choice fills capacity left by first choices)
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # "auto": Pallas splash attention on TPU (falls back to flash, then XLA),
    # elsewhere XLA. "splash" / "flash" / "xla" force one. The Pallas kernels
    # keep the [L, L] score matrix in VMEM tiles (never materialised in HBM)
    # — measured on the v5e, splash beats the older flash kernel by 5-10x on
    # fwd+bwd and its backward avoids flash's f32 [B,H,L,128] broadcasts,
    # which is what keeps the no-remat memory rung viable.
    attn_impl: str = "auto"
    # splash kernel tile sizes (None = kernel defaults). The q/kv block pair
    # is the main lever for small head_dim: at hd 128 the defaults leave the
    # MXU underfed (tools/mfu_sweep.py sweeps these)
    attn_block_q: int = 0
    attn_block_kv: int = 0
    # False = bidirectional encoder attention (FedNLP heads like span
    # extraction need lookahead; the LM paths keep the causal default)
    causal: bool = True
    # "rope" (default) or "learned" absolute positions. Learned positions
    # average cleanly under FedAvg (clients share one positional basis);
    # rotary models can converge to per-client-rotated solutions whose
    # average destroys the task — measured on the prefix-LM seq2seq head.
    pos_emb: str = "rope"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @staticmethod
    def llama2_7b() -> "TransformerConfig":
        return TransformerConfig()

    @staticmethod
    def tiny(vocab_size: int = 256) -> "TransformerConfig":
        return TransformerConfig(
            vocab_size=vocab_size, d_model=128, n_layers=2, n_heads=4,
            n_kv_heads=2, d_ff=384, max_seq_len=128, remat=False,
        )


def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * inv).astype(x.dtype) * weight


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        w = self.param(
            "weight",
            nn.with_partitioning(nn.initializers.ones, (None,)),
            (x.shape[-1],),
            jnp.float32,
        )
        return rms_norm(x, w.astype(x.dtype), self.eps)


def rotary_embedding(
    positions: jax.Array, head_dim: int, theta: float
) -> Tuple[jax.Array, jax.Array]:
    """cos/sin tables for the given positions: [*, L, head_dim/2] fp32."""
    freqs = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    angles = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.cos(angles), jnp.sin(angles)


def apply_rotary(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: [B, L, H, D]; cos/sin: [B, L, D/2] (or broadcastable)."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    c = cos[..., None, :]
    s = sin[..., None, :]
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).astype(x.dtype)


def _attn_backend(impl: str) -> str:
    """Resolve cfg.attn_impl to one of {"splash", "flash", "xla"}."""
    if impl in ("splash", "flash", "xla"):
        return impl
    import jax as _jax

    try:
        on_tpu = _jax.devices()[0].platform == "tpu"
    except Exception:
        on_tpu = False
    if not on_tpu:
        return "xla"
    try:
        import jax.experimental.pallas.ops.tpu.splash_attention  # noqa: F401

        return "splash"
    except ImportError:
        return "flash"


def _splash_blocks(L: int, block_q: int, block_kv: int, head_dim: int):
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
    )

    if not block_q and not block_kv:
        return None
    if block_q < 0 or block_kv < 0:
        raise ValueError(
            f"attn_block_q/attn_block_kv must be >= 0, got "
            f"({block_q}, {block_kv})"
        )

    def rounded(b, name):
        """Mosaic wants lane-aligned tiles: round a user block down to a
        multiple of 128 (min 128) rather than failing deep in the kernel
        with an opaque compile error."""
        r = max(b // 128 * 128, 128)
        if r != b:
            logger.info("%s=%d rounded to %d (multiple of 128)", name, b, r)
        return r

    bq = min(rounded(block_q, "attn_block_q") if block_q else 512, L)
    bkv = min(rounded(block_kv, "attn_block_kv") if block_kv else 1024, L)

    # clamp to the ~16 MB scoped-VMEM budget: the dkv kernel holds q/k/v/do
    # tiles plus fp32 [bq, bkv] score/dscore buffers; estimate with a 2x
    # margin and halve the larger block until it fits (hd512 at (512,1024)
    # measures 17 MB and aborts compilation without this)
    def est(q_, kv_):
        return 2 * (4 * head_dim * (q_ + 2 * kv_) + 8 * q_ * kv_)

    budget = 16 * 1024 * 1024
    bq0, bkv0 = bq, bkv
    while est(bq, bkv) > budget and max(bq, bkv) > 128:
        if bkv >= bq:
            bkv = max(bkv // 2 // 128 * 128, 128)
        else:
            bq = max(bq // 2 // 128 * 128, 128)
    if (bq, bkv) != (bq0, bkv0):
        logger.info("splash blocks clamped to (%d, %d) for head_dim %d",
                    bq, bkv, head_dim)
    return sk.BlockSizes(
        block_q=bq, block_kv=bkv, block_kv_compute=bkv,
        block_q_dkv=bq, block_kv_dkv=bkv, block_kv_dkv_compute=bkv,
        block_q_dq=bq, block_kv_dq=bkv,
    )


def splash_attention_tpu(q: jax.Array, k: jax.Array, v: jax.Array,
                         block_q: int = 0, block_kv: int = 0,
                         causal: bool = True) -> jax.Array:
    """Splash attention (the current-generation Pallas TPU kernel).

    q: [B, L, H, D]; k/v: [B, L, Hkv, D] → out [B, L, H, D]. GQA/MQA run
    NATIVELY (``make_splash_mqa`` vmapped over kv groups) — K/V are never
    repeated to H heads, cutting both the repeat's HBM traffic and the
    kernel's K/V block loads by H/Hkv.

    The kernel is built per trace — make_splash_mha captures trace-local
    mask arrays, so caching it across jit traces leaks tracers.
    """
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
        splash_attention_mask as sm,
    )

    B, L, H, D = q.shape
    Hkv = k.shape[2]
    scale = float(1.0 / D ** 0.5)
    blocks = _splash_blocks(L, block_q, block_kv, D)

    def head_mask(n):
        m = sm.CausalMask((L, L)) if causal else sm.FullMask((L, L))
        return sm.MultiHeadMask([m] * n)

    qt, kt, vt = (x.swapaxes(1, 2) for x in (q, k, v))  # [B, H(kv), L, D]
    if Hkv == H:
        kernel = sk.make_splash_mha(mask=head_mask(H), block_sizes=blocks,
                                    head_shards=1, q_seq_shards=1)
        out = jax.vmap(kernel)(qt * scale, kt, vt)
        return out.swapaxes(1, 2)
    # grouped-query: per kv group g, rep = H/Hkv query heads share k/v[g]
    rep = H // Hkv
    mask = head_mask(rep)
    kernel = sk.make_splash_mqa(mask=mask, block_sizes=blocks,
                                head_shards=1, q_seq_shards=1)
    qg = (qt * scale).reshape(B, Hkv, rep, L, D)
    out = jax.vmap(jax.vmap(kernel))(qg, kt, vt)  # [B, Hkv, rep, L, D]
    return out.reshape(B, H, L, D).swapaxes(1, 2)


def flash_attention_tpu(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True
) -> jax.Array:
    """Flash attention via the Pallas TPU kernel.

    q/k/v: [B, L, H, D] (Hkv already expanded for GQA) → out [B, L, H, D].
    The kernel wants [B, H, L, D]; blocks stream through VMEM so the [L, L]
    score matrix never hits HBM — replaces the XLA path's fp32
    ``bhlm`` logits tensor (the single biggest HBM consumer at long L).
    """
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as _flash,
    )

    D = q.shape[-1]
    qt, kt, vt = (x.swapaxes(1, 2) for x in (q, k, v))
    out = _flash(qt, kt, vt, causal=causal, sm_scale=float(1.0 / D ** 0.5))
    return out.swapaxes(1, 2)


def _constrain_batch_activations(x: jax.Array) -> jax.Array:
    """Pin [B, L, D] activations to the canonical batch sharding.

    Without this, GSPMD sometimes resolves the fsdp layout by REPLICATING
    activations and partial-summing over contraction-dim-sharded weights —
    full-batch [B, L, 2F] all-reduce temps per layer (measured: the fsdp-8
    llama2_7b step blows the v5e HBM budget on exactly those buffers, and
    the dryrun emits "[SPMD] Involuntary full rematerialization" on the
    adjacent converts). Proper FSDP keeps activations batch-sharded and
    all-gathers weights per layer; a with_sharding_constraint at each block
    boundary forces that resolution. No-op off-mesh (single chip, or under
    shard_map'd callers like the pipeline whose activations are per-shard).
    """
    from .context import get_mesh_context, get_seq_context
    from .sharding import batch_mesh_axes

    mesh = get_mesh_context()
    if mesh is None:
        return x
    batch = batch_mesh_axes(mesh)
    seq_ctx = get_seq_context()
    lspec = seq_ctx.axis_name if seq_ctx is not None else None
    if not batch and lspec is None:
        return x
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(batch if batch else None, lspec, None))
    )


def _constrain_lookup_table(w: jax.Array, shard_rows: bool = True) -> jax.Array:
    """Pin a [rows, d_model] lookup table to (tensor-sharded rows,
    replicated d) for the duration of a gather.

    The stored table is (vocab→tensor, embed→fsdp); partitioning a gather
    whose operand keeps d_model sharded makes GSPMD emit the D-sharded
    gather first and then reshard its output to the batch layout — the
    "[SPMD] Involuntary full rematerialization" path (r4 VERDICT weak #5).
    Un-sharding D for the lookup is the same per-use weight all-gather FSDP
    performs for every other parameter; the gather output then comes out
    index-passthrough-sharded, no resharding step."""
    from .context import get_mesh_context
    from .. import constants as _c

    mesh = get_mesh_context()
    if mesh is None:
        return w
    from jax.sharding import NamedSharding, PartitionSpec as P

    t = (_c.MESH_AXIS_TENSOR
         if int(mesh.shape.get(_c.MESH_AXIS_TENSOR, 1)) > 1 else None)
    if shard_rows is False:  # tables stored with replicated rows (pos_emb)
        t = None
    return jax.lax.with_sharding_constraint(
        w, NamedSharding(mesh, P(t, None))
    )


def _shard_attn_kernel(fn, q, k, v):
    """Run a Pallas attention kernel under the ambient mesh via shard_map.

    pjit cannot partition Mosaic kernels automatically — without this, the
    splash/flash paths fail to lower whenever the step is jitted over a
    multi-device mesh (the exact program every fsdp/tp pod runs). Specs are
    the Megatron layout: batch over (data, fsdp), heads over tensor, full
    sequence per shard (the sequence-sharded path uses ring attention
    instead and never reaches here).
    """
    from .context import get_mesh_context
    from .sharding import batch_mesh_axes, compat_shard_map

    mesh = get_mesh_context()
    if mesh is None:
        return fn(q, k, v)
    from .. import constants as _c

    batch = batch_mesh_axes(mesh)
    t = int(mesh.shape.get(_c.MESH_AXIS_TENSOR, 1))
    tp = _c.MESH_AXIS_TENSOR if t > 1 else None
    if not batch and tp is None:
        return fn(q, k, v)
    if tp is not None and (q.shape[2] % t or k.shape[2] % t):
        raise ValueError(
            f"tensor axis {t} must divide both n_heads {q.shape[2]} and "
            f"n_kv_heads {k.shape[2]} to shard the attention kernel "
            f"(GQA runs native — kv heads are NOT expanded); lower the "
            f"tensor extent or raise n_kv_heads"
        )
    from jax.sharding import PartitionSpec as P

    spec = P(batch if batch else None, None, tp, None)
    return compat_shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


def expand_gqa(k, v, n_heads):
    """Repeat K/V heads up to n_heads (GQA) — one convention, one place."""
    Hkv = k.shape[2]
    if Hkv != n_heads:
        rep = n_heads // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v


def attention_scores(
    q: jax.Array, k: jax.Array, v: jax.Array, mask: Optional[jax.Array],
    causal: bool = True,
) -> jax.Array:
    """Plain attention (single-device / tensor-parallel path).

    q: [B, L, H, D], k/v: [B, L, Hkv, D] → out [B, L, H, D]. GQA via repeat.
    The sequence-parallel path replaces this with ring attention
    (``ring_attention.py``).
    """
    B, L, H, D = q.shape
    k, v = expand_gqa(k, v, H)
    scale = 1.0 / jnp.sqrt(D).astype(jnp.float32)
    logits = jnp.einsum("blhd,bmhd->bhlm", q, k).astype(jnp.float32) * scale
    if causal:
        tri = jnp.tril(jnp.ones((L, L), jnp.bool_))
        logits = jnp.where(tri[None, None], logits, -1e30)
    if mask is not None:
        logits = jnp.where(mask[:, None, None, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhlm,bmhd->blhd", probs, v)


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, cos, sin, mask=None):
        cfg = self.cfg
        D, H, Hkv, hd = cfg.d_model, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
        init = nn.initializers.normal(0.02)
        # fused QKV: one [D, (H + 2*Hkv) * hd] matmul
        wqkv = self.param(
            "wqkv",
            nn.with_partitioning(init, (EMBED, HEADS)),
            (D, (H + 2 * Hkv) * hd),
            cfg.param_dtype,
        )
        wo = self.param(
            "wo",
            nn.with_partitioning(init, (HEADS, EMBED)),
            (H * hd, D),
            cfg.param_dtype,
        )
        B, L, _ = x.shape
        qkv = jnp.einsum("bld,de->ble", x, wqkv.astype(cfg.dtype))
        q, k, v = jnp.split(qkv, [H * hd, (H + Hkv) * hd], axis=-1)
        q = q.reshape(B, L, H, hd)
        k = k.reshape(B, L, Hkv, hd)
        v = v.reshape(B, L, Hkv, hd)
        q = apply_rotary(q, cos, sin)
        k = apply_rotary(k, cos, sin)

        from .context import get_seq_context

        seq_ctx = get_seq_context()
        if seq_ctx is not None:
            # sequence parallelism: exact attention over the ring (L stays
            # sharded; K/V rotate over ICI — ring_attention.py)
            from jax.sharding import PartitionSpec as P

            from .. import constants as _c
            from .ring_attention import make_ring_attention
            from .sharding import compat_shard_map

            k, v = expand_gqa(k, v, H)  # expand before sharding (GQA)
            spec = P(
                (_c.MESH_AXIS_DATA, _c.MESH_AXIS_FSDP),
                seq_ctx.axis_name,
                _c.MESH_AXIS_TENSOR,
                None,
            )
            # splash kernel inside the ring when the per-device block is in
            # the kernel's winning regime (tools/bench_ring_kernel.py). The
            # r5 backward is the splash dq/dkv kernels too (ring_attention
            # ._bwd_kernel), so the threshold is no longer bwd-limited; 4096
            # stands until the TPU block sweep re-measures the crossover
            Lb = L // seq_ctx.size
            use_kernel = (
                _attn_backend(cfg.attn_impl) == "splash"
                and Lb >= 4096 and Lb % 128 == 0
            )
            ring = make_ring_attention(
                seq_ctx.size, seq_ctx.axis_name, causal=cfg.causal,
                use_kernel=use_kernel,
                block_q=cfg.attn_block_q, block_kv=cfg.attn_block_kv,
            )
            out = compat_shard_map(
                ring, mesh=seq_ctx.mesh, in_specs=(spec, spec, spec),
                out_specs=spec,
            )(q, k, v)
        elif (
            mask is None and L >= 128 and L % 128 == 0
            and _attn_backend(cfg.attn_impl) != "xla"
        ):
            if _attn_backend(cfg.attn_impl) == "splash":
                # GQA handled natively by the kernel — no K/V expand
                from functools import partial

                out = _shard_attn_kernel(
                    partial(
                        splash_attention_tpu,
                        block_q=cfg.attn_block_q, block_kv=cfg.attn_block_kv,
                        causal=cfg.causal,
                    ),
                    q, k, v,
                )
            else:
                k, v = expand_gqa(k, v, H)
                out = _shard_attn_kernel(
                    partial(flash_attention_tpu, causal=cfg.causal), q, k, v
                )
        else:
            out = attention_scores(q, k, v, mask, causal=cfg.causal)
        out = out.reshape(B, L, H * hd)
        return jnp.einsum("ble,ed->bld", out, wo.astype(cfg.dtype))


class FeedForward(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        init = nn.initializers.normal(0.02)
        # fused gate+up: one [D, 2*F] matmul
        w_gate_up = self.param(
            "w_gate_up",
            nn.with_partitioning(init, (EMBED, MLP)),
            (cfg.d_model, 2 * cfg.d_ff),
            cfg.param_dtype,
        )
        w_down = self.param(
            "w_down",
            nn.with_partitioning(init, (MLP, EMBED)),
            (cfg.d_ff, cfg.d_model),
            cfg.param_dtype,
        )
        gu = jnp.einsum("bld,df->blf", x, w_gate_up.astype(cfg.dtype))
        gate, up = jnp.split(gu, 2, axis=-1)
        h = nn.silu(gate) * up
        return jnp.einsum("blf,fd->bld", h, w_down.astype(cfg.dtype))


class Block(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, cos, sin, mask=None):
        x = x + Attention(self.cfg)(RMSNorm(self.cfg.norm_eps)(x), cos, sin, mask)
        if self.cfg.moe_experts > 1:
            from .moe import MoEFeedForward

            y, aux = MoEFeedForward(self.cfg)(RMSNorm(self.cfg.norm_eps)(x))
            # surfaced through the "losses" collection; the trainer adds
            # moe_aux_weight * sum to the task loss
            self.sow("losses", "moe_aux", aux)
            return x + y
        x = x + FeedForward(self.cfg)(RMSNorm(self.cfg.norm_eps)(x))
        return x


class Transformer(nn.Module):
    """Decoder-only LM. tokens [B, L] int32 → logits [B, L, vocab] fp32."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, mask=None, positions=None, return_hidden=False):
        cfg = self.cfg
        embed = self.param(
            "embed",
            nn.with_partitioning(nn.initializers.normal(0.02), (VOCAB, EMBED)),
            (cfg.vocab_size, cfg.d_model),
            cfg.param_dtype,
        )
        # constrain AT the take: the table is (vocab→tensor, embed→fsdp)
        # sharded, and without an output annotation on the gather itself the
        # partitioner first shards the result like the table (d_model over
        # fsdp) and then hits an "[SPMD] Involuntary full rematerialization"
        # transition to the batch-sharded activation layout (r4 VERDICT
        # weak #5, reproduced on the fsdp×tensor×sequence fedllm mesh)
        x = _constrain_batch_activations(
            jnp.take(_constrain_lookup_table(embed), tokens, axis=0)
            .astype(cfg.dtype)
        )
        if positions is None:
            positions = jnp.arange(tokens.shape[1])[None, :]
        if cfg.pos_emb == "learned":
            pos_table = self.param(
                "pos_emb",
                nn.with_partitioning(nn.initializers.normal(0.02),
                                     (None, EMBED)),
                (cfg.max_seq_len, cfg.d_model),
                cfg.param_dtype,
            )
            # positions may be [1, L] (broadcast) or [B, L] (per-example,
            # same contract as the rotary branch)
            x = x + jnp.take(
                _constrain_lookup_table(pos_table, shard_rows=False),
                positions, axis=0,
            ).astype(cfg.dtype)
            # identity rotation: attention runs position-free
            ang = jnp.zeros(positions.shape + (cfg.head_dim // 2,),
                            jnp.float32)
            cos, sin = jnp.cos(ang), jnp.sin(ang)
        else:
            cos, sin = rotary_embedding(positions, cfg.head_dim,
                                        cfg.rope_theta)
        x = _constrain_batch_activations(x)

        if cfg.remat:
            policy = (
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable
                if cfg.remat_policy == "dots"
                else None
            )
            block_cls = nn.remat(Block, policy=policy)
        else:
            block_cls = Block
        for _ in range(cfg.n_layers):
            x = _constrain_batch_activations(
                block_cls(cfg)(x, cos, sin, mask)
            )

        x = RMSNorm(cfg.norm_eps)(x)
        if return_hidden:
            # returning BEFORE the head param is declared matters twice:
            # the chunked-CE caller (train_step.lm_loss_chunked) fuses the
            # head matmul itself so [B, L, vocab] fp32 logits never hit
            # HBM, and task-head backbones (models/transformer_heads.py)
            # never CREATE the [d_model, vocab] LM head — at 7B scale a
            # ~131M-param dead weight every FL round would otherwise ship
            return x
        # tied-untied choice: separate output head (Llama unties)
        w_out = self.param(
            "w_lm_head",
            nn.with_partitioning(nn.initializers.normal(0.02), (EMBED, VOCAB)),
            (cfg.d_model, cfg.vocab_size),
            cfg.param_dtype,
        )
        return jnp.einsum("bld,dv->blv", x, w_out.astype(cfg.dtype)).astype(
            jnp.float32
        )
