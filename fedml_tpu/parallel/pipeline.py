"""GPipe pipeline parallelism over the ``pipeline`` mesh axis.

New-capability work (SURVEY.md §2.5: the reference's only layer-split
precedent is SplitNN, ``simulation/mpi/split_nn/``) — here a TPU-native
schedule:

- the transformer's blocks live as ONE stacked param tree ``[n_layers, ...]``
  reshaped to ``[n_stages, layers_per_stage, ...]`` and sharded over the
  ``pipeline`` mesh axis: each pipeline rank holds its stage's slice only
- the whole schedule is a single ``shard_map`` program: a ``lax.scan`` over
  ``M + S - 1`` ticks; every tick each stage applies its blocks and hands its
  activation to the next stage over ICI with ``lax.ppermute``
- backward needs no hand-written schedule: the transpose of ``ppermute`` is
  the reverse rotation, so ``jax.grad`` through the scan IS the backward
  pipeline (GPipe with rematerialised stages)
- embedding / final norm / LM head are replicated across the pipeline axis
  (stage 0 consumes the embedding, the last stage the head; replication keeps
  the per-device program uniform, which SPMD requires)
- the ``data`` mesh axis composes: microbatches are additionally sharded over
  ``data`` and gradients psum over it — pp x dp in one program

Bubble fraction is the GPipe (S-1)/(M+S-1); raise ``microbatches`` to
amortise.
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .sharding import compat_shard_map as shard_map

from .. import constants
from .transformer import (
    Block,
    TransformerConfig,
    rms_norm,
    rotary_embedding,
)

logger = logging.getLogger(__name__)

PyTree = Any

DATA = constants.MESH_AXIS_DATA
PIPELINE = constants.MESH_AXIS_PIPELINE


class PipelineCheetah:
    """Pipeline-parallel trainer for the Cheetah transformer.

    ``mesh`` must carry a ``pipeline`` axis of size S >= 2 and
    ``cfg.n_layers`` must divide evenly into S stages.

    Capabilities (explicit, so nobody infers more than is here):

    - schedule: ``"gpipe"`` (default) — M microbatches through S stages
      over ``M + S - 1`` ticks, backward by autodiff; or ``"1f1b"`` —
      hand-scheduled one-forward-one-backward ticks whose in-flight
      activation memory is O(S) instead of O(M)
      (``_train_step_device_1f1b``; gradient-exact vs gpipe, verified by
      ``tests/test_pipeline.py::test_1f1b_matches_gpipe``). Bubble
      fraction is (S-1)/(M+S-1) for both (non-interleaved); 1F1B's win is
      the memory headroom that lets M grow. No interleaved stages.
    - backward: ``jax.grad`` through the scan (ppermute's transpose is the
      reverse rotation) — exact, rematerialised per stage
    - composes with a ``data`` mesh axis (pp x dp); tensor/sequence axes
      INSIDE a stage are not supported — use ``CheetahTrainer`` for tp/sp
    - embedding/norm/head replicated across stages; every stage computes the
      stage-0 embedding gather each tick (SPMD-uniform program; the waste is
      one [mb, L, D] gather per tick per stage, accepted for uniformity)
    """

    def __init__(
        self,
        cfg: TransformerConfig,
        mesh: Mesh,
        microbatches: int = 4,
        optimizer: Optional[optax.GradientTransformation] = None,
        schedule: str = "gpipe",
    ):
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"schedule must be 'gpipe' or '1f1b', got {schedule!r}")
        if getattr(cfg, "pos_emb", "rope") != "rope":
            # both schedules hard-code rotary; silently dropping a
            # config knob the single-device path honours would train a
            # DIFFERENT model than the same YAML elsewhere
            raise NotImplementedError(
                "PipelineCheetah supports pos_emb='rope' only"
            )
        self.schedule = schedule
        self.cfg = cfg
        self.mesh = mesh
        self.n_stages = int(mesh.shape[PIPELINE])
        if self.n_stages < 2:
            raise ValueError("pipeline axis must have size >= 2")
        if cfg.n_layers % self.n_stages:
            raise ValueError(
                f"n_layers {cfg.n_layers} not divisible by "
                f"{self.n_stages} stages"
            )
        self.layers_per_stage = cfg.n_layers // self.n_stages
        self.microbatches = int(microbatches)
        self.block = Block(cfg)
        self.opt = optimizer or optax.adamw(3e-4)
        self._step = None
        self._loss_jit = None
        self._blocks_struct = None  # computed once, reused everywhere

    def bubble_fraction(self) -> float:
        """GPipe idle fraction: (S-1)/(M+S-1) of each device's schedule."""
        S, M = self.n_stages, self.microbatches
        return (S - 1) / (M + S - 1)

    # -- params -------------------------------------------------------------
    def init_params(self, rng: jax.Array) -> PyTree:
        """{'embed', 'blocks' (stacked [n_layers, ...]), 'norm_f', 'head'}."""
        cfg = self.cfg
        k_embed, k_blocks, k_head = jax.random.split(rng, 3)
        block_keys = jax.random.split(k_blocks, cfg.n_layers)
        blocks = jax.jit(jax.vmap(self._init_one_block))(block_keys)
        params = {
            "embed": jax.random.normal(
                k_embed, (cfg.vocab_size, cfg.d_model), cfg.param_dtype
            ) * 0.02,
            "blocks": blocks,
            "norm_f": jnp.ones((cfg.d_model,), jnp.float32),
            "head": jax.random.normal(
                k_head, (cfg.d_model, cfg.vocab_size), cfg.param_dtype
            ) * 0.02,
        }
        return jax.device_put(params, self.param_shardings())

    def param_shardings(self) -> PyTree:
        """blocks sharded over pipeline on the layer axis; rest replicated."""
        repl = NamedSharding(self.mesh, P())
        stage = NamedSharding(self.mesh, P(PIPELINE))
        return {
            "embed": repl,
            "blocks": jax.tree.map(lambda _: stage, self._blocks_structure()),
            "norm_f": repl,
            "head": repl,
        }

    def _init_one_block(self, k):
        """Init + unbox one block's params — the single source of the block
        param structure (init_params vmaps it; _blocks_structure shapes it)."""
        cfg = self.cfg
        dummy = jnp.zeros((1, 8, cfg.d_model), cfg.dtype)
        pos = jnp.arange(8)[None, :]
        cos, sin = rotary_embedding(pos, cfg.head_dim, cfg.rope_theta)
        variables = self.block.init(k, dummy, cos, sin)
        return jax.tree.map(
            lambda p: p.value if hasattr(p, "value") else p,
            variables["params"],
            is_leaf=lambda x: hasattr(x, "value"),
        )

    def _blocks_structure(self):
        """Unboxed single-block param shapes (computed once)."""
        if self._blocks_struct is None:
            self._blocks_struct = jax.eval_shape(
                self._init_one_block, jax.random.PRNGKey(0)
            )
        return self._blocks_struct

    # -- the pipelined program ----------------------------------------------
    def _apply_stage(self, stage_blocks, x, cos, sin):
        """Run this stage's layers_per_stage blocks (scan over the slice)."""

        def body(h, layer_params):
            unboxed = jax.tree.map(
                lambda p: p.value if hasattr(p, "value") else p,
                layer_params, is_leaf=lambda q: hasattr(q, "value"),
            )
            h = self.block.apply({"params": unboxed}, h, cos, sin)
            return h, None

        x, _ = jax.lax.scan(body, x, stage_blocks)
        return x

    def _loss_device(self, params, tokens, mask):
        """Per-device GPipe loop. tokens [M, mb_local, L] (local slice)."""
        cfg = self.cfg
        S, M = self.n_stages, self.microbatches
        stage = jax.lax.axis_index(PIPELINE)
        Mb, L = tokens.shape[1], tokens.shape[2]
        pos = jnp.arange(L)[None, :]
        cos, sin = rotary_embedding(pos, cfg.head_dim, cfg.rope_theta)
        # this device's stage slice: [layers_per_stage, ...] — under
        # shard_map the leading n_layers axis arrives already sliced
        stage_blocks = params["blocks"]

        perm = [(i, (i + 1) % S) for i in range(S)]
        T = M + S - 1

        def tick(buf, t):
            # stage 0 embeds microbatch t (junk for t >= M; dropped later)
            mb = jnp.take(
                tokens, jnp.minimum(t, M - 1), axis=0
            )  # [mb_local, L]
            x0 = jnp.take(params["embed"], mb, axis=0).astype(cfg.dtype)
            x_in = jnp.where(stage == 0, x0, buf)
            y = self._apply_stage(stage_blocks, x_in, cos, sin)
            buf_next = jax.lax.ppermute(y, PIPELINE, perm)
            return buf_next, y

        buf0 = jnp.zeros((Mb, L, cfg.d_model), cfg.dtype)
        _, ys = jax.lax.scan(tick, buf0, jnp.arange(T))  # [T, mb, L, D]

        # last stage's ticks S-1 .. T-1 hold microbatches 0..M-1
        outs = jax.lax.dynamic_slice_in_dim(ys, S - 1, M, axis=0)
        h = rms_norm(
            outs, params["norm_f"].astype(jnp.float32), cfg.norm_eps
        )
        logits = jnp.einsum(
            "mbld,dv->mblv", h, params["head"].astype(cfg.dtype)
        ).astype(jnp.float32)
        targets = tokens[:, :, 1:]
        m = mask[:, :, 1:].astype(jnp.float32)
        per = optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :, :-1], targets
        )
        local_sum = (per * m).sum()
        local_cnt = m.sum()
        # only the final stage's logits are meaningful. The returned value is
        # the LOCAL loss over the GLOBAL token count — never psum the
        # numerator inside the differentiated function: psum's transpose is
        # psum, so a psum'd numerator multiplies every gradient by the axis
        # size. Callers psum the scalar afterwards for reporting.
        is_last = (stage == S - 1).astype(jnp.float32)
        cnt = jax.lax.psum(local_cnt * is_last, PIPELINE)
        if DATA in self.mesh.axis_names and self.mesh.shape[DATA] > 1:
            cnt = jax.lax.psum(cnt, DATA)
        return local_sum * is_last / jnp.maximum(cnt, 1.0)

    def _all_reduce_scalar(self, x):
        x = jax.lax.psum(x, PIPELINE)
        if DATA in self.mesh.axis_names and self.mesh.shape[DATA] > 1:
            x = jax.lax.psum(x, DATA)
        return x

    def _train_step_device(self, params, opt_state, tokens, mask):
        loss, grads = jax.value_and_grad(self._loss_device)(
            params, tokens, mask
        )
        loss = self._all_reduce_scalar(loss)  # reporting only
        # cross-stage grad flow rode the ppermute transpose; replicated
        # params (embed/norm/head) need their grads summed across stages,
        # and everything psums over data
        def sync(path_is_blocks, g):
            if not path_is_blocks:
                g = jax.lax.psum(g, PIPELINE)
            if DATA in self.mesh.axis_names and self.mesh.shape[DATA] > 1:
                g = jax.lax.psum(g, DATA)
            return g

        grads = {
            "embed": sync(False, grads["embed"]),
            "blocks": jax.tree.map(partial(sync, True), grads["blocks"]),
            "norm_f": sync(False, grads["norm_f"]),
            "head": sync(False, grads["head"]),
        }
        updates, opt_state = self.opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    # -- 1F1B schedule --------------------------------------------------------
    def _train_step_device_1f1b(self, params, opt_state, tokens, mask):
        """Hand-scheduled one-forward-one-backward pipeline tick loop.

        GPipe-by-autodiff (``_train_step_device``) lets ``jax.grad`` run the
        whole forward scan first, so every tick's stage output — M + S - 1
        activations of [mb, L, D] — is live until its backward. 1F1B
        interleaves: at tick t each stage forwards microbatch ``t - s`` and
        backwards microbatch ``t - 2(S-1) + s`` (the last stage backwards a
        microbatch at the same tick its forward completes), so only a ring
        of 2S in-flight stage INPUTS is ever saved — activation memory
        O(S), independent of M. Bubble fraction is unchanged vs GPipe for
        the non-interleaved schedule — the win is memory, which is what
        lets M grow (and the bubble shrink) without re-enabling remat.

        Gradients are exact: each backward tick recomputes its stage
        forward from the saved input and applies the cotangent arriving
        from the next stage over the reverse ``ppermute``.
        """
        cfg = self.cfg
        S, M = self.n_stages, self.microbatches
        stage = jax.lax.axis_index(PIPELINE)
        Mb, L = tokens.shape[1], tokens.shape[2]
        pos = jnp.arange(L)[None, :]
        cos, sin = rotary_embedding(pos, cfg.head_dim, cfg.rope_theta)
        R = 2 * S  # ring capacity > max in-flight (2(S-1)+1)
        T = M + 2 * (S - 1)
        perm_fwd = [(i, (i + 1) % S) for i in range(S)]
        perm_bwd = [((i + 1) % S, i) for i in range(S)]
        is_last = (stage == S - 1)

        def stage_fwd(p_blocks, p_embed, buf, mb_tokens):
            x0 = jnp.take(p_embed, mb_tokens, axis=0).astype(cfg.dtype)
            x_in = jnp.where(stage == 0, x0, buf)
            return self._apply_stage(p_blocks, x_in, cos, sin)

        def loss_sum_fn(p_norm, p_head, y, mb_tokens, mb_mask):
            h = rms_norm(y, p_norm.astype(jnp.float32), cfg.norm_eps)
            logits = jnp.einsum(
                "bld,dv->blv", h, p_head.astype(cfg.dtype)
            ).astype(jnp.float32)
            per = optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], mb_tokens[:, 1:]
            )
            return (per * mb_mask[:, 1:].astype(jnp.float32)).sum()

        zeros_g = {
            "embed": jnp.zeros_like(params["embed"]),
            "blocks": jax.tree.map(jnp.zeros_like, params["blocks"]),
            "norm_f": jnp.zeros_like(params["norm_f"]),
            "head": jnp.zeros_like(params["head"]),
        }

        def tick(carry, t):
            fwd_buf, bwd_buf, saved, g, loss_sum = carry
            # ---- forward of microbatch m_f = t - stage
            m_f = t - stage
            f_valid = ((m_f >= 0) & (m_f < M)).astype(jnp.float32)
            tok_f = jnp.take(tokens, jnp.clip(m_f, 0, M - 1), axis=0)
            msk_f = jnp.take(mask, jnp.clip(m_f, 0, M - 1), axis=0)
            y = stage_fwd(params["blocks"], params["embed"], fwd_buf, tok_f)
            # save this microbatch's stage INPUT for its backward recompute
            slot_f = jnp.where(m_f >= 0, m_f % R, 0)
            cur = jax.lax.dynamic_index_in_dim(saved, slot_f, 0,
                                               keepdims=False)
            saved = jax.lax.dynamic_update_index_in_dim(
                saved,
                jnp.where(f_valid > 0, fwd_buf, cur),
                slot_f, 0,
            )
            # ---- last stage: loss grads for THIS microbatch, immediately.
            # Gated with lax.cond (r4 ADVICE): ungated, the [mb,L,D]x[D,V]
            # head fwd+bwd ran on EVERY tick of EVERY stage and was masked
            # after the fact — M+2(S-1) head matmul pairs per step per
            # stage vs the M the last stage needs, a real tax at vocab 32k.
            def head_grads(ops):
                p_norm, p_head, y_, tok_, msk_ = ops
                lval, (g_norm, g_head, dy_loss) = jax.value_and_grad(
                    loss_sum_fn, argnums=(0, 1, 2)
                )(p_norm, p_head, y_, tok_, msk_)
                return lval, g_norm, g_head, dy_loss

            def head_skip(ops):
                p_norm, p_head, y_, _tok, _msk = ops
                return (jnp.zeros(()), jnp.zeros_like(p_norm),
                        jnp.zeros_like(p_head), jnp.zeros_like(y_))

            lval, g_norm, g_head, dy_loss = jax.lax.cond(
                is_last & (f_valid > 0), head_grads, head_skip,
                (params["norm_f"], params["head"], y, tok_f, msk_f),
            )
            loss_sum = loss_sum + lval
            g["norm_f"] = g["norm_f"] + g_norm
            g["head"] = g["head"] + g_head
            # ---- backward of microbatch m_b = t - 2(S-1) + stage
            m_b = t - 2 * (S - 1) + stage
            b_valid = ((m_b >= 0) & (m_b < M)).astype(jnp.float32)
            tok_b = jnp.take(tokens, jnp.clip(m_b, 0, M - 1), axis=0)
            slot_b = jnp.where(m_b >= 0, m_b % R, 0)
            x_saved = jax.lax.dynamic_index_in_dim(saved, slot_b, 0,
                                                   keepdims=False)
            # cotangent: the last stage's is its own fresh loss grad
            # (m_b == m_f there); other stages' arrived over the ring
            dy = jnp.where(is_last, dy_loss.astype(cfg.dtype), bwd_buf)
            _, vjp = jax.vjp(
                lambda pb, pe, xb: stage_fwd(pb, pe, xb, tok_b),
                params["blocks"], params["embed"], x_saved,
            )
            d_blocks, d_embed, dx = vjp(dy)
            g["blocks"] = jax.tree.map(
                lambda a, b: a + b * b_valid, g["blocks"], d_blocks
            )
            g["embed"] = g["embed"] + d_embed * b_valid
            # ---- rotate: activations forward, cotangents backward
            fwd_buf = jax.lax.ppermute(y, PIPELINE, perm_fwd)
            bwd_buf = jax.lax.ppermute(
                (dx * b_valid).astype(cfg.dtype), PIPELINE, perm_bwd
            )
            return (fwd_buf, bwd_buf, saved, g, loss_sum), None

        buf0 = jnp.zeros((Mb, L, cfg.d_model), cfg.dtype)
        saved0 = jnp.zeros((R, Mb, L, cfg.d_model), cfg.dtype)
        carry = jax.lax.scan(
            tick, (buf0, buf0, saved0, zeros_g, jnp.zeros(())),
            jnp.arange(T),
        )[0]
        g, loss_sum = carry[3], carry[4]
        # normalize by the GLOBAL token count and sync exactly like GPipe
        cnt = mask[:, :, 1:].astype(jnp.float32).sum()  # replicated over pp
        if DATA in self.mesh.axis_names and self.mesh.shape[DATA] > 1:
            cnt = jax.lax.psum(cnt, DATA)
        cnt = jnp.maximum(cnt, 1.0)

        def sync(path_is_blocks, gr):
            if not path_is_blocks:
                gr = jax.lax.psum(gr, PIPELINE)
            if DATA in self.mesh.axis_names and self.mesh.shape[DATA] > 1:
                gr = jax.lax.psum(gr, DATA)
            return gr / cnt

        grads = {
            "embed": sync(False, g["embed"]),
            "blocks": jax.tree.map(partial(sync, True), g["blocks"]),
            "norm_f": sync(False, g["norm_f"]),
            "head": sync(False, g["head"]),
        }
        # loss_sum is already nonzero only on the last stage (w_last mask)
        loss = self._all_reduce_scalar(loss_sum) / cnt
        updates, opt_state = self.opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    # -- public API ----------------------------------------------------------
    def init_opt_state(self, params: PyTree) -> PyTree:
        with self.mesh:
            return jax.jit(self.opt.init)(params)

    def _specs(self):
        blocks_spec = jax.tree.map(
            lambda _: P(PIPELINE), self._blocks_structure()
        )
        p_spec = {
            "embed": P(), "blocks": blocks_spec, "norm_f": P(), "head": P(),
        }
        d_spec = P(None, DATA) if DATA in self.mesh.axis_names else P(None, None)
        return p_spec, d_spec

    def loss(self, params, tokens, mask) -> jax.Array:
        """tokens/mask: [M, B, L] microbatched global arrays."""
        if self._loss_jit is None:
            p_spec, d_spec = self._specs()

            def full_loss(params, tokens, mask):
                return self._all_reduce_scalar(
                    self._loss_device(params, tokens, mask)
                )

            fn = shard_map(
                full_loss, mesh=self.mesh,
                in_specs=(p_spec, d_spec, d_spec), out_specs=P(),
            )
            self._loss_jit = jax.jit(fn)
        with self.mesh:
            return self._loss_jit(params, tokens, mask)

    def train_step(self, params, opt_state, tokens, mask):
        if self._step is None:
            p_spec, d_spec = self._specs()
            o_spec = _opt_state_specs(p_spec, opt_state)
            device_fn = (
                self._train_step_device_1f1b
                if self.schedule == "1f1b"
                else self._train_step_device
            )
            fn = shard_map(
                device_fn, mesh=self.mesh,
                in_specs=(p_spec, o_spec, d_spec, d_spec),
                out_specs=(p_spec, o_spec, P()),
            )
            self._step = jax.jit(fn)
        with self.mesh:
            return self._step(params, opt_state, tokens, mask)


def _path_keys(path) -> tuple:
    """Normalize a jax key path to plain hashable tokens."""
    out = []
    for k in path:
        for attr in ("key", "name", "idx"):
            if hasattr(k, attr):
                out.append(str(getattr(k, attr)))
                break
        else:
            out.append(str(k))
    return tuple(out)


def _opt_state_specs(p_spec: PyTree, opt_state: PyTree) -> PyTree:
    """PartitionSpecs for an optimizer state mirroring param sharding.

    Optimizer moments (adam mu/nu, momentum buffers, ...) embed the param
    tree inside wrapper structures, so an opt-state leaf's key path ENDS
    with the corresponding param's key path — match by longest path suffix,
    never by leaf shape (two same-shaped params with different shardings
    would collide silently). Scalars like adam's ``count`` match nothing
    and stay replicated.
    """
    import jax.tree_util as jtu

    spec_by_path = {
        _path_keys(path): sp
        for path, sp in jtu.tree_flatten_with_path(
            p_spec, is_leaf=lambda x: isinstance(x, P)
        )[0]
    }

    def one(path, _x):
        keys = _path_keys(path)
        for start in range(len(keys)):  # longest suffix first
            sp = spec_by_path.get(keys[start:])
            if sp is not None:
                return sp
        return P()

    return jtu.tree_map_with_path(one, opt_state)


def microbatch(tokens: np.ndarray, mask: np.ndarray, m: int):
    """[B, L] -> [M, B/M, L]."""
    B, L = tokens.shape
    if B % m:
        raise ValueError(f"batch {B} not divisible by microbatches {m}")
    return (
        tokens.reshape(m, B // m, L),
        mask.reshape(m, B // m, L),
    )
