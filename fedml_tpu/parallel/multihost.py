"""Multi-host runtime: one logical device mesh spanning OS processes.

reference: the MPI plane — ``mpirun`` launches N ranks, each rank binds a GPU,
and NCCL/MPI collectives move tensors between them
(``simulation/mpi/base_framework/``, ``core/distributed/communication/mpi/
mpi_comm_manager.py``, gRPC/TRPC variants). That is the reference's only way
to scale past one process.

TPU re-grounding: JAX's runtime already *is* the multi-process backend — each
host in a pod runs one process, ``jax.distributed.initialize`` connects them
through a coordinator, and afterwards ``jax.devices()`` is the GLOBAL device
list, so the same ``Mesh`` + ``pjit`` program runs unchanged with XLA moving
data over ICI/DCN. No per-message send/recv code exists at all — the mesh
APIs (``mesh_api``, ``train_step``, ``pipeline``) become multi-host by
construction. This module supplies the two missing pieces:

- ``initialize(...)`` — rank bootstrap (the analog of ``MPI.Init`` +
  NCCL communicator setup), driven by env vars that cover TPU pods
  (``megascale`` auto-detection), GCE, SLURM, and the explicit
  coordinator/rank form the launcher uses;
- ``spawn(worker_argv, n_processes, ...)`` — a single-machine N-process
  launcher (the analog of ``mpirun -np N``) used by tests and by
  ``examples/``: every child gets the coordinator address, its process id,
  and a ``--xla_force_host_platform_device_count`` fan-out so multi-host
  semantics (device locality, cross-process collectives over the gRPC
  coordinator) are exercised for real without N machines.

The launcher is also the honest emulation story for CI: a 2-process × 4
virtual-device run has the same global/local device split, the same
addressable-shard semantics, and the same collective routing as a 2-host
pod slice — only the wire underneath differs.
"""

from __future__ import annotations

import logging
import os
import socket
import subprocess
import sys
from typing import Dict, List, Optional, Sequence

logger = logging.getLogger("fedml_tpu.multihost")

ENV_COORDINATOR = "FEDML_TPU_COORDINATOR"
ENV_PROCESS_ID = "FEDML_TPU_PROCESS_ID"
ENV_NUM_PROCESSES = "FEDML_TPU_NUM_PROCESSES"
ENV_LOCAL_DEVICES = "FEDML_TPU_LOCAL_DEVICES"


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_count: Optional[int] = None) -> None:
    """Join this process to the global runtime (analog of MPI.Init).

    Resolution order mirrors how pods are actually launched: explicit args,
    then the ``FEDML_TPU_*`` env contract set by :func:`spawn`, then JAX's
    own auto-detection (TPU pod metadata / SLURM), which needs no args at
    all. Must run before first jax backend touch.
    """
    coordinator = coordinator or os.environ.get(ENV_COORDINATOR)
    if num_processes is None and ENV_NUM_PROCESSES in os.environ:
        num_processes = int(os.environ[ENV_NUM_PROCESSES])
    if process_id is None and ENV_PROCESS_ID in os.environ:
        process_id = int(os.environ[ENV_PROCESS_ID])
    if local_device_count is None and ENV_LOCAL_DEVICES in os.environ:
        local_device_count = int(os.environ[ENV_LOCAL_DEVICES])

    if local_device_count:  # virtual CPU fan-out for emulation runs
        import re

        flags = os.environ.get("XLA_FLAGS", "")
        opt = "xla_force_host_platform_device_count"
        if re.search(rf"{opt}=\d+", flags):  # override an inherited fan-out
            flags = re.sub(rf"{opt}=\d+", f"{opt}={local_device_count}", flags)
        else:
            flags = (flags + f" --{opt}={local_device_count}").strip()
        os.environ["XLA_FLAGS"] = flags

    import jax

    if local_device_count:
        jax.config.update("jax_platforms", "cpu")
    if coordinator is None and num_processes is None:
        # TPU pod / SLURM: jax works out everything from the environment
        jax.distributed.initialize()
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    logger.info(
        "multihost: process %d/%d up, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )


def spawn(worker_argv: Sequence[str], n_processes: int,
          local_device_count: int = 1,
          coordinator_port: Optional[int] = None,
          env: Optional[Dict[str, str]] = None,
          timeout_s: float = 300.0) -> List[subprocess.CompletedProcess]:
    """Run ``worker_argv`` as N coordinated processes (analog: mpirun -np N).

    Children read the ``FEDML_TPU_*`` env contract and call
    :func:`initialize` (no args) before touching jax. Returns the completed
    processes; raises if any exits nonzero, with its tail echoed.
    """
    import threading
    import time

    port = coordinator_port or free_port()
    procs = []
    for pid in range(n_processes):
        child_env = dict(os.environ)
        child_env.update(env or {})
        child_env.update({
            ENV_COORDINATOR: f"127.0.0.1:{port}",
            ENV_PROCESS_ID: str(pid),
            ENV_NUM_PROCESSES: str(n_processes),
            ENV_LOCAL_DEVICES: str(local_device_count),
        })
        procs.append(subprocess.Popen(
            [sys.executable, *worker_argv], env=child_env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        ))

    # drain every pipe concurrently: ranks block on collectives together, so
    # one undrained worker filling its pipe buffer would deadlock the mesh
    outputs: List[Optional[str]] = [None] * n_processes

    def _drain(idx: int, p: subprocess.Popen) -> None:
        out, _ = p.communicate()
        outputs[idx] = out

    drainers = [threading.Thread(target=_drain, args=(i, p), daemon=True)
                for i, p in enumerate(procs)]
    for t in drainers:
        t.start()
    # one shared deadline: n sequential joins must not stretch the documented
    # timeout to n * timeout_s
    deadline = time.monotonic() + timeout_s
    for t in drainers:
        t.join(timeout=max(0.0, deadline - time.monotonic()))
    if any(t.is_alive() for t in drainers):
        for q in procs:
            q.kill()
        for t in drainers:
            t.join(timeout=10)  # collect post-kill output for the error
        tails = "\n".join(
            f"--- worker {i} tail ---\n" +
            "\n".join((outputs[i] or "").splitlines()[-10:])
            for i in range(n_processes)
        )
        raise TimeoutError(
            f"multihost launch exceeded {timeout_s}s; workers killed.\n{tails}"
        )

    done = [
        subprocess.CompletedProcess(p.args, p.returncode, outputs[i] or "")
        for i, p in enumerate(procs)
    ]
    for pid, r in enumerate(done):
        if r.returncode != 0:
            tail = "\n".join(r.stdout.splitlines()[-25:])
            raise RuntimeError(
                f"multihost worker {pid} exited nonzero:\n{tail}"
            )
    return done
