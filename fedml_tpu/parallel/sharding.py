"""Sharding rules: logical axis names → mesh axes.

This is the heart of the Cheetah design (SURVEY.md §2.5): where the reference
scales by NCCL process groups + DDP wrappers, the TPU build picks a mesh,
annotates shardings, and lets XLA insert collectives (scaling-book recipe).

Mesh axes (constants.py): ``data`` (pure DP), ``fsdp`` (parameter-sharded DP),
``tensor`` (Megatron-style TP over ICI), ``sequence`` (context parallelism /
ring attention), ``pipeline``, ``expert``. Any axis can be size 1 — the same
rules serve 1 chip to a pod.

Parameter sharding follows the standard recipe:
- attention QKV [d, heads*hd]: (fsdp, tensor) — column-parallel
- attention out [heads*hd, d]: (tensor, fsdp) — row-parallel
- MLP gate/up  [d, ff]:        (fsdp, tensor)
- MLP down     [ff, d]:        (tensor, fsdp)
- embedding    [vocab, d]:     (tensor, fsdp) — vocab-parallel
- lm head      [d, vocab]:     (fsdp, tensor)
- norms: replicated

Activations: batch over (data, fsdp), sequence over (sequence).
"""

from __future__ import annotations

import logging
from typing import Any, Optional, Tuple

import jax
import numpy as np
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import constants
from .transformer import BATCH, EMBED, HEADS, KV, LENGTH, MLP, VOCAB

logger = logging.getLogger(__name__)

PyTree = Any

DATA = constants.MESH_AXIS_DATA
FSDP = constants.MESH_AXIS_FSDP
TENSOR = constants.MESH_AXIS_TENSOR
SEQUENCE = constants.MESH_AXIS_SEQUENCE
PIPELINE = constants.MESH_AXIS_PIPELINE
EXPERT = constants.MESH_AXIS_EXPERT

from .moe import EXPERT_AXIS  # noqa: E402  (no cycle: moe imports names only)

# logical → mesh axis (t5x-style rules)
LOGICAL_RULES = (
    (EXPERT_AXIS, EXPERT),
    (EMBED, FSDP),
    (VOCAB, TENSOR),
    (HEADS, TENSOR),
    (KV, None),
    (MLP, TENSOR),
    (BATCH, (DATA, FSDP)),
    (LENGTH, SEQUENCE),
)


def make_mesh(
    shape: Optional[dict] = None, devices=None
) -> Mesh:
    """Build the Cheetah mesh. Default: all devices on ``fsdp``.

    ``shape`` e.g. ``{"data": 1, "fsdp": 2, "tensor": 2, "sequence": 2}``;
    missing axes get size 1 so downstream PartitionSpecs always resolve.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if not shape:
        shape = {FSDP: n}
    full = {DATA: 1, FSDP: 1, TENSOR: 1, SEQUENCE: 1, EXPERT: 1}
    full.update(shape)
    if -1 in full.values():
        known = int(np.prod([s for s in full.values() if s != -1]))
        for k, v in full.items():
            if v == -1:
                full[k] = n // known
    total = int(np.prod(list(full.values())))
    if total != n:
        raise ValueError(f"mesh {full} needs {total} devices, have {n}")
    dev_array = np.asarray(devices).reshape(list(full.values()))
    return Mesh(dev_array, axis_names=tuple(full.keys()))


def compat_shard_map(fn, mesh, in_specs, out_specs):
    """shard_map across jax versions (check_rep/check_vma kwarg churn).

    The single compat point — pipeline, attention kernels, and ring
    attention all wrap through here so a jax upgrade breaks zero or all of
    them, never one.
    """
    try:
        from jax import shard_map as _sm
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map as _sm
    for kw in ({"check_vma": False}, {"check_rep": False}, {}):
        try:
            return _sm(fn, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, **kw)
        except TypeError:
            continue
    raise RuntimeError("no compatible shard_map signature")


def batch_mesh_axes(mesh: Mesh) -> Tuple[str, ...]:
    """The mesh axes that shard the batch dimension of activations — the
    canonical layout every constraint/kernel wrap must agree on."""
    from .. import constants as _c

    return tuple(
        a for a in (_c.MESH_AXIS_DATA, _c.MESH_AXIS_FSDP)
        if int(mesh.shape.get(a, 1)) > 1
    )


def logical_to_mesh_spec(logical_axes: Tuple) -> P:
    rules = dict(LOGICAL_RULES)
    return P(*(rules.get(a) if a is not None else None for a in logical_axes))


def param_shardings(mesh: Mesh, params: PyTree) -> PyTree:
    """NamedShardings for a param tree produced by modules that used
    ``nn.with_partitioning`` (boxed params carry their logical axis names)."""

    def _one(p):
        if isinstance(p, nn.Partitioned):
            spec = logical_to_mesh_spec(p.names)
        else:
            spec = P()
        return NamedSharding(mesh, spec)

    return jax.tree.map(
        _one, params, is_leaf=lambda x: isinstance(x, nn.Partitioned)
    )


def unbox(params: PyTree) -> PyTree:
    """Strip nn.Partitioned boxes → raw arrays (after placement)."""
    return jax.tree.map(
        lambda p: p.value if isinstance(p, nn.Partitioned) else p,
        params,
        is_leaf=lambda x: isinstance(x, nn.Partitioned),
    )


def unboxed_param_shardings(mesh: Mesh, boxed_params: PyTree) -> PyTree:
    """Shardings matching the *unboxed* tree structure."""
    shardings = param_shardings(mesh, boxed_params)
    # shardings tree has NamedSharding at the positions of boxed leaves;
    # structure already matches the unboxed tree (one leaf per param)
    return shardings


def batch_sharding(mesh: Mesh, seq_sharded: bool = False) -> NamedSharding:
    """Sharding for token batches [B, L]."""
    if seq_sharded:
        return NamedSharding(mesh, P((DATA, FSDP), SEQUENCE))
    return NamedSharding(mesh, P((DATA, FSDP), None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
