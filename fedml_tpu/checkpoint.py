"""Checkpoint / resume — a required upgrade over the reference.

The reference has essentially NO checkpointing (SURVEY.md §5 "Checkpoint /
resume": models move as in-memory state dicts or S3 artifacts per round; no
round-resume logic anywhere). Orbax-backed save/restore of any pytree
(TrainState, FL global params + round index), with retention.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

import orbax.checkpoint as ocp

logger = logging.getLogger(__name__)

PyTree = Any


class CheckpointManager:
    """Thin wrapper over orbax CheckpointManager keyed by integer step."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True,
                # save() below blocks on wait_until_finished() anyway (the
                # donated round state forces it), so async buys nothing —
                # and orbax's background serialize thread intermittently
                # segfaults against concurrent jax tracing on CPU hosts
                # (observed: deepcopy in type_handlers.serialize vs
                # pjit_staging_rule, killing the tier-1 run mid-suite)
                enable_async_checkpointing=False,
            ),
        )

    def save(self, state: PyTree, step: Optional[int] = None) -> int:
        if step is None:
            step = int(getattr(state, "step", 0))
        # Copy every leaf to host FIRST: the fused round engine (simulation/
        # round_engine.py) donates the state buffers to the next round's XLA
        # program, so a device reference held across the next dispatch would
        # be read-after-donate. device_get blocks until the values are
        # computed — but on the CPU backend it returns ZERO-COPY numpy views
        # over the jax buffers (owndata=False, dlpack-capsule base), so the
        # donation would still invalidate them mid-serialize. Force owned
        # copies of any non-owning leaf.
        import jax
        import numpy as np

        state = jax.device_get(state)
        state = jax.tree.map(
            lambda x: np.array(x)
            if isinstance(x, np.ndarray) and not x.flags.owndata else x,
            state,
        )
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        self._mgr.wait_until_finished()
        logger.info("checkpoint: saved step %d to %s", step, self.directory)
        return step

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def steps(self) -> list:
        """Every retained step, oldest first — the substrate a restarted
        server rebuilds its version-store ring from (the retention window
        IS the recoverable version history)."""
        return sorted(int(s) for s in self._mgr.all_steps())

    def restore(self, step: int, abstract_state: PyTree) -> PyTree:
        """Restore one retained step into the structure/shardings of
        ``abstract_state`` (pass a concrete template state)."""
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract_state)
        )
        # re-commit every leaf to the template's sharding: orbax may land
        # scalars on a single device, which breaks jit with mesh-sharded args.
        # Copy through jnp.array FIRST: device_put on the CPU backend
        # zero-copy ALIASES 64-byte-aligned numpy buffers, and the restored
        # leaves become the round state the fused engine donates — XLA
        # reclaiming a buffer numpy also owns is a use-after-free (observed
        # as intermittent segfaults / silently corrupted resumes).
        import jax
        import jax.numpy as jnp

        restored = jax.tree.map(
            lambda r, t: jax.device_put(jnp.array(r), t.sharding)
            if hasattr(t, "sharding") else r,
            restored,
            abstract_state,
        )
        logger.info("checkpoint: restored step %d from %s", step, self.directory)
        return restored

    def restore_latest(self, abstract_state: PyTree) -> Optional[PyTree]:
        """Restore the newest checkpoint into the structure/shardings of
        ``abstract_state`` (pass a concrete template state)."""
        step = self._mgr.latest_step()
        if step is None:
            return None
        return self.restore(step, abstract_state)

    def close(self) -> None:
        self._mgr.close()
