"""fedml_tpu — a TPU-native federated & distributed ML framework.

From-scratch JAX/XLA re-founding of the capabilities of FedML
(``/root/reference``, v0.7.285). API shape preserved from the reference's
``python/fedml/__init__.py:27-311`` and launchers (one-line ``run_simulation``,
five-line init → device → data → model → run), architecture re-designed
TPU-first: FL clients are shards of a device-mesh axis, aggregation is a
weighted on-device collective, local training is a ``lax.scan`` under ``vmap``,
and cross-silo FL is an async message plane over gRPC/TCP.
"""

from __future__ import annotations

import logging
import threading as _threading
from typing import Optional

from . import constants  # noqa: F401
from .arguments import Arguments, load_arguments
from .utils.seed import seed_everything

__version__ = "0.1.0"

_global_args: Optional[Arguments] = None
# guards the ambient-args latch (graftiso I001): concurrent inits (the
# multi-tenant shape) must not interleave the publish
_global_args_lock = _threading.Lock()


def init(args: Optional[Arguments] = None, should_init_logs: bool = True) -> Arguments:
    """Initialise the framework (reference: ``fedml.init``, __init__.py:27-109).

    Loads YAML config (``--cf``), seeds RNGs deterministically, and performs
    per-platform setup. Unlike the reference there is no MPI rank discovery or
    spawn-method fiddling — the TPU runtime discovers its mesh from JAX.
    """
    global _global_args
    if should_init_logs:
        logging.basicConfig(
            level=logging.INFO,
            format="[fedml_tpu] %(asctime)s %(levelname)s %(name)s: %(message)s",
        )
    if args is None:
        args = load_arguments()
    args.rng = seed_everything(int(args.random_seed))
    _update_client_id_list(args)
    _maybe_enable_compilation_cache(args)
    from .core import mlops

    mlops.init(args)
    with _global_args_lock:
        _global_args = args
    logging.getLogger(__name__).info(
        "init: platform=%s backend=%s optimizer=%s",
        args.training_type,
        args.backend,
        args.federated_optimizer,
    )
    return args


def _maybe_enable_compilation_cache(args: Arguments) -> None:
    """Point XLA's persistent compilation cache at ``compilation_cache_dir``.

    Repeat runs — and the driver's bench legs — then deserialize compiled
    executables instead of re-lowering them, which removes the compile wall
    that made BENCH legs time out (ISSUE 1). A low min-compile-time floor
    keeps even mid-sized programs cached; disk is the only cost.
    """
    cache_dir = str(getattr(args, "compilation_cache_dir", "") or "")
    if not cache_dir:
        return
    import os

    import jax

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    if "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in os.environ:
        # don't clobber an explicitly configured floor (e.g. raised to keep
        # a slow shared cache dir from thrashing on tiny entries)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    logging.getLogger(__name__).info(
        "init: persistent XLA compilation cache at %s", cache_dir
    )


def _update_client_id_list(args: Arguments) -> None:
    """Synthesise client id list when absent (reference: __init__.py:259-311)."""
    cil = getattr(args, "client_id_list", None)
    if not cil or cil in ("[]", "None"):
        args.client_id_list = str(list(range(1, args.client_num_in_total + 1)))


def get_args() -> Optional[Arguments]:
    return _global_args


# ---------------------------------------------------------------------------
# One-line launchers (reference: launch_simulation.py:10-30,
# launch_cross_silo_horizontal.py:7-52, launch_cross_device.py:6-28)
# ---------------------------------------------------------------------------
def run_simulation(backend: str = constants.FEDML_SIMULATION_TYPE_SP):
    """One-line FL simulation: init → device → data → model → run.

    Returns the final eval metrics (an upgrade over the reference's
    ``launch_simulation.py``, which discards them).
    """
    from . import data as data_mod
    from . import models as model_mod
    from .runner import FedMLRunner

    args = load_arguments(
        constants.FEDML_TRAINING_PLATFORM_SIMULATION, comm_backend=backend
    )
    args = init(args)
    device = get_device(args)
    dataset, output_dim = data_mod.load(args)
    model = model_mod.create(args, output_dim)
    runner = FedMLRunner(args, device, dataset, model)
    return runner.run()


def run_cross_silo_server(**kwargs):
    from .cross_silo import run_server

    return run_server(**kwargs)


def run_cross_silo_client(**kwargs):
    from .cross_silo import run_client

    return run_client(**kwargs)


def get_device(args: Optional[Arguments] = None):
    from .device import get_device as _get

    return _get(args)


# Sub-module conveniences mirroring `fedml.device` / `fedml.data` / `fedml.model`
from . import device  # noqa: E402,F401
