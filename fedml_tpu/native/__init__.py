"""``fedml_tpu.native`` — C++ host-runtime components via ctypes.

The compute path is JAX/XLA; the host runtime around it (data pipeline) is
native, mirroring how the reference leans on torch's C++ DataLoader workers
(SURVEY.md §1 L0). ``host_pipeline.cpp`` is compiled with g++ on first use
(no pybind11 in the image — C ABI + ctypes per environment constraints);
everything degrades to numpy when no toolchain is present.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
import threading
from typing import Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(__file__), "host_pipeline.cpp")
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False
# guards the build-once latch (graftiso I001): two threads racing get_lib()
# would otherwise both shell out to g++ against the same cache path
_LIB_LOCK = threading.Lock()


def _build_lib() -> Optional[str]:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    cache_dir = os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "fedml_tpu",
    )
    os.makedirs(cache_dir, exist_ok=True)
    so_path = os.path.join(cache_dir, f"host_pipeline_{digest}.so")
    if os.path.exists(so_path):
        return so_path
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           _SRC, "-o", so_path + ".tmp"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(so_path + ".tmp", so_path)
        logger.info("native: built %s", so_path)
        return so_path
    except (subprocess.CalledProcessError, FileNotFoundError,
            subprocess.TimeoutExpired) as e:
        logger.warning("native: build failed (%s); using numpy fallback", e)
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LIB_LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        so = _build_lib()
        if so is None:
            return None
        lib = ctypes.CDLL(so)
        lib.gather_rows_f32.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int64, ctypes.POINTER(ctypes.c_float),
            ctypes.c_int,
        ]
        lib.gather_rows_i32.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int,
        ]
        lib.gather_windows_i32.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int,
        ]
        lib.prefetcher_create.restype = ctypes.c_void_p
        lib.prefetcher_create.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
        ]
        lib.prefetcher_next.restype = ctypes.c_int64
        lib.prefetcher_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.prefetcher_destroy.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return _LIB


def have_native() -> bool:
    return get_lib() is not None


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _iptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def gather_rows(src: np.ndarray, idx: np.ndarray, threads: int = 4) -> np.ndarray:
    """Gather src[idx] along axis 0 (float32/int32 fast path)."""
    lib = get_lib()
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    if lib is None:
        return src[idx]
    k = idx.shape[0]
    row = int(np.prod(src.shape[1:], dtype=np.int64))
    out = np.empty((k,) + src.shape[1:], src.dtype)
    iptr = idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    if src.dtype == np.float32:
        lib.gather_rows_f32(_fptr(src), iptr, k, row, _fptr(out), threads)
    elif src.dtype == np.int32:
        lib.gather_rows_i32(_iptr(src), iptr, k, row, _iptr(out), threads)
    else:
        return src[idx]
    return out


def gather_windows(stream: np.ndarray, starts: np.ndarray, length: int,
                   threads: int = 4) -> np.ndarray:
    """stream[starts[i] : starts[i]+length] for every i — the LM corpus
    batch slicer (cheetah). Threaded C++ memcpy when the lib is built;
    vectorized numpy fancy-indexing fallback otherwise."""
    stream = np.ascontiguousarray(stream, dtype=np.int32)
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    if starts.size and (int(starts.min()) < 0
                        or int(starts.max()) + length > stream.size):
        # the C++ path is a raw memcpy with no bounds checks; validate here so
        # bad input raises on both paths instead of reading garbage natively
        raise ValueError(
            f"window out of range: starts in [{starts.min()}, {starts.max()}]"
            f" + length {length} vs stream size {stream.size}"
        )
    lib = get_lib()
    if lib is None:
        return stream[starts[:, None] + np.arange(length, dtype=np.int64)]
    k = starts.shape[0]
    out = np.empty((k, length), np.int32)
    lib.gather_windows_i32(
        _iptr(stream), starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        k, length, _iptr(out), threads,
    )
    return out


class BatchPrefetcher:
    """Background shuffled-batch producer over (x [N, ...] f32, y [N, ...] i32).

    Keeps ``depth`` batches materialized ahead of the consumer; ``next()``
    returns (x_batch, y_batch, epoch). Pure-numpy fallback shuffles inline.
    """

    def __init__(self, x: np.ndarray, y: np.ndarray, batch_size: int,
                 seed: int = 0, threads: int = 4, depth: int = 3):
        self.x = np.ascontiguousarray(x, dtype=np.float32)
        # y rows move as opaque 4-byte elements through the C++ gather, so
        # float32 targets (detection grids, regression) ride BIT-EXACT via
        # an int32 view — no native change, no precision loss
        y = np.asarray(y)
        self._y_dtype = (
            np.float32 if np.issubdtype(y.dtype, np.floating) else np.int32
        )
        self.y = np.ascontiguousarray(y, dtype=self._y_dtype)
        self.batch = int(batch_size)
        self._lib = get_lib()
        self._handle = None
        self._row = int(np.prod(self.x.shape[1:], dtype=np.int64))
        self._yrow = int(np.prod(self.y.shape[1:], dtype=np.int64)) or 1
        if self._lib is not None:
            self._handle = self._lib.prefetcher_create(
                _fptr(self.x), _iptr(self.y.view(np.int32)), self.x.shape[0],
                self._row, self._yrow, self.batch,
                int(seed) & (2**64 - 1), threads, depth,
            )
        else:
            self._rng = np.random.RandomState(seed)
            self._perm = self._rng.permutation(self.x.shape[0])
            self._cursor = 0
            self._epoch = 0

    def next(self) -> Tuple[np.ndarray, np.ndarray, int]:
        bx = np.empty((self.batch,) + self.x.shape[1:], np.float32)
        by = np.empty((self.batch,) + self.y.shape[1:], self._y_dtype)
        if self._handle is not None:
            epoch = self._lib.prefetcher_next(
                self._handle, _fptr(bx), _iptr(by.view(np.int32))
            )
            return bx, by, int(epoch)
        idx = []
        for _ in range(self.batch):
            if self._cursor >= len(self._perm):
                self._epoch += 1
                self._perm = self._rng.permutation(self.x.shape[0])
                self._cursor = 0
            idx.append(self._perm[self._cursor])
            self._cursor += 1
        idx = np.asarray(idx)
        return self.x[idx], self.y[idx], self._epoch

    def close(self) -> None:
        if self._handle is not None and self._lib is not None:
            self._lib.prefetcher_destroy(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass
