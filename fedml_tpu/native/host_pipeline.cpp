// Host-side data pipeline: threaded batch gather + prefetch ring buffer.
//
// Role in the framework: the reference's input pipeline rides torch
// DataLoader's native worker pool (SURVEY.md §1 L0 — "torch (C++/CUDA)" is a
// pip-dep native backend). This TPU build feeds jit'd steps from numpy
// arrays; the Python-side gather of a cohort/batch is GIL-bound and can
// starve the device between steps. This translation unit provides:
//
//   gather_rows_f32 / gather_rows_i32 — multi-threaded row gather
//     (memcpy per row, rows split across a small thread pool)
//   prefetcher_*                      — a background ring buffer that keeps
//     the next `depth` shuffled batches materialized while the device
//     computes (per-epoch mt19937_64 Fisher–Yates shuffle, epoch-tagged)
//
// Exposed as a C ABI for ctypes (no pybind11 in the image); the Python
// wrapper (fedml_tpu/native/__init__.py) compiles this file on first use and
// falls back to numpy when a toolchain is unavailable.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

namespace {

void gather_rows_impl(const char* src, const int64_t* idx, int64_t k,
                      int64_t row_bytes, char* dst, int threads) {
  if (threads < 1) threads = 1;
  if (threads == 1 || k < 4 * threads) {
    for (int64_t i = 0; i < k; ++i) {
      std::memcpy(dst + i * row_bytes, src + idx[i] * row_bytes, row_bytes);
    }
    return;
  }
  std::vector<std::thread> pool;
  int64_t chunk = (k + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = std::min(lo + chunk, k);
    if (lo >= hi) break;
    pool.emplace_back([=] {
      for (int64_t i = lo; i < hi; ++i) {
        std::memcpy(dst + i * row_bytes, src + idx[i] * row_bytes, row_bytes);
      }
    });
  }
  for (auto& th : pool) th.join();
}

struct Batch {
  std::vector<float> x;
  std::vector<int32_t> y;
  int64_t epoch;
};

struct Prefetcher {
  const float* x;
  const int32_t* y;
  int64_t n, row_elems, y_elems, batch;
  int gather_threads;
  size_t depth;
  std::mt19937_64 rng;

  std::deque<Batch> ring;
  std::mutex mu;
  std::condition_variable cv_put, cv_get;
  std::atomic<bool> stop{false};
  std::thread worker;

  std::vector<int64_t> perm;
  int64_t cursor = 0;
  int64_t epoch = 0;

  void reshuffle() {
    perm.resize(n);
    for (int64_t i = 0; i < n; ++i) perm[i] = i;
    for (int64_t i = n - 1; i > 0; --i) {
      std::uniform_int_distribution<int64_t> d(0, i);
      std::swap(perm[i], perm[d(rng)]);
    }
    cursor = 0;
  }

  void fill_loop() {
    while (!stop.load()) {
      Batch b;
      b.x.resize(batch * row_elems);
      b.y.resize(batch * y_elems);
      {
        // assemble indices for the next batch (wrap => new epoch/shuffle)
        std::vector<int64_t> idx(batch);
        for (int64_t i = 0; i < batch; ++i) {
          if (cursor >= n) {
            ++epoch;
            reshuffle();
          }
          idx[i] = perm[cursor++];
        }
        gather_rows_impl(reinterpret_cast<const char*>(x), idx.data(), batch,
                         row_elems * sizeof(float),
                         reinterpret_cast<char*>(b.x.data()), gather_threads);
        gather_rows_impl(reinterpret_cast<const char*>(y), idx.data(), batch,
                         y_elems * sizeof(int32_t),
                         reinterpret_cast<char*>(b.y.data()), gather_threads);
        b.epoch = epoch;
      }
      std::unique_lock<std::mutex> lk(mu);
      cv_put.wait(lk, [&] { return ring.size() < depth || stop.load(); });
      if (stop.load()) return;
      ring.push_back(std::move(b));
      cv_get.notify_one();
    }
  }
};

}  // namespace

extern "C" {

void gather_rows_f32(const float* src, const int64_t* idx, int64_t k,
                     int64_t row_elems, float* dst, int threads) {
  gather_rows_impl(reinterpret_cast<const char*>(src), idx, k,
                   row_elems * static_cast<int64_t>(sizeof(float)),
                   reinterpret_cast<char*>(dst), threads);
}

void gather_rows_i32(const int32_t* src, const int64_t* idx, int64_t k,
                     int64_t row_elems, int32_t* dst, int threads) {
  gather_rows_impl(reinterpret_cast<const char*>(src), idx, k,
                   row_elems * static_cast<int64_t>(sizeof(int32_t)),
                   reinterpret_cast<char*>(dst), threads);
}

// Gather k overlapping windows stream[starts[i] : starts[i]+len] — the LM
// batch slicer (cheetah corpus sampling). Windows overlap arbitrarily, so
// this cannot be expressed as a row gather over a materialized [N, len]
// matrix without first copying the whole stream len times.
void gather_windows_i32(const int32_t* stream, const int64_t* starts,
                        int64_t k, int64_t len, int32_t* dst, int threads) {
  if (threads < 1) threads = 1;
  const int64_t bytes = len * static_cast<int64_t>(sizeof(int32_t));
  auto copy_range = [=](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      std::memcpy(reinterpret_cast<char*>(dst) + i * bytes,
                  reinterpret_cast<const char*>(stream + starts[i]), bytes);
    }
  };
  if (threads == 1 || k < 4 * threads) {
    copy_range(0, k);
    return;
  }
  std::vector<std::thread> pool;
  int64_t chunk = (k + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = std::min(lo + chunk, k);
    if (lo >= hi) break;
    pool.emplace_back([=] { copy_range(lo, hi); });
  }
  for (auto& th : pool) th.join();
}

void* prefetcher_create(const float* x, const int32_t* y, int64_t n,
                        int64_t row_elems, int64_t y_elems, int64_t batch,
                        uint64_t seed, int gather_threads, int depth) {
  auto* p = new Prefetcher();
  p->x = x;
  p->y = y;
  p->n = n;
  p->row_elems = row_elems;
  p->y_elems = y_elems;
  p->batch = batch;
  p->gather_threads = gather_threads;
  p->depth = depth > 0 ? static_cast<size_t>(depth) : 2;
  p->rng.seed(seed);
  p->reshuffle();
  p->worker = std::thread([p] { p->fill_loop(); });
  return p;
}

// Blocks until a batch is ready; copies into out_x/out_y; returns the epoch
// index the batch belongs to, or -1 after destroy.
int64_t prefetcher_next(void* vp, float* out_x, int32_t* out_y) {
  auto* p = static_cast<Prefetcher*>(vp);
  Batch b;
  {
    std::unique_lock<std::mutex> lk(p->mu);
    p->cv_get.wait(lk, [&] { return !p->ring.empty() || p->stop.load(); });
    if (p->ring.empty()) return -1;
    b = std::move(p->ring.front());
    p->ring.pop_front();
    p->cv_put.notify_one();
  }
  std::memcpy(out_x, b.x.data(), b.x.size() * sizeof(float));
  std::memcpy(out_y, b.y.data(), b.y.size() * sizeof(int32_t));
  return b.epoch;
}

void prefetcher_destroy(void* vp) {
  auto* p = static_cast<Prefetcher*>(vp);
  p->stop.store(true);
  p->cv_put.notify_all();
  p->cv_get.notify_all();
  if (p->worker.joinable()) p->worker.join();
  delete p;
}

}  // extern "C"
