"""``fedml_tpu.cross_silo`` — the Octopus pillar (cross-org FL).

Facades mirror the reference (``cross_silo/fedml_client.py:5-57``,
``fedml_server.py:4-53``): optimizer dispatch "FedAvg" → managers; "LSA" →
LightSecAgg flow (``lightsecagg/``).
"""

from __future__ import annotations

from .. import constants
from ..ml.aggregator import create_server_aggregator
from ..ml.trainer import create_model_trainer


class FedMLCrossSiloServer:
    def __init__(self, args, device, dataset, model, server_aggregator=None):
        from .server_manager import FedMLServerManager

        self.args = args
        aggregator = server_aggregator or create_server_aggregator(model, args)
        aggregator.set_id(0)
        opt = str(getattr(args, "federated_optimizer", "FedAvg"))
        size = int(getattr(args, "client_num_in_total", 1)) + 1
        if opt == constants.FEDML_FEDERATED_OPTIMIZER_LSA:
            from .lightsecagg.lsa_server_manager import LightSecAggServerManager

            self.manager = LightSecAggServerManager(
                args, aggregator, rank=0, size=size,
                backend=str(getattr(args, "backend", constants.COMM_BACKEND_LOOPBACK)),
                dataset=dataset, model=model,
            )
        else:
            self.manager = FedMLServerManager(
                args, aggregator, rank=0, size=size,
                backend=str(getattr(args, "backend", constants.COMM_BACKEND_LOOPBACK)),
                dataset=dataset, model=model,
            )

    def run(self):
        self.manager.run()
        return self.manager.final_metrics


class FedMLCrossSiloClient:
    def __init__(self, args, device, dataset, model, client_trainer=None):
        self.args = args
        trainer = client_trainer or create_model_trainer(model, args)
        rank = int(getattr(args, "rank", 1))
        trainer.set_id(rank)
        size = int(getattr(args, "client_num_in_total", 1)) + 1
        opt = str(getattr(args, "federated_optimizer", "FedAvg"))
        if opt == constants.FEDML_FEDERATED_OPTIMIZER_LSA:
            from .lightsecagg.lsa_client_manager import LightSecAggClientManager

            self.manager = LightSecAggClientManager(
                args, trainer, rank=rank, size=size,
                backend=str(getattr(args, "backend", constants.COMM_BACKEND_LOOPBACK)),
                dataset=dataset,
            )
        else:
            from .client_manager import ClientMasterManager

            self.manager = ClientMasterManager(
                args, trainer, rank=rank, size=size,
                backend=str(getattr(args, "backend", constants.COMM_BACKEND_LOOPBACK)),
                dataset=dataset,
            )

    def run(self):
        self.manager.run()


def run_server(**overrides):
    """One-line server launcher (reference: launch_cross_silo_horizontal.py:7)."""
    import fedml_tpu as fedml
    from .. import data as data_mod
    from .. import models as model_mod
    from ..arguments import Arguments

    args = fedml.init(
        Arguments(training_type=constants.FEDML_TRAINING_PLATFORM_CROSS_SILO,
                  overrides={**overrides, "role": "server"})
    )
    device = fedml.get_device(args)
    dataset, output_dim = data_mod.load(args)
    model = model_mod.create(args, output_dim)
    server = FedMLCrossSiloServer(args, device, dataset, model)
    return server.run()


def run_client(**overrides):
    import fedml_tpu as fedml
    from .. import data as data_mod
    from .. import models as model_mod
    from ..arguments import Arguments

    args = fedml.init(
        Arguments(training_type=constants.FEDML_TRAINING_PLATFORM_CROSS_SILO,
                  overrides={**overrides, "role": "client"})
    )
    device = fedml.get_device(args)
    dataset, output_dim = data_mod.load(args)
    model = model_mod.create(args, output_dim)
    client = FedMLCrossSiloClient(args, device, dataset, model)
    return client.run()
