"""``fedml_tpu.cross_silo`` — the Octopus pillar (cross-org FL).

Facades mirror the reference (``cross_silo/fedml_client.py:5-57``,
``fedml_server.py:4-53``): optimizer dispatch "FedAvg" → managers; "LSA" →
LightSecAgg flow (``lightsecagg/``).
"""

from __future__ import annotations

from .. import constants
from ..ml.aggregator import create_server_aggregator
from ..ml.trainer import create_model_trainer


def _world_size(args) -> int:
    """Comm world size: server + clients in a flat world; the full rank
    space [root, clients, edge aggregators] in a tiered one
    (fedml_tpu/hierarchy/topology.py)."""
    from ..hierarchy import Topology

    topo = Topology.from_args(args)
    if topo is not None:
        return topo.world_size
    return int(getattr(args, "client_num_in_total", 1)) + 1


class FedMLCrossSiloServer:
    def __init__(self, args, device, dataset, model, server_aggregator=None):
        from .server_manager import FedMLServerManager

        self.args = args
        aggregator = server_aggregator or create_server_aggregator(model, args)
        aggregator.set_id(0)
        opt = str(getattr(args, "federated_optimizer", "FedAvg"))
        size = _world_size(args)
        if opt == constants.FEDML_FEDERATED_OPTIMIZER_LSA:
            from .lightsecagg.lsa_server_manager import LightSecAggServerManager

            self.manager = LightSecAggServerManager(
                args, aggregator, rank=0, size=size,
                backend=str(getattr(args, "backend", constants.COMM_BACKEND_LOOPBACK)),
                dataset=dataset, model=model,
            )
        else:
            self.manager = FedMLServerManager(
                args, aggregator, rank=0, size=size,
                backend=str(getattr(args, "backend", constants.COMM_BACKEND_LOOPBACK)),
                dataset=dataset, model=model,
            )

    def run(self):
        self.manager.run()
        if getattr(self.manager, "preempted", False):
            # surface the drain as the sp/mesh engines do: FedMLRunner maps
            # PreemptionError to the distinct "preempted, resumable" exit
            # status (75) so supervisors restart with --resume auto instead
            # of treating the preemption as a completed run
            from ..core.runstate import PreemptionError

            raise PreemptionError(self.manager.round_idx - 1)
        return self.manager.final_metrics


class FedMLCrossSiloClient:
    """One silo. Hierarchical knobs (reference ``client_launcher.py`` +
    ``process_group_manager.py``):

    - ``args.silo_device_indices``: chips this silo trains over — intra-silo
      data parallelism as ONE jit over a local mesh (per-step gradient psum,
      the torch-DDP analog on ICI).
    - ``args.silo_proc_num`` > 1: DCN-separated silo members; slaves run the
      ``ClientSlaveManager`` FSM and the master round-averages the silo
      before one update goes to the FL server.
    """

    def __init__(self, args, device, dataset, model, client_trainer=None):
        self.args = args
        trainer = client_trainer or create_model_trainer(model, args)
        rank = int(getattr(args, "rank", 1))
        trainer.set_id(rank)
        size = _world_size(args)
        backend = str(getattr(args, "backend", constants.COMM_BACKEND_LOOPBACK))
        opt = str(getattr(args, "federated_optimizer", "FedAvg"))

        silo_devices = getattr(args, "silo_device_indices", None)
        if silo_devices and not getattr(trainer, "silo_parallel", False):
            # the FedLLM trainer meshes its silo chips itself; everything
            # else gets the per-step-psum DP adapter
            from .process_group import SiloProcessGroup
            from .trainer_dist_adapter import TrainerDistAdapter

            group = SiloProcessGroup([int(i) for i in silo_devices])
            trainer = TrainerDistAdapter(args, trainer, group)

        if opt == constants.FEDML_FEDERATED_OPTIMIZER_LSA:
            from .lightsecagg.lsa_client_manager import LightSecAggClientManager

            self.manager = LightSecAggClientManager(
                args, trainer, rank=rank, size=size,
                backend=backend, dataset=dataset,
            )
            return

        from .client_manager import ClientMasterManager

        silo_plane = None
        silo_shard = None
        self._slaves = []
        silo_procs = int(getattr(args, "silo_proc_num", 1) or 1)
        if silo_procs > 1:
            # the in-process analog of the reference's client_launcher:
            # spawn silo members and a master plane on a silo-private world
            from ..core.distributed.loopback import LoopbackCommManager
            from .client_slave_manager import (
                ClientSlaveManager, SiloMasterPlane, split_silo_shard,
            )

            world = f"{getattr(args, 'run_id', 'default')}:silo:{rank}"
            shards = split_silo_shard(
                *dataset.client_shard(rank - 1), m=silo_procs,
                batch_size=int(getattr(args, "batch_size", 1)),
            )
            silo_shard = shards[0]
            for s in range(1, silo_procs):
                slave_trainer = create_model_trainer(model, args)
                slave_trainer.set_id(rank * 1000 + s)
                slave = ClientSlaveManager(
                    args, slave_trainer,
                    comm=LoopbackCommManager(s, silo_procs, world),
                    rank=s, size=silo_procs, dataset=shards[s],
                )
                slave.run_async()
                self._slaves.append(slave)
            silo_plane = SiloMasterPlane(
                args, comm=LoopbackCommManager(0, silo_procs, world),
                size=silo_procs,
            )

        self.manager = ClientMasterManager(
            args, trainer, rank=rank, size=size,
            backend=backend, dataset=dataset,
            silo_plane=silo_plane, silo_shard=silo_shard,
        )

    def run(self):
        self.manager.run()


def run_server(**overrides):
    """One-line server launcher (reference: launch_cross_silo_horizontal.py:7).

    Parses the CLI (``--cf config.yaml --rank 0 --role server``) like the
    simulation launcher, then applies keyword overrides on top.
    """
    import fedml_tpu as fedml
    from .. import data as data_mod
    from .. import models as model_mod
    from ..arguments import add_args, Arguments

    args = fedml.init(
        Arguments(add_args(),
                  training_type=constants.FEDML_TRAINING_PLATFORM_CROSS_SILO,
                  overrides={**overrides, "role": "server"})
    )
    device = fedml.get_device(args)
    dataset, output_dim = data_mod.load(args)
    model = model_mod.create(args, output_dim)
    server = FedMLCrossSiloServer(args, device, dataset, model)
    return server.run()


def run_client(**overrides):
    import fedml_tpu as fedml
    from .. import data as data_mod
    from .. import models as model_mod
    from ..arguments import add_args, Arguments

    args = fedml.init(
        Arguments(add_args(),
                  training_type=constants.FEDML_TRAINING_PLATFORM_CROSS_SILO,
                  overrides={"role": "client", **overrides})
    )
    device = fedml.get_device(args)
    dataset, output_dim = data_mod.load(args)
    model = model_mod.create(args, output_dim)
    client = FedMLCrossSiloClient(args, device, dataset, model)
    return client.run()
