"""FedLLM: cross-silo federated fine-tuning of the Cheetah transformer.

The reference's two product promises — FL between organizations (Octopus,
``cross_silo/fedml_client.py:5``, ``server/fedml_aggregator.py``) and
distributed large-model training (Cheetah, an EMPTY stub:
``python/fedml/distributed/`` + ``constants.py:5``) — never meet in its
codebase. This module is the meeting point in ours:

- each silo's local training is the REAL Cheetah step: the silo's chips form
  a ``jax.sharding.Mesh`` (fsdp/tensor/sequence axes from ``mesh_shape``)
  and ``parallel.train_step.CheetahTrainer`` runs jit-sharded
  forward/backward/AdamW over it — XLA inserts the ICI collectives;
- rounds ride the UNCHANGED cross-silo FSM (``client_manager.py`` /
  ``server_manager.py``): ONLINE barrier, S2C_INIT/SYNC, C2S model,
  deadlines/quorum — with the payload store carrying the GB-scale weights
  off the control channel and ``core/compression.UpdateCodec`` optionally
  shrinking the C2S delta;
- aggregation is the same weighted tree-average every zoo model uses; the
  server needs no Cheetah machinery at all.

Local-optimizer semantics follow the reference's trainers (a FRESH torch
optimizer per round, ``ml/trainer/my_model_trainer_classification.py:30-45``):
optimizer state is re-initialised around each round's broadcast params and
never crosses the wire.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.alg_frame import ClientTrainer
from ..ml.optimizer import create_client_optimizer
from ..parallel.sharding import make_mesh
from ..parallel.train_step import CheetahTrainer

logger = logging.getLogger(__name__)

PyTree = Any


def _mesh_from_args(args, devices=None):
    """Silo mesh: ``args.mesh_shape`` ("fsdp:2,tensor:2") over the silo's
    chips (``args.silo_device_indices`` or all local devices)."""
    if devices is None:
        indices = getattr(args, "silo_device_indices", None)
        if indices:
            pool = jax.devices()
            devices = [pool[int(i)] for i in indices]
    from ..arguments import parse_mesh_shape

    shape = parse_mesh_shape(getattr(args, "mesh_shape", "")) or None
    return make_mesh(shape, devices)


class CheetahClientTrainer(ClientTrainer):
    """ClientTrainer whose ``train()`` is sharded Cheetah local steps.

    Drops into every message-driven runtime that speaks the ClientTrainer
    contract (cross-silo master manager, LSA flow). The packed nwp shard
    (x [cap, L] inputs, y [cap, L] shifted targets, n real rows) is
    reassembled into token windows [cap, L+1]; each local step draws
    ``batch_size`` windows (host RNG, deterministic in
    (random_seed, round_idx, client id)) and runs one
    ``CheetahTrainer.train_step`` — forward, backward, optimizer update, all
    sharded over the silo mesh.
    """

    # the trainer owns its silo parallelism (mesh over silo chips); the
    # facade must not wrap it in the vision-path TrainerDistAdapter
    silo_parallel = True

    def __init__(self, bundle, args=None, mesh=None, devices=None):
        super().__init__(bundle, args)
        self.mesh = mesh if mesh is not None else _mesh_from_args(args, devices)
        seq_sharded = int(self.mesh.shape.get("sequence", 1)) > 1
        self.trainer = CheetahTrainer(
            bundle.cfg,
            self.mesh,
            optimizer=create_client_optimizer(args),
            accum_steps=1,
            seq_sharded=seq_sharded,
        )
        logger.info(
            "fedllm: silo trainer over mesh %s%s",
            dict(self.mesh.shape), " (sequence-sharded)" if seq_sharded else "",
        )

    # -- local training ------------------------------------------------------
    def _local_steps(self, n: int, batch: int) -> int:
        explicit = int(getattr(self.args, "local_steps", 0) or 0)
        if explicit:
            return explicit
        epochs = int(getattr(self.args, "epochs", 1) or 1)
        return max(int(n) // batch, 1) * epochs

    def train(self, train_data, device, args) -> Dict[str, Any]:
        x, y, n = train_data
        n = int(n)
        # the packed x rows ARE the token windows ([cap, L]); the Cheetah
        # loss shifts internally (targets = tokens[:, 1:] == y[:, :-1]), so
        # y adds nothing the window doesn't carry — and keeping L unchanged
        # keeps the sequence axis divisibility the mesh was built for
        tokens_all = np.asarray(x).astype(np.int32)
        batch = int(getattr(args, "batch_size", 8))
        steps = self._local_steps(n, batch)
        seed = (
            int(getattr(args, "random_seed", 0)) * 1000003
            + int(getattr(args, "round_idx", 0)) * 100003
            + self.id
        )
        rng = np.random.RandomState(seed & 0x7FFFFFFF)

        # pad id: losses.PAD_TOKEN is the ONE framework-wide constant — the
        # nwp loss, eval metrics (ml/losses.py:21, matching the reference's
        # NWP masking of id 0), and this training mask must all agree, so a
        # corpus where 0 is a real symbol must remap at ingestion rather
        # than override here (a train-only knob would silently diverge the
        # train and eval token sets)
        from ..ml.losses import PAD_TOKEN

        state = self.trainer.state_from_params(self.model_params["params"])
        losses = []
        for _ in range(steps):
            idx = rng.randint(0, max(n, 1), size=batch)
            tok = tokens_all[idx]
            mask = (tok != PAD_TOKEN).astype(np.float32)
            state, metrics = self.trainer.train_step(
                state, jnp.asarray(tok), jnp.asarray(mask)
            )
            # host float, not an eager jnp op: trainers run on FSM threads,
            # and concurrent eager dispatch from multiple threads is not a
            # contract the CPU client honours
            losses.append(float(metrics["loss"]))
        self.model_params = {"params": state.params}
        return {
            "train_loss": float(np.mean(losses)) if losses else 0.0,
            "num_samples": float(n),
            "local_steps": float(steps),
        }

