"""Cross-silo message protocol constants.

reference: ``cross_silo/server/message_define.py`` / ``client/message_define.py``
(S2C_INIT / S2C_SYNC / C2S_SEND / status messages) — FSM documented at
SURVEY.md §3.4.
"""


class MyMessage:
    MSG_TYPE_CONNECTION_IS_READY = "connection_ready"

    MSG_TYPE_C2S_CLIENT_STATUS = "c2s_client_status"
    MSG_TYPE_C2S_SEND_MODEL_TO_SERVER = "c2s_send_model_to_server"
    # delta delivery plane (docs/delivery.md): the client-pull FedBuff
    # dispatch policy (--async_dispatch client_pull) — a client asks for a
    # model newer than the version it carries; the server answers
    # immediately when the head is already newer, else on the next bump
    MSG_TYPE_C2S_PULL_REQUEST = "c2s_pull_request"

    MSG_TYPE_S2C_INIT_CONFIG = "s2c_init_config"
    MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT = "s2c_sync_model_to_client"
    MSG_TYPE_S2C_FINISH = "s2c_finish"
    # async traffic plane (aggregation_mode=async, docs/traffic.md):
    # admission control shed a C2S model — the explicit NACK carrying the
    # shed update's version and a retry_after_s the client backs off by
    MSG_TYPE_S2C_SHED_NOTICE = "s2c_shed_notice"

    # intra-silo master <-> slave plane (hierarchical cross-silo;
    # reference: cross_silo/client/fedml_client_slave_manager.py)
    MSG_TYPE_SILO_SYNC = "silo_m2s_sync"
    MSG_TYPE_SILO_RESULT = "silo_s2m_result"
    MSG_TYPE_SILO_FINISH = "silo_m2s_finish"

    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
    MSG_ARG_KEY_ROUND_IDX = "round_idx"
    MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
    MSG_ARG_KEY_CLIENT_STATUS = "client_status"
    MSG_ARG_KEY_TRAIN_LOSS = "train_loss"
    # async traffic plane: in aggregation_mode=async the round index IS the
    # server model version (version-tagged dispatch → exact staleness);
    # these keys ride the shed NACK
    MSG_ARG_KEY_RETRY_AFTER_S = "retry_after_s"
    MSG_ARG_KEY_SHED_REASON = "shed_reason"
    # delta delivery plane: a C2S message sets this when its sender can
    # decode S2C delta frames (capability negotiation — swarm devices and
    # pre-delta clients never set it and keep receiving full frames). The
    # version the message is tagged with becomes the sender's last-ACKed
    # base for S2C delta encoding.
    MSG_ARG_KEY_DELTA_CAPABLE = "delta_capable"

    CLIENT_STATUS_ONLINE = "ONLINE"
    CLIENT_STATUS_OFFLINE = "OFFLINE"
