"""Cross-silo message protocol constants.

reference: ``cross_silo/server/message_define.py`` / ``client/message_define.py``
(S2C_INIT / S2C_SYNC / C2S_SEND / status messages) — FSM documented at
SURVEY.md §3.4.
"""


class MyMessage:
    MSG_TYPE_CONNECTION_IS_READY = "connection_ready"

    MSG_TYPE_C2S_CLIENT_STATUS = "c2s_client_status"
    MSG_TYPE_C2S_SEND_MODEL_TO_SERVER = "c2s_send_model_to_server"
    # delta delivery plane (docs/delivery.md): the client-pull FedBuff
    # dispatch policy (--async_dispatch client_pull) — a client asks for a
    # model newer than the version it carries; the server answers
    # immediately when the head is already newer, else on the next bump
    MSG_TYPE_C2S_PULL_REQUEST = "c2s_pull_request"

    # survivable serving plane (docs/robustness.md "Server failover &
    # resync"): the client liveness/resync FSM. Heartbeats lease the
    # server connection (a missed-ack window means the server is gone or
    # partitioned away); c2s_resync is the idempotent reconnect
    # handshake — it doubles as an ONLINE announcement on a restarted
    # server, and its ack tells the client whether its last trained
    # update was durably aggregated (COMMITTED_ROUND) so an unACKed
    # update is replayed through the existing dedup window instead of
    # being lost or double-counted.
    MSG_TYPE_C2S_HEARTBEAT = "c2s_heartbeat"
    MSG_TYPE_C2S_RESYNC = "c2s_resync"

    MSG_TYPE_S2C_INIT_CONFIG = "s2c_init_config"
    MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT = "s2c_sync_model_to_client"
    MSG_TYPE_S2C_FINISH = "s2c_finish"
    # heartbeat lease renewal + the resync handshake's answer (carries the
    # server's round/version head and the sender's last committed
    # contribution round)
    MSG_TYPE_S2C_HEARTBEAT_ACK = "s2c_heartbeat_ack"
    MSG_TYPE_S2C_RESYNC_ACK = "s2c_resync_ack"
    # async traffic plane (aggregation_mode=async, docs/traffic.md):
    # admission control shed a C2S model — the explicit NACK carrying the
    # shed update's version and a retry_after_s the client backs off by
    MSG_TYPE_S2C_SHED_NOTICE = "s2c_shed_notice"

    # intra-silo master <-> slave plane (hierarchical cross-silo;
    # reference: cross_silo/client/fedml_client_slave_manager.py)
    MSG_TYPE_SILO_SYNC = "silo_m2s_sync"
    MSG_TYPE_SILO_RESULT = "silo_s2m_result"
    MSG_TYPE_SILO_FINISH = "silo_m2s_finish"

    # hierarchical edge tier (docs/traffic.md "Hierarchical edge tier",
    # docs/robustness.md "Edge tier failure domains"): an edge aggregator
    # pre-folds its clients' updates CONTROL-PLANE-ONLY (admission, dedup,
    # staleness annotation, canonical ordering) and ships the buffered
    # entries up as ONE batched summary frame; the root expands the
    # entries through the exact flat fold, which is what makes a 2-tier
    # run bitwise-equal to flat FedBuff.
    MSG_TYPE_E2S_EDGE_SUMMARY = "e2s_edge_summary"
    # an edge (re)joining the root — same idempotent handshake shape as
    # c2s_resync; the ack re-seeds the edge's model-store replica
    MSG_TYPE_E2S_EDGE_RESYNC = "e2s_edge_resync"
    # edge-death re-homing: an orphaned client adopting a sibling edge
    # (or the root in degraded mode) after its resync budget ran out on
    # the dead home edge; the ack is a plain s2c_resync_ack
    MSG_TYPE_C2E_REHOME = "c2e_rehome"
    # a restarted edge re-soliciting its leased clients' uncommitted
    # updates (the edge-tier analog of _recover_serving_state): clients
    # answer by re-offering their cached still-stamped update
    MSG_TYPE_E2C_RESOLICIT = "e2c_resolicit"

    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
    MSG_ARG_KEY_ROUND_IDX = "round_idx"
    MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
    MSG_ARG_KEY_CLIENT_STATUS = "client_status"
    MSG_ARG_KEY_TRAIN_LOSS = "train_loss"
    # async traffic plane: in aggregation_mode=async the round index IS the
    # server model version (version-tagged dispatch → exact staleness);
    # these keys ride the shed NACK
    MSG_ARG_KEY_RETRY_AFTER_S = "retry_after_s"
    MSG_ARG_KEY_SHED_REASON = "shed_reason"
    # survivable serving plane: the resync ack's record of the sender's
    # highest trained round whose contribution was durably aggregated —
    # a client whose last trained round is newer replays its cached
    # (still-stamped) update; one that is covered does not
    MSG_ARG_KEY_COMMITTED_ROUND = "committed_round"
    # delta delivery plane: a C2S message sets this when its sender can
    # decode S2C delta frames (capability negotiation — swarm devices and
    # pre-delta clients never set it and keep receiving full frames). The
    # version the message is tagged with becomes the sender's last-ACKed
    # base for S2C delta encoding.
    MSG_ARG_KEY_DELTA_CAPABLE = "delta_capable"
    # distributed-tracing clock probes (docs/tracing.md "Clock
    # alignment"): NTP-style monotonic timestamp pairs piggybacked on the
    # heartbeat exchange so the trace merge's offset estimator has samples
    # even on quiet links. The client stamps T_SEND on c2s_heartbeat; the
    # ack echoes it (T_ECHO) next to the server's receive/reply clocks
    # (T_RECV / T_REPLY); the client closes the pair at ack receipt.
    MSG_ARG_KEY_HB_T_SEND = "hb_t_send"
    MSG_ARG_KEY_HB_T_ECHO = "hb_t_echo"
    MSG_ARG_KEY_HB_T_RECV = "hb_t_recv"
    MSG_ARG_KEY_HB_T_REPLY = "hb_t_reply"

    # hierarchical edge tier: the summary's per-entry control-plane
    # metadata (sender/client_version/num_samples/codec meta per buffered
    # update, JSON-encoded) and the edge's piggybacked health stats
    # (folds, re-homed clients, staleness histogram) — stats ride the
    # summary so they survive process boundaries under gRPC
    MSG_ARG_KEY_SUMMARY_META = "edge_summary_meta"
    MSG_ARG_KEY_EDGE_STATS = "edge_stats"
    # c2e_rehome: the rank of the dead edge the client is abandoning
    MSG_ARG_KEY_OLD_EDGE = "old_edge"

    CLIENT_STATUS_ONLINE = "ONLINE"
    CLIENT_STATUS_OFFLINE = "OFFLINE"
