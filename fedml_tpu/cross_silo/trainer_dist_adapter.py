"""Intra-silo data parallelism: one silo client training over its local chips.

reference: ``cross_silo/client/fedml_trainer_dist_adapter.py:24-36`` — wraps
the trainer in torch DDP over the silo's process group and
``fedml_client_slave_manager.py`` keeps non-master ranks training in step.

TPU-native re-design: the silo's chips are ICI-connected, so instead of a
DDP wrapper + per-step NCCL all-reduce, the whole local-training loop runs as
ONE ``shard_map`` program over the silo mesh (``process_group.SiloProcessGroup``):

- each device holds a contiguous ``cap/k`` slice of the client's packed shard
- every optimizer step draws ``batch_size`` samples per device (global batch
  = k x batch_size, the torch-DDP convention) and weighted-``psum``s the
  gradients over the ``silo_dp`` axis — the exact global-batch gradient,
  with padding masked per device
- the optimizer update is computed identically on every device, so params
  stay replicated without any broadcast

The master/slave message FSM survives only for DCN-separated silo members
(``client_slave_manager.ClientSlaveManager``) where per-step psum is not
economical.
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.containers import BoundedDict
from ..ml.losses import get_loss_fn
from ..ml.optimizer import create_client_optimizer
from .process_group import SILO_AXIS, SiloProcessGroup

logger = logging.getLogger(__name__)

PyTree = Any


def make_silo_dp_train_fn(bundle, args, local_cap: int, mesh, axis=SILO_AXIS):
    """Per-device local training with per-step gradient psum over the silo.

    Returns a jitted fn ``(global_params, x, y, n_per_dev, rng) -> (params,
    metrics)`` where ``x``/``y`` are [k*local_cap, ...] (sharded over devices
    on axis 0) and ``n_per_dev`` is [k] real-sample counts per device slice.
    """
    k = int(mesh.shape[axis])
    batch_size = int(args.batch_size)
    epochs = int(args.epochs)
    num_batches = max(local_cap // batch_size, 1)
    loss_fn_raw = get_loss_fn(bundle.task)
    opt = create_client_optimizer(args)

    def loss_fn(params, bx, by, bmask, rng):
        logits = bundle.apply(params, bx, train=True, rngs={"dropout": rng})
        loss, metrics = loss_fn_raw(logits.astype(jnp.float32), by, bmask)
        return loss, metrics

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def device_train(global_params, x, y, n_dev, rng):
        """One device's view: x [local_cap, ...], n_dev [1]."""
        n_local = n_dev[0].astype(jnp.float32)
        # distinct sampling stream per device, same param trajectory
        drng = jax.random.fold_in(rng, jax.lax.axis_index(axis))
        opt_state = opt.init(global_params)

        def epoch_body(carry, e):
            params, opt_state = carry
            erng = jax.random.fold_in(drng, e)
            # key discipline (graftrep D001): shuffle key and per-batch base
            # derived up front — the consumed perm key is never reused
            perm_rng, step_rng = jax.random.split(erng)
            perm = jax.random.permutation(perm_rng, local_cap)

            def batch_body(carry, i):
                params, opt_state = carry
                idx = jax.lax.dynamic_slice(
                    perm, (i * batch_size,), (batch_size,)
                )
                bx = jnp.take(x, idx, axis=0)
                by = jnp.take(y, idx, axis=0)
                bmask = (idx < n_local).astype(jnp.float32)
                brng = jax.random.fold_in(step_rng, i)
                (loss, _), grads = grad_fn(params, bx, by, bmask, brng)
                # weighted all-reduce: exact global-batch gradient with
                # per-device padding masked out
                w = bmask.sum()
                wsum = jax.lax.psum(w, axis)
                safe = jnp.maximum(wsum, 1.0)
                grads = jax.tree.map(
                    lambda g: jax.lax.psum(g * w, axis) / safe, grads
                )
                loss = jax.lax.psum(loss * w, axis) / safe
                has_data = (wsum > 0).astype(jnp.float32)
                grads = jax.tree.map(lambda g: g * has_data, grads)
                updates, opt_state = opt.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), loss

            (params, opt_state), losses = jax.lax.scan(
                batch_body, (params, opt_state), jnp.arange(num_batches)
            )
            return (params, opt_state), losses.mean()

        (params, _), epoch_losses = jax.lax.scan(
            epoch_body, (global_params, opt_state), jnp.arange(epochs)
        )
        n_total = jax.lax.psum(n_local, axis)
        steps = jnp.ceil(n_total / (k * batch_size))
        metrics = {
            "train_loss": epoch_losses.mean(),
            "num_samples": n_total,
            "tau": jnp.maximum(steps * epochs, 1.0),
        }
        return params, metrics

    data_spec = P(axis)
    try:  # jax >= 0.8: check_rep retired (VMA inference handles it)
        fn = shard_map(
            device_train,
            mesh=mesh,
            in_specs=(P(), data_spec, data_spec, data_spec, P()),
            out_specs=(P(), P()),
        )
    except TypeError:
        fn = shard_map(
            device_train,
            mesh=mesh,
            in_specs=(P(), data_spec, data_spec, data_spec, P()),
            out_specs=(P(), P()),
            check_rep=False,
        )
    return jax.jit(fn)


class TrainerDistAdapter:
    """Adapts a ClientTrainer so ``train()`` runs silo-data-parallel.

    reference: ``fedml_trainer_dist_adapter.py:24-36`` (DDP wrap + update_model
    / update_dataset). Holds the silo ``SiloProcessGroup``; with one device it
    degrades to the plain trainer.
    """

    def __init__(self, args, trainer, process_group: Optional[SiloProcessGroup] = None):
        self.args = args
        self.trainer = trainer
        self.model = trainer.model  # bundle passthrough for manager FSMs
        self.group = process_group or SiloProcessGroup()
        # jit cache keyed by padded per-device capacity (graftmem M002):
        # capacities are batch-multiples of a fixed geometry, so a handful
        # of entries is steady state — the bound is a backstop against a
        # pathological shard-size walk recompiling (and retaining) forever
        self._jitted: Dict[int, Any] = BoundedDict(8, lru=True,
                                                   name="trainer.jit_cache")

    # trainer facade ---------------------------------------------------------
    def get_model_params(self) -> PyTree:
        return self.trainer.get_model_params()

    def set_model_params(self, params: PyTree) -> None:
        self.trainer.set_model_params(params)

    def train(self, train_data, device, args) -> Dict[str, Any]:
        """train_data = (x [cap, ...], y [cap, ...], n) for this client."""
        k = self.group.size
        if k <= 1:
            return self.trainer.train(train_data, device, args)
        x, y, n = train_data
        # shared split geometry with the DCN path (client_slave_manager):
        # per-device capacity a non-zero batch multiple, contiguous real rows
        from .client_slave_manager import padded_silo_split

        x, y, local_cap, n_dev = padded_silo_split(
            x, y, int(n), k, int(self.args.batch_size)
        )
        if local_cap not in self._jitted:
            self._jitted[local_cap] = make_silo_dp_train_fn(
                self.trainer.model, self.args, local_cap, self.group.mesh
            )
        fn = self._jitted[local_cap]
        rng = jax.random.fold_in(
            jax.random.PRNGKey(int(getattr(args, "random_seed", 0))),
            int(getattr(args, "round_idx", 0)) * 100003
            + int(getattr(self.trainer, "id", 0)),
        )
        shard = NamedSharding(self.group.mesh, P(SILO_AXIS))
        with self.group.mesh:
            params, metrics = fn(
                self.trainer.get_model_params(),
                jax.device_put(jnp.asarray(x), shard),
                jax.device_put(jnp.asarray(y), shard),
                jax.device_put(jnp.asarray(n_dev), shard),
                rng,
            )
        self.trainer.set_model_params(params)
        return {key: float(v) for key, v in metrics.items()}
